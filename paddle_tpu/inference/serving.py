"""Continuous-batching LLM serving over the paged KV cache
(ref: the reference's serving decode stack — block_multihead_attention
paged decode, phi/kernels/fusion/gpu/block_multi_head_attention_kernel;
fluid/inference/api/analysis_predictor.cc:2320 Run() driving it; the
block-table allocator in fluid/framework/new_executor/block tables).

TPU-native design: a global KV PAGE POOL `[L, kvh, n_pages, page, d]`
(the Pallas paged_attention kernel's pool layout) plus a host-side
free-list allocator and per-slot block tables — KV memory is
proportional to live tokens, not batch * max_seq.

Two scheduler regimes, flag-gated (`FLAGS_ragged_attention`, default on):

* CHUNKED-PREFILL continuous batching (the ragged regime — ref "Ragged
  Paged Attention", arxiv 2604.15464): admission splits prompts into
  KV-budgeted prefill CHUNKS (`max_chunk_tokens` per tick) that are
  packed into the SAME compiled step as the active decode slots — one
  ragged kernel invocation per tick, one KV page-scatter per tick per
  layer, ONE compiled shape total (rows pad to a fixed bucket). Prefill
  no longer head-of-line-blocks decoding users, and pool accounting
  moves to token granularity (pages are funded chunk by chunk).
* The legacy bucketed regime (`FLAGS_ragged_attention=0` restores it
  exactly): each admitted request prefills as a bucketed batched
  compile, then joins the shared single-token decode tick.

Both regimes: finished sequences return their pages to the pool, and
pool exhaustion preempts the latest-admitted sequence (recompute-style
resume). Serving telemetry rides the observability registry
(serving.ttft_seconds / serving.tpot_seconds / serving.kv_pages_in_use /
serving.preemptions_total / serving.packed_tokens_per_tick).

SLO resilience layer (`FLAGS_serving_slo`, default on — ISSUE 10; ref
the vLLM priority scheduler + the Gemma-on-Cloud-TPU tail-latency
framing, arxiv 2605.25645). Armed, the engine grows four coordinated
behaviors; disarmed (`=0`) every one of them is skipped and the
scheduler is the exact pre-SLO FIFO engine (same admission order, same
preemption victims, same compiled step signatures — kill-switch parity
held to the `FLAGS_ragged_attention=0` bar):

* **SLO scheduling** — `GenerationRequest.priority` (higher wins) and
  `deadline_s` (relative to arrival); the wait queue orders by
  (priority, earliest-deadline-first slack) with a STABLE sort so
  equal-key requests keep FIFO order, preemption never evicts a
  higher-priority page-holder on behalf of a lower one, and a request
  whose deadline passes fails fast with a `DeadlineExceeded` terminal
  status instead of holding pages.
* **Admission control + shedding** — `max_queue_tokens` bounds the
  queue; a full queue rejects AT SUBMIT with `QueueFull` carrying a
  `retry_after_s` hint, and sustained admission starvation sheds the
  (lowest-priority, most-slack) waiting request instead of wedging.
  Adaptive degradation shrinks the effective prefill chunk budget with
  hysteresis under pool pressure — decode TPOT holds while TTFT
  degrades gracefully (same compiled shape: only the packing changes).
* **Per-request fault isolation** — `serving.tick` / `serving.admit` /
  `serving.page_alloc` fault points; a tick that raises quarantines
  ONE request (suspicion falls on the latest admission — the data new
  to the failing batch) and a row whose logits go non-finite is
  quarantined EXACTLY (slot + pages reclaimed, terminal `failed`
  status) while the engine keeps serving everyone else; an optional
  per-tick watchdog (`tick_timeout_s`) detects a wedged tick and dumps
  through the flight recorder.
* **Telemetry** — serving.deadline_misses_total / sheds_total /
  quarantines_total counters, serving.queue_depth + serving.degraded
  gauges, priority-labeled TTFT/TPOT observations, and
  `health_snapshot()` (also exported at /healthz next to /metrics) as
  the readiness view for a future HTTP front-end.

Prefix caching (`FLAGS_prefix_cache`, default on — ISSUE 12; ref the
vLLM automatic-prefix-cache / RadixAttention design over the paged
pool): the ragged kernel already reads ARBITRARY per-sequence block
tables (arxiv 2604.15464), so sharing a prompt prefix is pure pool
accounting. A content-hash chain index maps each fully-written PAGE of
an admitted prompt to its physical page; a later admission whose prompt
starts with the same token pages attaches the cached pages (refcount++)
and prefills only the uncached suffix — the shared system-prompt/
few-shot prefix every chat request repeats is computed ONCE. Sharing is
full-page granular, so a shared page is never written again (the
copy-on-write degenerate case: appends always land in a fresh page) and
greedy outputs stay token-identical to the uncached engine. Eviction is
refcount-aware LRU: only pages NO running sequence holds (refcount 0)
are reclaimable, on demand from `PagePool.alloc`, so the cache never
competes with live sequences and the priority-aware preemption contract
is untouched. `FLAGS_prefix_cache=0` (or the bucketed regime) drops the
index entirely — every page is refcount-1 and the allocator is
bitwise the pre-cache free list.

Self-speculative decoding (`FLAGS_speculative`, default on — ISSUE 15;
ragged regime, greedy only): decode is the engine's throughput floor —
one token per sequence per tick — and the ragged grid already treats a
q_len=k decode row as a small prefill chunk, so multi-token
verification rows are pure scheduling. An n-gram PROMPT-LOOKUP drafter
(no draft model: match the last few tokens against the request's
prompt + generated history, propose the continuation — the big win is
code/RAG/summarization traffic where output quotes input, and the
repetition loops greedy decoding falls into) proposes up to
`max_draft_tokens` per decode slot; the scheduler packs (1 real + k
draft) tokens as ONE q_len=k+1 row inside the SAME `max_chunk_tokens`
row budget (prefill chunks are funded first; speculation spends only
the leftover), so every tick still compiles to the ONE fixed padded
shape. Verification compares the model's greedy argmax at each packed
row with the draft fed at the next row and commits the longest
agreeing prefix plus the bonus token from the first disagreement —
exactly the tokens the non-speculative engine would have produced, so
greedy outputs are token-identical by construction. KV already written
for rejected rows is rolled back exactly: `kv_len` truncates via
slot.length and pages past the new length return to the pool
(refcount-aware — draft rows only ever write PAST the prompt, so a
prefix-shared page is never touched). Acceptance telemetry
(serving.spec_drafted_total / spec_accepted_total, acceptance-rate
gauge, per-request counters) steers the draft length adaptively per
slot: shrink on low acceptance, regrow after a hysteresis window of
full-acceptance ticks (the chunk-budget idiom). `FLAGS_speculative=0`
is a bitwise kill switch: no drafting, single-token decode rows, the
pre-speculation compiled signatures and scheduling trace exactly.

Cache-aware admission ordering (ISSUE 15 satellite — the vLLM
cache-aware scheduling trick): `_admit_ragged` prefers the waiter
whose prompt prefix is hot in the prefix cache (a side-effect-free
probe, strictly subordinate to the SLO (priority, EDF) order and
stable within equal keys), so admissions reuse cached pages instead of
evicting them to prefill cold prompts. A cold cache, the bucketed
regime, or `FLAGS_prefix_cache=0` keep pure FIFO.

Weight-only int8 (PTQ) inference: `quantize="int8"` stores every 2-D
projection as int8 + per-output-channel scale (the PTQ absmax rule,
ref quantization post-training observers; inference int8 path
paddle/fluid/inference int8). Dequant happens in-trace, fused by XLA
into the matmul operand read — weights move through HBM at half/quarter
width, which is what decode (memory-bound) is priced by.
"""
from __future__ import annotations

import time
import weakref
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import core as _core
from ..observability import device_events as _devev
from ..observability import metrics as _metrics
from ..observability import reqtrace as _rtrace
from ..utils.fault_injection import fault_point
from .router import RETRY_AFTER_CEILING_S
from .router import chain_key as _chain_key

__all__ = ["GenerationRequest", "ContinuousBatchingEngine", "PagePool",
           "quantize_state_int8", "DeadlineExceeded", "QueueFull"]

_TTFT = _metrics.histogram(
    "serving.ttft_seconds",
    "request arrival to first generated token (time-to-first-token)")
_TPOT = _metrics.histogram(
    "serving.tpot_seconds",
    "mean per-output-token latency after the first token")
_KV_PAGES = _metrics.gauge(
    "serving.kv_pages_in_use",
    "allocated (non-free, non-scratch) pages in the KV page pool")
_PREEMPTS = _metrics.counter(
    "serving.preemptions_total",
    "recompute-style preemptions forced by KV pool pressure")
_PACKED = _metrics.histogram(
    "serving.packed_tokens_per_tick",
    "ragged rows (prefill-chunk + decode) packed into one mixed step",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0))
_DEADLINE_MISSES = _metrics.counter(
    "serving.deadline_misses_total",
    "requests failed fast with DeadlineExceeded (waiting or in-flight)")
_SHEDS = _metrics.counter(
    "serving.sheds_total",
    "waiting requests shed under sustained admission starvation")
_QUARANTINES = _metrics.counter(
    "serving.quarantines_total",
    "requests failed individually by tick-fault / non-finite isolation")
_QUEUE_DEPTH = _metrics.gauge(
    "serving.queue_depth", "requests waiting for admission (per tick)")
_DEGRADED = _metrics.gauge(
    "serving.degraded",
    "1 while adaptive degradation holds the effective prefill chunk "
    "budget below max_chunk_tokens")
_PREFIX_HITS = _metrics.counter(
    "serving.prefix_hits_total",
    "admissions that attached at least one cached prefix page")
_PREFIX_MISSES = _metrics.counter(
    "serving.prefix_misses_total",
    "admissions that found no cached prefix page")
_PREFIX_REUSED = _metrics.counter(
    "serving.prefix_pages_reused_total",
    "KV pages attached from the prefix cache instead of prefilled")
_PREFIX_RATIO = _metrics.gauge(
    "serving.prefix_reuse_ratio",
    "cumulative cacheable-prompt-pages served from the prefix cache "
    "(reused / seen)")
_SPEC_DRAFTED = _metrics.counter(
    "serving.spec_drafted_total",
    "draft tokens proposed by the n-gram prompt-lookup drafter")
_SPEC_ACCEPTED = _metrics.counter(
    "serving.spec_accepted_total",
    "draft tokens confirmed by greedy multi-row verification")
_SPEC_RATE = _metrics.gauge(
    "serving.spec_acceptance_rate",
    "cumulative draft acceptance rate (accepted / drafted) across the "
    "engine lifetime; per-request rates live on GenerationRequest")
_CACHE_AWARE = _metrics.counter(
    "serving.cache_aware_admits_total",
    "admissions reordered ahead of FIFO because their prompt prefix "
    "was hot in the prefix cache")
_ATTR = _metrics.histogram(
    "serving.attribution_seconds",
    "per-request wall decomposed into the request-trace attribution "
    "buckets (label bucket=queue_wait|prefill_compute|decode_compute|"
    "preempted|page_wait|draft_overhead|failover|stream_write); per "
    "request, sum over buckets == wall by construction (ISSUE 18)")


class DeadlineExceeded(RuntimeError):
    """A request's deadline_s passed before it finished; the engine
    failed it fast (terminal status 'deadline_missed') and reclaimed
    its slot/pages instead of spending pool on a dead-on-arrival
    answer."""


class QueueFull(RuntimeError):
    """add_request rejected at submit: the bounded wait queue
    (max_queue_tokens) is full. `retry_after_s` estimates when enough
    queue will have drained (from the engine's observed token
    throughput) — the backpressure hint an HTTP front-end turns into
    a Retry-After header."""

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


# ---------------- weight-only int8 PTQ ------------------------------------

def quantize_state_int8(state: Dict[str, jax.Array], min_size=4096):
    """Per-output-channel absmax int8 quantization of 2-D+ weights
    (ref: PTQ AbsmaxObserver rule; embeddings/norms stay full precision —
    norm scales are 1-D, embedding rows are gathered not matmul'd).
    The scale plumbing is quantization/comm.py's — the same rounding/
    clipping rules the quantized collectives put on the wire (ISSUE 8).

    Returns a pytree where quantized entries are `(q_int8, scale_f32)`
    tuples; `_dequant_state` restores them in-trace."""
    from ..quantization import comm as _qcomm
    out = {}
    for k, v in state.items():
        arr = v
        if (hasattr(arr, "ndim") and arr.ndim == 2
                and jnp.issubdtype(arr.dtype, jnp.floating)
                and arr.size >= min_size
                and "embed" not in k and "norm" not in k):
            out[k] = _qcomm.channelwise_absmax_int8(arr, axis=0)
        else:
            out[k] = arr
    return out


def _dequant_state(state, dtype):
    """In-trace: (int8, scale) -> dtype weight; XLA fuses the convert +
    scale into the consuming dot's operand read."""
    from ..quantization import comm as _qcomm
    return {k: (_qcomm.dequantize_channelwise(v[0], v[1], dtype)
                if isinstance(v, tuple) else v)
            for k, v in state.items()}


# ---------------- requests -------------------------------------------------

@dataclass
class GenerationRequest:
    """One decode job (ref: the serving request in analysis_predictor's
    batched Run loop).

    SLO fields (consumed only when the engine's SLO layer is armed):
    `priority` — higher value wins admission/retention; equal
    priorities keep FIFO order. `deadline_s` — seconds from arrival
    after which the request is failed fast with DeadlineExceeded.
    `status` tracks the lifecycle: queued -> running -> one of
    served / shed / deadline_missed / failed / cancelled; `error`
    carries the terminal error text for the non-served outcomes."""
    prompt: List[int]
    max_new_tokens: int = 32
    eos_token_id: Optional[int] = None
    request_id: Optional[int] = None
    priority: int = 0
    deadline_s: Optional[float] = None
    # filled by the engine
    output: List[int] = field(default_factory=list)
    arrived_s: float = 0.0
    finished_s: Optional[float] = None
    first_token_s: Optional[float] = None
    status: str = "queued"
    error: Optional[str] = None
    # speculative-decoding bookkeeping (ISSUE 15): how many draft
    # tokens this request's slot proposed / had confirmed — the
    # per-request acceptance-rate view behind the engine-wide gauge
    spec_drafted: int = 0
    spec_accepted: int = 0
    # cache-aware admission bookkeeping: how many times a hotter-prefix
    # waiter was admitted ahead of this one — bounded by the engine's
    # cache_jump_limit so heat can never starve a cold request
    admit_bypassed: int = 0
    # request-scope tracing (ISSUE 18): the traceparent-style id the
    # router/gateway minted (or honored from the client), the seconds
    # the router already spent on failed hops before THIS replica saw
    # the request (preloaded into the ledger's `failover` bucket AND
    # the reported wall, keeping sum(buckets)==wall end-to-end), and
    # the engine-attached RequestTrace carrying timeline + ledger
    trace_id: Optional[str] = None
    failover_preload_s: float = 0.0
    trace: Optional[object] = field(default=None, repr=False)

    @property
    def done(self) -> bool:
        return self.finished_s is not None

    @property
    def deadline_at(self) -> Optional[float]:
        """Absolute perf_counter deadline, or None (no deadline)."""
        if self.deadline_s is None:
            return None
        return self.arrived_s + float(self.deadline_s)


class _Slot:
    __slots__ = ("req", "length", "produced", "last_token", "admit_seq",
                 "pending", "prefix_tokens", "cache_upto", "cache_key",
                 "spec_k", "spec_calm")

    def __init__(self):
        self.req: Optional[GenerationRequest] = None
        self.length = 0
        self.produced = 0
        self.last_token = 0
        self.admit_seq = -1
        # chunked-prefill regime: effective-prompt tokens not yet in KV
        self.pending: List[int] = []
        # prefix cache: the full effective prompt at admission, how many
        # of its pages were already offered to the index, and the chain
        # hash key up to that page (set by _admit_ragged when armed)
        self.prefix_tokens: List[int] = []
        self.cache_upto = 0
        self.cache_key = b""
        # speculative decoding: this slot's CURRENT draft-length cap
        # (adaptive: shrinks on low acceptance, regrows after spec_
        # hysteresis consecutive full-acceptance ticks) and the calm
        # counter driving the regrowth
        self.spec_k = 0
        self.spec_calm = 0

    @property
    def free(self):
        return self.req is None


# ---------------- page pool ------------------------------------------------

class PagePool:
    """Host-side free-list allocator over the global KV page pool
    (ref: the reference's block tables —
    phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu
    `block_tables` arg and incubate/nn/functional/block_multihead_attention:
    pages are allocated on demand per sequence and shared across the pool,
    so KV memory is proportional to LIVE tokens, not batch * max_seq).

    Page 0 is reserved as a scratch page: inactive slots and padding
    positions write there; it is never allocated.

    Refcounts (ISSUE 12): every allocated page carries a slot-holder
    count. `alloc` hands pages out at refcount 1, `share` attaches an
    additional holder (a prefix-cache hit), and `free` only returns a
    page to the free list when its LAST holder releases it — unless an
    attached prefix cache still indexes the page, in which case it goes
    idle-cached (reclaimable on demand, counted by `n_free`). With no
    cache attached every page is refcount-1 and alloc/free are bitwise
    the pre-cache free list (same pop order, same append order)."""

    def __init__(self, n_pages: int, page_size: int = 16):
        if n_pages < 2:
            raise ValueError("pool needs >= 2 pages (page 0 is scratch)")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self._free = list(range(self.n_pages - 1, 0, -1))  # pop() -> low ids
        self._refs: Dict[int, int] = {}      # page -> slot-holder count
        self._cache = None                   # attached _PrefixCache

    def attach_cache(self, cache) -> None:
        self._cache = cache

    @property
    def n_free(self) -> int:
        """Immediately-free pages plus idle-cached pages the attached
        prefix cache would evict on demand — the scheduler's funding
        math must see cached-idle capacity as available, or an idle
        cache would starve admission."""
        n = len(self._free)
        if self._cache is not None:
            n += self._cache.evictable_count()
        return n

    def refcount(self, page: int) -> int:
        return self._refs.get(page, 0)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n pages or None (caller keeps the request waiting / preempts).
        Shortfalls first reclaim idle-cached pages (refcount-0 LRU) from
        the attached prefix cache; pages a running sequence holds are
        never touched."""
        fault_point("serving.page_alloc")
        if n > len(self._free) and self._cache is not None:
            self._cache.evict(n - len(self._free))
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        return pages

    def free(self, pages: List[int]) -> None:
        """Release one holder of each page; the page returns to the free
        list only when no holder remains and the prefix cache does not
        index it (then it stays idle-cached until evicted or re-shared)."""
        for p in pages:
            r = self._refs.get(p, 1) - 1
            if r > 0:
                self._refs[p] = r
                continue
            self._refs.pop(p, None)
            if self._cache is not None and self._cache.owns(p):
                continue
            self._free.append(p)

    def share(self, pages: List[int]) -> None:
        """Attach an additional holder to each page (prefix-cache hit);
        an idle-cached page (refcount 0) comes back live here."""
        for p in pages:
            self._refs[p] = self._refs.get(p, 0) + 1

    def release_unindexed(self, page: int) -> None:
        """The cache dropped its claim on `page`; if no slot holds it
        either, it is free again."""
        if self._refs.get(page, 0) == 0:
            self._free.append(page)


# ---------------- prefix cache ---------------------------------------------


class _PrefixEntry:
    __slots__ = ("key", "page", "parent", "children", "last_use")

    def __init__(self, key: bytes, page: int, parent: bytes):
        self.key = key
        self.page = page
        self.parent = parent
        self.children: set = set()
        self.last_use = 0


class _PrefixCache:
    """Content-hash chain index of fully-written prompt pages over a
    PagePool (ISSUE 12; the vLLM automatic-prefix-cache idea expressed
    as pool accounting — the ragged kernel reads arbitrary block tables,
    so a shared page needs no kernel support at all).

    Each entry maps `blake2(parent_key || page_tokens)` to the physical
    page holding those tokens' KV, chaining from the prompt start, so a
    lookup walks the prompt page by page and stops at the first miss —
    the longest cached prefix. Pages are shared at FULL-page granularity
    only: a shared page is never appended to (the next token lands in a
    fresh page), which is what keeps shared-prefix decoding bitwise
    identical to the uncached engine without copy-on-write data moves —
    the refcounts carry the ownership story and a would-be "write" is
    simply a fresh allocation.

    Eviction is refcount-aware LRU, on demand from `PagePool.alloc`:
    only pages with NO slot holder (refcount 0) are candidates, so a
    running sequence's pages are never reclaimed and the engine's
    priority-aware preemption contract is untouched. Evicting an entry
    drops its whole cached subtree (children are unreachable once the
    chain breaks); subtree pages a slot still holds are merely
    unindexed and return to the free list when that slot releases them.
    """

    def __init__(self, pool: PagePool, page_size: int):
        self.pool = pool
        self.page = int(page_size)
        self.entries: Dict[bytes, _PrefixEntry] = {}
        self.by_page: Dict[int, bytes] = {}
        # children of the chain root (parent key b"")
        self._root_children: set = set()
        self._clock = 0
        # bumped only when cached entries are DROPPED — the
        # invalidation key for admission-ordering probe memos. An
        # insert can only make a waiter hotter, so a memoized count
        # stays a valid lower bound; a drop can overstate heat, which
        # is the case that must force a re-probe
        self.epoch = 0
        self.hits = 0
        self.misses = 0
        self.pages_reused = 0
        self.pages_seen = 0          # cacheable prompt pages offered to lookup
        self.evictions = 0
        # heat-oracle memo, keyed (epoch, entry count): inserts change
        # the count, drops bump the epoch — same invalidation story as
        # the engine's probe memo (ISSUE 17)
        self._heat_memo: Tuple[Optional[tuple], Dict[str, int]] = (None, {})
        pool.attach_cache(self)

    def _key(self, parent: bytes, toks: List[int]) -> bytes:
        # single source of truth shared with the fleet router's
        # affinity lookup (router.chain_key) — the cross-process heat
        # oracle only works if both sides hash a page identically
        return _chain_key(parent, toks)

    def owns(self, page: int) -> bool:
        return page in self.by_page

    def evictable_count(self) -> int:
        return sum(1 for p in self.by_page
                   if self.pool.refcount(p) == 0)

    # -- lookup / insert -----------------------------------------------------

    def lookup(self, eff: List[int]) -> Tuple[List[int], bytes]:
        """Longest cached full-page prefix of token stream `eff`:
        increfs and returns (page ids, chain key up to them). At least
        one trailing token always stays uncached so the admitted slot
        still has a query row to produce its next token from."""
        self._clock += 1
        n = (len(eff) - 1) // self.page
        self.pages_seen += n
        key = b""
        pages: List[int] = []
        for j in range(n):
            nxt = self._key(key, eff[j * self.page:(j + 1) * self.page])
            e = self.entries.get(nxt)
            if e is None:
                break
            e.last_use = self._clock
            key = nxt
            pages.append(e.page)
        if pages:
            self.pool.share(pages)
            self.hits += 1
            self.pages_reused += len(pages)
            _PREFIX_HITS.inc()
            _PREFIX_REUSED.inc(len(pages))
        else:
            self.misses += 1
            _PREFIX_MISSES.inc()
        if self.pages_seen:
            _PREFIX_RATIO.set(self.pages_reused / self.pages_seen)
        return pages, key

    def probe(self, eff: List[int]) -> int:
        """Side-effect-free longest-cached-prefix PAGE COUNT for token
        stream `eff`: no incref, no LRU touch, no hit/miss counters —
        the cache-aware admission ordering peek (a probe that perturbed
        eviction order or counters would make scheduling observable
        through telemetry)."""
        n = (len(eff) - 1) // self.page
        key = b""
        pages = 0
        for j in range(n):
            nxt = self._key(key, eff[j * self.page:(j + 1) * self.page])
            if nxt not in self.entries:
                break
            key = nxt
            pages += 1
        return pages

    def insert(self, parent: bytes, toks: List[int], page: int) -> bytes:
        """Offer one fully-written page to the index. First writer wins:
        if the chain key already exists (another slot prefilled the same
        content concurrently) the duplicate physical page stays plainly
        slot-owned and is freed normally. Returns the chain key — the
        caller threads it through successive offers."""
        key = self._key(parent, toks)
        if key in self.entries:
            return key
        e = _PrefixEntry(key, page, parent)
        self._clock += 1
        e.last_use = self._clock
        self.entries[key] = e
        self.by_page[page] = key
        if parent:
            pe = self.entries.get(parent)
            if pe is not None:
                pe.children.add(key)
        else:
            self._root_children.add(key)
        return key

    # -- eviction ------------------------------------------------------------

    def evict(self, need: int) -> int:
        """Reclaim up to `need` idle-cached pages (refcount 0) into the
        pool's free list. Never touches a page a running sequence
        holds. LEAVES go first (deepest chain tail, LRU among leaves):
        evicting from the tail frees exactly one page per step and
        preserves the chain HEAD — the most shareable part of a prefix
        — as long as possible (the vLLM eviction order). Only when no
        idle leaf exists does a ref-0 inner entry go, taking its now
        unreachable cached subtree with it."""
        fault_point("serving.prefix_evict")
        freed = 0
        while freed < need:
            cands = [e for e in self.entries.values()
                     if self.pool.refcount(e.page) == 0]
            if not cands:
                break
            leaves = [e for e in cands
                      if not any(k in self.entries for k in e.children)]
            victim = min(leaves or cands, key=lambda e: e.last_use)
            freed += self._drop_subtree(victim)
        return freed

    def _drop_subtree(self, entry: _PrefixEntry) -> int:
        """Unindex `entry` and every cached descendant (unreachable once
        the chain breaks). Returns how many pages landed back on the
        free list (refcount-0 ones; slot-held pages are only unindexed)."""
        parent = self.entries.get(entry.parent)
        if parent is not None:
            parent.children.discard(entry.key)
        self._root_children.discard(entry.key)
        self.epoch += 1
        freed = 0
        stack = [entry]
        while stack:
            e = stack.pop()
            self.entries.pop(e.key, None)
            self.by_page.pop(e.page, None)
            self.evictions += 1
            if self.pool.refcount(e.page) == 0:
                self.pool.release_unindexed(e.page)
                freed += 1
            stack.extend(self.entries[k] for k in e.children
                         if k in self.entries)
        return freed

    def heat(self, cap: int = 64) -> Dict[str, int]:
        """The per-replica heat oracle the fleet router routes on
        (ISSUE 17, the seam ROADMAP names): chain-HEAD key (hex) ->
        cached pages reachable under that head. Side-effect-free like
        `probe` — no incref, no LRU touch, no counters — and memoized
        on (epoch, entry count), the same invalidation rule as the
        admission-ordering probe memo: an insert only grows a subtree
        (count changes), a drop can shrink one (epoch bumps). Capped
        at the `cap` hottest heads so the /healthz payload the router
        polls stays bounded."""
        key = (self.epoch, len(self.entries))
        memo_key, memo = self._heat_memo
        if memo_key == key:
            return memo
        out: Dict[str, int] = {}
        for head in self._root_children:
            pages = 0
            stack = [head]
            while stack:
                e = self.entries.get(stack.pop())
                if e is None:
                    continue
                pages += 1
                stack.extend(e.children)
            out[head.hex()] = pages
        if len(out) > cap:
            out = dict(sorted(out.items(),
                              key=lambda kv: -kv[1])[:cap])
        self._heat_memo = (key, out)
        return out

    def stats(self) -> dict:
        return {"entries": len(self.entries),
                "hits": self.hits, "misses": self.misses,
                "pages_reused": self.pages_reused,
                "pages_seen": self.pages_seen,
                "evictions": self.evictions,
                "reuse_ratio": round(
                    self.pages_reused / self.pages_seen, 4)
                if self.pages_seen else 0.0}


# ---------------- self-speculative drafting ---------------------------------


def _ngram_propose(ctx: List[int], k: int, max_ngram: int,
                   min_ngram: int) -> List[int]:
    """Prompt-lookup drafting (the self-speculative n-gram rule): match
    the last n tokens of `ctx` (prompt + generated history) against the
    earlier context, longest n first, and propose up to k continuation
    tokens from the MOST RECENT occurrence. No draft model — the bet is
    that output quotes input (code, RAG, summarization) or repeats
    itself (the loop greedy decoding of small models falls into), and
    exact verification makes a wrong bet cost nothing but the tick's
    spare row budget."""
    L = len(ctx)
    if k <= 0 or L < min_ngram + 1:
        return []
    arr = np.asarray(ctx, np.int64)
    for n in range(min(max_ngram, L - 1), min_ngram - 1, -1):
        pat = arr[L - n:]
        # windows over ctx[:-1] so every match has >= 1 continuation
        # token; a match overlapping the suffix is fine (that is how a
        # period-p repetition extends itself)
        win = np.lib.stride_tricks.sliding_window_view(arr[:L - 1], n)
        hits = np.nonzero((win == pat).all(axis=1))[0]
        if hits.size:
            # most recent occurrence wins — but a match butting up
            # against the end of history truncates the proposal, so
            # prefer the newest occurrence with a FULL k-token
            # continuation when one exists (a period-p loop then
            # drafts k tokens every tick instead of p-1)
            full = hits[hits + n + k <= L]
            j = int(full[-1]) if full.size else int(hits[-1])
            return [int(t) for t in arr[j + n:j + n + k]]
    return []


# ---------------- engine ---------------------------------------------------

class ContinuousBatchingEngine:
    """Slot-based continuous batching over the paged-KV decode path.

    model: LlamaForCausalLM (any model exposing config + state_dict with
    the llama cache-forward layout). max_batch = decode slots; max_seq =
    per-slot KV capacity (page-aligned). max_chunk_tokens bounds the
    prefill tokens packed into one ragged tick; ragged=None follows
    FLAGS_ragged_attention (the chunked-prefill kill switch).

    prefix_cache=None follows FLAGS_prefix_cache: in the ragged regime,
    admissions attach cached pages for any previously-prefilled
    full-page prompt prefix and fully-written prompt pages enter the
    content-hash index (see _PrefixCache); =False (or the bucketed
    regime) drops the cache entirely — bitwise the uncached allocator.

    speculative=None follows FLAGS_speculative (ragged + greedy only):
    self-speculative n-gram drafting with multi-token verification
    rows; max_draft_tokens caps the per-slot draft length (None =
    FLAGS_speculative_draft_tokens), spec_min_ngram/spec_max_ngram
    bound the prompt-lookup match, and spec_hysteresis is the
    full-acceptance tick count before a backed-off slot regrows its
    draft length.

    SLO layer (slo=None follows FLAGS_serving_slo; see the module
    docstring): max_queue_tokens bounds the wait queue (None =
    unbounded, shedding disabled); shed_patience = consecutive
    admission-starved ticks before one (lowest-priority, most-slack)
    waiter is shed; min_chunk_tokens is the degradation floor and
    degrade_high_water / degrade_low_water / degrade_hysteresis the
    pool-utilization thresholds + calm-tick count steering the
    effective chunk budget; tick_timeout_s arms a per-tick watchdog
    (flight-recorder dump on a wedged tick; None = off).
    """

    def __init__(self, model, max_batch: int = 4, max_seq: int = 256,
                 prefill_buckets=(32, 64, 128, 256), quantize=None,
                 greedy: bool = True, seed: int = 0,
                 total_pages: Optional[int] = None, page_size: int = 16,
                 max_chunk_tokens: int = 64, ragged: Optional[bool] = None,
                 prefix_cache: Optional[bool] = None,
                 speculative: Optional[bool] = None,
                 max_draft_tokens: Optional[int] = None,
                 spec_min_ngram: int = 1, spec_max_ngram: int = 3,
                 spec_hysteresis: int = 4, cache_jump_limit: int = 8,
                 slo: Optional[bool] = None,
                 max_queue_tokens: Optional[int] = None,
                 shed_patience: int = 8, min_chunk_tokens: int = 8,
                 degrade_high_water: float = 0.85,
                 degrade_low_water: float = 0.5,
                 degrade_hysteresis: int = 16,
                 tick_timeout_s: Optional[float] = None,
                 request_trace: Optional[bool] = None):
        from ..models import llama as L
        self.cfg = model.cfg
        self.B = int(max_batch)
        page = int(page_size)
        self.page = page
        self.S = int(-(-max_seq // page) * page)     # page-aligned
        self.ppmax = self.S // page                  # pages per sequence cap
        # always include the full slot capacity so any prompt <= max_seq
        # has a bucket
        self.buckets = tuple(sorted(
            {b for b in prefill_buckets if b < self.S} | {self.S}))
        self.greedy = greedy
        self._fwd = L._forward_with_cache
        self._decode_paged = L._decode_step_paged
        self._ragged_step = L._ragged_step_paged
        raw = {k: t.data for k, t in model.state_dict().items()}
        self.dtype = raw["model.embed_tokens"].dtype
        self.state = (quantize_state_int8(raw) if quantize == "int8"
                      else raw)
        self._quantized = quantize == "int8"
        cfg = self.cfg
        L_, kvh, d = (cfg.num_hidden_layers, cfg.kv_heads, cfg.head_dim)
        # page pool: +1 for the reserved scratch page. Default is the
        # dense-equivalent capacity; pass total_pages to bound KV memory
        # to live tokens (admission then gates on free pages and decode
        # growth preempts when the pool is dry).
        n_pages = int(total_pages) if total_pages else self.B * self.ppmax + 1
        self.pool = PagePool(n_pages, page)
        self.k_pool = jnp.zeros((L_, kvh, n_pages, page, d), self.dtype)
        self.v_pool = jnp.zeros_like(self.k_pool)
        # host-side block table: page ids per slot (0 = scratch/unused)
        self.page_table = np.zeros((self.B, self.ppmax), np.int32)
        self.slot_pages: List[List[int]] = [[] for _ in range(self.B)]
        self.slots = [_Slot() for _ in range(self.B)]
        self.waiting: List[GenerationRequest] = []
        self.finished: List[GenerationRequest] = []
        self._next_id = 0
        self._admit_seq = 0
        self.preemptions = 0
        self._key = jax.random.key(seed)
        self._compiled_prefill = {}
        self._compiled_decode = None
        self._compiled_write = None
        self._compiled_ragged = None
        # chunked-prefill regime: FLAGS_ragged_attention is the kill
        # switch (0 restores the bucketed-prefill engine exactly)
        self._ragged = (_core.get_bool_flag("FLAGS_ragged_attention", True)
                        if ragged is None else bool(ragged))
        if int(max_chunk_tokens) < 1:
            # fail fast: a zero budget would make _schedule_chunks park
            # every prefill forever and preempt-thrash instead of erroring
            raise ValueError(
                f"max_chunk_tokens must be >= 1, got {max_chunk_tokens}")
        self.max_chunk_tokens = int(max_chunk_tokens)
        # ONE compiled ragged shape: rows pad to a fixed power-of-two
        # bucket >= decode slots + the chunk budget (the kernel's
        # autotune size class, so tuned blocks match what we compile)
        from ..kernels.ragged_paged_attention import _size_class
        self._T_pack = _size_class(self.B + self.max_chunk_tokens)
        self.last_packed_tokens = 0
        self.prefill_tokens_total = 0    # prompt tokens actually prefilled
        # prefix caching (ISSUE 12): ragged regime only — the bucketed
        # prefill computes whole prompts in one batched call, so there
        # is no seam to skip cached pages through (and the =0 kill
        # switch must stay bitwise either way)
        pfx = (_core.get_bool_flag("FLAGS_prefix_cache", True)
               if prefix_cache is None else bool(prefix_cache))
        self._pcache = (_PrefixCache(self.pool, page)
                        if pfx and self._ragged else None)
        # self-speculative decoding (ISSUE 15): ragged + GREEDY only —
        # verification is defined by greedy-argmax agreement, so a
        # sampling engine never speculates. FLAGS_speculative=0 (or
        # max_draft_tokens=0) is the bitwise kill switch: no drafting,
        # the single-token decode rows and last-row-only compiled
        # signatures of the pre-speculation engine exactly. Draft rows
        # ride the max_chunk_tokens budget, so _T_pack (the one fixed
        # padded shape) is untouched and the compile cache never grows
        # with the draft length.
        spec = (_core.get_bool_flag("FLAGS_speculative", True)
                if speculative is None else bool(speculative))
        if max_draft_tokens is None:
            max_draft_tokens = int(_core.get_flag(
                "FLAGS_speculative_draft_tokens", 4) or 0)
        self.max_draft_tokens = max(int(max_draft_tokens), 0)
        self._spec = (spec and self._ragged and self.greedy
                      and self.max_draft_tokens > 0)
        self.spec_min_ngram = max(int(spec_min_ngram), 1)
        self.spec_max_ngram = max(int(spec_max_ngram), self.spec_min_ngram)
        self.spec_hysteresis = max(int(spec_hysteresis), 1)
        self.spec_drafted = 0
        self.spec_accepted = 0
        # cache-aware admission: how many FIFO jumps one waiter may
        # suffer before it is admitted regardless of heat (liveness —
        # equal-priority no-deadline waiters must not starve under a
        # sustained hot-prefix arrival stream), plus a probe memo so
        # the per-admission peek does not re-hash unchanged prompts
        self.cache_jump_limit = max(int(cache_jump_limit), 1)
        self.cache_aware_admits = 0
        self._probe_memo: Dict[int, Tuple[int, int, int]] = {}
        # donation lets XLA scatter into the pool in place; CPU jit would
        # just warn that the buffers were not donated
        self._donate = jax.default_backend() == "tpu"
        self.ticks = 0
        # -- SLO resilience layer (ISSUE 10). Disarmed, every branch it
        # guards is skipped and the engine is the exact pre-SLO FIFO
        # scheduler (kill-switch parity).
        self._slo = (_core.get_bool_flag("FLAGS_serving_slo", True)
                     if slo is None else bool(slo))
        self.max_queue_tokens = (None if max_queue_tokens is None
                                 else int(max_queue_tokens))
        self.shed_patience = max(int(shed_patience), 1)
        self.min_chunk_tokens = max(
            1, min(int(min_chunk_tokens), self.max_chunk_tokens))
        self.degrade_high_water = float(degrade_high_water)
        self.degrade_low_water = float(degrade_low_water)
        self.degrade_hysteresis = max(int(degrade_hysteresis), 1)
        self._eff_chunk = self.max_chunk_tokens
        self._calm_ticks = 0
        self._pressure_ticks = 0
        self._admitted_this_tick = False
        self._tick_failures = 0
        self._last_tick_s: Optional[float] = None
        self._tokens_per_s = 0.0          # EMA over ticks (retry hints)
        self.deadline_misses = 0
        self.sheds = 0
        self.quarantines = 0
        self._wd = None
        if self._slo and tick_timeout_s is not None:
            # PRIVATE watchdog (never the watch() singleton — PR 2
            # review rule): a wedged tick warns + flight-dumps through
            # the PR 3 recorder, naming 'serving.tick' as the stuck
            # section, while the engine itself stays untouched
            from ..distributed.watchdog import CommWatchdog
            self._wd = CommWatchdog(timeout=float(tick_timeout_s),
                                    on_timeout="warn")
        if self._slo:
            _register_health_engine(self)
        # -- request-scope tracing (ISSUE 18). Resolved ONCE here (the
        # established kill-switch idiom); every instrumented site guards
        # on the bool. =0 restores the pre-trace tick loop bitwise:
        # tracing is pure observation — no scheduling decision reads it.
        self._rtrace = (_core.get_bool_flag("FLAGS_request_trace", True)
                        if request_trace is None else bool(request_trace))
        # request_id -> (req, bucket) for slots that DID something this
        # tick; settled into each request's ledger at the end of step()
        self._tick_roles: Dict[int, tuple] = {}

    # -- memory accounting ---------------------------------------------------

    @property
    def kv_cache_bytes(self) -> int:
        return int(self.k_pool.nbytes + self.v_pool.nbytes)

    @property
    def dense_equivalent_bytes(self) -> int:
        """What the pre-pool engine allocated: [L, B, S_max, kvh, d] x2."""
        cfg = self.cfg
        itemsize = jnp.dtype(self.dtype).itemsize
        return int(2 * cfg.num_hidden_layers * self.B * self.S
                   * cfg.kv_heads * cfg.head_dim * itemsize)

    # -- compiled kernels ---------------------------------------------------

    def _state_arg(self):
        return self.state

    def _prefill_fn(self, T, k=1):
        """(state, ids[k,T], n_valid[k]) -> (last_logits[k,V], k_new,
        v_new) — BATCHED prefill for k same-bucket admissions in one
        compiled call (VERDICT r3 weak #4: per-request prefill cost).
        Returns the prompts' KV planes [L, k, T, kvh, d]; the caller
        scatters JUST those tokens' pages into the pool. k is padded to
        a power of two by the admission path so the compile cache stays
        bounded at O(buckets x log2(max_batch))."""
        key = (T, k)
        if key in self._compiled_prefill:
            return self._compiled_prefill[key]
        cfg, dt = self.cfg, self.dtype
        fwd, dq, quant = self._fwd, _dequant_state, self._quantized

        @jax.jit
        def prefill(state, ids, n_valid):
            st = dq(state, dt) if quant else state
            ck = jnp.zeros((cfg.num_hidden_layers, k, T,
                            cfg.kv_heads, cfg.head_dim), dt)
            cv = jnp.zeros_like(ck)
            logits, ck, cv = fwd(st, cfg, ids, ck, cv,
                                 jnp.zeros((k,), jnp.int32))
            last = jnp.take_along_axis(
                logits, (n_valid - 1)[:, None, None], axis=1)[:, 0]
            return last, ck, cv

        self._compiled_prefill[key] = prefill
        return prefill

    def _write_fn(self):
        """(k_pool, v_pool, k_new[L,T,kvh,d], v_new, page_ids[T], offs[T])
        -> updated pools. Padding positions carry page id 0 (scratch)."""
        if self._compiled_write is not None:
            return self._compiled_write

        def write(k_pool, v_pool, k_new, v_new, page_ids, offs):
            kt = jnp.moveaxis(k_new, 2, 1)           # [L, kvh, T, d]
            vt = jnp.moveaxis(v_new, 2, 1)
            k_pool = k_pool.at[:, :, page_ids, offs].set(
                kt.astype(k_pool.dtype))
            v_pool = v_pool.at[:, :, page_ids, offs].set(
                vt.astype(v_pool.dtype))
            return k_pool, v_pool

        self._compiled_write = jax.jit(
            write, donate_argnums=(0, 1) if self._donate else ())
        return self._compiled_write

    def _decode_fn(self):
        """(state, toks[B], k_pool, v_pool, page_table, lens[B],
        active[B], key) -> (next[B], k_pool, v_pool) — one token for
        every active slot, straight over the page pool."""
        if self._compiled_decode is not None:
            return self._compiled_decode
        cfg, dt = self.cfg, self.dtype
        dq, quant = _dequant_state, self._quantized
        step_paged = self._decode_paged
        greedy = self.greedy
        slo = self._slo

        def decode(state, toks, k_pool, v_pool, page_table, lens, active,
                   key):
            st = dq(state, dt) if quant else state
            lg, k_pool, v_pool = step_paged(
                st, cfg, toks, k_pool, v_pool, page_table, lens, active)
            if greedy:
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            else:
                nxt = jax.random.categorical(key, lg).astype(jnp.int32)
            # inactive slots keep their token and cache position
            nxt = jnp.where(active, nxt, toks)
            if slo:
                # per-row poison detection: a slot whose logits go
                # non-finite is quarantined EXACTLY (idle rows exempt)
                ok = jnp.isfinite(lg).all(axis=-1) | ~active
                return nxt, ok, k_pool, v_pool
            return nxt, k_pool, v_pool

        self._compiled_decode = jax.jit(
            decode, donate_argnums=(2, 3) if self._donate else ())
        return self._compiled_decode

    def _ragged_fn(self):
        """(state, toks[T], k_pool, v_pool, page_ids[T], offs[T], pos[T],
        page_table, q_start[B], q_len[B], kv_len[B], produce[B], prev[B],
        key) -> (next[B], k_pool, v_pool) — ONE mixed prefill+decode step:
        every packed row's KV scatters into its page and one ragged paged
        attention covers both phases; next[b] is sampled from sequence
        b's last packed row (kept at prev[b] where produce[b] is False:
        mid-prompt chunks and idle slots). Speculation armed, the
        compiled variant returns next as PER-ROW argmax [T] instead
        (the `ok` poison flag stays per-sequence [B])."""
        if self._compiled_ragged is not None:
            return self._compiled_ragged
        cfg, dt = self.cfg, self.dtype
        dq, quant = _dequant_state, self._quantized
        step_ragged = self._ragged_step
        greedy = self.greedy
        slo = self._slo
        if self._spec:
            K = self.max_draft_tokens + 1

            def rstep_spec(state, toks, k_pool, v_pool, page_ids, offs,
                           pos, page_table, q_start, q_len, kv_len,
                           produce, verify, key):
                """Speculation armed (greedy): argmax at each sequence's
                last min(K, q_len) rows ([B, K], right-aligned — every
                row a draft could ride, and only those: prefill-chunk
                interiors never pay lm-head). Non-finite detection
                covers exactly the rows the host CONSUMES — all rows of
                a decode/verify entry (`verify`), only the last row of
                a producing prefill chunk, nothing for mid-prompt/idle
                slots — so the exemption semantics match the
                non-speculative step's `ok | ~produce` contract and the
                kill switch cannot change which requests fail."""
                st = dq(state, dt) if quant else state
                lg, k_pool, v_pool = step_ragged(
                    st, cfg, toks, pos, k_pool, v_pool, page_ids, offs,
                    page_table, q_start, q_len, kv_len, verify_rows=K)
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)  # [B, K]
                if slo:
                    j = jnp.arange(K)[None, :]
                    in_window = ((j >= K - jnp.minimum(q_len, K)[:, None])
                                 & (q_len > 0)[:, None])
                    consumed = jnp.where(
                        verify[:, None], in_window,
                        (produce & ~verify)[:, None] & (j == K - 1))
                    poison = (~jnp.isfinite(lg).all(axis=-1)) & consumed
                    return nxt, ~poison.any(axis=-1), k_pool, v_pool
                return nxt, k_pool, v_pool

            self._compiled_ragged = jax.jit(
                rstep_spec,
                donate_argnums=(2, 3) if self._donate else ())
            return self._compiled_ragged

        def rstep(state, toks, k_pool, v_pool, page_ids, offs, pos,
                  page_table, q_start, q_len, kv_len, produce, prev, key):
            st = dq(state, dt) if quant else state
            lg, k_pool, v_pool = step_ragged(
                st, cfg, toks, pos, k_pool, v_pool, page_ids, offs,
                page_table, q_start, q_len, kv_len)
            if greedy:
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            else:
                nxt = jax.random.categorical(key, lg).astype(jnp.int32)
            nxt = jnp.where(produce, nxt, prev)
            if slo:
                # per-row poison detection: non-finite logits quarantine
                # exactly the producing slot (mid-prompt/idle rows exempt)
                ok = jnp.isfinite(lg).all(axis=-1) | ~produce
                return nxt, ok, k_pool, v_pool
            return nxt, k_pool, v_pool

        self._compiled_ragged = jax.jit(
            rstep, donate_argnums=(2, 3) if self._donate else ())
        return self._compiled_ragged

    # -- scheduler ----------------------------------------------------------

    def add_request(self, req: GenerationRequest):
        # reject impossible prompts AT SUBMIT time: raising later from
        # inside step() would wedge the queue head forever and strand
        # every in-flight request (code-review r4)
        need = -(-len(req.prompt) // self.page)
        if need > self.pool.n_pages - 1:
            raise ValueError(
                f"prompt needs {need} pages but the pool only has "
                f"{self.pool.n_pages - 1} allocatable pages")
        if len(req.prompt) > self.S:
            raise ValueError(
                f"prompt length {len(req.prompt)} exceeds max_seq {self.S}")
        if self._slo:
            fault_point("serving.admit")
            if self.max_queue_tokens is not None:
                # admission control: reject at SUBMIT while the queue is
                # full — the caller gets backpressure + a retry hint
                # instead of the engine accepting work it cannot serve
                queued = self._queued_tokens()
                if queued + len(req.prompt) > self.max_queue_tokens:
                    retry = self._retry_after_hint(
                        queued + len(req.prompt) - self.max_queue_tokens)
                    raise QueueFull(
                        f"wait queue full ({queued} queued tokens, "
                        f"bound {self.max_queue_tokens}); retry in "
                        f"~{retry:.2f}s", retry_after_s=retry)
        if req.request_id is None:
            req.request_id = self._next_id
            self._next_id += 1
        req.arrived_s = time.perf_counter()
        req.status = "queued"
        if self._rtrace:
            tr = _rtrace.new_trace(req.trace_id, now=req.arrived_s)
            req.trace = tr
            req.trace_id = tr.trace_id
            if req.failover_preload_s > 0:
                # router-measured failed-hop seconds carried in on the
                # request: credited to the failover bucket AND the wall
                tr.preload("failover", req.failover_preload_s)
            tr.event("arrival", prompt_tokens=len(req.prompt),
                     priority=req.priority)
        self.waiting.append(req)
        return req.request_id

    def _queued_tokens(self) -> int:
        return sum(len(r.prompt) + len(r.output) for r in self.waiting)

    def _retry_after_hint(self, overflow_tokens: int) -> float:
        """Seconds until ~overflow_tokens of queue should have drained,
        from the EMA token throughput. Bounded on BOTH ends (ISSUE 17):
        a cold engine (no tick measured yet) or a degenerate near-zero
        EMA — idle ticks decay it arbitrarily low — must answer a
        finite default instead of telling a client to come back in a
        year; the ceiling matches the router/gateway Retry-After clamp."""
        if self.ticks > 0 and self._tokens_per_s > 1e-6:
            return min(max(overflow_tokens / self._tokens_per_s, 0.01),
                       RETRY_AFTER_CEILING_S)
        return 1.0

    def _bucket(self, T):
        for b in self.buckets:
            if T <= b:
                return b
        raise ValueError(f"prompt length {T} exceeds max_seq {self.S}")

    def _free_slot_pages(self, i):
        if self.slot_pages[i]:
            self.pool.free(self.slot_pages[i])
            self.slot_pages[i] = []
        self.page_table[i, :] = 0

    def _preempt(self, i):
        """Recompute-preemption (the vLLM/block-table eviction pattern):
        release slot i's pages and push its request back to the FRONT of
        the wait queue; re-admission prefills prompt+output so decoding
        resumes exactly where it stopped."""
        slot = self.slots[i]
        req = slot.req
        slot.req = None
        slot.pending = []
        self._free_slot_pages(i)
        req.status = "queued"
        if self._rtrace and req.trace is not None:
            tr = req.trace
            self._tick_roles.pop(req.request_id, None)
            # the span up to this instant was active work (this tick's
            # role if one was assigned, else the last charged bucket);
            # from here to re-admission it waits as `preempted`
            ent = self._tick_roles.pop(req.request_id, None)
            tr.charge(ent[1] if ent is not None else tr.pending_bucket)
            tr.pending_bucket = "preempted"
            tr.event("preempted")
        self.waiting.insert(0, req)
        self.preemptions += 1
        _PREEMPTS.inc()

    def _oversized(self, eff_len: int) -> bool:
        """A token stream that can NEVER fit: more pages than the pool
        can allocate, or longer than the per-slot KV capacity."""
        return (-(-eff_len // self.page) > self.pool.n_pages - 1
                or eff_len > self.S)

    def _trace_settle(self, req, event: str, **fields):
        """Terminal trace bookkeeping: charge the residual span (last
        mark -> finished_s) to the in-flight bucket, write the terminal
        record through the sink, and roll the ledger into the labeled
        attribution histogram with this trace as the exemplar. The
        charge chain guarantees sum(buckets) == wall by construction."""
        if not self._rtrace or req.trace is None:
            return
        tr = req.trace
        if tr.status is not None:
            return                       # already terminal (idempotent)
        now = (req.finished_s if req.finished_s is not None
               else time.perf_counter())
        ent = self._tick_roles.pop(req.request_id, None)
        bucket = ent[1] if ent is not None else tr.pending_bucket
        tr.charge(bucket, now)
        if req.error:
            fields.setdefault("error", req.error)
        tr.finish(req.status, event, now=now, **fields)
        for name, secs in tr.buckets.items():
            _ATTR.observe(secs, exemplar=tr.trace_id, bucket=name)

    def _trace_charge_tick(self):
        """End-of-tick ledger settlement: every request that played a
        role this tick gets the span since its last mark charged to
        that role (terminal requests already settled at finish and are
        skipped by the status guard in charge order)."""
        if not self._tick_roles:
            return
        now = time.perf_counter()
        for req, bucket in self._tick_roles.values():
            tr = req.trace
            if tr is None or tr.status is not None:
                continue
            tr.charge(bucket, now)
        self._tick_roles.clear()

    def _fail_request(self, req):
        """Defensive terminal path shared by both admission regimes:
        add_request gates prompts and _maybe_finish caps growth, so an
        oversized resume stream is unreachable — but if it ever occurs,
        FINISH the request (empty/partial output) instead of raising
        out of step() and wedging the queue head."""
        req.status = "failed"
        req.error = "oversized resume stream"
        req.finished_s = time.perf_counter()
        self._trace_settle(req, "failed")
        self.finished.append(req)

    def _note_first_token(self, req):
        """TTFT bookkeeping: the request's FIRST output token just landed
        (admission in the bucketed regime, prompt-complete chunk in the
        ragged one). Resumed requests keep their original stamp."""
        if len(req.output) == 1 and req.first_token_s is None:
            req.first_token_s = time.perf_counter()
            ttft = req.first_token_s - req.arrived_s
            # exemplar=None is a no-op inside observe(), so the metric
            # cells stay bitwise identical with tracing disarmed
            ex = (req.trace_id
                  if self._rtrace and req.trace is not None else None)
            if self._slo:
                _TTFT.observe(ttft, exemplar=ex,
                              priority=str(req.priority))
            else:
                _TTFT.observe(ttft, exemplar=ex)
            if ex is not None:
                req.trace.event("first_token", ttft_s=ttft)

    def _admit(self):
        """Move waiting requests into free slots, allocating ONLY the
        pages the prompts need; requests stay queued while the pool has
        no room (admission control by live tokens, not slot count).
        Same-bucket admissions in one tick share ONE batched prefill
        call and ONE pool scatter — admission cost amortizes instead of
        paying a compiled call + scatter per request. Rounds repeat
        while admissions made progress, so pages freed by a request
        that FINISHES at admission still serve later waiters in the
        same tick (the pre-batching behavior)."""
        while self._admit_round():
            pass

    def _admit_round(self) -> bool:
        free_slots = [i for i, s in enumerate(self.slots) if s.free]
        picked = []          # (slot_idx, req, eff, T, need, pages)
        while self.waiting and free_slots:
            req = self.waiting[0]
            # re-admission after preemption resumes from prompt + output
            eff = list(req.prompt) + list(req.output)
            T = len(eff)
            need = -(-T // self.page)
            if self._oversized(T):
                self.waiting.pop(0)
                self._fail_request(req)
                continue
            pages = self.pool.alloc(need)
            if pages is None:
                break                    # pool full: stay waiting
            self.waiting.pop(0)
            picked.append((free_slots.pop(0), req, eff, T, need, pages))
        if not picked:
            return False
        by_bucket: Dict[int, list] = {}
        for item in picked:
            by_bucket.setdefault(self._bucket(item[3]), []).append(item)
        for bucket, group in by_bucket.items():
            self._admit_group(bucket, group)
        return True

    def _admit_group(self, bucket, group):
        """One batched prefill + one pool scatter for a same-bucket
        admission group; k pads up to a power of two (padding rows write
        the scratch page) so compile keys stay bounded."""
        n = len(group)
        k = 1
        while k < n:
            k *= 2
        ids = np.zeros((k, bucket), np.int32)
        n_valid = np.ones((k,), np.int32)
        for j, (_, _, eff, T, _, _) in enumerate(group):
            ids[j, :T] = eff
            n_valid[j] = T
        if self._rtrace:
            # close the waiting span NOW, before the prefill dispatch,
            # so the compute lands in prefill_compute (settled at end
            # of step by _trace_charge_tick, or at finish)
            for _, req, _, T, need, _ in group:
                tr = req.trace
                if tr is None:
                    continue
                wait = tr.pending_bucket
                tr.charge(wait)
                tr.event("resumed" if wait == "preempted" else "admitted",
                         tokens=T, pages=need)
                tr.event("prefill_chunk", tokens=T, pages=need)
                self._tick_roles[req.request_id] = (req, "prefill_compute")
        # per-execution device telemetry: stable executable tag stamped
        # at trace time (xla.dispatch_seconds / compile attribution)
        with _devev.execution("serving.prefill"):
            last, k_new, v_new = self._prefill_fn(bucket, k)(
                self._state_arg(), jnp.asarray(ids), jnp.asarray(n_valid))
        # ONE flat scatter for the whole group: [L, k, T, kvh, d] ->
        # [L, k*T, kvh, d]; padding rows and beyond-prompt positions
        # land on the scratch page
        pos = np.arange(bucket)
        page_ids = np.zeros((k, bucket), np.int32)
        offs = np.broadcast_to(pos % self.page, (k, bucket)).astype(
            np.int32)
        for j, (_, _, _, T, need, pages) in enumerate(group):
            page_ids[j] = np.where(
                pos < T,
                np.asarray(pages, np.int32)[
                    np.minimum(pos // self.page, need - 1)],
                0)
        L_ = k_new.shape[0]
        k_flat = k_new.reshape(L_, k * bucket, *k_new.shape[3:])
        v_flat = v_new.reshape(L_, k * bucket, *v_new.shape[3:])
        self.k_pool, self.v_pool = self._write_fn()(
            self.k_pool, self.v_pool, k_flat, v_flat,
            jnp.asarray(page_ids.reshape(-1)),
            jnp.asarray(offs.reshape(-1)))
        last_np = None
        if self.greedy:
            last_np = np.asarray(last)
        else:
            # sampling engines must SAMPLE the admission token too
            # (first token of every request + preemption resumes)
            self._key, sub = jax.random.split(self._key)
            sampled = np.asarray(jax.random.categorical(sub, last))
        for j, (i, req, eff, T, need, pages) in enumerate(group):
            slot = self.slots[i]
            self.prefill_tokens_total += T
            self.slot_pages[i] = pages
            self.page_table[i, :] = 0
            self.page_table[i, :need] = pages
            tok = (int(np.argmax(last_np[j])) if self.greedy
                   else int(sampled[j]))
            slot.req = req
            req.status = "running"
            self._admitted_this_tick = True
            slot.length = T
            slot.produced = len(req.output) + 1
            slot.last_token = tok
            slot.admit_seq = self._admit_seq
            self._admit_seq += 1
            req.output.append(tok)
            self._note_first_token(req)
            self._maybe_finish(i)

    def _maybe_finish(self, i):
        slot = self.slots[i]
        req = slot.req
        if req is None:
            return
        eos_hit = (req.eos_token_id is not None
                   and req.output and req.output[-1] == req.eos_token_id)
        # capacity cap includes the POOL: one sequence can never hold
        # more than every allocatable page, and preempt/re-admit must
        # not grow `need` past that (it would raise inside step() and
        # lose all in-flight requests)
        cap = min(self.S, (self.pool.n_pages - 1) * self.page)
        full = slot.length + 1 > cap - 1
        if slot.produced >= req.max_new_tokens or eos_hit or full:
            req.finished_s = time.perf_counter()
            req.status = "served"
            if req.first_token_s is not None and len(req.output) > 1:
                tpot = ((req.finished_s - req.first_token_s)
                        / (len(req.output) - 1))
                ex = (req.trace_id
                      if self._rtrace and req.trace is not None else None)
                if self._slo:
                    _TPOT.observe(tpot, exemplar=ex,
                                  priority=str(req.priority))
                else:
                    _TPOT.observe(tpot, exemplar=ex)
            self._trace_settle(req, "finished", n_tokens=len(req.output))
            self.finished.append(req)
            slot.req = None
            slot.pending = []
            self._free_slot_pages(i)     # pages back to the pool

    def _grow(self):
        """Before a decode tick: every active DECODE-phase slot whose
        next token crosses a page boundary gets a fresh page; when the
        pool is dry, preempt the latest-admitted OTHER active slot and
        retry (the victim resumes later via recompute). Prefill-phase
        slots (ragged regime) fund their pages chunk by chunk in
        _schedule_chunks instead."""
        for i, slot in enumerate(self.slots):
            if slot.free or slot.pending:
                continue
            while slot.req is not None:
                have = len(self.slot_pages[i]) * self.page
                if slot.length < have:
                    break                # room for this token
                pg = self.pool.alloc(1)
                if pg is not None:
                    n = len(self.slot_pages[i])
                    self.slot_pages[i].append(pg[0])
                    self.page_table[i, n] = pg[0]
                    break
                # only page-HOLDING victims free anything; a freshly
                # admitted zero-page prefill slot would be a pointless
                # eviction (pages unchanged, preemption counted)
                victims = [j for j, s in enumerate(self.slots)
                           if j != i and not s.free and self.slot_pages[j]]
                if self._slo:
                    # never evict a higher-priority page-holder on
                    # behalf of a lower-priority grower; among eligible
                    # victims take the lowest priority, latest admission
                    mine = slot.req.priority
                    victims = [j for j in victims
                               if self.slots[j].req.priority <= mine]
                    if victims:
                        self._preempt(max(
                            victims,
                            key=lambda j: (-self.slots[j].req.priority,
                                           self.slots[j].admit_seq)))
                    else:
                        # everything else outranks this slot: it yields
                        self._preempt(i)
                elif victims:
                    self._preempt(max(
                        victims, key=lambda j: self.slots[j].admit_seq))
                else:
                    self._preempt(i)     # nothing else holds pages

    # -- chunked-prefill (ragged) scheduler ---------------------------------

    def _pick_waiter(self) -> int:
        """Index into self.waiting of the next admission. FIFO (queue
        order — the SLO sort already ran) unless the prefix cache is
        WARM: then prefer the waiter with the most cached prefix pages
        (the vLLM cache-aware scheduling trick — its admission attaches
        hot pages instead of evicting them to prefill a cold prompt).
        Strictly subordinate to the SLO keys (priority, then EDF
        slack) and stable within equal keys, so a cold cache, the
        bucketed regime, or FLAGS_prefix_cache=0 are exactly FIFO.

        Liveness: a waiter heat has jumped `cache_jump_limit` times is
        admitted next regardless (a sustained hot-prefix arrival
        stream must not starve a cold equal-priority request that
        carries no deadline for EDF to escalate). Probes are memoized
        per (cache drop-epoch, context length) — inserts only make a
        memoized count understate, so the peek re-hashes a prompt only
        after an eviction dropped entries or the request's own context
        changed (resume)."""
        if (self._pcache is None or len(self.waiting) < 2
                or not self._pcache.entries):
            return 0
        if self.waiting[0].admit_bypassed >= self.cache_jump_limit:
            return 0                     # aged out: head goes next
        epoch = self._pcache.epoch
        memo = self._probe_memo
        fresh: Dict[int, Tuple[int, int, int]] = {}
        best, best_key, best_hot = 0, None, 0
        for j, r in enumerate(self.waiting):
            ctx_len = len(r.prompt) + len(r.output)
            hit = memo.get(r.request_id)
            if hit is not None and hit[0] == epoch and hit[1] == ctx_len:
                hot = hit[2]
            else:
                hot = self._pcache.probe(list(r.prompt) + list(r.output))
            fresh[r.request_id] = (epoch, ctx_len, hot)
            if self._slo:
                dl = r.deadline_at
                key = (-r.priority,
                       dl if dl is not None else float("inf"), -hot, j)
            else:
                key = (-hot, j)
            if best_key is None or key < best_key:
                best, best_key, best_hot = j, key, hot
        self._probe_memo = fresh         # drop terminal/admitted entries
        if best != 0 and best_hot > 0:
            for r in self.waiting[:best]:
                r.admit_bypassed += 1
            self.cache_aware_admits += 1
            _CACHE_AWARE.inc()
        return best

    def _admit_ragged(self):
        """Token-granular admission: a waiting request takes a free slot
        as soon as ONE exists and the pool has any free page — its prompt
        is funded page by page as chunks are scheduled, not reserved
        up front (the chunked-prefill admission rule). Among waiters the
        pick is cache-aware (see _pick_waiter)."""
        free_slots = [i for i, s in enumerate(self.slots) if s.free]
        while self.waiting and free_slots and self.pool.n_free > 0:
            idx = self._pick_waiter()
            req = self.waiting[idx]
            # re-admission after preemption resumes from prompt + output
            eff = list(req.prompt) + list(req.output)
            if self._oversized(len(eff)):
                self.waiting.pop(idx)
                self._fail_request(req)
                continue
            self.waiting.pop(idx)
            i = free_slots.pop(0)
            slot = self.slots[i]
            # cache-aware admission: attach the longest cached full-page
            # prefix (refcount++) and prefill only the uncached suffix
            cached: List[int] = []
            ckey = b""
            if self._pcache is not None:
                cached, ckey = self._pcache.lookup(eff)
            slot.req = req
            req.status = "running"
            self._admitted_this_tick = True
            slot.length = len(cached) * self.page
            slot.produced = len(req.output)
            slot.last_token = 0
            slot.pending = eff[slot.length:]
            slot.prefix_tokens = eff
            slot.cache_upto = len(cached)
            slot.cache_key = ckey
            slot.spec_k = self.max_draft_tokens
            slot.spec_calm = 0
            slot.admit_seq = self._admit_seq
            self._admit_seq += 1
            self.slot_pages[i] = list(cached)
            self.page_table[i, :] = 0
            if cached:
                self.page_table[i, :len(cached)] = cached
            if self._rtrace and req.trace is not None:
                tr = req.trace
                wait = tr.pending_bucket
                tr.charge(wait)
                tr.event("resumed" if wait == "preempted" else "admitted",
                         cached_pages=len(cached))
                if cached:
                    tr.event("prefix_reuse", pages=len(cached))

    def _schedule_chunks(self) -> List[Tuple[int, List[int], bool]]:
        """Build this tick's ragged batch: one decode row per active
        decode-phase slot plus KV-budgeted prefill chunks (admission
        order, `max_chunk_tokens` total). Pages are funded at token
        granularity — a chunk shrinks to what the pool can hold. When
        every active slot is prefill-parked on a dry pool, the latest
        admission is preempted (recompute) so the head makes progress.
        Returns [(slot_idx, row_tokens, is_prefill)]."""
        while True:
            entries: List[Tuple[int, List[int], bool]] = []
            # adaptive degradation (SLO): the EFFECTIVE budget may sit
            # below max_chunk_tokens under pool pressure — same compiled
            # shape (_T_pack is sized from the max), just lighter packing
            budget = self._eff_chunk if self._slo else self.max_chunk_tokens
            for i, slot in enumerate(self.slots):
                if not slot.free and not slot.pending:
                    entries.append((i, [slot.last_token], False))
            order = sorted((i for i, s in enumerate(self.slots)
                            if not s.free and s.pending),
                           key=lambda i: self.slots[i].admit_seq)
            for i in order:
                if budget <= 0:
                    break
                slot = self.slots[i]
                chunk = min(len(slot.pending), budget,
                            self.S - slot.length)
                have = len(self.slot_pages[i]) * self.page
                fundable = (have + self.pool.n_free * self.page
                            - slot.length)
                chunk = min(chunk, fundable)
                if chunk <= 0:
                    continue             # parked this tick (pool dry)
                need = (-(-(slot.length + chunk) // self.page)
                        - len(self.slot_pages[i]))
                if need > 0:
                    pages = self.pool.alloc(need)  # fundable => succeeds
                    n0 = len(self.slot_pages[i])
                    self.slot_pages[i].extend(pages)
                    self.page_table[i, n0:n0 + need] = pages
                entries.append((i, list(slot.pending[:chunk]), True))
                self.prefill_tokens_total += chunk
                budget -= chunk
            if self._spec and budget > 0:
                # leftover row budget funds speculative draft tokens —
                # prefill (real work) always outranks speculation, and
                # the packed total still fits the one fixed _T_pack
                self._fund_drafts(entries, budget)
            if entries:
                return entries
            # prefer page-HOLDING victims (evicting a zero-page slot
            # frees nothing); fall back to any active slot so the loop
            # always shrinks the active set and terminates
            active = [i for i, s in enumerate(self.slots) if not s.free]
            if not active:
                return entries
            victims = [i for i in active if self.slot_pages[i]] or active
            if self._slo:
                # lowest priority yields first so the highest-priority
                # parked prefill streams through; the active set still
                # shrinks by one each round (termination unchanged)
                self._preempt(max(
                    victims, key=lambda j: (-self.slots[j].req.priority,
                                            self.slots[j].admit_seq)))
            else:
                self._preempt(max(victims,
                                  key=lambda j: self.slots[j].admit_seq))

    # -- self-speculative decoding (ISSUE 15) --------------------------------

    def _draft_for_slot(self, i: int, budget: int) -> List[int]:
        """Up to slot.spec_k draft tokens for decode-phase slot i,
        clamped by the tick's spare row budget, the request's remaining
        token allowance (k+1 tokens can land per verified row), and the
        slot's KV capacity (rows write positions length..length+k)."""
        slot = self.slots[i]
        req = slot.req
        k = min(slot.spec_k, budget,
                req.max_new_tokens - slot.produced - 1,
                self.S - 1 - slot.length)
        if k <= 0:
            return []
        fault_point("serving.draft")
        return _ngram_propose(list(req.prompt) + list(req.output), k,
                              self.spec_max_ngram, self.spec_min_ngram)

    def _fund_drafts(self, entries, budget: int) -> None:
        """Extend decode rows with draft tokens, funding their KV pages
        at token granularity. Speculation is strictly best-effort: it
        never takes the pool's LAST free page and never preempts, so
        real work (decode growth, prefill chunks, admission) is never
        starved by a bet that verification may throw away."""
        page = self.page
        for idx, (i, rows, is_prefill) in enumerate(entries):
            if budget <= 0:
                break
            if is_prefill:
                continue
            drafts = self._draft_for_slot(i, budget)
            if not drafts:
                continue
            slot = self.slots[i]
            have = len(self.slot_pages[i]) * page
            spare = max(self.pool.n_free - 1, 0)
            # page funding, the per-slot KV ceiling (rows write
            # positions length..length+k, which must stay < max_seq),
            # AND the compiled verify-row window (the [B, K] argmax
            # covers exactly max_draft_tokens+1 rows) — enforced here
            # even if a drafter override ignores _draft_for_slot's own
            # clamps
            cap_tokens = min(have + spare * page - slot.length - 1,
                             self.S - 1 - slot.length,
                             self.max_draft_tokens)
            drafts = drafts[:max(cap_tokens, 0)]
            if not drafts:
                continue
            need = (-(-(slot.length + 1 + len(drafts)) // page)
                    - len(self.slot_pages[i]))
            if need > 0:
                pages = self.pool.alloc(need)   # <= spare => succeeds
                if pages is None:
                    continue
                n0 = len(self.slot_pages[i])
                self.slot_pages[i].extend(pages)
                self.page_table[i, n0:n0 + need] = pages
            entries[idx] = (i, rows + drafts, False)
            budget -= len(drafts)

    def _verify_and_commit(self, i: int, rows: List[int], row_tok):
        """Greedy draft verification (the self-speculative accept
        rule): row j's argmax is the TRUE next token after row j's
        input, and draft d_j rode row j — so d_j is confirmed iff row
        j-1's argmax equals it. The longest agreeing prefix commits,
        plus the bonus token from the first disagreeing row — exactly
        the tokens the non-speculative engine would have produced one
        tick at a time. KV written for rejected rows is rolled back
        EXACTLY: kv_len truncates via slot.length, and pages wholly
        past the new length return to the pool through the refcounted
        free (draft rows only ever write past the prompt, so a
        prefix-shared page is never corrupted — the free is belt and
        suspenders on top of that invariant).

        row_tok is the compiled step's [B, K] right-aligned verify-row
        argmax: this entry's n rows sit at slots K-n..K-1 (n <= K
        because the drafter caps k at max_draft_tokens)."""
        slot = self.slots[i]
        req = slot.req
        n = len(rows)
        drafted = n - 1
        K = row_tok.shape[1]
        cap = min(self.S, (self.pool.n_pages - 1) * self.page)
        appended = 0
        for j in range(n):
            t = int(row_tok[i, K - n + j])
            req.output.append(t)
            appended += 1
            slot.last_token = t
            slot.produced = len(req.output)
            if (slot.produced >= req.max_new_tokens
                    or (req.eos_token_id is not None
                        and t == req.eos_token_id)
                    or slot.length + j + 2 > cap - 1):
                break                    # the request finishes here
            if j + 1 < n and rows[j + 1] != t:
                break                    # draft j+1 refuted: t replaces it
        slot.length += appended
        accepted = min(appended - 1, drafted)
        keep = -(-slot.length // self.page)
        if len(self.slot_pages[i]) > keep:
            # exact rollback: every position on these pages now lies
            # past the truncated kv_len — nothing valid is lost
            fault_point("serving.verify_rollback")
            extra = self.slot_pages[i][keep:]
            del self.slot_pages[i][keep:]
            self.page_table[i, keep:keep + len(extra)] = 0
            self.pool.free(extra)
        # acceptance telemetry + adaptive draft length (the
        # chunk-budget hysteresis idiom: back off fast, regrow slow)
        self.spec_drafted += drafted
        self.spec_accepted += accepted
        req.spec_drafted += drafted
        req.spec_accepted += accepted
        if drafted:
            _SPEC_DRAFTED.inc(drafted)
        if accepted:
            _SPEC_ACCEPTED.inc(accepted)
        if self.spec_drafted:
            _SPEC_RATE.set(self.spec_accepted / self.spec_drafted)
        if accepted == drafted and drafted > 0:
            slot.spec_calm += 1
            if (slot.spec_calm >= self.spec_hysteresis
                    and slot.spec_k < self.max_draft_tokens):
                slot.spec_k = min(self.max_draft_tokens,
                                  max(slot.spec_k * 2, 1))
                slot.spec_calm = 0
        else:
            slot.spec_calm = 0
            if 2 * accepted < drafted:
                slot.spec_k = max(1, slot.spec_k // 2)
        if self._rtrace and req.trace is not None and drafted:
            tr = req.trace
            tr.event("draft_proposed", n=drafted)
            if accepted:
                tr.event("draft_accepted", n=accepted)
            if drafted - accepted:
                tr.event("draft_rejected", n=drafted - accepted)
            # a tick whose entire draft was refuted bought nothing: its
            # wall is speculation overhead, not decode progress
            self._tick_roles[req.request_id] = (
                req, "draft_overhead" if accepted == 0 else "decode_compute")
        self._note_first_token(req)
        self._maybe_finish(i)

    def _offer_prefix(self, i: int):
        """Offer slot i's newly COMPLETED prompt pages to the prefix
        index (chain order, at most through the prompt's last full
        page). Generated-token pages are never offered — only the
        effective prompt captured at admission is content-addressable."""
        slot = self.slots[i]
        page = self.page
        limit = min(slot.length, len(slot.prefix_tokens)) // page
        while slot.cache_upto < limit:
            j = slot.cache_upto
            slot.cache_key = self._pcache.insert(
                slot.cache_key,
                slot.prefix_tokens[j * page:(j + 1) * page],
                self.slot_pages[i][j])
            slot.cache_upto += 1

    def _step_ragged(self):
        """One chunked-prefill tick: admission, decode page growth, chunk
        scheduling, then ONE ragged invocation covering every phase."""
        self._admit_ragged()
        self._grow()
        entries = self._schedule_chunks()
        if not entries:
            self.last_packed_tokens = 0
            return
        if self._rtrace:
            # tick-role assignment: what each in-flight request is DOING
            # this tick. The span since its last mark is charged to this
            # role at end of step (_trace_charge_tick) or at finish.
            scheduled = set()
            for i, rows, is_prefill in entries:
                scheduled.add(i)
                r = self.slots[i].req
                if r is None or r.trace is None:
                    continue
                if is_prefill:
                    self._tick_roles[r.request_id] = (r, "prefill_compute")
                    r.trace.event("prefill_chunk", tokens=len(rows),
                                  pages=len(self.slot_pages[i]))
                else:
                    self._tick_roles.setdefault(
                        r.request_id, (r, "decode_compute"))
                    r.trace.event("decode_tick")
            for i, slot in enumerate(self.slots):
                if slot.free or i in scheduled:
                    continue
                r = slot.req
                if r is None or r.trace is None:
                    continue
                # active but unscheduled: parked on a dry pool / spent
                # chunk budget — that wait is page_wait, not compute
                self._tick_roles[r.request_id] = (r, "page_wait")
        B, page, T = self.B, self.page, self._T_pack
        toks = np.zeros((T,), np.int32)
        pos = np.zeros((T,), np.int32)
        page_ids = np.zeros((T,), np.int32)
        offs = np.zeros((T,), np.int32)
        q_start = np.zeros((B,), np.int32)
        q_len = np.zeros((B,), np.int32)
        kv_len = np.zeros((B,), np.int32)
        produce = np.zeros((B,), bool)
        prev = np.zeros((B,), np.int32)
        verify = np.zeros((B,), bool)    # decode entries: every row's
        cur = 0                          # argmax may be consumed (spec)
        for i, rows, is_prefill in entries:
            slot = self.slots[i]
            n = len(rows)
            q_start[i] = cur
            q_len[i] = n
            kv_len[i] = slot.length + n
            prev[i] = slot.last_token
            verify[i] = not is_prefill
            # only a COMPLETED prompt (or a decode row) yields a token;
            # mid-prompt chunks keep prev so sampling engines stay
            # deterministic across chunk splits
            produce[i] = (not is_prefill) or n == len(slot.pending)
            for t, tok in enumerate(rows):
                p = slot.length + t
                toks[cur] = tok
                pos[cur] = p
                page_ids[cur] = self.page_table[i, p // page]
                offs[cur] = p % page
                cur += 1
        self.last_packed_tokens = cur
        _PACKED.observe(float(cur))
        key_before = self._key
        self._key, sub = jax.random.split(self._key)
        with _devev.execution("serving.ragged_step"):
            out = self._ragged_fn()(
                self._state_arg(), jnp.asarray(toks), self.k_pool,
                self.v_pool, jnp.asarray(page_ids), jnp.asarray(offs),
                jnp.asarray(pos), jnp.asarray(self.page_table),
                jnp.asarray(q_start), jnp.asarray(q_len),
                jnp.asarray(kv_len), jnp.asarray(produce),
                # the 13th arg is the spec variant's consumed-row mask;
                # the non-speculative step keeps its prev-token slot
                jnp.asarray(verify if self._spec else prev), sub)
        if self._slo:
            nxt, ok, self.k_pool, self.v_pool = out
            ok = np.asarray(ok)
            if not ok.all():
                # discard the tick BEFORE any slot state advanced: the
                # poisoned row(s) are quarantined exactly; everyone
                # else's rows reschedule next tick and rewrite the same
                # KV values, so their outputs stay token-identical.
                # The RNG key rewinds with the tick — a sampling engine
                # re-draws the SAME sub-key on the retry, so surviving
                # rows (same slot positions) sample identical tokens
                self._key = key_before
                for i in np.nonzero(~ok)[0]:
                    self._quarantine_slot(int(i), "non-finite logits")
                return
        else:
            nxt, self.k_pool, self.v_pool = out
        nxt = np.asarray(nxt)
        for i, rows, is_prefill in entries:
            slot = self.slots[i]
            req = slot.req
            n = len(rows)
            if self._spec and not is_prefill and n > 1:
                # decode row carrying draft tokens: verify the longest
                # agreeing prefix, commit it, roll the rest back exactly
                self._verify_and_commit(i, rows, nxt)
                continue
            slot.length += n
            if is_prefill:
                del slot.pending[:n]
                if self._pcache is not None:
                    # the tick's compiled call has committed these rows'
                    # KV: fully-written prompt pages join the index
                    self._offer_prefix(i)
                if slot.pending:
                    continue             # prompt still streaming in
            # speculation armed, nxt is [B, K] right-aligned verify-row
            # argmax: a sequence's produced token sits in the LAST slot
            # (bitwise the non-speculative last-row lm-head — same
            # rank-3 matmul over gathered rows)
            tok = int(nxt[i, -1] if self._spec else nxt[i])
            slot.last_token = tok
            req.output.append(tok)
            slot.produced = len(req.output)
            self._note_first_token(req)
            self._maybe_finish(i)

    # -- SLO resilience layer (ISSUE 10) ------------------------------------

    def _pool_utilization(self) -> float:
        alloc = self.pool.n_pages - 1
        return (alloc - self.pool.n_free) / alloc if alloc else 0.0

    def _slo_pre_tick(self):
        """Deadline sweeps (waiting + in-flight), SLO queue ordering,
        and the degradation controller — everything that must settle
        BEFORE this tick's admission/scheduling decisions."""
        now = time.perf_counter()
        # fail-fast expired waiters: they can never answer in time and
        # must not consume a slot, pages, or queue budget
        keep = []
        for r in self.waiting:
            dl = r.deadline_at
            if dl is not None and now >= dl:
                self._miss_deadline(r)
            else:
                keep.append(r)
        self.waiting[:] = keep
        # ... and expired in-flight requests: reclaim slot + pages
        # instead of decoding an answer nobody is waiting for
        for i, slot in enumerate(self.slots):
            if slot.free:
                continue
            dl = slot.req.deadline_at
            if dl is not None and now >= dl:
                req = slot.req
                slot.req = None
                slot.pending = []
                self._free_slot_pages(i)
                self._miss_deadline(req)
        # (priority, earliest-deadline-first slack) ordering; the sort
        # is STABLE so equal-key requests keep FIFO/resume order
        if len(self.waiting) > 1:
            self.waiting.sort(key=lambda r: (
                -r.priority,
                r.deadline_at if r.deadline_at is not None
                else float("inf")))
        # degradation controller: shrink the effective chunk budget
        # under pool pressure (decode TPOT holds, TTFT degrades), grow
        # it back only after a full hysteresis window of calm
        if self._ragged:
            util = self._pool_utilization()
            if util >= self.degrade_high_water:
                self._calm_ticks = 0
                if self._eff_chunk > self.min_chunk_tokens:
                    self._eff_chunk = max(self.min_chunk_tokens,
                                          self._eff_chunk // 2)
            elif util <= self.degrade_low_water:
                self._calm_ticks += 1
                if (self._calm_ticks >= self.degrade_hysteresis
                        and self._eff_chunk < self.max_chunk_tokens):
                    self._eff_chunk = min(self.max_chunk_tokens,
                                          self._eff_chunk * 2)
                    self._calm_ticks = 0
            else:
                self._calm_ticks = 0     # hysteresis band: hold
            _DEGRADED.set(
                1.0 if self._eff_chunk < self.max_chunk_tokens else 0.0)

    def _slo_post_tick(self):
        """Queue telemetry, the throughput EMA behind retry-after
        hints, and the shed controller (admission-starvation pressure)."""
        _QUEUE_DEPTH.set(float(len(self.waiting)))
        now = time.perf_counter()
        if self._last_tick_s is not None:
            dt = max(now - self._last_tick_s, 1e-6)
            tokens = (self.last_packed_tokens if self._ragged
                      else sum(not s.free for s in self.slots))
            rate = tokens / dt
            self._tokens_per_s = (rate if not self._tokens_per_s
                                  else 0.8 * self._tokens_per_s
                                  + 0.2 * rate)
        self._last_tick_s = now
        if self.max_queue_tokens is None:
            return                       # no admission control: no shed
        if self.waiting and not self._admitted_this_tick:
            self._pressure_ticks += 1
        else:
            self._pressure_ticks = 0
        if self._pressure_ticks >= self.shed_patience:
            self._shed_one()
            self._pressure_ticks = 0

    def _shed_one(self):
        """Shed the (lowest-priority, most-slack, latest-submitted)
        waiting request — load drops where it hurts least, and the
        queue can never wedge behind work it will not serve in time."""
        if not self.waiting:
            return

        def shed_key(r: GenerationRequest):
            slack = (r.deadline_at - time.perf_counter()
                     if r.deadline_at is not None else float("inf"))
            return (r.priority, -slack, -(r.request_id or 0))

        victim = min(self.waiting, key=shed_key)
        self.waiting.remove(victim)
        victim.status = "shed"
        victim.error = ("shed under sustained admission starvation "
                        f"({self.shed_patience} ticks)")
        victim.finished_s = time.perf_counter()
        self._trace_settle(victim, "shed")
        self.finished.append(victim)
        self.sheds += 1
        _SHEDS.inc()

    def _miss_deadline(self, req: GenerationRequest):
        req.status = "deadline_missed"
        req.error = (f"DeadlineExceeded: deadline_s={req.deadline_s} "
                     f"passed after {len(req.output)} token(s)")
        req.finished_s = time.perf_counter()
        self._trace_settle(req, "deadline_miss")
        self.finished.append(req)
        self.deadline_misses += 1
        _DEADLINE_MISSES.inc()

    def _quarantine_slot(self, i: int, reason: str):
        """Fail ONE in-flight request (slot + pages reclaimed) and keep
        serving everyone else — the per-request fault-isolation
        terminal path."""
        slot = self.slots[i]
        req = slot.req
        slot.req = None
        slot.pending = []
        self._free_slot_pages(i)
        req.status = "failed"
        req.error = reason
        req.finished_s = time.perf_counter()
        self._trace_settle(req, "failed")
        self.finished.append(req)
        self.quarantines += 1
        _QUARANTINES.inc()

    def _on_tick_failure(self, exc: BaseException):
        """A tick raised. Without per-row attribution (the exception
        came from the shared compiled step or the allocator), suspicion
        falls on the LATEST admission — the data newest to the failing
        batch; with no active slot the queue head is the only candidate.
        Repeated failures past one full batch of quarantines re-raise:
        that is an engine-level fault, not a poisoned request.

        Survivor token-identity across THIS path is guaranteed for
        greedy engines (the chaos acceptance bar); a sampling engine
        whose fault raised after the compiled call consumed the tick's
        RNG sub-key retries with an advanced key. The non-finite
        quarantine path rewinds the key and holds for sampling too."""
        self._tick_failures += 1
        if self._tick_failures > self.B + 1:
            raise                        # re-raises `exc` (dynamic except scope)
        active = [i for i, s in enumerate(self.slots) if not s.free]
        if active:
            victim = max(active, key=lambda j: self.slots[j].admit_seq)
            self._quarantine_slot(
                victim, f"{type(exc).__name__}: {exc}")
        elif self.waiting:
            req = self.waiting.pop(0)
            req.status = "failed"
            req.error = f"{type(exc).__name__}: {exc}"
            req.finished_s = time.perf_counter()
            self._trace_settle(req, "failed")
            self.finished.append(req)
            self.quarantines += 1
            _QUARANTINES.inc()
        else:
            raise                        # nothing to attribute the fault to

    def cancel_request(self, req: GenerationRequest,
                       reason: str = "cancelled") -> bool:
        """Terminal 'cancelled' path for a client that went away (the
        gateway's mid-stream disconnect contract): a waiting request
        leaves the queue, a running one releases its slot + pages —
        either way the engine keeps serving everyone else and nothing
        wedges on an answer nobody will read. Returns False if the
        request was not live (already terminal / never submitted)."""
        if req in self.waiting:
            self.waiting.remove(req)
        else:
            for i, slot in enumerate(self.slots):
                if slot.req is req:
                    slot.req = None
                    slot.pending = []
                    self._free_slot_pages(i)
                    break
            else:
                return False
        req.status = "cancelled"
        req.error = reason
        req.finished_s = time.perf_counter()
        self._trace_settle(req, "cancelled")
        self.finished.append(req)
        return True

    def health_snapshot(self) -> dict:
        """Readiness/health view for an HTTP front-end (also served at
        /healthz next to /metrics when FLAGS_metrics_port is up). Pure
        host-side state — no device sync."""
        alloc = self.pool.n_pages - 1
        queued = self._queued_tokens()
        accepting = (self.max_queue_tokens is None
                     or queued < self.max_queue_tokens)
        snap = {
            "ready": True,
            "slo_armed": self._slo,
            "ticks": self.ticks,
            "queue_depth": len(self.waiting),
            "queued_tokens": queued,
            "active_slots": sum(not s.free for s in self.slots),
            "max_batch": self.B,
            "kv_pages": {"total": alloc, "free": self.pool.n_free,
                         "utilization": round(self._pool_utilization(), 4)},
            "degraded": self._eff_chunk < self.max_chunk_tokens,
            "effective_chunk_tokens": self._eff_chunk,
            "max_chunk_tokens": self.max_chunk_tokens,
            "tokens_per_s_ema": round(self._tokens_per_s, 3),
            "accepting": accepting,
            "counters": {"deadline_misses": self.deadline_misses,
                         "sheds": self.sheds,
                         "quarantines": self.quarantines,
                         "preemptions": self.preemptions,
                         "cache_aware_admits": self.cache_aware_admits},
            "speculative": {
                "armed": self._spec,
                "max_draft_tokens": self.max_draft_tokens,
                "drafted": self.spec_drafted,
                "accepted": self.spec_accepted,
                "acceptance_rate": (
                    round(self.spec_accepted / self.spec_drafted, 4)
                    if self.spec_drafted else 0.0),
            },
        }
        if self._pcache is not None:
            # the router's affinity seam: chain-head heat + the page
            # size it must hash at ride the snapshot, so routing needs
            # no extra round trip (ISSUE 17)
            snap["prefix_cache"] = {**self._pcache.stats(),
                                    "page_size": self._pcache.page,
                                    "epoch": self._pcache.epoch,
                                    "heat": self._pcache.heat(),
                                    # heat freshness stamp: the router's
                                    # prober expires affinity when this
                                    # age crosses its TTL (or the epoch
                                    # moved — an eviction decayed heat)
                                    "heat_ts": time.time()}
        if not accepting:
            snap["retry_after_s"] = round(self._retry_after_hint(
                max(queued - self.max_queue_tokens, 1)), 3)
        return snap

    def _tick(self):
        """The scheduler tick body (both regimes) — exactly the pre-SLO
        step() work; step() wraps it with the SLO pre/post hooks and the
        fault-isolation boundary when the layer is armed."""
        if self._ragged:
            self._step_ragged()
            return
        self._admit()
        self._grow()
        active = np.array([not s.free for s in self.slots])
        if active.any():
            toks = np.array([s.last_token for s in self.slots],
                            np.int32)
            lens = np.array([s.length for s in self.slots], np.int32)
            key_before = self._key
            self._key, sub = jax.random.split(self._key)
            with _devev.execution("serving.decode"):
                out = self._decode_fn()(
                    self._state_arg(), jnp.asarray(toks), self.k_pool,
                    self.v_pool, jnp.asarray(self.page_table),
                    jnp.asarray(lens), jnp.asarray(active), sub)
            if self._slo:
                nxt, ok, self.k_pool, self.v_pool = out
                ok = np.asarray(ok)
                if not ok.all():
                    # discard the tick (no slot state advanced yet):
                    # quarantine the poisoned row(s), everyone else
                    # re-decodes the identical step next tick (key
                    # rewound, so sampling engines re-draw the same sub)
                    self._key = key_before
                    for i in np.nonzero(~ok)[0]:
                        self._quarantine_slot(int(i), "non-finite logits")
                    return
            else:
                nxt, self.k_pool, self.v_pool = out
            nxt = np.asarray(nxt)
            for i, slot in enumerate(self.slots):
                if slot.free:
                    continue
                slot.length += 1
                slot.produced += 1
                slot.last_token = int(nxt[i])
                slot.req.output.append(slot.last_token)
                if self._rtrace and slot.req.trace is not None:
                    self._tick_roles.setdefault(
                        slot.req.request_id, (slot.req, "decode_compute"))
                    slot.req.trace.event("decode_tick")
                self._maybe_finish(i)

    def step(self) -> List[GenerationRequest]:
        """One scheduler tick. Ragged regime: admit, grow, then ONE mixed
        prefill-chunk + decode invocation. Bucketed regime
        (FLAGS_ragged_attention=0): admit (bucketed prefill compiles),
        grow, then one decode step for every active slot. SLO layer
        armed: deadline sweeps + queue ordering before the tick, a
        fault-isolation boundary (and optional watchdog section) around
        it, shedding/telemetry after it. Returns requests finished this
        tick."""
        n_done_before = len(self.finished)
        if not self._slo:
            self._tick()
        else:
            self._slo_pre_tick()
            self._admitted_this_tick = False
            try:
                if self._wd is not None:
                    with self._wd.section("serving.tick"):
                        fault_point("serving.tick")
                        self._tick()
                else:
                    fault_point("serving.tick")
                    self._tick()
                self._tick_failures = 0
            except Exception as exc:    # isolation boundary: one
                self._on_tick_failure(exc)   # request fails, not the tick loop
            self._slo_post_tick()
        if self._rtrace:
            self._trace_charge_tick()
        _KV_PAGES.set(float(self.pool.n_pages - 1 - self.pool.n_free))
        self.ticks += 1
        return self.finished[n_done_before:]

    @property
    def has_work(self):
        return bool(self.waiting) or any(not s.free for s in self.slots)

    def run(self, requests: Optional[List[GenerationRequest]] = None,
            arrivals: Optional[List[float]] = None, max_ticks: int = 10000):
        """Drive until drained. `arrivals[i]` (seconds from start) delays
        request i's admission — the staggered-arrival serving pattern."""
        requests = requests or []
        order = sorted(range(len(requests)),
                       key=lambda i: (arrivals[i] if arrivals else 0.0))
        t0 = time.perf_counter()
        pending = [(arrivals[i] if arrivals else 0.0, requests[i])
                   for i in order]
        for _ in range(max_ticks):
            now = time.perf_counter() - t0
            while pending and pending[0][0] <= now:
                self.add_request(pending[0][1])
                pending.pop(0)
            if not self.has_work and not pending:
                break
            if not self.has_work and pending:
                time.sleep(max(0.0, pending[0][0] - now))
                continue
            self.step()
        return self.finished


# -- /healthz provider glue --------------------------------------------------

_health_engines = weakref.WeakSet()


def serving_health() -> dict:
    """Aggregate readiness view across live SLO-armed engines — what
    the metrics endpoint serves at /healthz."""
    return {"engines": [e.health_snapshot() for e in list(_health_engines)]}


def _register_health_engine(engine) -> None:
    """SLO-armed engines publish health_snapshot() through the metrics
    HTTP endpoint's /healthz (observability.export). Registration is
    WEAK: an engine dies with its owner, no teardown call needed."""
    _health_engines.add(engine)
    try:
        from ..observability import export as _oexp
        _oexp.register_health_provider("serving", serving_health)
    except Exception:
        pass        # telemetry must never fail engine construction
