"""Continuous-batching LLM serving over the paged KV cache
(ref: the reference's serving decode stack — block_multihead_attention
paged decode, phi/kernels/fusion/gpu/block_multi_head_attention_kernel;
fluid/inference/api/analysis_predictor.cc:2320 Run() driving it; the
block-table allocator in fluid/framework/new_executor/block tables).

TPU-native design: a global KV PAGE POOL `[L, kvh, n_pages, page, d]`
(the Pallas paged_attention kernel's pool layout) plus a host-side
free-list allocator and per-slot block tables — KV memory is
proportional to live tokens, not batch * max_seq.

Two scheduler regimes, flag-gated (`FLAGS_ragged_attention`, default on):

* CHUNKED-PREFILL continuous batching (the ragged regime — ref "Ragged
  Paged Attention", arxiv 2604.15464): admission splits prompts into
  KV-budgeted prefill CHUNKS (`max_chunk_tokens` per tick) that are
  packed into the SAME compiled step as the active decode slots — one
  ragged kernel invocation per tick, one KV page-scatter per tick per
  layer, ONE compiled shape total (rows pad to a fixed bucket). Prefill
  no longer head-of-line-blocks decoding users, and pool accounting
  moves to token granularity (pages are funded chunk by chunk).
* The legacy bucketed regime (`FLAGS_ragged_attention=0` restores it
  exactly): each admitted request prefills as a bucketed batched
  compile, then joins the shared single-token decode tick.

Both regimes: finished sequences return their pages to the pool, and
pool exhaustion preempts the latest-admitted sequence (recompute-style
resume). Serving telemetry rides the observability registry
(serving.ttft_seconds / serving.tpot_seconds / serving.kv_pages_in_use /
serving.preemptions_total / serving.packed_tokens_per_tick).

Weight-only int8 (PTQ) inference: `quantize="int8"` stores every 2-D
projection as int8 + per-output-channel scale (the PTQ absmax rule,
ref quantization post-training observers; inference int8 path
paddle/fluid/inference int8). Dequant happens in-trace, fused by XLA
into the matmul operand read — weights move through HBM at half/quarter
width, which is what decode (memory-bound) is priced by.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import core as _core
from ..observability import metrics as _metrics

__all__ = ["GenerationRequest", "ContinuousBatchingEngine", "PagePool",
           "quantize_state_int8"]

_TTFT = _metrics.histogram(
    "serving.ttft_seconds",
    "request arrival to first generated token (time-to-first-token)")
_TPOT = _metrics.histogram(
    "serving.tpot_seconds",
    "mean per-output-token latency after the first token")
_KV_PAGES = _metrics.gauge(
    "serving.kv_pages_in_use",
    "allocated (non-free, non-scratch) pages in the KV page pool")
_PREEMPTS = _metrics.counter(
    "serving.preemptions_total",
    "recompute-style preemptions forced by KV pool pressure")
_PACKED = _metrics.histogram(
    "serving.packed_tokens_per_tick",
    "ragged rows (prefill-chunk + decode) packed into one mixed step",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0))


# ---------------- weight-only int8 PTQ ------------------------------------

def quantize_state_int8(state: Dict[str, jax.Array], min_size=4096):
    """Per-output-channel absmax int8 quantization of 2-D+ weights
    (ref: PTQ AbsmaxObserver rule; embeddings/norms stay full precision —
    norm scales are 1-D, embedding rows are gathered not matmul'd).
    The scale plumbing is quantization/comm.py's — the same rounding/
    clipping rules the quantized collectives put on the wire (ISSUE 8).

    Returns a pytree where quantized entries are `(q_int8, scale_f32)`
    tuples; `_dequant_state` restores them in-trace."""
    from ..quantization import comm as _qcomm
    out = {}
    for k, v in state.items():
        arr = v
        if (hasattr(arr, "ndim") and arr.ndim == 2
                and jnp.issubdtype(arr.dtype, jnp.floating)
                and arr.size >= min_size
                and "embed" not in k and "norm" not in k):
            out[k] = _qcomm.channelwise_absmax_int8(arr, axis=0)
        else:
            out[k] = arr
    return out


def _dequant_state(state, dtype):
    """In-trace: (int8, scale) -> dtype weight; XLA fuses the convert +
    scale into the consuming dot's operand read."""
    from ..quantization import comm as _qcomm
    return {k: (_qcomm.dequantize_channelwise(v[0], v[1], dtype)
                if isinstance(v, tuple) else v)
            for k, v in state.items()}


# ---------------- requests -------------------------------------------------

@dataclass
class GenerationRequest:
    """One decode job (ref: the serving request in analysis_predictor's
    batched Run loop)."""
    prompt: List[int]
    max_new_tokens: int = 32
    eos_token_id: Optional[int] = None
    request_id: Optional[int] = None
    # filled by the engine
    output: List[int] = field(default_factory=list)
    arrived_s: float = 0.0
    finished_s: Optional[float] = None
    first_token_s: Optional[float] = None

    @property
    def done(self) -> bool:
        return self.finished_s is not None


class _Slot:
    __slots__ = ("req", "length", "produced", "last_token", "admit_seq",
                 "pending")

    def __init__(self):
        self.req: Optional[GenerationRequest] = None
        self.length = 0
        self.produced = 0
        self.last_token = 0
        self.admit_seq = -1
        # chunked-prefill regime: effective-prompt tokens not yet in KV
        self.pending: List[int] = []

    @property
    def free(self):
        return self.req is None


# ---------------- page pool ------------------------------------------------

class PagePool:
    """Host-side free-list allocator over the global KV page pool
    (ref: the reference's block tables —
    phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu
    `block_tables` arg and incubate/nn/functional/block_multihead_attention:
    pages are allocated on demand per sequence and shared across the pool,
    so KV memory is proportional to LIVE tokens, not batch * max_seq).

    Page 0 is reserved as a scratch page: inactive slots and padding
    positions write there; it is never allocated."""

    def __init__(self, n_pages: int, page_size: int = 16):
        if n_pages < 2:
            raise ValueError("pool needs >= 2 pages (page 0 is scratch)")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self._free = list(range(self.n_pages - 1, 0, -1))  # pop() -> low ids

    @property
    def n_free(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n pages or None (caller keeps the request waiting / preempts)."""
        if n > len(self._free):
            return None
        return [self._free.pop() for _ in range(n)]

    def free(self, pages: List[int]) -> None:
        self._free.extend(pages)


# ---------------- engine ---------------------------------------------------

class ContinuousBatchingEngine:
    """Slot-based continuous batching over the paged-KV decode path.

    model: LlamaForCausalLM (any model exposing config + state_dict with
    the llama cache-forward layout). max_batch = decode slots; max_seq =
    per-slot KV capacity (page-aligned). max_chunk_tokens bounds the
    prefill tokens packed into one ragged tick; ragged=None follows
    FLAGS_ragged_attention (the chunked-prefill kill switch).
    """

    def __init__(self, model, max_batch: int = 4, max_seq: int = 256,
                 prefill_buckets=(32, 64, 128, 256), quantize=None,
                 greedy: bool = True, seed: int = 0,
                 total_pages: Optional[int] = None, page_size: int = 16,
                 max_chunk_tokens: int = 64, ragged: Optional[bool] = None):
        from ..models import llama as L
        self.cfg = model.cfg
        self.B = int(max_batch)
        page = int(page_size)
        self.page = page
        self.S = int(-(-max_seq // page) * page)     # page-aligned
        self.ppmax = self.S // page                  # pages per sequence cap
        # always include the full slot capacity so any prompt <= max_seq
        # has a bucket
        self.buckets = tuple(sorted(
            {b for b in prefill_buckets if b < self.S} | {self.S}))
        self.greedy = greedy
        self._fwd = L._forward_with_cache
        self._decode_paged = L._decode_step_paged
        self._ragged_step = L._ragged_step_paged
        raw = {k: t.data for k, t in model.state_dict().items()}
        self.dtype = raw["model.embed_tokens"].dtype
        self.state = (quantize_state_int8(raw) if quantize == "int8"
                      else raw)
        self._quantized = quantize == "int8"
        cfg = self.cfg
        L_, kvh, d = (cfg.num_hidden_layers, cfg.kv_heads, cfg.head_dim)
        # page pool: +1 for the reserved scratch page. Default is the
        # dense-equivalent capacity; pass total_pages to bound KV memory
        # to live tokens (admission then gates on free pages and decode
        # growth preempts when the pool is dry).
        n_pages = int(total_pages) if total_pages else self.B * self.ppmax + 1
        self.pool = PagePool(n_pages, page)
        self.k_pool = jnp.zeros((L_, kvh, n_pages, page, d), self.dtype)
        self.v_pool = jnp.zeros_like(self.k_pool)
        # host-side block table: page ids per slot (0 = scratch/unused)
        self.page_table = np.zeros((self.B, self.ppmax), np.int32)
        self.slot_pages: List[List[int]] = [[] for _ in range(self.B)]
        self.slots = [_Slot() for _ in range(self.B)]
        self.waiting: List[GenerationRequest] = []
        self.finished: List[GenerationRequest] = []
        self._next_id = 0
        self._admit_seq = 0
        self.preemptions = 0
        self._key = jax.random.key(seed)
        self._compiled_prefill = {}
        self._compiled_decode = None
        self._compiled_write = None
        self._compiled_ragged = None
        # chunked-prefill regime: FLAGS_ragged_attention is the kill
        # switch (0 restores the bucketed-prefill engine exactly)
        self._ragged = (_core.get_bool_flag("FLAGS_ragged_attention", True)
                        if ragged is None else bool(ragged))
        if int(max_chunk_tokens) < 1:
            # fail fast: a zero budget would make _schedule_chunks park
            # every prefill forever and preempt-thrash instead of erroring
            raise ValueError(
                f"max_chunk_tokens must be >= 1, got {max_chunk_tokens}")
        self.max_chunk_tokens = int(max_chunk_tokens)
        # ONE compiled ragged shape: rows pad to a fixed power-of-two
        # bucket >= decode slots + the chunk budget (the kernel's
        # autotune size class, so tuned blocks match what we compile)
        from ..kernels.ragged_paged_attention import _size_class
        self._T_pack = _size_class(self.B + self.max_chunk_tokens)
        self.last_packed_tokens = 0
        # donation lets XLA scatter into the pool in place; CPU jit would
        # just warn that the buffers were not donated
        self._donate = jax.default_backend() == "tpu"
        self.ticks = 0

    # -- memory accounting ---------------------------------------------------

    @property
    def kv_cache_bytes(self) -> int:
        return int(self.k_pool.nbytes + self.v_pool.nbytes)

    @property
    def dense_equivalent_bytes(self) -> int:
        """What the pre-pool engine allocated: [L, B, S_max, kvh, d] x2."""
        cfg = self.cfg
        itemsize = jnp.dtype(self.dtype).itemsize
        return int(2 * cfg.num_hidden_layers * self.B * self.S
                   * cfg.kv_heads * cfg.head_dim * itemsize)

    # -- compiled kernels ---------------------------------------------------

    def _state_arg(self):
        return self.state

    def _prefill_fn(self, T, k=1):
        """(state, ids[k,T], n_valid[k]) -> (last_logits[k,V], k_new,
        v_new) — BATCHED prefill for k same-bucket admissions in one
        compiled call (VERDICT r3 weak #4: per-request prefill cost).
        Returns the prompts' KV planes [L, k, T, kvh, d]; the caller
        scatters JUST those tokens' pages into the pool. k is padded to
        a power of two by the admission path so the compile cache stays
        bounded at O(buckets x log2(max_batch))."""
        key = (T, k)
        if key in self._compiled_prefill:
            return self._compiled_prefill[key]
        cfg, dt = self.cfg, self.dtype
        fwd, dq, quant = self._fwd, _dequant_state, self._quantized

        @jax.jit
        def prefill(state, ids, n_valid):
            st = dq(state, dt) if quant else state
            ck = jnp.zeros((cfg.num_hidden_layers, k, T,
                            cfg.kv_heads, cfg.head_dim), dt)
            cv = jnp.zeros_like(ck)
            logits, ck, cv = fwd(st, cfg, ids, ck, cv,
                                 jnp.zeros((k,), jnp.int32))
            last = jnp.take_along_axis(
                logits, (n_valid - 1)[:, None, None], axis=1)[:, 0]
            return last, ck, cv

        self._compiled_prefill[key] = prefill
        return prefill

    def _write_fn(self):
        """(k_pool, v_pool, k_new[L,T,kvh,d], v_new, page_ids[T], offs[T])
        -> updated pools. Padding positions carry page id 0 (scratch)."""
        if self._compiled_write is not None:
            return self._compiled_write

        def write(k_pool, v_pool, k_new, v_new, page_ids, offs):
            kt = jnp.moveaxis(k_new, 2, 1)           # [L, kvh, T, d]
            vt = jnp.moveaxis(v_new, 2, 1)
            k_pool = k_pool.at[:, :, page_ids, offs].set(
                kt.astype(k_pool.dtype))
            v_pool = v_pool.at[:, :, page_ids, offs].set(
                vt.astype(v_pool.dtype))
            return k_pool, v_pool

        self._compiled_write = jax.jit(
            write, donate_argnums=(0, 1) if self._donate else ())
        return self._compiled_write

    def _decode_fn(self):
        """(state, toks[B], k_pool, v_pool, page_table, lens[B],
        active[B], key) -> (next[B], k_pool, v_pool) — one token for
        every active slot, straight over the page pool."""
        if self._compiled_decode is not None:
            return self._compiled_decode
        cfg, dt = self.cfg, self.dtype
        dq, quant = _dequant_state, self._quantized
        step_paged = self._decode_paged
        greedy = self.greedy

        def decode(state, toks, k_pool, v_pool, page_table, lens, active,
                   key):
            st = dq(state, dt) if quant else state
            lg, k_pool, v_pool = step_paged(
                st, cfg, toks, k_pool, v_pool, page_table, lens, active)
            if greedy:
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            else:
                nxt = jax.random.categorical(key, lg).astype(jnp.int32)
            # inactive slots keep their token and cache position
            nxt = jnp.where(active, nxt, toks)
            return nxt, k_pool, v_pool

        self._compiled_decode = jax.jit(
            decode, donate_argnums=(2, 3) if self._donate else ())
        return self._compiled_decode

    def _ragged_fn(self):
        """(state, toks[T], k_pool, v_pool, page_ids[T], offs[T], pos[T],
        page_table, q_start[B], q_len[B], kv_len[B], produce[B], prev[B],
        key) -> (next[B], k_pool, v_pool) — ONE mixed prefill+decode step:
        every packed row's KV scatters into its page and one ragged paged
        attention covers both phases; next[b] is sampled from sequence
        b's last packed row (kept at prev[b] where produce[b] is False:
        mid-prompt chunks and idle slots)."""
        if self._compiled_ragged is not None:
            return self._compiled_ragged
        cfg, dt = self.cfg, self.dtype
        dq, quant = _dequant_state, self._quantized
        step_ragged = self._ragged_step
        greedy = self.greedy

        def rstep(state, toks, k_pool, v_pool, page_ids, offs, pos,
                  page_table, q_start, q_len, kv_len, produce, prev, key):
            st = dq(state, dt) if quant else state
            lg, k_pool, v_pool = step_ragged(
                st, cfg, toks, pos, k_pool, v_pool, page_ids, offs,
                page_table, q_start, q_len, kv_len)
            if greedy:
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            else:
                nxt = jax.random.categorical(key, lg).astype(jnp.int32)
            nxt = jnp.where(produce, nxt, prev)
            return nxt, k_pool, v_pool

        self._compiled_ragged = jax.jit(
            rstep, donate_argnums=(2, 3) if self._donate else ())
        return self._compiled_ragged

    # -- scheduler ----------------------------------------------------------

    def add_request(self, req: GenerationRequest):
        # reject impossible prompts AT SUBMIT time: raising later from
        # inside step() would wedge the queue head forever and strand
        # every in-flight request (code-review r4)
        need = -(-len(req.prompt) // self.page)
        if need > self.pool.n_pages - 1:
            raise ValueError(
                f"prompt needs {need} pages but the pool only has "
                f"{self.pool.n_pages - 1} allocatable pages")
        if len(req.prompt) > self.S:
            raise ValueError(
                f"prompt length {len(req.prompt)} exceeds max_seq {self.S}")
        if req.request_id is None:
            req.request_id = self._next_id
            self._next_id += 1
        req.arrived_s = time.perf_counter()
        self.waiting.append(req)
        return req.request_id

    def _bucket(self, T):
        for b in self.buckets:
            if T <= b:
                return b
        raise ValueError(f"prompt length {T} exceeds max_seq {self.S}")

    def _free_slot_pages(self, i):
        if self.slot_pages[i]:
            self.pool.free(self.slot_pages[i])
            self.slot_pages[i] = []
        self.page_table[i, :] = 0

    def _preempt(self, i):
        """Recompute-preemption (the vLLM/block-table eviction pattern):
        release slot i's pages and push its request back to the FRONT of
        the wait queue; re-admission prefills prompt+output so decoding
        resumes exactly where it stopped."""
        slot = self.slots[i]
        req = slot.req
        slot.req = None
        slot.pending = []
        self._free_slot_pages(i)
        self.waiting.insert(0, req)
        self.preemptions += 1
        _PREEMPTS.inc()

    def _oversized(self, eff_len: int) -> bool:
        """A token stream that can NEVER fit: more pages than the pool
        can allocate, or longer than the per-slot KV capacity."""
        return (-(-eff_len // self.page) > self.pool.n_pages - 1
                or eff_len > self.S)

    def _fail_request(self, req):
        """Defensive terminal path shared by both admission regimes:
        add_request gates prompts and _maybe_finish caps growth, so an
        oversized resume stream is unreachable — but if it ever occurs,
        FINISH the request (empty/partial output) instead of raising
        out of step() and wedging the queue head."""
        req.finished_s = time.perf_counter()
        self.finished.append(req)

    def _note_first_token(self, req):
        """TTFT bookkeeping: the request's FIRST output token just landed
        (admission in the bucketed regime, prompt-complete chunk in the
        ragged one). Resumed requests keep their original stamp."""
        if len(req.output) == 1 and req.first_token_s is None:
            req.first_token_s = time.perf_counter()
            _TTFT.observe(req.first_token_s - req.arrived_s)

    def _admit(self):
        """Move waiting requests into free slots, allocating ONLY the
        pages the prompts need; requests stay queued while the pool has
        no room (admission control by live tokens, not slot count).
        Same-bucket admissions in one tick share ONE batched prefill
        call and ONE pool scatter — admission cost amortizes instead of
        paying a compiled call + scatter per request. Rounds repeat
        while admissions made progress, so pages freed by a request
        that FINISHES at admission still serve later waiters in the
        same tick (the pre-batching behavior)."""
        while self._admit_round():
            pass

    def _admit_round(self) -> bool:
        free_slots = [i for i, s in enumerate(self.slots) if s.free]
        picked = []          # (slot_idx, req, eff, T, need, pages)
        while self.waiting and free_slots:
            req = self.waiting[0]
            # re-admission after preemption resumes from prompt + output
            eff = list(req.prompt) + list(req.output)
            T = len(eff)
            need = -(-T // self.page)
            if self._oversized(T):
                self.waiting.pop(0)
                self._fail_request(req)
                continue
            pages = self.pool.alloc(need)
            if pages is None:
                break                    # pool full: stay waiting
            self.waiting.pop(0)
            picked.append((free_slots.pop(0), req, eff, T, need, pages))
        if not picked:
            return False
        by_bucket: Dict[int, list] = {}
        for item in picked:
            by_bucket.setdefault(self._bucket(item[3]), []).append(item)
        for bucket, group in by_bucket.items():
            self._admit_group(bucket, group)
        return True

    def _admit_group(self, bucket, group):
        """One batched prefill + one pool scatter for a same-bucket
        admission group; k pads up to a power of two (padding rows write
        the scratch page) so compile keys stay bounded."""
        n = len(group)
        k = 1
        while k < n:
            k *= 2
        ids = np.zeros((k, bucket), np.int32)
        n_valid = np.ones((k,), np.int32)
        for j, (_, _, eff, T, _, _) in enumerate(group):
            ids[j, :T] = eff
            n_valid[j] = T
        last, k_new, v_new = self._prefill_fn(bucket, k)(
            self._state_arg(), jnp.asarray(ids), jnp.asarray(n_valid))
        # ONE flat scatter for the whole group: [L, k, T, kvh, d] ->
        # [L, k*T, kvh, d]; padding rows and beyond-prompt positions
        # land on the scratch page
        pos = np.arange(bucket)
        page_ids = np.zeros((k, bucket), np.int32)
        offs = np.broadcast_to(pos % self.page, (k, bucket)).astype(
            np.int32)
        for j, (_, _, _, T, need, pages) in enumerate(group):
            page_ids[j] = np.where(
                pos < T,
                np.asarray(pages, np.int32)[
                    np.minimum(pos // self.page, need - 1)],
                0)
        L_ = k_new.shape[0]
        k_flat = k_new.reshape(L_, k * bucket, *k_new.shape[3:])
        v_flat = v_new.reshape(L_, k * bucket, *v_new.shape[3:])
        self.k_pool, self.v_pool = self._write_fn()(
            self.k_pool, self.v_pool, k_flat, v_flat,
            jnp.asarray(page_ids.reshape(-1)),
            jnp.asarray(offs.reshape(-1)))
        last_np = None
        if self.greedy:
            last_np = np.asarray(last)
        else:
            # sampling engines must SAMPLE the admission token too
            # (first token of every request + preemption resumes)
            self._key, sub = jax.random.split(self._key)
            sampled = np.asarray(jax.random.categorical(sub, last))
        for j, (i, req, eff, T, need, pages) in enumerate(group):
            slot = self.slots[i]
            self.slot_pages[i] = pages
            self.page_table[i, :] = 0
            self.page_table[i, :need] = pages
            tok = (int(np.argmax(last_np[j])) if self.greedy
                   else int(sampled[j]))
            slot.req = req
            slot.length = T
            slot.produced = len(req.output) + 1
            slot.last_token = tok
            slot.admit_seq = self._admit_seq
            self._admit_seq += 1
            req.output.append(tok)
            self._note_first_token(req)
            self._maybe_finish(i)

    def _maybe_finish(self, i):
        slot = self.slots[i]
        req = slot.req
        if req is None:
            return
        eos_hit = (req.eos_token_id is not None
                   and req.output and req.output[-1] == req.eos_token_id)
        # capacity cap includes the POOL: one sequence can never hold
        # more than every allocatable page, and preempt/re-admit must
        # not grow `need` past that (it would raise inside step() and
        # lose all in-flight requests)
        cap = min(self.S, (self.pool.n_pages - 1) * self.page)
        full = slot.length + 1 > cap - 1
        if slot.produced >= req.max_new_tokens or eos_hit or full:
            req.finished_s = time.perf_counter()
            if req.first_token_s is not None and len(req.output) > 1:
                _TPOT.observe((req.finished_s - req.first_token_s)
                              / (len(req.output) - 1))
            self.finished.append(req)
            slot.req = None
            slot.pending = []
            self._free_slot_pages(i)     # pages back to the pool

    def _grow(self):
        """Before a decode tick: every active DECODE-phase slot whose
        next token crosses a page boundary gets a fresh page; when the
        pool is dry, preempt the latest-admitted OTHER active slot and
        retry (the victim resumes later via recompute). Prefill-phase
        slots (ragged regime) fund their pages chunk by chunk in
        _schedule_chunks instead."""
        for i, slot in enumerate(self.slots):
            if slot.free or slot.pending:
                continue
            while slot.req is not None:
                have = len(self.slot_pages[i]) * self.page
                if slot.length < have:
                    break                # room for this token
                pg = self.pool.alloc(1)
                if pg is not None:
                    n = len(self.slot_pages[i])
                    self.slot_pages[i].append(pg[0])
                    self.page_table[i, n] = pg[0]
                    break
                # only page-HOLDING victims free anything; a freshly
                # admitted zero-page prefill slot would be a pointless
                # eviction (pages unchanged, preemption counted)
                victims = [j for j, s in enumerate(self.slots)
                           if j != i and not s.free and self.slot_pages[j]]
                if victims:
                    self._preempt(max(
                        victims, key=lambda j: self.slots[j].admit_seq))
                else:
                    self._preempt(i)     # nothing else holds pages

    # -- chunked-prefill (ragged) scheduler ---------------------------------

    def _admit_ragged(self):
        """Token-granular admission: a waiting request takes a free slot
        as soon as ONE exists and the pool has any free page — its prompt
        is funded page by page as chunks are scheduled, not reserved
        up front (the chunked-prefill admission rule)."""
        free_slots = [i for i, s in enumerate(self.slots) if s.free]
        while self.waiting and free_slots and self.pool.n_free > 0:
            req = self.waiting[0]
            # re-admission after preemption resumes from prompt + output
            eff = list(req.prompt) + list(req.output)
            if self._oversized(len(eff)):
                self.waiting.pop(0)
                self._fail_request(req)
                continue
            self.waiting.pop(0)
            i = free_slots.pop(0)
            slot = self.slots[i]
            slot.req = req
            slot.length = 0
            slot.produced = len(req.output)
            slot.last_token = 0
            slot.pending = eff
            slot.admit_seq = self._admit_seq
            self._admit_seq += 1
            self.slot_pages[i] = []
            self.page_table[i, :] = 0

    def _schedule_chunks(self) -> List[Tuple[int, List[int], bool]]:
        """Build this tick's ragged batch: one decode row per active
        decode-phase slot plus KV-budgeted prefill chunks (admission
        order, `max_chunk_tokens` total). Pages are funded at token
        granularity — a chunk shrinks to what the pool can hold. When
        every active slot is prefill-parked on a dry pool, the latest
        admission is preempted (recompute) so the head makes progress.
        Returns [(slot_idx, row_tokens, is_prefill)]."""
        while True:
            entries: List[Tuple[int, List[int], bool]] = []
            budget = self.max_chunk_tokens
            for i, slot in enumerate(self.slots):
                if not slot.free and not slot.pending:
                    entries.append((i, [slot.last_token], False))
            order = sorted((i for i, s in enumerate(self.slots)
                            if not s.free and s.pending),
                           key=lambda i: self.slots[i].admit_seq)
            for i in order:
                if budget <= 0:
                    break
                slot = self.slots[i]
                chunk = min(len(slot.pending), budget,
                            self.S - slot.length)
                have = len(self.slot_pages[i]) * self.page
                fundable = (have + self.pool.n_free * self.page
                            - slot.length)
                chunk = min(chunk, fundable)
                if chunk <= 0:
                    continue             # parked this tick (pool dry)
                need = (-(-(slot.length + chunk) // self.page)
                        - len(self.slot_pages[i]))
                if need > 0:
                    pages = self.pool.alloc(need)  # fundable => succeeds
                    n0 = len(self.slot_pages[i])
                    self.slot_pages[i].extend(pages)
                    self.page_table[i, n0:n0 + need] = pages
                entries.append((i, list(slot.pending[:chunk]), True))
                budget -= chunk
            if entries:
                return entries
            # prefer page-HOLDING victims (evicting a zero-page slot
            # frees nothing); fall back to any active slot so the loop
            # always shrinks the active set and terminates
            active = [i for i, s in enumerate(self.slots) if not s.free]
            if not active:
                return entries
            victims = [i for i in active if self.slot_pages[i]] or active
            self._preempt(max(victims,
                              key=lambda j: self.slots[j].admit_seq))

    def _step_ragged(self):
        """One chunked-prefill tick: admission, decode page growth, chunk
        scheduling, then ONE ragged invocation covering every phase."""
        self._admit_ragged()
        self._grow()
        entries = self._schedule_chunks()
        if not entries:
            self.last_packed_tokens = 0
            return
        B, page, T = self.B, self.page, self._T_pack
        toks = np.zeros((T,), np.int32)
        pos = np.zeros((T,), np.int32)
        page_ids = np.zeros((T,), np.int32)
        offs = np.zeros((T,), np.int32)
        q_start = np.zeros((B,), np.int32)
        q_len = np.zeros((B,), np.int32)
        kv_len = np.zeros((B,), np.int32)
        produce = np.zeros((B,), bool)
        prev = np.zeros((B,), np.int32)
        cur = 0
        for i, rows, is_prefill in entries:
            slot = self.slots[i]
            n = len(rows)
            q_start[i] = cur
            q_len[i] = n
            kv_len[i] = slot.length + n
            prev[i] = slot.last_token
            # only a COMPLETED prompt (or a decode row) yields a token;
            # mid-prompt chunks keep prev so sampling engines stay
            # deterministic across chunk splits
            produce[i] = (not is_prefill) or n == len(slot.pending)
            for t, tok in enumerate(rows):
                p = slot.length + t
                toks[cur] = tok
                pos[cur] = p
                page_ids[cur] = self.page_table[i, p // page]
                offs[cur] = p % page
                cur += 1
        self.last_packed_tokens = cur
        _PACKED.observe(float(cur))
        self._key, sub = jax.random.split(self._key)
        nxt, self.k_pool, self.v_pool = self._ragged_fn()(
            self._state_arg(), jnp.asarray(toks), self.k_pool,
            self.v_pool, jnp.asarray(page_ids), jnp.asarray(offs),
            jnp.asarray(pos), jnp.asarray(self.page_table),
            jnp.asarray(q_start), jnp.asarray(q_len),
            jnp.asarray(kv_len), jnp.asarray(produce),
            jnp.asarray(prev), sub)
        nxt = np.asarray(nxt)
        for i, rows, is_prefill in entries:
            slot = self.slots[i]
            req = slot.req
            n = len(rows)
            slot.length += n
            if is_prefill:
                del slot.pending[:n]
                if slot.pending:
                    continue             # prompt still streaming in
            tok = int(nxt[i])
            slot.last_token = tok
            req.output.append(tok)
            slot.produced = len(req.output)
            self._note_first_token(req)
            self._maybe_finish(i)

    def step(self) -> List[GenerationRequest]:
        """One scheduler tick. Ragged regime: admit, grow, then ONE mixed
        prefill-chunk + decode invocation. Bucketed regime
        (FLAGS_ragged_attention=0): admit (bucketed prefill compiles),
        grow, then one decode step for every active slot. Returns
        requests finished this tick."""
        n_done_before = len(self.finished)
        if self._ragged:
            self._step_ragged()
        else:
            self._admit()
            self._grow()
            active = np.array([not s.free for s in self.slots])
            if active.any():
                toks = np.array([s.last_token for s in self.slots],
                                np.int32)
                lens = np.array([s.length for s in self.slots], np.int32)
                self._key, sub = jax.random.split(self._key)
                nxt, self.k_pool, self.v_pool = self._decode_fn()(
                    self._state_arg(), jnp.asarray(toks), self.k_pool,
                    self.v_pool, jnp.asarray(self.page_table),
                    jnp.asarray(lens), jnp.asarray(active), sub)
                nxt = np.asarray(nxt)
                for i, slot in enumerate(self.slots):
                    if slot.free:
                        continue
                    slot.length += 1
                    slot.produced += 1
                    slot.last_token = int(nxt[i])
                    slot.req.output.append(slot.last_token)
                    self._maybe_finish(i)
        _KV_PAGES.set(float(self.pool.n_pages - 1 - self.pool.n_free))
        self.ticks += 1
        return self.finished[n_done_before:]

    @property
    def has_work(self):
        return bool(self.waiting) or any(not s.free for s in self.slots)

    def run(self, requests: Optional[List[GenerationRequest]] = None,
            arrivals: Optional[List[float]] = None, max_ticks: int = 10000):
        """Drive until drained. `arrivals[i]` (seconds from start) delays
        request i's admission — the staggered-arrival serving pattern."""
        requests = requests or []
        order = sorted(range(len(requests)),
                       key=lambda i: (arrivals[i] if arrivals else 0.0))
        t0 = time.perf_counter()
        pending = [(arrivals[i] if arrivals else 0.0, requests[i])
                   for i in order]
        for _ in range(max_ticks):
            now = time.perf_counter() - t0
            while pending and pending[0][0] <= now:
                self.add_request(pending[0][1])
                pending.pop(0)
            if not self.has_work and not pending:
                break
            if not self.has_work and pending:
                time.sleep(max(0.0, pending[0][0] - now))
                continue
            self.step()
        return self.finished
