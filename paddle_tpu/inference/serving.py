"""Continuous-batching LLM serving over the paged KV cache
(ref: the reference's serving decode stack — block_multihead_attention
paged decode, phi/kernels/fusion/gpu/block_multi_head_attention_kernel;
fluid/inference/api/analysis_predictor.cc:2320 Run() driving it; the
block-table allocator in fluid/framework/new_executor/block tables).

TPU-native design: a fixed pool of B decode SLOTS backed by the KV page
pool (kernels/paged_attention block-table layout). The scheduler admits
waiting requests into free slots MID-DECODE (one bucketed single-
sequence prefill writes the slot's pages), every decode tick advances
all active slots with ONE compiled step (per-slot lengths — ragged
batching), and finished sequences free their slot for reuse. All compute
is jit-compiled once per (bucket/batch) shape; the Python scheduler only
moves request metadata.

Weight-only int8 (PTQ) inference: `quantize="int8"` stores every 2-D
projection as int8 + per-output-channel scale (the PTQ absmax rule,
ref quantization post-training observers; inference int8 path
paddle/fluid/inference int8). Dequant happens in-trace, fused by XLA
into the matmul operand read — weights move through HBM at half/quarter
width, which is what decode (memory-bound) is priced by.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["GenerationRequest", "ContinuousBatchingEngine",
           "quantize_state_int8"]


# ---------------- weight-only int8 PTQ ------------------------------------

def quantize_state_int8(state: Dict[str, jax.Array], min_size=4096):
    """Per-output-channel absmax int8 quantization of 2-D+ weights
    (ref: PTQ AbsmaxObserver rule; embeddings/norms stay full precision —
    norm scales are 1-D, embedding rows are gathered not matmul'd).

    Returns a pytree where quantized entries are `(q_int8, scale_f32)`
    tuples; `dequantize_entry` restores them in-trace."""
    out = {}
    for k, v in state.items():
        arr = v
        if (hasattr(arr, "ndim") and arr.ndim == 2
                and jnp.issubdtype(arr.dtype, jnp.floating)
                and arr.size >= min_size
                and "embed" not in k and "norm" not in k):
            a32 = arr.astype(jnp.float32)
            scale = jnp.max(jnp.abs(a32), axis=0, keepdims=True) / 127.0
            scale = jnp.maximum(scale, 1e-8)
            q = jnp.clip(jnp.round(a32 / scale), -127, 127).astype(jnp.int8)
            out[k] = (q, scale.astype(jnp.float32))
        else:
            out[k] = arr
    return out


def _dequant_state(state, dtype):
    """In-trace: (int8, scale) -> dtype weight; XLA fuses the convert +
    scale into the consuming dot's operand read."""
    return {k: ((v[0].astype(jnp.float32) * v[1]).astype(dtype)
                if isinstance(v, tuple) else v)
            for k, v in state.items()}


# ---------------- requests -------------------------------------------------

@dataclass
class GenerationRequest:
    """One decode job (ref: the serving request in analysis_predictor's
    batched Run loop)."""
    prompt: List[int]
    max_new_tokens: int = 32
    eos_token_id: Optional[int] = None
    request_id: Optional[int] = None
    # filled by the engine
    output: List[int] = field(default_factory=list)
    arrived_s: float = 0.0
    finished_s: Optional[float] = None

    @property
    def done(self) -> bool:
        return self.finished_s is not None


class _Slot:
    __slots__ = ("req", "length", "produced", "last_token")

    def __init__(self):
        self.req: Optional[GenerationRequest] = None
        self.length = 0
        self.produced = 0
        self.last_token = 0

    @property
    def free(self):
        return self.req is None


# ---------------- engine ---------------------------------------------------

class ContinuousBatchingEngine:
    """Slot-based continuous batching over the paged-KV decode path.

    model: LlamaForCausalLM (any model exposing config + state_dict with
    the llama cache-forward layout). max_batch = decode slots; max_seq =
    per-slot KV capacity (page-aligned).
    """

    def __init__(self, model, max_batch: int = 4, max_seq: int = 256,
                 prefill_buckets=(32, 64, 128, 256), quantize=None,
                 greedy: bool = True, seed: int = 0):
        from ..models import llama as L
        self.cfg = model.cfg
        self.B = int(max_batch)
        page = 16
        self.S = int(-(-max_seq // page) * page)     # page-aligned
        # always include the full slot capacity so any prompt <= max_seq
        # has a bucket
        self.buckets = tuple(sorted(
            {b for b in prefill_buckets if b < self.S} | {self.S}))
        self.greedy = greedy
        self._fwd = L._forward_with_cache
        raw = {k: t.data for k, t in model.state_dict().items()}
        self.dtype = raw["model.embed_tokens"].dtype
        self.state = (quantize_state_int8(raw) if quantize == "int8"
                      else raw)
        self._quantized = quantize == "int8"
        cfg = self.cfg
        L_, kvh, d = (cfg.num_hidden_layers, cfg.kv_heads, cfg.head_dim)
        self.cache_k = jnp.zeros((L_, self.B, self.S, kvh, d), self.dtype)
        self.cache_v = jnp.zeros_like(self.cache_k)
        self.slots = [_Slot() for _ in range(self.B)]
        self.waiting: List[GenerationRequest] = []
        self.finished: List[GenerationRequest] = []
        self._next_id = 0
        self._key = jax.random.key(seed)
        self._compiled_prefill = {}
        self._compiled_decode = None
        self.ticks = 0

    # -- compiled kernels ---------------------------------------------------

    def _state_arg(self):
        return self.state

    def _prefill_fn(self, T):
        """(state, ids[1,T], n_valid) -> (last_logits[V], k_slot, v_slot)
        — single-sequence prefill producing the slot's cache planes."""
        if T in self._compiled_prefill:
            return self._compiled_prefill[T]
        cfg, S, dt = self.cfg, self.S, self.dtype
        fwd, dq, quant = self._fwd, _dequant_state, self._quantized

        @jax.jit
        def prefill(state, ids, n_valid):
            st = dq(state, dt) if quant else state
            ck = jnp.zeros((cfg.num_hidden_layers, 1, S,
                            cfg.kv_heads, cfg.head_dim), dt)
            cv = jnp.zeros_like(ck)
            logits, ck, cv = fwd(st, cfg, ids, ck, cv,
                                 jnp.zeros((1,), jnp.int32))
            last = jax.lax.dynamic_index_in_dim(
                logits[0], n_valid - 1, axis=0, keepdims=False)
            return last, ck[:, 0], cv[:, 0]

        self._compiled_prefill[T] = prefill
        return prefill

    def _decode_fn(self):
        """(state, toks[B], ck, cv, lens[B], active[B], key) ->
        (next[B], ck, cv) — one token for every active slot."""
        if self._compiled_decode is not None:
            return self._compiled_decode
        cfg, dt = self.cfg, self.dtype
        fwd, dq, quant = self._fwd, _dequant_state, self._quantized
        greedy = self.greedy

        @jax.jit
        def decode(state, toks, ck, cv, lens, active, key):
            st = dq(state, dt) if quant else state
            # [L,B,S,kvh,d] carries per-slot caches; lens is ragged
            logits, ck, cv = fwd(st, cfg, toks[:, None], ck, cv, lens)
            lg = logits[:, 0]
            if greedy:
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            else:
                nxt = jax.random.categorical(key, lg).astype(jnp.int32)
            # inactive slots keep their token and cache position
            nxt = jnp.where(active, nxt, toks)
            return nxt, ck, cv

        self._compiled_decode = decode
        return decode

    # -- scheduler ----------------------------------------------------------

    def add_request(self, req: GenerationRequest):
        if req.request_id is None:
            req.request_id = self._next_id
            self._next_id += 1
        req.arrived_s = time.perf_counter()
        self.waiting.append(req)
        return req.request_id

    def _bucket(self, T):
        for b in self.buckets:
            if T <= b:
                return b
        raise ValueError(f"prompt length {T} exceeds max_seq {self.S}")

    def _admit(self):
        """Move waiting requests into free slots (mid-decode slot reuse:
        the evicted sequence's pages are simply overwritten)."""
        for i, slot in enumerate(self.slots):
            if not self.waiting or not slot.free:
                continue
            req = self.waiting.pop(0)
            T = len(req.prompt)
            bucket = self._bucket(T)
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :T] = req.prompt
            last, k_slot, v_slot = self._prefill_fn(bucket)(
                self._state_arg(), jnp.asarray(ids), np.int32(T))
            tok = int(np.argmax(np.asarray(last)))
            self.cache_k = self.cache_k.at[:, i].set(k_slot)
            self.cache_v = self.cache_v.at[:, i].set(v_slot)
            slot.req = req
            slot.length = T
            slot.produced = 1
            slot.last_token = tok
            req.output.append(tok)
            self._maybe_finish(i)

    def _maybe_finish(self, i):
        slot = self.slots[i]
        req = slot.req
        if req is None:
            return
        eos_hit = (req.eos_token_id is not None
                   and req.output and req.output[-1] == req.eos_token_id)
        full = slot.length + 1 > self.S - 1
        if slot.produced >= req.max_new_tokens or eos_hit or full:
            req.finished_s = time.perf_counter()
            self.finished.append(req)
            slot.req = None          # slot + pages reusable immediately

    def step(self) -> List[GenerationRequest]:
        """One scheduler tick: admit into free slots, then one decode
        step for every active slot. Returns requests finished this tick."""
        n_done_before = len(self.finished)
        self._admit()
        active = np.array([not s.free for s in self.slots])
        if active.any():
            toks = np.array([s.last_token for s in self.slots], np.int32)
            lens = np.array([s.length for s in self.slots], np.int32)
            self._key, sub = jax.random.split(self._key)
            nxt, self.cache_k, self.cache_v = self._decode_fn()(
                self._state_arg(), jnp.asarray(toks), self.cache_k,
                self.cache_v, jnp.asarray(lens), jnp.asarray(active), sub)
            nxt = np.asarray(nxt)
            for i, slot in enumerate(self.slots):
                if slot.free:
                    continue
                slot.length += 1
                slot.produced += 1
                slot.last_token = int(nxt[i])
                slot.req.output.append(slot.last_token)
                self._maybe_finish(i)
        self.ticks += 1
        return self.finished[n_done_before:]

    @property
    def has_work(self):
        return bool(self.waiting) or any(not s.free for s in self.slots)

    def run(self, requests: Optional[List[GenerationRequest]] = None,
            arrivals: Optional[List[float]] = None, max_ticks: int = 10000):
        """Drive until drained. `arrivals[i]` (seconds from start) delays
        request i's admission — the staggered-arrival serving pattern."""
        requests = requests or []
        order = sorted(range(len(requests)),
                       key=lambda i: (arrivals[i] if arrivals else 0.0))
        t0 = time.perf_counter()
        pending = [(arrivals[i] if arrivals else 0.0, requests[i])
                   for i in order]
        for _ in range(max_ticks):
            now = time.perf_counter() - t0
            while pending and pending[0][0] <= now:
                self.add_request(pending[0][1])
                pending.pop(0)
            if not self.has_work and not pending:
                break
            if not self.has_work and pending:
                time.sleep(max(0.0, pending[0][0] - now))
                continue
            self.step()
        return self.finished
