"""Fault-tolerant serving fleet: replica supervision + cache-affinity
failover routing over `inference.serve` replicas (ISSUE 17).

One serving process on one chip dies with a single SIGKILL, wedged tick
or deploy. This module is the data plane that survives all three,
stdlib-only in its own logic (ThreadingHTTPServer + http.client — no
jax, no numpy — the same discipline as `gateway.py` and
`observability/federation.py`, so a routing tier bakes into a serving
image without a backend):

* `ReplicaSupervisor` — spawns N `python -m paddle_tpu.inference.serve`
  subprocesses and relaunches dead ones under fresh INCARNATION ids
  with capped exponential backoff and a restart budget (the
  `launch --elastic_level 1` supervisor idiom). Every lifecycle event —
  replica_spawn / replica_death / replica_relaunch / replica_giveup /
  replica_eject / replica_readmit / replica_drained — lands as one
  crash-safe JSONL line (`observability.export.append_jsonl`), the
  flight-recorder record a postmortem greps.

* `FleetRouter` — one `POST /v1/generate` front door over the replica
  set:

  - **prefix-affinity routing**: the request prompt's first full page
    is hashed with `chain_key` — the SAME blake2b chain the engine's
    `_PrefixCache` keys on (`serving._PrefixCache._key` delegates here,
    so router and replica agree by construction) — and looked up in
    each replica's exported heat oracle (`health_snapshot()` →
    `prefix_cache.heat`, refreshed by the active prober). The replica
    already holding the hot prefix gets the request and its 8x
    shared-prefix TTFT win; cold prompts go least-loaded.
  - **failure detection**: passive (connect errors, mid-stream socket
    death during a relay) plus an active `/healthz` prober; failures
    EJECT a replica from rotation, probe-success streaks re-admit it.
  - **failure handling end-to-end**: a request that has not yet
    streamed a token fails over transparently to another replica with
    bounded retries + jittered backoff; a mid-stream death emits a
    structured `event: error` SSE frame (never a silent hang);
    429+Retry-After from a replica redirects to the next candidate and
    sheds at FLEET scope (min observed Retry-After, clamped) only when
    every replica is backpressured.
  - **fleet `/metrics` + `/healthz`**: per-replica registry snapshots
    (each replica publishes `metrics.rank{R}.inc{K}.json` via
    FLAGS_metrics_snapshot) merge through
    `observability.federation.merge_snapshots` — counters sum into
    job-level cells, gauges stay per-replica, relaunched incarnations
    relabel — with the router's own registry riding along as
    rank="router". `/healthz` answers 200 while ANY replica can take
    work, so a 1-of-N death never flips the fleet unready.

Fault points: `router.dispatch` (each dispatch attempt),
`router.probe` (each active health probe), `router.relaunch` (each
supervisor respawn) — schedule via FLAGS_fault_inject, same grammar as
every other chaos point.

`python -m paddle_tpu.inference.fleet` (fleet.py) wires both into a
CLI with a rolling SIGTERM drain: stop accepting at the router, then
SIGTERM replicas one at a time through their existing drain semantics
— zero dropped in-flight streams, the zero-downtime rollout primitive.
"""
from __future__ import annotations

import hashlib
import http.client
import json
import math
import os
import random
import re
import signal
import struct
import subprocess
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..observability import export as _oexp
from ..observability import federation as _ofed
from ..observability import metrics as _metrics
from ..observability import reqtrace as _rtrace
from ..utils.fault_injection import fault_point

__all__ = ["chain_key", "head_key_hex", "Replica", "ReplicaSupervisor",
           "FleetRouter", "RETRY_AFTER_CEILING_S"]

# ceiling for every Retry-After the fleet emits or relays: a degenerate
# throughput estimate must never tell a client to come back in an hour
RETRY_AFTER_CEILING_S = 60.0

_ROUTED = _metrics.counter(
    "router.routed_total",
    "requests dispatched to a replica, labeled by replica index")
_AFFINITY = _metrics.counter(
    "router.affinity_hits_total",
    "dispatches that followed the prefix-cache heat oracle (the "
    "router-side cache-hit counter), labeled by replica index")
_FAILOVER = _metrics.counter(
    "router.failovers_total",
    "dispatch attempts abandoned for another replica, labeled by the "
    "replica that failed")
_SHED = _metrics.counter(
    "router.sheds_total",
    "requests answered 429/503 at fleet scope (no replica available)")
_EJECT = _metrics.counter(
    "router.ejections_total",
    "replicas removed from rotation, labeled by replica index")
_READMIT = _metrics.counter(
    "router.readmissions_total",
    "ejected replicas returned to rotation, labeled by replica index")
_RELAUNCH = _metrics.counter(
    "router.relaunches_total",
    "dead replica respawns, labeled by replica index")


# ---------------- the shared chain hash -------------------------------------

def chain_key(parent: bytes, toks) -> bytes:
    """The `_PrefixCache` chain hash — THE single source of truth:
    blake2b(parent_key, digest_size=16) over the page's token ids as
    little-endian int64 (bit-identical to the engine's former
    `np.asarray(toks, np.int64).tobytes()` form). `serving._PrefixCache`
    delegates its `_key` here, so the router's affinity lookup and the
    replica's cache index can never disagree about what a prefix
    hashes to."""
    h = hashlib.blake2b(parent, digest_size=16)
    h.update(struct.pack("<%dq" % len(toks), *(int(t) for t in toks)))
    return h.digest()


def head_key_hex(prompt, page_size: int) -> Optional[str]:
    """Chain-HEAD key (hex) of `prompt`'s first full page — the unit the
    heat oracle is keyed on — or None when the prompt has no cacheable
    page. Mirrors `_PrefixCache.lookup`'s `(len-1)//page` rule: at
    least one trailing token always stays uncached, so a prompt needs
    page_size+1 tokens before its head page can be indexed."""
    if page_size <= 0 or (len(prompt) - 1) // page_size < 1:
        return None
    return chain_key(b"", prompt[:page_size]).hex()


# ---------------- replica state ---------------------------------------------

class Replica:
    """One serving backend as the fleet sees it. The supervisor owns
    spawn/port/incarnation, the router owns routing state — both under
    the router's lock once attached."""

    def __init__(self, idx: int, host: str = "127.0.0.1",
                 port: Optional[int] = None):
        self.idx = int(idx)
        self.host = host
        self.port = port
        self.incarnation = 0
        self.pid: Optional[int] = None
        # starting -> healthy <-> ejected; dead = restart budget spent
        self.state = "starting"
        self.accepting = True        # optimistic until the first probe
        self.retry_after_s = 1.0
        self.heat: Dict[str, int] = {}   # chain-head hex -> cached pages
        self.heat_page_size = 0
        # heat freshness (ISSUE 18 satellite): when the map was last
        # refreshed (router monotonic clock) and the cache epoch it
        # reflects — affinity ignores a map older than heat_ttl_s, so a
        # silent replica cannot keep attracting its old hot prefixes
        self.heat_mono = 0.0
        self.heat_epoch = -1
        self.consecutive_fail = 0
        self.consecutive_ok = 0
        self.inflight = 0
        self.routed_total = 0
        self.affinity_hits = 0
        self.failovers = 0
        self.ejections = 0

    @property
    def routable(self) -> bool:
        return (self.port is not None
                and self.state in ("starting", "healthy"))

    def stats(self) -> dict:
        return {"idx": self.idx, "port": self.port, "pid": self.pid,
                "incarnation": self.incarnation, "state": self.state,
                "accepting": self.accepting, "inflight": self.inflight,
                "routed_total": self.routed_total,
                "affinity_hits": self.affinity_hits,
                "failovers": self.failovers,
                "ejections": self.ejections,
                "hot_prefixes": len(self.heat)}


# ---------------- replica supervision ---------------------------------------

_STARTUP_PORT_RE = re.compile(r"http://[^:\s]+:(\d+)")


class ReplicaSupervisor:
    """Spawn N replica subprocesses; relaunch the dead under fresh
    incarnation ids with capped backoff (the `launch --elastic_level 1`
    idiom scaled down to one host). Ports are discovered from each
    child's startup line (`serving on http://host:port ...` — children
    run `--port 0`), so a relaunched replica may come back on a NEW
    port: the shared `Replica` record is updated in place and the
    router's next probe picks it up.

    Each child gets PADDLE_TRAINER_ID / PADDLE_INCARNATION plus
    FLAGS_metrics_snapshot=<log_dir>/metrics.rank{R}.inc{K}.json, so
    the fleet /metrics merge sees exactly the federation layer's
    per-rank snapshot files."""

    def __init__(self, argv_factory, nreplicas: int,
                 host: str = "127.0.0.1", log_dir: Optional[str] = None,
                 events_path: Optional[str] = None,
                 max_restarts: int = 5, backoff_base_s: float = 0.5,
                 backoff_cap_s: float = 8.0):
        self.argv_factory = argv_factory
        self.replicas = [Replica(i, host=host) for i in range(nreplicas)]
        self.log_dir = log_dir
        self.events_path = events_path
        self.max_restarts = int(max_restarts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.draining = False
        self._procs: Dict[int, subprocess.Popen] = {}
        self._restarts: Dict[int, int] = {}
        self._respawn_at: Dict[int, float] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._rng = random.Random(0xF1EE7)

    # -- flight recorder ------------------------------------------------------

    def record(self, rec: dict) -> None:
        """One JSONL flight-recorder line (append + flush: survives the
        supervisor itself being killed). Also the router's eject/readmit
        recorder when wired through FleetRouter(recorder=...)."""
        if self.events_path:
            try:
                _oexp.append_jsonl(self.events_path,
                                   {"ts": round(time.time(), 3), **rec})
            except OSError:
                pass                 # telemetry must not kill the fleet

    # -- spawn / relaunch -----------------------------------------------------

    def _spawn(self, rep: Replica) -> None:
        env = dict(os.environ)
        env["PADDLE_TRAINER_ID"] = str(rep.idx)
        env["PADDLE_INCARNATION"] = str(rep.incarnation)
        if self.log_dir:
            env["FLAGS_metrics_snapshot"] = os.path.join(
                self.log_dir,
                f"metrics.rank{rep.idx}.inc{rep.incarnation}.json")
            # per-replica request-trace JSONL sink (ISSUE 18): written
            # through live, so the router can still serve
            # GET /v1/trace/<id> for a replica that died by SIGKILL
            env["FLAGS_request_trace_sink"] = os.path.join(
                self.log_dir,
                f"trace.rank{rep.idx}.inc{rep.incarnation}.jsonl")
        p = subprocess.Popen(
            self.argv_factory(rep), env=env, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        with self._lock:
            self._procs[rep.idx] = p
            rep.pid = p.pid
            rep.port = None
            rep.state = "starting"
            rep.accepting = True
            rep.heat = {}
            rep.consecutive_ok = rep.consecutive_fail = 0
        # stdout pump exits on the child's EOF (child death IS the
        # join)  # graft-lint: disable=thread-hygiene
        threading.Thread(target=self._read_child, args=(rep, p),
                         daemon=True,
                         name=f"replica{rep.idx}-stdout").start()
        self.record({"ev": "replica_spawn", "replica": rep.idx,
                     "incarnation": rep.incarnation, "pid": p.pid})

    def _read_child(self, rep: Replica, p: subprocess.Popen) -> None:
        """Tee the child's stdout to a per-incarnation log and parse the
        startup line for its port (children run `--port 0`)."""
        logf = None
        if self.log_dir:
            try:
                logf = open(os.path.join(
                    self.log_dir,
                    f"replica{rep.idx}.inc{rep.incarnation}.log"), "a")
            except OSError:
                logf = None
        try:
            for line in p.stdout:
                if logf is not None:
                    logf.write(line)
                    logf.flush()
                if rep.port is None and "serving on http://" in line:
                    m = _STARTUP_PORT_RE.search(line)
                    if m:
                        with self._lock:
                            rep.port = int(m.group(1))
        except (OSError, ValueError):
            pass
        finally:
            if logf is not None:
                logf.close()

    def start(self) -> "ReplicaSupervisor":
        for rep in self.replicas:
            self._spawn(rep)
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._monitor, daemon=True, name="fleet-supervisor")
            self._thread.start()
        return self

    def wait_ready(self, timeout: float = 120.0) -> None:
        """Block until every non-dead replica has reported a port."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                pending = [r for r in self.replicas
                           if r.state != "dead" and r.port is None]
            if not pending:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"replicas never reported a port: "
            f"{[r.idx for r in pending]}")

    def _monitor(self) -> None:
        """Death watch: a dead child (outside a drain) is relaunched
        under the next incarnation after a capped, jittered backoff;
        the restart budget turns a crash LOOP into a terminal 'dead'
        state instead of an infinite respawn storm."""
        while not self._stop.wait(0.1):
            now = time.monotonic()
            for rep in self.replicas:
                with self._lock:
                    p = self._procs.get(rep.idx)
                    due = self._respawn_at.get(rep.idx)
                if due is not None:
                    if now >= due:
                        with self._lock:
                            self._respawn_at.pop(rep.idx, None)
                            rep.incarnation += 1
                        fault_point("router.relaunch")
                        self._spawn(rep)
                        _RELAUNCH.inc(replica=str(rep.idx))
                        self.record({"ev": "replica_relaunch",
                                     "replica": rep.idx,
                                     "incarnation": rep.incarnation})
                    continue
                if p is None or p.poll() is None or self.draining:
                    continue
                if rep.state == "dead":
                    continue
                rc = p.returncode
                self.record({"ev": "replica_death", "replica": rep.idx,
                             "incarnation": rep.incarnation, "rc": rc})
                with self._lock:
                    self._procs.pop(rep.idx, None)
                    rep.port = None
                    rep.state = "ejected"   # out of rotation immediately
                    n = self._restarts[rep.idx] = \
                        self._restarts.get(rep.idx, 0) + 1
                if n > self.max_restarts:
                    with self._lock:
                        rep.state = "dead"
                    self.record({"ev": "replica_giveup",
                                 "replica": rep.idx,
                                 "restarts": n - 1})
                    continue
                backoff = min(self.backoff_cap_s,
                              self.backoff_base_s * (2 ** (n - 1)))
                backoff *= 0.5 + self._rng.random()   # jitter 0.5x-1.5x
                with self._lock:
                    self._respawn_at[rep.idx] = now + backoff

    # -- drain / stop ---------------------------------------------------------

    def drain_rolling(self, per_replica_timeout: float = 60.0) -> bool:
        """Rolling drain, one replica at a time: SIGTERM (the child's
        own graceful-drain contract — finish in-flight streams, then
        exit), wait for exit, move on. Returns True when every child
        exited inside its budget. Marks the supervisor draining FIRST
        so the death watch never relaunches a drained replica."""
        self.draining = True
        ok = True
        for rep in self.replicas:
            with self._lock:
                p = self._procs.get(rep.idx)
            if p is None or p.poll() is not None:
                continue
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                continue
            try:
                p.wait(timeout=per_replica_timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
                ok = False
            self.record({"ev": "replica_drained", "replica": rep.idx,
                         "incarnation": rep.incarnation,
                         "rc": p.returncode})
        return ok

    def stop(self) -> None:
        self.draining = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        with self._lock:
            procs = list(self._procs.values())
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass


# ---------------- the fleet router ------------------------------------------

class FleetRouter:
    """Cache-affinity failover router over a set of `Replica` backends.
    See the module docstring for the routing / failure-handling /
    metrics contracts. `replicas` may come from a `ReplicaSupervisor`
    (shared records, updated across relaunches) or be built from static
    `endpoints=[(host, port), ...]` for in-process fleets (tests,
    serving_bench)."""

    def __init__(self, replicas: Optional[List[Replica]] = None,
                 endpoints: Optional[List[Tuple[str, int]]] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 snapshot_dir: Optional[str] = None,
                 probe_interval_s: float = 0.5,
                 probe_timeout_s: float = 2.0,
                 eject_after: int = 2, readmit_after: int = 2,
                 max_retries: int = 3, backoff_base_s: float = 0.05,
                 backoff_cap_s: float = 0.5,
                 stream_timeout_s: float = 30.0,
                 policy: str = "affinity", recorder=None,
                 heat_ttl_s: float = 5.0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        if replicas is None:
            replicas = [Replica(i, host=h, port=p)
                        for i, (h, p) in enumerate(endpoints or [])]
        if not replicas:
            raise ValueError("router needs replicas= or endpoints=")
        if policy not in ("affinity", "random"):
            raise ValueError(f"unknown routing policy {policy!r}")
        self.replicas = replicas
        self.snapshot_dir = snapshot_dir
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.eject_after = int(eject_after)
        self.readmit_after = int(readmit_after)
        self.max_retries = int(max_retries)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.stream_timeout_s = float(stream_timeout_s)
        self.policy = policy
        self.recorder = recorder
        self.heat_ttl_s = float(heat_ttl_s)
        self.draining = False
        self.inflight = 0
        # trace id -> this router's failover-hop records (bounded LRU):
        # the fleet-scope /v1/trace/<id> merge names every hop a
        # request took even when a replica's sink never saw it
        self._trace_hops: "OrderedDict[str, list]" = OrderedDict()
        self.lock = threading.RLock()
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        self._rng = random.Random(0x5EED)
        rt = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"   # close-delimited SSE bodies

            def log_message(self, *a):
                pass

            def do_GET(self):
                rt._handle_get(self)

            def do_POST(self):
                rt._handle_post(self)

        self._server = ThreadingHTTPServer((host, int(port)), _Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------------

    def start(self, probe: bool = True) -> int:
        """Serve; `probe=False` skips the background prober (tests and
        benches drive `probe_all()` by hand for determinism)."""
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._server.serve_forever, name="router-http",
                daemon=True)
            self._thread.start()
        if probe and (self._probe_thread is None
                      or not self._probe_thread.is_alive()):
            self._stop.clear()
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="router-probe", daemon=True)
            self._probe_thread.start()
        return self.port

    def drain(self) -> None:
        """Stop accepting new work (healthz + submits flip 503);
        in-flight relays keep streaming — the rolling-drain first
        phase."""
        self.draining = True

    def wait_idle(self, timeout: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self.lock:
                if self.inflight == 0:
                    return True
            time.sleep(0.02)
        return False

    def stop(self) -> None:
        self._stop.set()
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)

    def _record(self, rec: dict) -> None:
        if self.recorder is not None:
            try:
                self.recorder(rec)
            except Exception:
                pass

    # -- active probing / ejection / re-admission -----------------------------

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            self.probe_all()
            self._stop.wait(self.probe_interval_s)

    def probe_all(self) -> None:
        for rep in self.replicas:
            if rep.state == "dead" or rep.port is None:
                continue
            self._probe_one(rep)

    def _probe_one(self, rep: Replica) -> None:
        try:
            fault_point("router.probe")
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=self.probe_timeout_s)
            conn.request("GET", "/healthz")
            r = conn.getresponse()
            body = json.loads(r.read() or b"{}")
            status = r.status
            conn.close()
        except Exception:
            self._probe_failed(rep)
            return
        # any well-formed answer means the process is ALIVE — 503 only
        # says it is draining/saturated, which gates routing via
        # `accepting`, not membership
        with self.lock:
            rep.consecutive_fail = 0
            rep.consecutive_ok += 1
            rep.accepting = status == 200
            eng = body.get("engine") or {}
            rep.retry_after_s = float(eng.get("retry_after_s", 1.0))
            inc = body.get("incarnation")
            if inc is not None:
                try:
                    rep.incarnation = int(inc)
                except (TypeError, ValueError):
                    pass
            pc = eng.get("prefix_cache") or {}
            heat = pc.get("heat")
            if isinstance(heat, dict):
                rep.heat = {str(k): int(v) for k, v in heat.items()}
                rep.heat_page_size = int(pc.get("page_size", 0))
                rep.heat_mono = time.monotonic()   # freshness stamp
                try:
                    rep.heat_epoch = int(pc.get("epoch", -1))
                except (TypeError, ValueError):
                    rep.heat_epoch = -1
            if rep.state == "starting":
                rep.state = "healthy"
            elif (rep.state == "ejected"
                    and rep.consecutive_ok >= self.readmit_after):
                rep.state = "healthy"
                _READMIT.inc(replica=str(rep.idx))
                self._record({"ev": "replica_readmit",
                              "replica": rep.idx,
                              "incarnation": rep.incarnation})

    def _probe_failed(self, rep: Replica) -> None:
        with self.lock:
            rep.consecutive_ok = 0
            rep.consecutive_fail += 1
            if (rep.state == "healthy"
                    and rep.consecutive_fail >= self.eject_after):
                self._eject(rep, "probe failures")

    def _eject(self, rep: Replica, reason: str) -> None:
        """Caller holds self.lock."""
        if rep.state in ("ejected", "dead"):
            return
        rep.state = "ejected"
        rep.ejections += 1
        rep.consecutive_ok = 0
        # its cache is gone with the process (a relaunch starts cold):
        # drop the heat map NOW so re-admission cannot route by a
        # dead incarnation's prefixes before the next probe refresh
        rep.heat = {}
        rep.heat_epoch = -1
        _EJECT.inc(replica=str(rep.idx))
        self._record({"ev": "replica_eject", "replica": rep.idx,
                      "incarnation": rep.incarnation, "reason": reason})

    def _passive_fail(self, rep: Replica, reason: str) -> None:
        """Connect/mid-stream failure observed on the request path: the
        replica leaves rotation NOW (a refused connect means the
        process is gone — waiting out eject_after probes would keep
        routing real traffic at a corpse); the prober re-admits it
        after `readmit_after` consecutive successes."""
        with self.lock:
            rep.consecutive_ok = 0
            rep.consecutive_fail += 1
            self._eject(rep, reason)

    # -- routing --------------------------------------------------------------

    def _head_hex(self, prompt) -> Optional[str]:
        if not isinstance(prompt, list) or not prompt or \
                not all(isinstance(t, int) for t in prompt):
            return None
        with self.lock:
            page = next((r.heat_page_size for r in self.replicas
                         if r.heat_page_size), 0)
        return head_key_hex(prompt, page) if page else None

    def _pick(self, head_hex: Optional[str],
              exclude: set) -> Tuple[Optional[Replica], bool]:
        """(replica, via_affinity). Healthy+accepting candidates first;
        'starting' replicas count too (optimistic first contact — a
        failure ejects them through the passive path)."""
        with self.lock:
            cands = [r for r in self.replicas
                     if r.routable and r.accepting
                     and r.idx not in exclude]
            if not cands:
                return None, False
            if self.policy == "random":
                return self._rng.choice(cands), False
            if head_hex:
                # stale-heat expiry (ISSUE 18 satellite): a map older
                # than heat_ttl_s no longer predicts the replica's
                # cache — fall through to least-loaded instead of
                # chasing prefixes that were likely evicted since
                fresh_after = time.monotonic() - self.heat_ttl_s
                hot = [r for r in cands
                       if r.heat.get(head_hex)
                       and r.heat_mono >= fresh_after]
                if hot:
                    return max(hot, key=lambda r: (r.heat[head_hex],
                                                   -r.inflight)), True
            return min(cands, key=lambda r: (r.inflight, r.idx)), False

    # -- GET ------------------------------------------------------------------

    def _handle_get(self, h) -> None:
        path = h.path.split("?", 1)[0].rstrip("/")
        if path == "/healthz":
            self._healthz(h)
        elif path.startswith("/v1/trace/"):
            tid = path.rsplit("/", 1)[1]
            snap = self.trace_lookup(tid)
            if snap is None:
                self._json(h, 404, {"error": f"unknown trace {tid!r}"})
            else:
                self._json(h, 200, snap)
        elif path in ("", "/metrics"):
            try:
                text = self.metrics_text()
            except Exception as exc:
                self._json(h, 500,
                           {"error": f"{type(exc).__name__}: {exc}"})
                return
            self._raw(h, 200, "text/plain; version=0.0.4",
                      text.encode())
        else:
            self._json(h, 404, {"error": f"no route for {h.path!r}"})

    def _healthz(self, h) -> None:
        with self.lock:
            stats = [r.stats() for r in self.replicas]
            usable = [r for r in self.replicas
                      if r.routable and r.accepting]
            hints = [r.retry_after_s for r in self.replicas
                     if r.port is not None]
        accepting = bool(usable) and not self.draining
        body = {"accepting": accepting, "draining": self.draining,
                "port": self.port, "policy": self.policy,
                "healthy_replicas": len(usable),
                "replicas": stats}
        extra = {}
        if not accepting:
            extra["Retry-After"] = _retry_after_header(
                min(hints) if hints else 1.0)
        self._json(h, 200 if accepting else 503, body, extra)

    def metrics_text(self) -> str:
        """Fleet-level exposition: every replica's published registry
        snapshot + the router's own registry, merged through
        federation's defined semantics (counters sum into job-level
        cells, gauges keep per-rank cells, stale/superseded
        incarnations flagged)."""
        snaps = []
        if self.snapshot_dir:
            snaps = _ofed.read_snapshots(self.snapshot_dir)
        snaps.append({"ts": time.time(), "metrics": _metrics.snapshot(),
                      "rank": "router", "incarnation": "0"})
        return _oexp.prometheus_text(_ofed.merge_snapshots(snaps))

    # -- fleet-scope trace view (ISSUE 18) -----------------------------------

    def trace_lookup(self, tid: str) -> Optional[dict]:
        """Merge every view of one trace id across the fleet: the
        per-replica JSONL sinks under snapshot_dir (written through
        live, so they survive a SIGKILLed replica), this router's own
        failover-hop records, and — when no sink is configured — the
        live replicas' /v1/trace endpoints. None when nobody has it."""
        out = {"trace_id": tid, "terminal": False, "events": [],
               "hops": [], "replicas": []}
        found = False
        if self.snapshot_dir:
            pat = re.compile(r"trace\.rank(\d+)\.inc(\d+)\.jsonl$")
            try:
                names = sorted(os.listdir(self.snapshot_dir))
            except OSError:
                names = []
            for name in names:
                m = pat.match(name)
                if not m:
                    continue
                evs, term = _scan_trace_jsonl(
                    os.path.join(self.snapshot_dir, name), tid)
                if not evs and term is None:
                    continue
                found = True
                src = {"replica": int(m.group(1)),
                       "incarnation": int(m.group(2))}
                out["replicas"].append(src)
                # live event lines already include the terminal event
                # (finish() streams it before the terminal record), so
                # the timeline needs no extraction from `term`
                out["events"].extend({**e, **src} for e in evs)
                if term is not None:
                    out["terminal"] = True
                    for k in ("status", "wall", "buckets",
                              "decode_ticks"):
                        if k in term:
                            out[k] = term[k]
        else:
            for rep in list(self.replicas):
                if not rep.routable:
                    continue
                try:
                    conn = http.client.HTTPConnection(
                        rep.host, rep.port,
                        timeout=self.probe_timeout_s)
                    conn.request("GET", f"/v1/trace/{tid}")
                    r = conn.getresponse()
                    body = json.loads(r.read() or b"{}")
                    status = r.status
                    conn.close()
                except Exception:
                    continue
                if status != 200:
                    continue
                found = True
                src = {"replica": rep.idx,
                       "incarnation": rep.incarnation}
                out["replicas"].append(src)
                out["events"].extend(
                    {**e, **src} for e in body.get("events", ()))
                if body.get("terminal"):
                    out["terminal"] = True
                    for k in ("status", "wall", "buckets",
                              "decode_ticks"):
                        if k in body:
                            out[k] = body[k]
        with self.lock:
            hops = list(self._trace_hops.get(tid, ()))
        if hops:
            found = True
            out["hops"] = hops
        if not found:
            return None
        out["events"].sort(key=lambda e: e.get("ts", 0))
        return out

    def _note_hop(self, tid: Optional[str], hop: int, rep: Replica,
                  reason: str) -> None:
        """One failover hop: flight-recorder line (fleet_events.jsonl,
        trace id echoed — the satellite contract) + the bounded
        in-router store the fleet trace view merges from."""
        rec = {"ev": "failover_hop", "hop": hop, "replica": rep.idx,
               "incarnation": rep.incarnation, "reason": reason,
               "ts": round(time.time(), 3)}
        if tid:
            rec["trace_id"] = tid
        self._record(rec)
        if not tid:
            return
        with self.lock:
            self._trace_hops.setdefault(tid, []).append(rec)
            while len(self._trace_hops) > 512:
                self._trace_hops.popitem(last=False)

    # -- POST (the request plane) --------------------------------------------

    def _handle_post(self, h) -> None:
        path = h.path.split("?", 1)[0].rstrip("/")
        if path not in ("/v1/generate", "/v1/infer"):
            self._json(h, 404, {"error": f"no route for {h.path!r}"})
            return
        try:
            n = int(h.headers.get("Content-Length") or 0)
            raw = h.rfile.read(n) if n else b"{}"
            try:
                spec = json.loads(raw or b"{}")
            except ValueError:
                spec = {}
            with self.lock:
                self.inflight += 1
            try:
                self._dispatch(h, path, raw, spec)
            finally:
                with self.lock:
                    self.inflight -= 1
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as exc:       # one request fails, not the router
            try:
                self._json(h, 500,
                           {"error": f"{type(exc).__name__}: {exc}"})
            except Exception:
                pass

    def _dispatch(self, h, path: str, raw: bytes, spec: dict) -> None:
        if self.draining:
            self._json(h, 503, {"error": "fleet is draining"},
                       {"Retry-After": "1"})
            return
        head = self._head_hex(spec.get("prompt")) \
            if path == "/v1/generate" else None
        state = {"headers_sent": False, "tokens": 0, "terminal": False,
                 "trace_id": None}
        tid: Optional[str] = None
        t0 = time.perf_counter()
        if path == "/v1/generate":
            # request-scope tracing (ISSUE 18): honor the client's id
            # (X-Request-Trace or W3C traceparent), mint otherwise —
            # ONE id for every hop this request takes across the fleet
            tid = (_rtrace.parse_trace_header(
                h.headers.get("X-Request-Trace")
                or h.headers.get("traceparent"))
                or _rtrace.mint_trace_id())
            state["trace_id"] = tid
        tried: set = set()
        saw_429: Optional[float] = None
        for attempt in range(self.max_retries + 1):
            rep, via_affinity = self._pick(head, tried)
            if rep is None:
                break
            headers = {"Content-Type": "application/json"}
            if tid:
                headers["X-Request-Trace"] = tid
                # seconds already burned at the router (failed hops,
                # backoff) — the replica preloads this into the
                # `failover` bucket so its ledger sums to the
                # CLIENT-observed wall, not just its own
                headers["X-Trace-Failover-S"] = (
                    "%.6f" % (time.perf_counter() - t0))
            try:
                # inside the try: an armed raise is indistinguishable
                # from a connect failure, so it drives the real
                # bounded-retry failover path
                fault_point("router.dispatch")
                conn = http.client.HTTPConnection(
                    rep.host, rep.port, timeout=self.stream_timeout_s)
                conn.request("POST", path, body=raw, headers=headers)
                resp = conn.getresponse()
            except Exception:
                self._passive_fail(rep, "connect/submit failed")
                tried.add(rep.idx)
                with self.lock:
                    rep.failovers += 1
                _FAILOVER.inc(replica=str(rep.idx))
                self._note_hop(tid, attempt, rep, "connect/submit failed")
                self._backoff(attempt)
                continue
            if resp.status == 429:
                # redirect-then-shed: remember the hint, try the next
                # candidate; only a fully backpressured fleet sheds
                saw_429 = self._min_hint(saw_429, resp)
                with self.lock:
                    rep.accepting = False
                    if saw_429 is not None:
                        rep.retry_after_s = saw_429
                tried.add(rep.idx)
                conn.close()
                continue
            if resp.status in (500, 503) and not _has_outcome(resp):
                # replica-health failure (draining gateway / handler
                # crash), NOT a generation outcome — fail over.
                # _has_outcome consumed the body; the conn is done.
                tried.add(rep.idx)
                with self.lock:
                    rep.accepting = False
                    rep.failovers += 1
                _FAILOVER.inc(replica=str(rep.idx))
                self._note_hop(tid, attempt, rep, "replica unhealthy")
                conn.close()
                self._backoff(attempt)
                continue
            # a real answer (stream, JSON outcome, or a 4xx the client
            # must see) — account the dispatch and relay it
            with self.lock:
                rep.routed_total += 1
                rep.inflight += 1
                if via_affinity:
                    rep.affinity_hits += 1
            _ROUTED.inc(replica=str(rep.idx))
            if via_affinity:
                _AFFINITY.inc(replica=str(rep.idx))
            try:
                ctype = resp.getheader("Content-Type", "") or ""
                if resp.status == 200 and "text/event-stream" in ctype:
                    outcome = self._relay_sse(h, resp, rep, state)
                else:
                    outcome = self._relay_plain(h, resp)
            finally:
                with self.lock:
                    rep.inflight -= 1
                conn.close()
            if outcome == "retry":
                # upstream died before ANY token reached the client:
                # transparent failover
                self._passive_fail(rep, "died before first token")
                tried.add(rep.idx)
                with self.lock:
                    rep.failovers += 1
                _FAILOVER.inc(replica=str(rep.idx))
                self._note_hop(tid, attempt, rep,
                               "died before first token")
                self._backoff(attempt)
                continue
            if outcome == "mid_stream_death":
                # tokens already streamed — the stream cannot be
                # replayed; the client got a structured error frame
                self._passive_fail(rep, "died mid-stream")
                with self.lock:
                    rep.failovers += 1
                _FAILOVER.inc(replica=str(rep.idx))
                self._note_hop(tid, attempt, rep, "died mid-stream")
            return
        # candidates exhausted: shed at fleet scope
        _SHED.inc()
        with self.lock:
            hints = [r.retry_after_s for r in self.replicas
                     if r.port is not None and r.state != "dead"]
        if saw_429 is not None:
            hints.append(saw_429)
        retry = min(hints) if hints else 1.0
        if state["headers_sent"]:
            self._error_frame(h, state, "shed",
                              "no replica available (fleet saturated)")
            return
        status = 429 if saw_429 is not None else 503
        self._json(h, status,
                   {"error": "no replica available",
                    "retry_after_s": round(
                        _clamp_retry(retry), 3)},
                   {"Retry-After": _retry_after_header(retry)})

    def _backoff(self, attempt: int) -> None:
        base = min(self.backoff_cap_s,
                   self.backoff_base_s * (2 ** attempt))
        time.sleep(base * (0.5 + self._rng.random() * 0.5))

    @staticmethod
    def _min_hint(cur: Optional[float], resp) -> Optional[float]:
        try:
            resp.read()              # drain the 429 body
        except Exception:
            pass
        try:
            hint = float(resp.getheader("Retry-After", "1") or 1)
        except ValueError:
            hint = 1.0
        return hint if cur is None else min(cur, hint)

    # -- relays ---------------------------------------------------------------

    def _relay_sse(self, h, resp, rep: Replica, state: dict) -> str:
        """Frame-preserving SSE relay: upstream bytes are split on the
        frame delimiter and re-emitted VERBATIM (byte-identical bodies
        — the nreplicas=1 parity bar), while the router tracks whether
        a token frame has reached the client (the failover window) and
        whether the terminal frame arrived (anything else is a
        mid-stream death). Returns 'done' | 'retry' |
        'mid_stream_death' | 'client_gone'."""
        if not state["headers_sent"]:
            h.send_response(200)
            h.send_header("Content-Type", "text/event-stream")
            h.send_header("Cache-Control", "no-cache")
            h.send_header("Connection", "close")
            if state.get("trace_id"):
                # relays forward only body frames, so the router must
                # re-stamp the correlation header itself
                h.send_header("X-Request-Id", state["trace_id"])
            h.end_headers()
            state["headers_sent"] = True
        buf = b""
        while True:
            try:
                chunk = resp.read1(65536)
            except Exception:
                chunk = b""              # upstream died / read timeout
            if not chunk:
                if state["terminal"]:
                    return "done"
                return "retry" if state["tokens"] == 0 \
                    else self._mid_stream(h, rep, state)
            buf += chunk
            while b"\n\n" in buf:
                frame, buf = buf.split(b"\n\n", 1)
                try:
                    h.wfile.write(frame + b"\n\n")
                    h.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    return "client_gone"   # closing resp cancels upstream
                if frame.startswith(b"data:"):
                    try:
                        state["tokens"] += len(
                            json.loads(frame[5:])["tokens"])
                    except (ValueError, KeyError, TypeError):
                        state["tokens"] += 1
                elif frame.startswith(b"event:"):
                    state["terminal"] = True
            if state["terminal"]:
                return "done"

    def _mid_stream(self, h, rep: Replica, state: dict) -> str:
        self._error_frame(
            h, state, "failed",
            f"replica {rep.idx} (incarnation {rep.incarnation}) "
            f"died mid-stream")
        return "mid_stream_death"

    def _error_frame(self, h, state: dict, status: str,
                     error: str) -> None:
        """The structured terminal frame the gateway contract promises:
        a client mid-stream NEVER sees a silent close."""
        payload = {"status": status, "n_tokens": state["tokens"],
                   "error": error}
        if state.get("trace_id"):
            payload["trace_id"] = state["trace_id"]
        try:
            h.wfile.write(b"event: error\ndata: "
                          + json.dumps(payload).encode() + b"\n\n")
            h.wfile.flush()
        except Exception:
            pass

    def _relay_plain(self, h, resp) -> str:
        """Buffer-then-relay for JSON answers (stream:false, 4xx,
        generation outcomes): nothing reaches the client until the
        whole upstream body arrived, so an upstream death here is
        always transparently retryable."""
        try:
            body = resp.read()
        except Exception:
            return "retry"
        extra = {}
        ra = resp.getheader("Retry-After")
        if ra:
            extra["Retry-After"] = ra
        self._raw(h, resp.status,
                  resp.getheader("Content-Type", "application/json")
                  or "application/json", body, extra)
        return "done"

    # -- response helpers -----------------------------------------------------

    def _json(self, h, status, obj, extra_headers=None):
        self._raw(h, status, "application/json",
                  json.dumps(obj).encode(), extra_headers)

    def _raw(self, h, status, ctype, body, extra_headers=None):
        try:
            h.send_response(status)
            h.send_header("Content-Type", ctype)
            h.send_header("Content-Length", str(len(body)))
            for k, v in (extra_headers or {}).items():
                h.send_header(k, v)
            h.end_headers()
            h.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass


def _scan_trace_jsonl(path: str, tid: str) -> Tuple[list, Optional[dict]]:
    """Pull one trace id's records out of a replica sink file:
    (event lines, terminal record or None). Torn tails (a replica
    SIGKILLed mid-write) and foreign lines are skipped, not fatal."""
    evs: list = []
    term: Optional[dict] = None
    try:
        with open(path) as f:
            for line in f:
                if tid not in line:        # cheap pre-filter
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("trace_id") != tid:
                    continue
                if rec.get("ev") == "terminal":
                    term = rec
                else:
                    evs.append(rec)
    except OSError:
        pass
    return evs, term


def _has_outcome(resp) -> bool:
    """True when a non-200 answer carries a GENERATION outcome (shed /
    deadline_missed / failed from `_collect`) rather than a
    replica-health error: outcomes are terminal and must reach the
    client; health errors fail over. Consumes the response body and
    stashes it on the response for the relay."""
    try:
        body = resp.read()
    except Exception:
        return False
    resp.read = lambda *a, **k: body      # replay for _relay_plain
    try:
        return "status" in json.loads(body or b"{}")
    except ValueError:
        return False


def _clamp_retry(seconds: float) -> float:
    return max(0.01, min(float(seconds), RETRY_AFTER_CEILING_S))


def _retry_after_header(seconds: float) -> str:
    return str(max(1, math.ceil(_clamp_retry(seconds))))
