"""paddle.inference — the deployment API (L8).

ref: paddle/fluid/inference/api/analysis_predictor.cc:1280 (Run), :2320
(ZeroCopyRun), python/paddle/inference/. The reference predictor loads a
saved Program, runs 159 IR fusion passes, and executes via InterpreterCore
(optionally TensorRT). TPU-native equivalent: the artifact IS a compiled
program — `jit.save` serializes StableHLO (jax.export) and the predictor
replays it through the XLA runtime; the pass pipeline's job (fusion,
layout, constant folding) is done by XLA at artifact build time, so
config knobs for IR passes are accepted-and-ignored shims.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor as PTensor

__all__ = ["Config", "Predictor", "Tensor", "create_predictor",
           "PrecisionType", "PlaceType", "convert_to_mixed_precision"]


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"
    XPU = "xpu"
    CUSTOM = "custom"


_warned_noops = set()


def _warn_ignored(setting: str, why: str):
    """One warning per ignored compat knob per process (VERDICT r3 #9:
    silently swallowing a requested setting hides behavior changes from
    users porting configs)."""
    if setting in _warned_noops:
        return
    _warned_noops.add(setting)
    import warnings
    warnings.warn(f"paddle_tpu.inference.Config.{setting} is accepted for "
                  f"API compatibility but has no effect on TPU: {why}",
                  UserWarning, stacklevel=3)


class Config:
    """ref: paddle_infer.Config. Knobs that steer CUDA/TRT specifics are
    accepted for API compatibility and ignored on TPU (XLA already applies
    the equivalent optimizations when the artifact was exported); each
    ignored knob warns once."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # paddle convention: prog_file may be the common prefix
        self.model_path = prog_file
        self.params_path = params_file
        self._ir_optim = True
        self._device = "tpu"
        self._mem_optim = True

    def set_prog_file(self, p):
        self.model_path = p

    def set_params_file(self, p):
        self.params_path = p

    def set_model(self, prog, params=None):
        self.model_path = prog
        self.params_path = params

    def model_dir(self):
        return self.model_path

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0,
                       precision=None):
        self._device = "gpu"

    def disable_gpu(self):
        self._device = "cpu"

    def enable_xpu(self, *a, **kw):
        self._device = "xpu"

    def enable_custom_device(self, device_type, device_id=0):
        self._device = device_type

    def use_gpu(self):
        return self._device == "gpu"

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def ir_optim(self):
        return self._ir_optim

    def enable_memory_optim(self, flag=True):
        self._mem_optim = flag

    def switch_use_feed_fetch_ops(self, flag):
        pass  # structural no-op: the artifact has no feed/fetch ops

    def switch_specify_input_names(self, flag=True):
        pass  # inputs are always named in the exported artifact

    def enable_tensorrt_engine(self, *a, **kw):
        _warn_ignored("enable_tensorrt_engine",
                      "TensorRT has no TPU analog; XLA compiled the "
                      "artifact at export time")

    def enable_mkldnn_int8(self, *a, **kw):
        """ref AnalysisConfig::EnableMkldnnInt8 — int8 inference. The
        TPU-native int8 path is weight-only PTQ consumed by the serving
        engine (inference.serving.quantize_state_int8 /
        ContinuousBatchingEngine(quantize='int8'))."""
        self._int8 = True

    def mkldnn_int8_enabled(self):
        return getattr(self, "_int8", False)

    def enable_mkldnn(self):
        _warn_ignored("enable_mkldnn", "oneDNN is a CPU backend; the "
                      "TPU artifact is already XLA-compiled")

    def set_cpu_math_library_num_threads(self, n):
        _warn_ignored("set_cpu_math_library_num_threads",
                      "XLA manages host threading")

    def summary(self):
        return (f"Config(model={self.model_path}, device={self._device}, "
                f"ir_optim={self._ir_optim})")


class Tensor:
    """Zero-copy handle (ref paddle_infer.Tensor: copy_from_cpu/copy_to_cpu)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = jnp.asarray(arr)

    def share_external_data(self, arr):
        self.copy_from_cpu(arr)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return list(self._value.shape) if self._value is not None else []

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)


class Predictor:
    """ref AnalysisPredictor. Wraps a TranslatedLayer (exported StableHLO)
    or any callable Layer; run() is ZeroCopyRun (device arrays in/out)."""

    def __init__(self, config_or_layer):
        if isinstance(config_or_layer, Config):
            from .. import jit
            path = config_or_layer.model_path
            if path is None:
                raise ValueError("Config.model_path not set")
            if path.endswith(".pdmodel"):
                path = path[: -len(".pdmodel")]
            self._layer = jit.load(path)
            if not callable(self._layer):
                raise ValueError(
                    f"no .pdmodel artifact next to {path}; re-export with "
                    "paddle.jit.save(layer, path, input_spec=[...])")
        else:
            self._layer = config_or_layer
        self._n_inputs = None
        self._inputs: Dict[str, Tensor] = {}
        self._outputs: List = []

    def get_input_names(self):
        exp = getattr(self._layer, "_exported", None)
        n = (len(exp.in_avals) - len(getattr(self._layer, "_state", {}))
             if exp is not None else (self._n_inputs or 1))
        return [f"input_{i}" for i in range(max(n, 1))]

    def get_input_handle(self, name):
        return self._inputs.setdefault(name, Tensor(name))

    get_input_tensor = get_input_handle

    def run(self, inputs: Optional[list] = None):
        if inputs is not None:                       # new-style API
            outs = self._layer(*inputs)
            return list(outs) if isinstance(outs, (tuple, list)) else [outs]
        args = [self._inputs[n]._value for n in self.get_input_names()
                if n in self._inputs]
        outs = self._layer(*args)
        outs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
        self._outputs = outs
        return True

    def get_output_names(self):
        return [f"output_{i}" for i in range(max(len(self._outputs), 1))]

    def get_output_handle(self, name):
        idx = int(name.rsplit("_", 1)[1])
        t = Tensor(name)
        out = self._outputs[idx]
        t._value = out.data if isinstance(out, PTensor) else out
        return t

    get_output_tensor = get_output_handle

    def try_shrink_memory(self):
        pass

    def clear_intermediate_tensor(self):
        pass


def create_predictor(config: Config) -> Predictor:
    """ref: paddle_infer.create_predictor."""
    return Predictor(config)


from .serving import (ContinuousBatchingEngine,  # noqa: E402,F401
                      DeadlineExceeded, GenerationRequest, PagePool,
                      QueueFull, quantize_state_int8)
from .gateway import (EngineRunner, ServingGateway,  # noqa: E402,F401
                      build_engine, load_generation_model,
                      load_static_model, resolve_config,
                      save_for_serving)
from .router import (FleetRouter, Replica,  # noqa: E402,F401
                     ReplicaSupervisor, chain_key, head_key_hex)

__all__ += ["ContinuousBatchingEngine", "GenerationRequest", "PagePool",
            "DeadlineExceeded", "QueueFull", "quantize_state_int8",
            "EngineRunner", "ServingGateway", "build_engine",
            "load_generation_model", "load_static_model",
            "resolve_config", "save_for_serving",
            "FleetRouter", "Replica", "ReplicaSupervisor",
            "chain_key", "head_key_hex"]


def convert_to_mixed_precision(*a, **kw):
    raise NotImplementedError(
        "mixed-precision artifact conversion: re-export with "
        "paddle.jit.save under amp.auto_cast instead")
