"""Goodput ledger: step-time decomposition into labeled buckets + a live
MFU gauge (the attribution layer the ROADMAP MFU-recovery campaign is
blocked on — 0.27-0.33 MFU says the gap exists, this says WHERE the
wall-clock goes; measurement frame per the Gemma-on-TPU serving
comparison, PAPERS.md arxiv 2605.25645).

Model: the training loop's wall time is a sequence of step WINDOWS —
`step_boundary()` is called once per step (jit.TrainStep does this; any
custom loop may too) and closes the window opened by the previous
boundary (or by an explicit `open_window()` at loop start). Inside a
window, instrumented subsystems attribute badput seconds to a category:

  data_wait        consumer blocked on the input pipeline — fed from the
                   DevicePrefetcher starved/warmup seam (io/prefetch.py)
                   and from `timed_iter` wrapping the hapi fit loop
  host_pull        blocking jax.device_get syncs (hapi.model._host_pull)
  compile          XLA compilation, via the jax.monitoring duration-event
                   listener (observability/device_events.py)
  checkpoint_stall trainer blocked on a synchronous checkpoint commit
  elastic_barrier  recovery/health barrier waits (distributed/elastic)
  elastic_recovery checkpoint restore + replay after a world change

Whatever remains of the window is PRODUCTIVE device-execute time:

  productive = max(0, wall - sum(badput))        [category=device_execute]

so the bucket seconds sum to the measured wall time by construction and
roll into `goodput.productive_seconds_total` / `goodput.badput_seconds_total`
counters. The live MFU gauge divides the executable's own
`lowered.cost_analysis()` FLOPs (the seam
distributed/auto_parallel/cost_model.py already reads) by
step-seconds * peak FLOP/s of the local chip.

Disarmed (the registry discipline): `attribute()` / `step_boundary()` are
a single module-global bool check — the hot-path overhead guard in
tests/test_goodput.py holds the line.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

from . import metrics as _m

__all__ = ["attribute", "time_section", "timed_iter", "consumer_wait",
           "open_window", "step_boundary", "summary", "reset",
           "peak_flops_per_sec", "CATEGORIES"]

CATEGORIES = ("data_wait", "host_pull", "compile", "checkpoint_stall",
              "elastic_barrier", "elastic_recovery", "other")

_C_PRODUCTIVE = _m.counter(
    "goodput.productive_seconds_total",
    "step-window seconds left after badput attribution "
    "(category=device_execute)")
_C_BADPUT = _m.counter(
    "goodput.badput_seconds_total",
    "step-window seconds attributed to a non-productive category")
_C_STEPS = _m.counter("goodput.steps_total",
                      "step windows closed by the ledger")
_G_MFU = _m.gauge(
    "goodput.mfu", "live model FLOPs utilization: executable FLOPs / "
    "(step seconds * peak FLOP/s); 0 when peak is unknown")
_G_STEP_FLOPS = _m.gauge(
    "goodput.step_flops",
    "XLA cost_analysis FLOPs of the compiled step feeding the MFU gauge")
_G_LAST_STEP = _m.gauge("goodput.last_step_seconds",
                        "wall seconds of the last closed step window")

_lock = threading.RLock()
_t0: Optional[float] = None              # open-window start
_window_attr: Dict[str, float] = {}      # category -> seconds this window
_totals: Dict[str, float] = {}           # category -> seconds since reset
_productive_total = 0.0
_steps = 0
_last_mfu = 0.0

# thread-local guard: while `timed_iter` is timing a consumer-side
# `next()`, the DevicePrefetcher's starved/warmup attribution for the
# same wait must not double-count (the q.get block happens INSIDE that
# next() on the same thread)
_tl = threading.local()


def attribute(category: str, seconds: float) -> None:
    """Attribute `seconds` of the current step window to a badput
    category. Disarmed: one bool check."""
    if not _m.enabled():
        return
    if seconds <= 0:
        return
    with _lock:
        _window_attr[category] = _window_attr.get(category, 0.0) + seconds


class time_section:
    """`with time_section("checkpoint_stall"): ...` — attribute the block's
    wall time. Disarmed: an object allocation + one bool check."""

    __slots__ = ("category", "_t0")

    def __init__(self, category: str):
        self.category = category

    def __enter__(self):
        self._t0 = time.perf_counter() if _m.enabled() else None
        return self

    def __exit__(self, *exc):
        if self._t0 is not None:
            attribute(self.category, time.perf_counter() - self._t0)
        return False


def timed_iter(iterable, category: str = "data_wait"):
    """Wrap an iterable so time the consumer spends blocked in `next()`
    is attributed to `category` (hapi fit wraps its loader with this).
    Sets the dedup guard so the DevicePrefetcher's starved/warmup seam
    does not attribute the same wait twice."""
    it = iter(iterable)
    while True:
        t0 = time.perf_counter()
        _tl.timing = True
        try:
            item = next(it)
        except StopIteration:
            return
        finally:
            _tl.timing = False
        attribute(category, time.perf_counter() - t0)
        yield item


def consumer_wait(seconds: float) -> None:
    """The DevicePrefetcher starved/warmup seam: attribute a staged-batch
    queue wait as data_wait UNLESS a `timed_iter` on this thread is
    already timing the enclosing next() (hapi fit path)."""
    if getattr(_tl, "timing", False):
        return
    attribute("data_wait", seconds)


def open_window() -> None:
    """Start (or restart) a step window NOW, discarding attribution that
    accumulated outside any window. Called at loop start so the first
    step's window covers its data wait and compile."""
    global _t0
    if not _m.enabled():
        return
    with _lock:
        _window_attr.clear()
        _t0 = time.perf_counter()


def step_boundary(flops: Optional[float] = None) -> Optional[dict]:
    """Close the current step window and open the next one. Returns the
    window's breakdown {wall, productive, badput: {category: s}} — or
    None when disarmed or no window was open (first boundary just opens
    one). `flops` (the executable's cost_analysis count) drives the MFU
    gauge."""
    global _t0, _productive_total, _steps, _last_mfu
    if not _m.enabled():
        return None
    now = time.perf_counter()
    with _lock:
        if _t0 is None:
            _window_attr.clear()
            _t0 = now
            return None
        wall = now - _t0
        attrs = dict(_window_attr)
        _window_attr.clear()
        _t0 = now
        badput = sum(attrs.values())
        productive = max(0.0, wall - badput)
        for cat, s in attrs.items():
            _totals[cat] = _totals.get(cat, 0.0) + s
        _productive_total += productive
        _steps += 1
    _C_PRODUCTIVE.inc(productive, category="device_execute")
    for cat, s in attrs.items():
        _C_BADPUT.inc(s, category=cat)
    _C_STEPS.inc()
    _G_LAST_STEP.set(wall)
    mfu = 0.0
    if flops:
        _G_STEP_FLOPS.set(float(flops))
        peak = peak_flops_per_sec()
        if peak and wall > 0:
            mfu = float(flops) / (wall * peak)
            _G_MFU.set(mfu)
            # only a flops-carrying boundary updates the summary's MFU:
            # auxiliary windows (bench's drain window, manual
            # boundaries) must not zero the last real reading
            with _lock:
                _last_mfu = mfu
    return {"wall": wall, "productive": productive, "badput": attrs,
            "mfu": mfu}


def summary() -> dict:
    """Cumulative ledger view since reset(): step count, productive and
    per-category badput seconds, the attributed fraction of total window
    wall, and the last MFU reading."""
    with _lock:
        badput = dict(_totals)
        productive = _productive_total
        steps = _steps
        mfu = _last_mfu
    wall = productive + sum(badput.values())
    return {
        "steps": steps,
        "wall_seconds": wall,
        "productive_seconds": productive,
        "badput_seconds": badput,
        "productive_fraction": (productive / wall) if wall else 0.0,
        "mfu": mfu,
    }


def reset() -> None:
    """Drop window state and cumulative totals (registry counters are
    reset separately via metrics.reset())."""
    global _t0, _productive_total, _steps, _last_mfu
    with _lock:
        _t0 = None
        _window_attr.clear()
        _totals.clear()
        _productive_total = 0.0
        _steps = 0
        _last_mfu = 0.0


# bf16 peak FLOP/s by TPU generation (bench.py's table; order matters —
# "v5e"/"v5lite" before the bare "v5" -> v5p fallback)
_PEAK = {
    "v3": 123e12,
    "v4": 275e12,
    "v5litepod": 197e12, "v5lite": 197e12, "v5e": 197e12,
    "v6e": 918e12, "trillium": 918e12,
    "v5p": 459e12, "v5": 459e12,
}

_peak_cache: Optional[float] = None


def peak_flops_per_sec() -> float:
    """Peak FLOP/s of the local chip for the MFU gauge.
    PADDLE_PEAK_FLOPS overrides (tests, unlisted hardware); 0.0 on
    backends with no known peak (CPU) — the gauge then stays unset."""
    global _peak_cache
    env = os.environ.get("PADDLE_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    if _peak_cache is not None:
        return _peak_cache
    peak = 0.0
    try:
        import jax
        d = jax.local_devices()[0]
        kind = getattr(d, "device_kind", "").lower().replace(" ", "")
        for tag, p in _PEAK.items():
            if tag in kind:
                peak = p
                break
        if not peak and d.platform == "tpu":
            peak = 459e12            # assume v5p (BASELINE.md hardware)
    except Exception:
        peak = 0.0
    _peak_cache = peak
    return peak
