"""Per-execution device telemetry: a jax.monitoring duration-event
listener bridged into registry histograms, plus per-executable execution
accounting keyed by a stable tag stamped at trace time.

Closes the documented trace-time-only caveat on in-shard_map collective
accounting (distributed/collective.py): the host-side telemetry wrapper
there runs once per COMPILE for compiled collectives, so
`collective.calls_total` under-counts executed steps. The fix rides two
seams:

- `execution(tag)` — a context manager the owner of a compiled callable
  wraps around each invocation (jit.TrainStep stamps "train_step*"; the
  serving engine stamps "serving.decode"/"serving.ragged_step"/
  "serving.prefill"). Each exit observes `xla.dispatch_seconds{
  executable=tag}` — HOST-observed dispatch wall: exact on synchronous
  backends, a dispatch-side lower bound under async TPU dispatch. The
  series is NAMED for what it measures (ISSUE 18 honesty pass):
  `xla.execute_seconds` is reserved for DEVICE-side execute durations,
  fed by the jax.monitoring bridge where the runtime reports them (and
  by `note_device_execute()` for an XProf post-processor); on backends
  with no device-side source the series is honestly EMPTY instead of
  silently republishing host wall under a device name.
- `note_traced_collective(op)` — called by the collective wrapper while
  a TRACE is in progress inside an open execution window. The noted ops
  become the tag's composition; every later execution of that tag then
  increments `collective.executed_calls_total{op=..., executable=tag}`
  by the composition counts — per-execution numbers derived from
  trace-time composition x execution count. A re-trace (new shapes)
  REPLACES the composition, so recompiles never double it.

The jax.monitoring listener feeds `xla.compile_seconds{executable=tag}`
(and the goodput ledger's `compile` bucket) from the
`/jax/core/compile/*` duration events; it is registered once on first
arming and bails on the armed bool when disarmed.
"""
from __future__ import annotations

import threading
import time
from typing import Dict

from . import goodput as _goodput
from . import metrics as _m

__all__ = ["execution", "tagged", "note_traced_collective",
           "note_device_execute", "install_listener", "current_tag",
           "tag_composition"]

# wide-range buckets: compiles run seconds-to-minutes, executes ms-to-s
_H_COMPILE = _m.histogram(
    "xla.compile_seconds",
    "XLA compile-phase durations (jax.monitoring events) by the "
    "executable tag active when they fired",
    buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0, 300.0))
_H_DISPATCH = _m.histogram(
    "xla.dispatch_seconds",
    "HOST-observed wall seconds per dispatched call of a tagged "
    "executable; under async dispatch this is a dispatch-side LOWER "
    "BOUND on device time, not device execute seconds (those are "
    "xla.execute_seconds, device-derived where available)")
_H_EXECUTE = _m.histogram(
    "xla.execute_seconds",
    "DEVICE-side execute seconds per tagged executable, XProf/"
    "jax.monitoring-derived; empty when the backend reports no "
    "device-side durations (host-observed wall lives in "
    "xla.dispatch_seconds)")
_C_COLL_EXEC = _m.counter(
    "collective.executed_calls_total",
    "per-EXECUTION collective counts: trace-time composition of a "
    "tagged executable x its execution count (closes the trace-time-"
    "only caveat on collective.calls_total for compiled collectives)")

_lock = threading.RLock()
# executable tag -> {op: count} recorded at its last trace
_tag_ops: Dict[str, Dict[str, int]] = {}

_tl = threading.local()          # .stack: [execution frames]

_listener_installed = False


class _Frame:
    __slots__ = ("tag", "t0", "fresh")

    def __init__(self, tag: str):
        self.tag = tag
        self.t0 = time.perf_counter()
        self.fresh: Dict[str, int] = {}


def current_tag():
    """The innermost open execution tag on this thread, or None."""
    stack = getattr(_tl, "stack", None)
    return stack[-1].tag if stack else None


def tag_composition(tag: str) -> Dict[str, int]:
    """The collective composition recorded at `tag`'s last trace."""
    with _lock:
        return dict(_tag_ops.get(tag, {}))


class execution:
    """`with execution("train_step"): compiled(...)` — times the call
    into xla.dispatch_seconds{executable=tag} and replays the tag's
    traced collective composition into per-execution counters.
    Disarmed: an object allocation + one bool check."""

    __slots__ = ("tag", "_frame")

    def __init__(self, tag: str):
        self.tag = tag
        self._frame = None

    def __enter__(self):
        if not _m.enabled():
            return self
        self._frame = _Frame(self.tag)
        stack = getattr(_tl, "stack", None)
        if stack is None:
            stack = _tl.stack = []
        stack.append(self._frame)
        return self

    def __exit__(self, exc_type, exc, tb):
        f = self._frame
        if f is None:
            return False
        stack = getattr(_tl, "stack", None)
        if stack and stack[-1] is f:
            stack.pop()
        self._frame = None
        _H_DISPATCH.observe(time.perf_counter() - f.t0, executable=f.tag)
        with _lock:
            if f.fresh:
                # this execution TRACED (first call or a re-trace):
                # the fresh note set IS the composition now — replace,
                # never append, so recompiles cannot double it
                _tag_ops[f.tag] = dict(f.fresh)
            comp = _tag_ops.get(f.tag)
        if comp and exc_type is None:
            for op, n in comp.items():
                _C_COLL_EXEC.inc(n, op=op, executable=f.tag)
        return False


class tagged:
    """Trace-only tag window: compile durations and traced-collective
    notes attribute to `tag`, but NO execution is counted (no
    xla.dispatch_seconds sample, no composition replay). Wraps explicit
    `.lower()` calls — which may populate the jit trace cache, so the
    composition they trace must be kept for later executions."""

    __slots__ = ("tag", "_frame")

    def __init__(self, tag: str):
        self.tag = tag
        self._frame = None

    def __enter__(self):
        if not _m.enabled():
            return self
        self._frame = _Frame(self.tag)
        stack = getattr(_tl, "stack", None)
        if stack is None:
            stack = _tl.stack = []
        stack.append(self._frame)
        return self

    def __exit__(self, exc_type, exc, tb):
        f = self._frame
        if f is None:
            return False
        stack = getattr(_tl, "stack", None)
        if stack and stack[-1] is f:
            stack.pop()
        self._frame = None
        if f.fresh:
            with _lock:
                _tag_ops[f.tag] = dict(f.fresh)
        return False


def note_traced_collective(op: str) -> None:
    """Record that a collective op was traced into the executable whose
    execution window is open on this thread. No-op outside a window or
    outside tracing."""
    if not _m.enabled():
        return
    stack = getattr(_tl, "stack", None)
    if not stack:
        return
    try:
        import jax
        if jax.core.trace_state_clean():
            return                   # eager call, not a trace
    except Exception:
        return
    f = stack[-1]
    f.fresh[op] = f.fresh.get(op, 0) + 1


# device-side execute duration events, where this jax/runtime version
# reports them (older jaxlibs report none — xla.execute_seconds then
# stays honestly empty rather than echoing host dispatch wall)
_EXECUTE_EVENT_PREFIXES = ("/jax/core/execute", "/jax/pjit/execute",
                           "/xla/execute")


def note_device_execute(tag: str, seconds: float) -> None:
    """Feed a DEVICE-measured execute duration for `tag` into
    xla.execute_seconds — the hook for an XProf trace post-processor
    (profiler integration) or any backend that exposes real device
    durations out-of-band."""
    if not _m.enabled():
        return
    _H_EXECUTE.observe(float(seconds), executable=tag)


def _on_duration(event, duration, **kw) -> None:
    if not _m.enabled():
        return
    if event.startswith(_EXECUTE_EVENT_PREFIXES):
        # runtime-reported DEVICE execute duration: the honest source
        # for xla.execute_seconds
        _H_EXECUTE.observe(float(duration),
                           executable=current_tag() or "untagged")
        return
    # exact compile-phase events only: a bare "compile" substring would
    # also match /jax/compilation_cache/compile_time_saved_sec — time
    # that was NOT spent (warm persistent cache), which would inject a
    # phantom compile stall bigger than the window wall
    if not event.startswith("/jax/core/compile/"):
        return
    tag = current_tag() or "untagged"
    _H_COMPILE.observe(float(duration), executable=tag)
    _goodput.attribute("compile", float(duration))


def install_listener() -> None:
    """Register the jax.monitoring duration listener once per process
    (jax has no unregister; the callback bails on the armed bool)."""
    global _listener_installed
    if _listener_installed:
        return
    _listener_installed = True
    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:
        pass                         # jax absent/old: histograms stay 0
