"""Multi-host metric federation: per-rank snapshot publishing + a
job-level /metrics on the launch supervisor.

Prometheus on one rank of a multi-rank job sees 1/N of the story (the
ISSUE 3 follow-on). The federation layer closes that:

- each supervised child runs a `SnapshotPublisher` (armed via
  FLAGS_metrics_snapshot=<path>, which the `launch --elastic_level 1
  --metrics_port P` supervisor sets per child to
  `<log_dir>/metrics.rank{R}.inc{K}.json`): a daemon thread that
  atomically rewrites the registry snapshot JSON — stamped with
  rank/incarnation/pid/ts — every FLAGS_metrics_snapshot_interval
  seconds, plus once at exit.
- the supervisor's `FederationServer` reads every
  `metrics.rank*.inc*.json` under the log dir at scrape time, merges
  them, and serves ONE job-level /metrics + /healthz on the master.

Merge semantics (defined, not improvised):
- every series cell gains `rank` and `incarnation` labels — a
  relaunched rank's series appear under the new incarnation label while
  the dead incarnation's cells remain visible (and marked stale);
- counters SUM: a job-level cell (no rank/incarnation labels) carries
  the sum over every rank x incarnation, so job totals stay monotone
  across relaunches;
- gauges keep per-rank cells only (summing a gauge is meaningless);
- histograms MERGE BUCKETS: the job-level cell sums per-bucket counts,
  sum and count across snapshots sharing the same bucket edges.

Dead/relaunching ranks never wedge the scrape: a missing, torn or stale
snapshot is skipped (or served as-is) and the per-snapshot
`federation.last_seen_ts` / `federation.snapshot_fresh` gauges say which
series are current — freshness is `now - ts <= stale_after` (default 10s,
PADDLE_FEDERATION_STALE_AFTER overrides).

Everything here is stdlib + the local registry modules — no jax — so
the launch supervisor can serve federation without touching a backend.
"""
from __future__ import annotations

import atexit
import glob
import json
import os
import re
import threading
import time
from typing import Dict, List, Optional

from . import export as _export
from . import metrics as _metrics

__all__ = ["SnapshotPublisher", "start_publisher", "stop_publisher",
           "read_snapshots", "merge_snapshots", "FederationServer",
           "DEFAULT_STALE_AFTER"]

DEFAULT_STALE_AFTER = 10.0

_SNAP_NAME_RE = re.compile(r"metrics\.rank(\d+)\.inc(\d+)\.json$")


# -- per-rank publisher ------------------------------------------------------

def _atomic_write_json(path: str, payload: dict) -> None:
    """tmp + fsync + os.replace commit, stdlib-only: the publisher runs
    on a daemon thread possibly DURING package import, so it must not
    import framework.io (a cross-thread partial-module import would
    poison the main import)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class SnapshotPublisher:
    """Daemon thread atomically rewriting the registry snapshot JSON at
    `path` every `interval` seconds, identity-stamped (rank/incarnation
    from the supervisor env, pid, ts). A final snapshot is written on
    stop() and at interpreter exit so counters survive a graceful end."""

    def __init__(self, path: str, interval: float = 2.0):
        self.path = path
        self.interval = max(0.05, float(interval))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _identity(self) -> dict:
        out = {"pid": os.getpid()}
        rank = os.environ.get("PADDLE_TRAINER_ID")
        if rank is not None:
            out["rank"] = rank
        inc = os.environ.get("PADDLE_INCARNATION")
        if inc is not None:
            out["incarnation"] = inc
        return out

    def publish_once(self) -> None:
        try:
            _atomic_write_json(self.path, {
                "ts": time.time(), "metrics": _metrics.snapshot(),
                **self._identity()})
        except Exception:
            pass                     # telemetry must not kill the trainer

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.publish_once()

    def start(self) -> "SnapshotPublisher":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self.publish_once()      # first snapshot lands immediately
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="paddle-metrics-publisher")
            self._thread.start()
        return self

    def stop(self, final: bool = True) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None
        if final:
            self.publish_once()


_publisher: Optional[SnapshotPublisher] = None
_atexit_hooked = False


def start_publisher(path: str, interval: Optional[float] = None) \
        -> SnapshotPublisher:
    """Module-level publisher management (FLAGS_metrics_snapshot). Also
    arms the registry: a publisher of a disarmed registry would publish
    zeros forever."""
    global _publisher, _atexit_hooked
    stop_publisher(final=False)
    if interval is None:
        # get_flag's env-wins-then-registry resolution: a supervisor
        # child inherits the env knob, while paddle.set_flags values
        # land in the registry (its _apply_flag interval branch no-ops
        # while no publisher exists, so the flag must be read HERE)
        try:
            from ..framework.core import get_flag
            interval = float(get_flag("FLAGS_metrics_snapshot_interval",
                                      2.0) or 2.0)
        except Exception:
            interval = 2.0
    if not _metrics.enabled():
        from . import enable
        enable(True)
    _publisher = SnapshotPublisher(path, interval).start()
    if not _atexit_hooked:
        _atexit_hooked = True
        atexit.register(lambda: stop_publisher(final=True))
    return _publisher


def stop_publisher(final: bool = True) -> None:
    global _publisher
    if _publisher is not None:
        _publisher.stop(final=final)
        _publisher = None


# -- snapshot collection + merge ---------------------------------------------

def read_snapshots(source) -> List[dict]:
    """Load snapshot payloads from a directory (every
    metrics.rank*.inc*.json under it), a glob, or an explicit list of
    paths. Torn/missing files are skipped — a dying rank must never
    wedge the scrape. Rank/incarnation fall back to the filename when
    the payload lacks them."""
    if isinstance(source, (list, tuple)):
        paths = list(source)
    elif os.path.isdir(source):
        paths = sorted(glob.glob(
            os.path.join(source, "metrics.rank*.inc*.json")))
    else:
        paths = sorted(glob.glob(source))
    out = []
    for p in paths:
        try:
            with open(p) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(snap, dict) or \
                not isinstance(snap.get("metrics", {}), dict):
            continue             # valid JSON, wrong shape: still skipped
        m = _SNAP_NAME_RE.search(os.path.basename(p))
        if m:
            snap.setdefault("rank", m.group(1))
            snap.setdefault("incarnation", m.group(2))
        snap.setdefault("rank", "?")
        snap.setdefault("incarnation", "0")
        out.append(snap)
    return out


def _relabel(label_key: str, rank, inc) -> str:
    """Add rank/incarnation labels to a registry label key, preserving
    the registry's sorted + escaped key form."""
    pairs = dict(_metrics.split_label_key(label_key))
    pairs["rank"] = str(rank)
    pairs["incarnation"] = str(inc)
    return ",".join(
        f"{k}={_metrics._esc_label_value(v)}" for k, v in
        sorted(pairs.items()))


def _merge_hist_cells(a: dict, b: dict) -> Optional[dict]:
    """Bucket-merge two histogram cells; None when edges disagree.
    Per-bucket exemplars survive the merge: the NEWEST exemplar (by its
    observation ts) wins per bucket, so the job-level rollup still links
    a p99 bucket to a pullable trace id."""
    ea = [x[0] for x in a["buckets"]]
    eb = [x[0] for x in b["buckets"]]
    if ea != eb:
        return None
    out = {"buckets": [[le, na + nb] for (le, na), (_, nb) in
                       zip(a["buckets"], b["buckets"])],
           "sum": a["sum"] + b["sum"], "count": a["count"] + b["count"]}
    exemplars = dict(a.get("exemplars") or {})
    for le, ex in (b.get("exemplars") or {}).items():
        cur = exemplars.get(le)
        if cur is None or ex.get("ts", 0) >= cur.get("ts", 0):
            exemplars[le] = ex
    if exemplars:
        out["exemplars"] = exemplars
    return out


def _int_inc(snap) -> int:
    try:
        return int(snap.get("incarnation", 0))
    except (TypeError, ValueError):
        return 0


def merge_snapshots(snaps: List[dict],
                    stale_after: float = DEFAULT_STALE_AFTER,
                    now: Optional[float] = None) -> dict:
    """Merge per-rank snapshot payloads into one registry-shaped dict
    (see the module docstring for the semantics). The result feeds
    export.prometheus_text(snap) directly.

    Staleness is both time- AND succession-based (ISSUE 13): a rank's
    superseded incarnations are marked stale the moment a NEWER
    incarnation publishes its first snapshot, so a re-admitted rank's
    rejoin flips the grown world into /metrics within one scrape
    instead of waiting out PADDLE_FEDERATION_STALE_AFTER on the dead
    incarnation's last snapshot."""
    now = time.time() if now is None else now
    newest_inc: Dict[str, int] = {}
    for snap in snaps:
        r = snap["rank"]
        newest_inc[r] = max(newest_inc.get(r, 0), _int_inc(snap))
    merged: dict = {"counters": {}, "gauges": {}, "histograms": {}}
    job_counters: Dict[str, Dict[str, float]] = {}
    job_hists: Dict[str, Dict[str, dict]] = {}
    for snap in snaps:
        rank, inc = snap["rank"], snap["incarnation"]
        ts = float(snap.get("ts", 0.0))
        superseded = _int_inc(snap) < newest_inc[rank]
        fresh = 1.0 if (now - ts) <= stale_after and not superseded \
            else 0.0
        key = _relabel("", rank, inc)
        merged["gauges"].setdefault(
            "federation.last_seen_ts", {})[key] = ts
        merged["gauges"].setdefault(
            "federation.snapshot_fresh", {})[key] = fresh
        reg = snap.get("metrics", {})
        for mid, series in reg.get("counters", {}).items():
            cells = merged["counters"].setdefault(mid, {})
            job = job_counters.setdefault(mid, {})
            for lk, v in series.items():
                cells[_relabel(lk, rank, inc)] = v
                job[lk] = job.get(lk, 0.0) + v
        for mid, series in reg.get("gauges", {}).items():
            cells = merged["gauges"].setdefault(mid, {})
            for lk, v in series.items():
                cells[_relabel(lk, rank, inc)] = v
        for mid, series in reg.get("histograms", {}).items():
            cells = merged["histograms"].setdefault(mid, {})
            job = job_hists.setdefault(mid, {})
            for lk, cell in series.items():
                cells[_relabel(lk, rank, inc)] = cell
                if lk in job:
                    combined = _merge_hist_cells(job[lk], cell)
                    if combined is not None:
                        job[lk] = combined
                else:
                    job[lk] = dict(cell)
    # job-level rollups: counter sums and bucket-merged histograms land
    # as cells WITHOUT rank/incarnation labels next to the per-rank ones
    for mid, job in job_counters.items():
        merged["counters"][mid].update(job)
    for mid, job in job_hists.items():
        merged["histograms"][mid].update(job)
    return merged


# -- job-level HTTP endpoint -------------------------------------------------

class FederationServer:
    """Background HTTP server on the master: /metrics serves the merged
    Prometheus text over every child snapshot under `snapshot_dir`;
    /healthz serves per-snapshot freshness plus whatever the optional
    `status_provider` callable reports (the supervisor passes its
    rank-status view)."""

    def __init__(self, snapshot_dir: str, port: int,
                 host: Optional[str] = None,
                 stale_after: Optional[float] = None,
                 status_provider=None):
        self.snapshot_dir = snapshot_dir
        self.port = int(port)
        self.host = host or os.environ.get("PADDLE_METRICS_HOST",
                                           "127.0.0.1")
        if stale_after is None:
            try:
                stale_after = float(os.environ.get(
                    "PADDLE_FEDERATION_STALE_AFTER", "") or
                    DEFAULT_STALE_AFTER)
            except ValueError:
                stale_after = DEFAULT_STALE_AFTER
        self.stale_after = stale_after
        self.status_provider = status_provider
        self._server = None
        self._thread = None

    def merged_snapshot(self) -> dict:
        return merge_snapshots(read_snapshots(self.snapshot_dir),
                               stale_after=self.stale_after)

    def metrics_text(self) -> str:
        return _export.prometheus_text(self.merged_snapshot())

    def health(self) -> dict:
        now = time.time()
        snaps = read_snapshots(self.snapshot_dir)
        ranks = {}
        for s in snaps:
            ts = float(s.get("ts", 0.0))
            cell = {"incarnation": s["incarnation"], "ts": ts,
                    "fresh": (now - ts) <= self.stale_after}
            prev = ranks.get(s["rank"])
            # a rank's health is its NEWEST incarnation's freshness —
            # ordered by incarnation first (a rejoined rank's fresh
            # incarnation wins immediately), snapshot time as tiebreak
            if prev is None or (_int_inc(s), ts) >= \
                    (_int_inc(prev), prev["ts"]):
                ranks[s["rank"]] = cell
        out = {"ok": True, "ranks": ranks,
               "fresh_ranks": sum(1 for c in ranks.values() if c["fresh"]),
               "snapshots": len(snaps)}
        if self.status_provider is not None:
            try:
                out["supervisor"] = self.status_provider()
            except Exception as e:
                out["supervisor"] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def start(self) -> int:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        fed = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                path = self.path.rstrip("/")
                try:
                    if path == "/healthz":
                        body = json.dumps(fed.health(), indent=1).encode()
                        ctype = "application/json"
                    elif path in ("", "/metrics"):
                        body = fed.metrics_text().encode()
                        ctype = ("text/plain; version=0.0.4; "
                                 "charset=utf-8")
                    else:
                        self.send_error(404)
                        return
                except Exception as e:
                    # a torn snapshot mid-parse must not 500-wedge the
                    # job scrape: report and keep serving
                    body = f"# federation scrape error: {e}\n".encode()
                    ctype = "text/plain; charset=utf-8"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self._server = ThreadingHTTPServer((self.host, self.port),
                                           _Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name="paddle-federation")
        self._thread.start()
        return self._server.server_address[1]

    def stop(self) -> None:
        if self._server is not None:
            try:
                self._server.shutdown()
                self._server.server_close()
            except Exception:
                pass
        self._server = None
        self._thread = None
