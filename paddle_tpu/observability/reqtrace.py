"""Request-scope tracing: per-request event timelines + the exact
attribution ledger (ISSUE 18).

Aggregate histograms (`serving.ttft_seconds`, `serving.tpot_seconds`)
cannot say WHICH layer made THIS request slow. This module gives every
request a `traceparent`-style trace id (minted at the fleet router or
the gateway, honored when a client sends one) and records, per trace id:

- an **event timeline** in a bounded per-trace ring (arrival, admission,
  each prefill chunk with token/page counts, preempt/resume, draft
  proposed/accepted/rejected, prefix pages reused, deadline/shed/cancel,
  failover hops) — request-scoped ids, so concurrent streams never
  interleave the way a global span ring would;
- an **attribution ledger** (the goodput-ledger discipline from PR 10,
  applied per request): wall time decomposed into named buckets with
  `sum(buckets) == wall` BY CONSTRUCTION — every charge advances a
  single mark, so the buckets partition the request's lifetime with no
  gaps and no double counting (fp association error only, << 1e-6).

Event names are a REGISTERED TAXONOMY (`EVENTS`): call sites pass
literal snake_case ids and `emit()` rejects anything unregistered, so
free-form strings cannot fork series (the graft-lint metric-names pass
enforces the same discipline on the call-site literals).

A JSONL **sink** (the flight-recorder write-through discipline: append +
flush per line, handle kept open) persists every non-coalesced event
live and the terminal record at finish, so a replica killed with SIGKILL
still leaves enough on disk for the fleet router to serve
`GET /v1/trace/<id>` for the dead replica's requests. High-volume
`decode_tick` events are coalesced to a counter and surface only in the
terminal record. Arm with FLAGS_request_trace_sink=<path> (env, read at
import by observability/__init__) or `set_sink(path)`.

Everything here is pure observation: the serving engine guards each call
site on its once-resolved `FLAGS_request_trace` bool, and `=0` restores
the pre-trace tick loop bitwise (the FLAGS_speculative parity bar).
"""
from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import OrderedDict
from typing import Dict, List, Optional

__all__ = ["EVENTS", "BUCKETS", "RequestTrace", "mint_trace_id",
           "parse_trace_header", "new_trace", "get_trace", "lookup",
           "traces", "clear", "set_sink", "sink_path", "set_store_size"]

# -- registered taxonomy -----------------------------------------------------

# Every event a request timeline may carry. Literal snake_case ids at
# call sites (lint-enforced); emit() raises on anything else so a typo
# cannot silently fork a new event series.
EVENTS = frozenset((
    "arrival",          # request entered the gateway queue
    "admitted",         # scheduler granted a slot (fields: cached_pages)
    "prefill_chunk",    # one chunk scheduled (fields: tokens, pages)
    "decode_tick",      # coalesced: counted, not stored per-event
    "preempted",        # slot reclaimed, pages released
    "resumed",          # re-admitted after preemption
    "draft_proposed",   # speculative rows funded (fields: n)
    "draft_accepted",   # verification kept n draft tokens (fields: n)
    "draft_rejected",   # verification dropped n draft tokens (fields: n)
    "prefix_reuse",     # prefix-cache hit at admission (fields: pages)
    "first_token",      # TTFT point (fields: ttft_s)
    "deadline_miss",    # SLO deadline exceeded
    "shed",             # dropped by overload shedding
    "cancelled",        # client disconnect / explicit cancel
    "failed",           # engine fault terminal
    "finished",         # clean completion (fields: n_tokens)
    "failover_hop",     # router retried on another replica (fields: hop,
                        # replica)
    "stream_write",     # gateway pushed tokens to the client stream
))

# The attribution buckets. queue_wait/prefill_compute/preempted/
# page_wait/draft_overhead/failover/stream_write are the ISSUE taxonomy;
# decode_compute completes the partition (without it decode time would
# have to hide inside another bucket and the exactness invariant would
# be a lie).
BUCKETS = ("queue_wait", "prefill_compute", "decode_compute", "preempted",
           "page_wait", "draft_overhead", "failover", "stream_write")

_TERMINAL_EVENTS = frozenset((
    "finished", "failed", "cancelled", "shed", "deadline_miss"))

_EVENTS_PER_TRACE = 256      # per-trace timeline bound
_DEFAULT_STORE = 1024        # live + recently-finished traces kept

_lock = threading.RLock()
_store: "OrderedDict[str, RequestTrace]" = OrderedDict()
_store_max = _DEFAULT_STORE

_sink_path: Optional[str] = None
_sink_fh = None


# -- trace ids ---------------------------------------------------------------

def mint_trace_id() -> str:
    """A fresh 32-hex trace id (the W3C traceparent trace-id width)."""
    return uuid.uuid4().hex


def parse_trace_header(value: Optional[str]) -> Optional[str]:
    """Extract a trace id from an incoming header value: either a bare
    hex id (our `X-Request-Trace`) or a W3C `traceparent`
    (`00-<32hex trace>-<16hex span>-flags`). Returns None when the value
    is absent or malformed — the caller mints instead."""
    if not value:
        return None
    v = value.strip()
    if "-" in v:                       # traceparent form
        parts = v.split("-")
        if len(parts) >= 2:
            v = parts[1]
        else:
            return None
    v = v.lower()
    if 8 <= len(v) <= 64 and all(c in "0123456789abcdef" for c in v):
        return v
    return None


# -- the per-request record --------------------------------------------------

class RequestTrace:
    """One request's timeline + attribution ledger.

    The ledger is a single monotonic `mark`: `charge(bucket, now)` adds
    `now - mark` to `bucket` and advances the mark. Because every
    instant between the first mark and the last charge lands in exactly
    one bucket, `sum(buckets)` equals the marked wall span by
    construction. `preload()` adds seconds spent BEFORE this process saw
    the request (router failover time, carried in on a header) to both a
    bucket and the reported wall, preserving the invariant end-to-end.
    """

    __slots__ = ("trace_id", "events", "decode_ticks", "buckets", "mark",
                 "start_mark", "preloaded", "start_ts", "status",
                 "terminal_ts", "wall", "pending_bucket")

    def __init__(self, trace_id: str, now: Optional[float] = None):
        self.trace_id = trace_id
        self.events: List[dict] = []
        self.decode_ticks = 0
        self.buckets: Dict[str, float] = {}
        now = time.perf_counter() if now is None else now
        self.mark = now
        self.start_mark = now
        self.preloaded = 0.0
        self.start_ts = time.time()
        self.status: Optional[str] = None
        self.terminal_ts: Optional[float] = None
        self.wall: Optional[float] = None
        # the bucket the IN-PROGRESS span (mark..now) belongs to when
        # the next charger does not know better: charge() keeps it at
        # the last charged bucket; preemption overrides it to
        # `preempted` so the re-admission wait does not bill to
        # `queue_wait`. A request that dies before its first charge
        # bills its whole life to queue_wait — the only place it was.
        self.pending_bucket: str = "queue_wait"

    # -- ledger --

    def charge(self, bucket: str, now: Optional[float] = None) -> None:
        if bucket not in BUCKETS:
            raise ValueError(f"unregistered attribution bucket {bucket!r} "
                             f"(registered: {BUCKETS})")
        now = time.perf_counter() if now is None else now
        with _lock:
            self.buckets[bucket] = \
                self.buckets.get(bucket, 0.0) + (now - self.mark)
            self.mark = now
            self.pending_bucket = bucket

    def preload(self, bucket: str, seconds: float) -> None:
        """Credit seconds spent before arrival (router failover) to
        `bucket` AND to the reported wall, keeping sum==wall exact."""
        if bucket not in BUCKETS:
            raise ValueError(f"unregistered attribution bucket {bucket!r}")
        if seconds <= 0:
            return
        with _lock:
            self.buckets[bucket] = self.buckets.get(bucket, 0.0) + seconds
            self.preloaded += seconds

    # -- timeline --

    def event(self, name: str, ts: Optional[float] = None,
              **fields) -> None:
        if name not in EVENTS:
            raise ValueError(f"unregistered trace event {name!r} "
                             f"(register it in reqtrace.EVENTS)")
        if name == "decode_tick":      # coalesced: count only
            with _lock:
                self.decode_ticks += int(fields.get("n", 1))
            return
        ev = {"ev": name, "ts": time.time() if ts is None else ts}
        if fields:
            ev.update(fields)
        with _lock:
            if len(self.events) < _EVENTS_PER_TRACE:
                self.events.append(ev)
        _sink_write({"trace_id": self.trace_id, **ev})

    def finish(self, status: str, event: str,
               now: Optional[float] = None, **fields) -> dict:
        """Terminal: charge nothing (callers settle the ledger first),
        record the terminal event, stamp status/wall, and write the full
        terminal record through the sink. Idempotent per trace."""
        if event not in _TERMINAL_EVENTS:
            raise ValueError(f"{event!r} is not a terminal trace event "
                             f"({sorted(_TERMINAL_EVENTS)})")
        now = time.perf_counter() if now is None else now
        with _lock:
            if self.status is not None:        # already terminal
                return self.snapshot()
            self.status = status
            self.terminal_ts = time.time()
            self.wall = (now - self.start_mark) + self.preloaded
        self.event(event, **fields)
        rec = self.snapshot()
        _sink_write({"trace_id": self.trace_id, "ev": "terminal", **{
            k: rec[k] for k in ("ts", "status", "wall", "buckets",
                                "decode_ticks", "events")}})
        return rec

    def snapshot(self) -> dict:
        with _lock:
            return {
                "trace_id": self.trace_id,
                "ts": self.start_ts,
                "status": self.status,
                "terminal": self.status is not None,
                "wall": self.wall,
                "buckets": dict(self.buckets),
                "decode_ticks": self.decode_ticks,
                "events": [dict(e) for e in self.events],
            }


# -- the process-wide store --------------------------------------------------

def set_store_size(n: int) -> None:
    global _store_max
    with _lock:
        _store_max = max(int(n), 1)
        while len(_store) > _store_max:
            _store.popitem(last=False)


def new_trace(trace_id: Optional[str] = None,
              now: Optional[float] = None) -> RequestTrace:
    """Create (or return the existing) trace for `trace_id`, bounded
    LRU: the oldest trace falls out when the store is full."""
    tid = trace_id or mint_trace_id()
    with _lock:
        tr = _store.get(tid)
        if tr is not None:
            _store.move_to_end(tid)
            return tr
        tr = RequestTrace(tid, now=now)
        _store[tid] = tr
        while len(_store) > _store_max:
            _store.popitem(last=False)
        return tr


def get_trace(trace_id: str) -> Optional[RequestTrace]:
    with _lock:
        return _store.get(trace_id)


def lookup(trace_id: str) -> Optional[dict]:
    """Snapshot view for `GET /v1/trace/<id>`; None when unknown."""
    tr = get_trace(trace_id)
    return tr.snapshot() if tr is not None else None


def traces() -> List[str]:
    with _lock:
        return list(_store.keys())


def clear() -> None:
    with _lock:
        _store.clear()


# -- JSONL sink --------------------------------------------------------------

def set_sink(path: Optional[str]) -> None:
    """Point the write-through sink at `path` (append-only JSONL, handle
    kept open, flushed per line — survives SIGKILL like the flight
    recorder). None closes it."""
    global _sink_path, _sink_fh
    with _lock:
        if _sink_fh is not None:
            try:
                _sink_fh.close()
            except OSError:
                pass
            _sink_fh = None
        _sink_path = path
        if path:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            _sink_fh = open(path, "a")


def sink_path() -> Optional[str]:
    return _sink_path


def _sink_write(obj: dict) -> None:
    if _sink_fh is None:
        return
    try:
        line = json.dumps(obj) + "\n"
    except (TypeError, ValueError):
        return
    with _lock:
        fh = _sink_fh
        if fh is None:
            return
        try:
            fh.write(line)
            fh.flush()                 # to the kernel: survives SIGKILL
        except (OSError, ValueError, RuntimeError):
            pass    # a broken sink must not break the serving path
