"""Process-wide metrics registry: named counters, gauges and fixed-bucket
histograms with label support (ref: paddle/fluid/platform/profiler/* stats
+ the VisualDL scalar surface; Prometheus client semantics).

Discipline (same as utils/fault_injection.py): the registry is DISARMED by
default and every record call — `Counter.inc`, `Gauge.set`,
`Histogram.observe` — bails on a single module-global bool check, so
production code carries the instrumentation at no measurable cost (the
eager-dispatch bench's >= 3x bound is the regression guard). Arm with
`FLAGS_metrics=1` (env or paddle.set_flags), `observability.enable()`, or
by running a `paddle_tpu.profiler.Profiler`.

Instruments are created ONCE at module level with a literal
`subsystem.name` snake-case id (enforced by tools/check_metric_names.py)
and then incremented through the returned handle:

    from ..observability import metrics as _m
    _SAVES = _m.counter("ckpt.saves_total", "completed checkpoint saves")
    ...
    _SAVES.inc()                       # disarmed: one global load + bool
    _SAVES.inc(3, rank="0")            # labeled series

`counter()/gauge()/histogram()` are get-or-create: re-requesting an id
returns the existing instrument; requesting it as a DIFFERENT type raises.

Always-on subsystem counters that predate the registry (eager dispatch
cache, fault injection, watchdog) stay on their own cheap attribute
increments and bridge in through `register_collector` — a callable polled
at snapshot/export time — so their hot paths gained zero new work while
`snapshot()`/`prometheus_text()` still see them. The old
`profiler.*_stats()` functions remain as thin per-subsystem views.
"""
from __future__ import annotations

import re
import threading
import time
from bisect import bisect_left
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "counter", "gauge", "histogram",
           "enable", "enabled", "snapshot", "reset", "register_collector",
           "unregister_collector", "instruments", "split_label_key",
           "DEFAULT_BUCKETS"]

# fast-path guard: every record call reads this module global and returns
# when False — the disarmed cost of an instrumented site
_enabled = False

# RLock, not Lock: the flight recorder's SIGTERM/watchdog dump calls
# snapshot() and may run on the MAIN thread between bytecodes of a
# record call that already holds a lock — a non-reentrant lock would
# deadlock the dying process instead of letting it dump and exit
_lock = threading.RLock()                # registry structure, not values
_instruments: Dict[str, "_Instrument"] = {}
_collectors: Dict[str, Callable] = {}

# subsystem.name snake_case (e.g. "ckpt.save_seconds"); the AST lint in
# tools/check_metric_names.py enforces the same shape on call-site literals
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*\.[a-z][a-z0-9_]*$")

DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   60.0)


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def _esc_label_value(v) -> str:
    """Escape the separators so free-form values (worker names, section
    labels) cannot fork or merge series when the key is split back."""
    return (str(v).replace("\\", "\\\\").replace(",", "\\,")
            .replace("=", "\\="))


def _label_key(labels: Optional[dict]) -> str:
    """Flat 'k=v,k2=v2' series key (sorted; values escaped). Label KEYS
    are python identifiers (they arrive as **kwargs), so only values
    need escaping; split_label_key is the inverse."""
    if not labels:
        return ""
    return ",".join(f"{k}={_esc_label_value(labels[k])}"
                    for k in sorted(labels))


def split_label_key(key: str) -> List[Tuple[str, str]]:
    """Inverse of _label_key: [(k, v), ...] with escapes resolved. A
    char scanner, not a regex split — escapes consume in pairs, so a
    value ENDING in a backslash ('x\\' -> 'x\\\\') still parses."""
    if not key:
        return []
    out = []
    k: list = []
    v: list = []
    cur = k
    i, n = 0, len(key)
    while i < n:
        c = key[i]
        if c == "\\" and i + 1 < n:
            cur.append(key[i + 1])
            i += 2
            continue
        if c == "=" and cur is k:
            cur = v
        elif c == ",":
            out.append(("".join(k), "".join(v)))
            k, v = [], []
            cur = k
        else:
            cur.append(c)
        i += 1
    out.append(("".join(k), "".join(v)))
    return out


class _Instrument:
    kind = "abstract"

    __slots__ = ("name", "help", "_values", "_vlock")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._values: dict = {}
        # per-instrument: increments from two threads must not lose
        # counts; reentrant so a signal-handler dump interrupting a
        # held record call cannot self-deadlock (see _lock above)
        self._vlock = threading.RLock()

    def snapshot(self) -> dict:
        with self._vlock:
            return dict(self._values)

    def reset(self) -> None:
        with self._vlock:
            self._values.clear()


class Counter(_Instrument):
    """Monotonic count, optionally per label set."""

    kind = "counter"
    __slots__ = ()

    def inc(self, n: float = 1, **labels) -> None:
        if not _enabled:
            return
        key = _label_key(labels)
        with self._vlock:
            self._values[key] = self._values.get(key, 0) + n


class Gauge(_Instrument):
    """Last-written value, optionally per label set."""

    kind = "gauge"
    __slots__ = ()

    def set(self, v: float, **labels) -> None:
        if not _enabled:
            return
        key = _label_key(labels)
        with self._vlock:
            self._values[key] = v

    def inc(self, n: float = 1, **labels) -> None:
        if not _enabled:
            return
        key = _label_key(labels)
        with self._vlock:
            self._values[key] = self._values.get(key, 0) + n

    def dec(self, n: float = 1, **labels) -> None:
        self.inc(-n, **labels)


class Histogram(_Instrument):
    """Fixed-bucket histogram: per-bucket counts + sum + count per label
    set. Bucket bounds are upper-inclusive edges; an implicit +Inf bucket
    catches the tail (Prometheus histogram semantics).

    `observe(v, exemplar="<trace id>")` additionally pins the LAST
    exemplar per bucket — `{trace_id, value, ts}` riding the bucket the
    observation landed in (OpenMetrics exemplar semantics) — so a p99
    bucket in the exported histogram links to a concrete inspectable
    request trace instead of being an anonymous count."""

    kind = "histogram"
    __slots__ = ("buckets",)

    def __init__(self, name: str, help: str = "",
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        super().__init__(name, help)
        b = tuple(sorted(float(x) for x in buckets))
        if not b:
            raise ValueError(f"histogram {name!r}: needs >= 1 bucket")
        self.buckets = b

    def observe(self, v: float, exemplar: Optional[str] = None,
                **labels) -> None:
        if not _enabled:
            return
        key = _label_key(labels)
        i = bisect_left(self.buckets, v)    # index of first bound >= v
        with self._vlock:
            cell = self._values.get(key)
            if cell is None:
                # [counts per bucket + overflow, sum, count,
                #  {bucket_idx: [trace_id, value, ts]}]
                cell = self._values[key] = \
                    [[0] * (len(self.buckets) + 1), 0.0, 0, {}]
            cell[0][i] += 1
            cell[1] += v
            cell[2] += 1
            if exemplar is not None:
                cell[3][i] = [str(exemplar), float(v), time.time()]

    def snapshot(self) -> dict:
        with self._vlock:
            out = {}
            for key, cell in self._values.items():
                counts, total, n = cell[0], cell[1], cell[2]
                d = {
                    "buckets": [[b, c] for b, c in
                                zip(self.buckets, counts)] +
                               [["+Inf", counts[-1]]],
                    "sum": total, "count": n}
                exemplars = cell[3] if len(cell) > 3 else None
                if exemplars:
                    edges = list(self.buckets) + ["+Inf"]
                    d["exemplars"] = {
                        ("+Inf" if edges[i] == "+Inf" else "%g" % edges[i]):
                        {"trace_id": ex[0], "value": ex[1], "ts": ex[2]}
                        for i, ex in sorted(exemplars.items())}
                out[key] = d
            return out


def _get_or_create(cls, name: str, help: str, **kw):
    if not _NAME_RE.match(name or ""):
        raise ValueError(
            f"metric id {name!r} must be snake_case 'subsystem.name' "
            f"(e.g. 'ckpt.save_seconds')")
    with _lock:
        inst = _instruments.get(name)
        if inst is not None:
            if type(inst) is not cls:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{inst.kind}, requested {cls.kind}")
            return inst
        inst = cls(name, help, **kw)
        _instruments[name] = inst
        return inst


def counter(name: str, help: str = "") -> Counter:
    return _get_or_create(Counter, name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return _get_or_create(Gauge, name, help)


def histogram(name: str, help: str = "",
              buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
    return _get_or_create(Histogram, name, help, buckets=buckets)


def instruments() -> Dict[str, _Instrument]:
    with _lock:
        return dict(_instruments)


def register_collector(name: str, fn: Callable[[], List[tuple]]) -> None:
    """Bridge for always-on subsystem counters (dispatch cache, fault
    injection, watchdog): `fn()` is polled at snapshot/export time and
    returns rows `(kind, metric_id, labels_dict_or_None, value)` with
    kind in {"counter", "gauge"} — zero added work on the subsystem's
    hot path."""
    with _lock:
        _collectors[name] = fn


def unregister_collector(name: str) -> None:
    with _lock:
        _collectors.pop(name, None)


def snapshot() -> dict:
    """{'counters': {id: {label_key: val}}, 'gauges': {...},
    'histograms': {id: {label_key: {'buckets': [[le, n]...], 'sum': s,
    'count': c}}}} — instruments merged with collector rows."""
    out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
    for name, inst in sorted(instruments().items()):
        out[inst.kind + "s"][name] = inst.snapshot()
    with _lock:
        colls = list(_collectors.items())
    for cname, fn in colls:
        try:
            rows = fn()
        except Exception:
            continue        # a broken collector must not kill the export
        for kind, name, labels, value in rows:
            if kind not in ("counter", "gauge"):
                continue
            out[kind + "s"].setdefault(name, {})[_label_key(labels)] = value
    return out


def reset() -> None:
    """Zero every instrument's values (instruments and collectors stay
    registered)."""
    for inst in instruments().values():
        inst.reset()
