"""Span tracing: begin/end/duration records in a bounded in-memory ring,
forwarded to jax.profiler.TraceAnnotation so user spans, checkpoint
phases and collective calls show up in XProf with no extra code
(ref: python/paddle/profiler RecordEvent; fluid/platform/profiler host
tracer events).

Armed/disarmed follows the metrics registry's discipline: a disarmed
`span(...)` is an object allocation + one bool check, nothing else — no
ring append, no TraceAnnotation, no sink calls. Arm via FLAGS_metrics /
`observability.enable()`.

Every armed span begin/end event also fans out to registered SINKS —
the crash flight recorder (observability/export.py) registers one to
write-through each event to an append-only JSONL file, which is what
lets a SIGKILLed trainer leave a post-mortem artifact naming the span
that was open at death (the begin line is on disk; the end line never
happens).
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Callable, Dict, List

__all__ = ["span", "enable", "enabled", "ring", "clear", "set_ring_size",
           "open_spans", "add_sink", "remove_sink"]

_enabled = False
_DEFAULT_RING = 512

# RLock: the flight recorder's signal-handler dump reads ring()/
# open_spans() and may interrupt a record call on the SAME (main)
# thread mid-hold — a plain Lock would deadlock the dying process
_lock = threading.RLock()
_ring: deque = deque(maxlen=_DEFAULT_RING)
_seq = itertools.count(1)
_open: Dict[int, dict] = {}      # sid -> begin event (all threads)
_sinks: List[Callable] = []

_jax = None                      # lazy: None = untried, False = absent


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def set_ring_size(n: int) -> None:
    """Re-bound the ring (keeps the newest events)."""
    global _ring
    n = max(int(n), 1)
    with _lock:
        _ring = deque(_ring, maxlen=n)


def ring() -> list:
    with _lock:
        return list(_ring)


def clear() -> None:
    with _lock:
        _ring.clear()
        _open.clear()


def open_spans() -> list:
    """Begin events of every span currently open in ANY thread — the
    flight recorder dumps this to name what a hung/dying trainer was
    doing."""
    with _lock:
        return [dict(ev) for ev in _open.values()]


def add_sink(fn: Callable[[dict], None]) -> None:
    with _lock:
        if fn not in _sinks:
            _sinks.append(fn)


def remove_sink(fn: Callable) -> None:
    with _lock:
        if fn in _sinks:
            _sinks.remove(fn)


def _emit(ev: dict) -> None:
    with _lock:
        _ring.append(ev)
        sinks = list(_sinks)
    for s in sinks:
        try:
            s(ev)
        except Exception:
            pass        # a broken sink must not break the traced code


def _trace_annotation(name: str):
    """jax.profiler.TraceAnnotation when jax is importable (so armed
    spans land in an active XProf trace); None otherwise. The import is
    resolved once and cached."""
    global _jax
    if _jax is None:
        try:
            import jax as _j
            _jax = _j
        except Exception:
            _jax = False
    if _jax is False:
        return None
    try:
        return _jax.profiler.TraceAnnotation(name)
    except Exception:
        return None


class span:
    """Context manager: `with span("ckpt.save", path=p): ...` records a
    begin/end pair (wall epoch + monotonic duration) into the ring and an
    XProf TraceAnnotation. Disarmed: one bool check."""

    __slots__ = ("name", "attrs", "_sid", "_p0", "_ann")

    def __init__(self, name: str, **attrs):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        if not _enabled:
            self._sid = None
            return self
        self._sid = next(_seq)
        self._p0 = time.perf_counter()
        ev = {"ev": "span_begin", "sid": self._sid, "name": self.name,
              "ts": time.time(), "thread": threading.get_ident(),
              "thread_name": threading.current_thread().name}
        if self.attrs:
            ev["attrs"] = {k: str(v) for k, v in self.attrs.items()}
        with _lock:
            _open[self._sid] = ev
        _emit(ev)
        self._ann = _trace_annotation(self.name)
        if self._ann is not None:
            try:
                self._ann.__enter__()
            except Exception:
                self._ann = None
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._sid is None:
            return False
        if self._ann is not None:
            try:
                self._ann.__exit__(exc_type, exc, tb)
            except Exception:
                pass
        ev = {"ev": "span_end", "sid": self._sid, "name": self.name,
              "ts": time.time(),
              "dur_s": time.perf_counter() - self._p0}
        if exc_type is not None:
            ev["error"] = exc_type.__name__
        with _lock:
            _open.pop(self._sid, None)
        _emit(ev)
        return False
