"""Exporters + crash flight recorder.

Three output surfaces over the metrics registry and span ring:

- `prometheus_text()` — Prometheus text exposition format (metric ids
  have their '.' separator mapped to '_'); `serve_metrics(port)` exposes
  it on a background HTTP endpoint at /metrics (gated by
  FLAGS_metrics_port; binds loopback unless PADDLE_METRICS_HOST says
  otherwise).
- `write_snapshot(path)` — one machine-readable JSON file ({ts, metrics,
  spans}) committed via framework.io.atomic_write;
  `append_jsonl(path, record)` — append-only JSONL (crash-safe by
  construction: append never destroys prior bytes; flushed per record so
  a SIGKILL loses at most the in-flight line).
- the crash FLIGHT RECORDER — `install_flight_recorder(path)` attaches
  an append-only JSONL event log (FLAGS_flight_recorder): every armed
  span begin/end is written through live, and a final `dump` record
  (open spans, span-ring tail, metrics snapshot) is appended from an
  atexit hook, a SIGTERM handler, `CommWatchdog` firing, and explicit
  `flight_dump(reason)` calls. `faulthandler` is pointed at the same
  file, so a fatal-signal traceback lands next to the telemetry. A
  trainer killed with SIGKILL still leaves the write-through event lines
  (kernel-buffered writes survive process death), so the post-mortem can
  name the span that was open at death: the begin line without its end.
  This is what lets the elastic-training chaos suite assert WHY a worker
  died.
"""
from __future__ import annotations

import atexit
import faulthandler
import json
import os
import re
import threading
import time
from typing import Optional

from . import metrics, spans

__all__ = ["prometheus_text", "serve_metrics", "stop_metrics_server",
           "http_get_payload", "register_health_provider",
           "unregister_health_provider", "health_payload",
           "write_snapshot", "append_jsonl", "install_flight_recorder",
           "uninstall_flight_recorder", "flight_recorder_path",
           "flight_dump"]

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(metric_id: str) -> str:
    return _NAME_SANITIZE.sub("_", metric_id)


def _prom_value(v) -> str:
    """Full-precision sample rendering: %g rounds to 6 significant
    digits, which corrupts any counter past ~1e6 (one 128MB all_reduce
    already overflows byte counters). Integral values print exact;
    floats use repr (shortest round-trip)."""
    f = float(v)
    if f.is_integer() and abs(f) < 2**53:
        return str(int(f))
    return repr(f)


def _prom_label_str(label_key: str, extra: Optional[dict] = None) -> str:
    """'op=all_reduce' (registry label-key form) + extras ->
    '{op="all_reduce"}'; empty -> ''. split_label_key resolves the
    registry's escaping, so a ','/'=' inside a label VALUE (worker
    names, section labels) cannot fork into bogus label pairs."""
    parts = list(metrics.split_label_key(label_key))
    for k, v in (extra or {}).items():
        parts.append((k, v))
    if not parts:
        return ""
    body = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", "\\\\").replace('"', '\\"')
                     .replace("\n", "\\n"))
        for k, v in parts)
    return "{%s}" % body


def prometheus_text(snap: Optional[dict] = None) -> str:
    """Prometheus text format of the full registry (instruments +
    collector-bridged counters). Histograms emit cumulative _bucket
    series plus _sum/_count, per Prometheus histogram convention."""
    snap = snap if snap is not None else metrics.snapshot()
    insts = metrics.instruments()
    lines = []

    def _head(metric_id, kind):
        name = _prom_name(metric_id)
        inst = insts.get(metric_id)
        if inst is not None and inst.help:
            lines.append(f"# HELP {name} {inst.help}")
        lines.append(f"# TYPE {name} {kind}")
        return name

    for kind in ("counter", "gauge"):
        for metric_id, series in sorted(snap.get(kind + "s", {}).items()):
            name = _head(metric_id, kind)
            for label_key, value in sorted(series.items()):
                lines.append(f"{name}{_prom_label_str(label_key)} "
                             f"{_prom_value(value)}")
    for metric_id, series in sorted(snap.get("histograms", {}).items()):
        name = _head(metric_id, "histogram")
        for label_key, cell in sorted(series.items()):
            exemplars = cell.get("exemplars") or {}
            cum = 0
            for le, n in cell["buckets"]:
                cum += n
                le_s = "+Inf" if le == "+Inf" else "%g" % le
                line = (f"{name}_bucket"
                        f"{_prom_label_str(label_key, {'le': le_s})} {cum}")
                ex = exemplars.get(le_s)
                if ex:
                    # OpenMetrics exemplar: a p99 bucket names a
                    # concrete trace id to pull via GET /v1/trace/<id>
                    line += (' # {trace_id="%s"} %s %s'
                             % (ex["trace_id"], _prom_value(ex["value"]),
                                _prom_value(ex["ts"])))
                lines.append(line)
            lines.append(
                f"{name}_sum{_prom_label_str(label_key)} "
                f"{_prom_value(cell['sum'])}")
            lines.append(
                f"{name}_count{_prom_label_str(label_key)} "
                f"{cell['count']}")
    return "\n".join(lines) + "\n"


# -- JSON / JSONL ------------------------------------------------------------

def write_snapshot(path: str, extra: Optional[dict] = None) -> dict:
    """Atomically commit {ts, metrics, spans, **extra} as JSON at `path`
    (framework.io.atomic_write: tmp + fsync + os.replace). Returns the
    payload."""
    from ..framework.io import atomic_write
    payload = {"ts": time.time(), "metrics": metrics.snapshot(),
               "spans": spans.ring()}
    if extra:
        payload.update(extra)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    blob = json.dumps(payload).encode()
    atomic_write(path, lambda f: f.write(blob))
    return payload


def append_jsonl(path: str, record: dict) -> None:
    """Append one JSON line + flush. Append mode never destroys prior
    bytes (the atomic-write lint's own exemption) and the flush pushes
    the line to the kernel, so it survives the process being killed."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(record) + "\n")
        f.flush()


# -- HTTP /metrics endpoint --------------------------------------------------

_server = None
_server_thread = None

# -- health/readiness providers (ISSUE 10): subsystems (e.g. the serving
# engine's health_snapshot) register a zero-arg dict provider; the
# metrics endpoint serves the merged view at /healthz so a future HTTP
# front-end gets a readiness probe for free next to /metrics.
_health_providers: dict = {}


def register_health_provider(name: str, fn) -> None:
    """Register (or replace) a named zero-arg provider returning a
    JSON-serializable dict for the /healthz payload."""
    _health_providers[name] = fn


def unregister_health_provider(name: str) -> None:
    _health_providers.pop(name, None)


def health_payload() -> dict:
    """The merged /healthz body. A broken provider reports its error
    under its own key instead of failing the whole probe."""
    out = {"ok": True}
    for name, fn in sorted(_health_providers.items()):
        try:
            out[name] = fn()
        except Exception as e:        # readiness must not 500 on one bad hook
            out[name] = {"error": f"{type(e).__name__}: {e}"}
            out["ok"] = False
    return out


def http_get_payload(path: str):
    """The shared GET surface over the registry: (status, content_type,
    body bytes) for '/metrics' (or '') and '/healthz', None for unknown
    paths. One implementation worn by the FLAGS_metrics_port endpoint
    AND the serving gateway (inference/gateway.py), so both speak the
    same exposition format and the same readiness semantics (a broken
    health provider reads 503 — probes key on the STATUS code)."""
    path = path.split("?", 1)[0].rstrip("/")
    if path == "/healthz":
        payload = health_payload()
        status = 200 if payload.get("ok", False) else 503
        return (status, "application/json",
                json.dumps(payload, indent=1).encode())
    if path in ("", "/metrics"):
        return (200, "text/plain; version=0.0.4; charset=utf-8",
                prometheus_text().encode())
    return None


def serve_metrics(port: int, host: Optional[str] = None) -> Optional[int]:
    """Start (or move) the background /metrics (+ /healthz) HTTP
    endpoint; port 0 stops it. Returns the bound port. Consumed by
    FLAGS_metrics_port."""
    global _server, _server_thread
    stop_metrics_server()
    if not port:
        return None
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            got = http_get_payload(self.path)
            if got is None:
                self.send_error(404)
                return
            status, ctype, body = got
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):    # no stderr chatter per scrape
            pass

    host = host or os.environ.get("PADDLE_METRICS_HOST", "127.0.0.1")
    _server = ThreadingHTTPServer((host, int(port)), _Handler)
    _server_thread = threading.Thread(target=_server.serve_forever,
                                      daemon=True,
                                      name="paddle-metrics-http")
    _server_thread.start()
    return _server.server_address[1]


def stop_metrics_server() -> None:
    global _server, _server_thread
    if _server is not None:
        try:
            _server.shutdown()
            _server.server_close()
        except Exception:
            pass
    _server = None
    _server_thread = None


# -- crash flight recorder ---------------------------------------------------

def _identity() -> dict:
    """Rank + incarnation stamped on every flight-recorder start/dump
    record (ISSUE 6): a chaos post-mortem must name WHICH rank's WHICH
    relaunch died without correlating pids against the supervisor log."""
    out = {}
    rank = os.environ.get("PADDLE_TRAINER_ID")
    if rank is not None:
        out["rank"] = rank
    inc = os.environ.get("PADDLE_INCARNATION")
    if inc is not None:
        out["incarnation"] = inc
    return out


class _FlightRecorder:
    """Append-only JSONL event log with write-through span events and
    on-demand `dump` records. The file handle stays open for the process
    lifetime so faulthandler can target it."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fh = open(path, "a")
        # RLock: the SIGTERM/atexit dump can interrupt the main thread
        # mid-write of a span event; re-acquiring the write lock on the
        # same thread must not deadlock the dying process
        self._wlock = threading.RLock()
        self._write({"ev": "flight_recorder_start", "ts": time.time(),
                     "pid": os.getpid(), **_identity()})
        spans.add_sink(self._on_span)

    def _on_span(self, ev: dict) -> None:
        self._write(ev)

    def _write(self, obj: dict) -> None:
        try:
            line = json.dumps(obj) + "\n"
        except (TypeError, ValueError):
            return
        with self._wlock:
            try:
                self._fh.write(line)
                self._fh.flush()      # to the kernel: survives SIGKILL
            except (OSError, ValueError, RuntimeError):
                # RuntimeError: "reentrant call inside BufferedWriter" —
                # the SIGTERM/watchdog dump can interrupt the main
                # thread MID-write of a span event; losing that one
                # line must not abort the signal handler (which still
                # has to restore the prior disposition and re-deliver)
                pass

    def dump(self, reason: str) -> None:
        # thread ident -> NAME of every live thread, so a post-mortem
        # reading open_spans (which carry idents) can say "wedged in
        # router-probe", not "wedged in Thread-7"
        threads = {str(t.ident): t.name for t in threading.enumerate()
                   if t.ident is not None}
        self._write({"ev": "dump", "reason": reason, "ts": time.time(),
                     "pid": os.getpid(), **_identity(),
                     "threads": threads,
                     "open_spans": spans.open_spans(),
                     "ring_tail": spans.ring()[-64:],
                     "metrics": metrics.snapshot()})

    def close(self) -> None:
        spans.remove_sink(self._on_span)
        with self._wlock:
            try:
                self._fh.close()
            except OSError:
                pass


_recorder: Optional[_FlightRecorder] = None
_hooks_installed = False
_faulthandler_ours = False
_prev_sigterm = None


def _atexit_dump() -> None:
    if _recorder is not None:
        _recorder.dump("atexit")


def _on_sigterm(signum, frame):
    flight_dump("signal:SIGTERM")
    import signal as _signal
    # restore the PRIOR disposition (signal.signal accepts handler
    # callables and SIG_IGN/SIG_DFL alike), then honor it: a process
    # that had configured SIGTERM ignored (preemption drain) must keep
    # ignoring it — only non-ignoring dispositions get the re-delivery
    # that lets the process die / the prior handler run
    prev = _prev_sigterm
    try:
        _signal.signal(_signal.SIGTERM,
                       prev if prev is not None else _signal.SIG_DFL)
    except (TypeError, ValueError):
        _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
        prev = _signal.SIG_DFL
    if prev == _signal.SIG_IGN:
        return
    os.kill(os.getpid(), signum)


def install_flight_recorder(path: str) -> None:
    """Attach the flight recorder to `path` (FLAGS_flight_recorder).
    Also arms spans+metrics if they are not armed yet — a flight
    recorder with no events would be useless."""
    global _recorder, _hooks_installed, _faulthandler_ours, _prev_sigterm
    if _recorder is not None:
        if os.path.abspath(_recorder.path) == os.path.abspath(path):
            return
        uninstall_flight_recorder()
    _recorder = _FlightRecorder(path)
    if not metrics.enabled():
        metrics.enable(True)
    if not spans.enabled():
        spans.enable(True)
    try:
        if not faulthandler.is_enabled():
            faulthandler.enable(file=_recorder._fh)
            _faulthandler_ours = True
    except Exception:
        pass
    if not _hooks_installed:
        _hooks_installed = True
        atexit.register(_atexit_dump)
        try:
            import signal as _signal
            if threading.current_thread() is threading.main_thread():
                _prev_sigterm = _signal.getsignal(_signal.SIGTERM)
                _signal.signal(_signal.SIGTERM, _on_sigterm)
        except (ValueError, OSError):
            pass


def uninstall_flight_recorder() -> None:
    global _recorder, _faulthandler_ours
    if _recorder is not None:
        if _faulthandler_ours:
            # faulthandler still points at the file we are about to
            # close — a later fatal signal would hit a dead fd
            try:
                faulthandler.disable()
            except Exception:
                pass
            _faulthandler_ours = False
        _recorder.close()
        _recorder = None


def flight_recorder_path() -> Optional[str]:
    return _recorder.path if _recorder is not None else None


def flight_dump(reason: str) -> None:
    """Append a dump record (open spans + ring tail + metrics snapshot)
    if a recorder is installed; no-op otherwise. Called by
    CommWatchdog when a step overruns."""
    if _recorder is not None:
        _recorder.dump(reason)


def flight_event(record: dict) -> None:
    """Write one record through the installed flight recorder (no-op
    otherwise). For events that must survive SIGKILL the instant they
    happen — the lock witness reports inversions through here."""
    if _recorder is not None:
        _recorder._write(record)
