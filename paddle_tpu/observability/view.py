"""`python -m paddle_tpu.observability.view` — merge flight-recorder
JSONL files across ranks and incarnations into ONE time-ordered
post-mortem timeline.

A supervised elastic job leaves a pile of artifacts under --log_dir:
`flight.rank{R}.inc{K}.jsonl` per worker incarnation (write-through span
events + dump records, observability/export.py) and
`supervisor_flight.jsonl` (spawn/death/relaunch/degrade transitions,
distributed/launch/main.py). Reading WHY a job died means correlating
all of them by wall clock — this CLI does the merge:

    python -m paddle_tpu.observability.view <log_dir>
    python -m paddle_tpu.observability.view flight.rank0.inc0.jsonl \\
        flight.rank1.inc*.jsonl supervisor_flight.jsonl

Output: one line per event, time-ordered across every file, tagged with
its origin (`r1.i0` = rank 1 incarnation 0, `sup` = supervisor),
followed by a post-mortem summary — per-origin last-event time, spans
still OPEN at the end of each file (the begin line without its end:
what a SIGKILLed worker was doing when it died), dump reasons, and the
supervisor's death/relaunch/degrade record. `--json` emits the merged
records as JSONL instead for machine consumption.

Non-JSON lines (faulthandler tracebacks share the flight file) are
skipped; files that fail to parse entirely are reported, not fatal.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time
from typing import List, Optional, Tuple

__all__ = ["main", "collect_files", "load_events"]

_FLIGHT_NAME_RE = re.compile(r"\.rank(\d+)\.inc(\d+)\.jsonl$")


def collect_files(args_paths: List[str]) -> List[str]:
    """Expand directories (all *.jsonl under them) and globs into a
    sorted, de-duplicated file list."""
    out = []
    for p in args_paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "*.jsonl"))))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(glob.glob(p)))
        else:
            out.append(p)
    seen = set()
    uniq = []
    for p in out:
        ap = os.path.abspath(p)
        if ap not in seen:
            seen.add(ap)
            uniq.append(p)
    return uniq


def _origin_of(path: str, rec: dict) -> str:
    base = os.path.basename(path)
    if base == "supervisor_flight.jsonl":
        return "sup"
    m = _FLIGHT_NAME_RE.search(base)
    if m:
        return f"r{m.group(1)}.i{m.group(2)}"
    rank = rec.get("rank")
    inc = rec.get("incarnation")
    if rank is not None:
        return f"r{rank}.i{inc if inc is not None else '?'}"
    return base


def load_events(paths: List[str]) -> Tuple[List[dict], List[str]]:
    """Parse every file's JSONL records, tagging each with `_origin` and
    `_file`. Returns (time-sorted records, per-file problems)."""
    events = []
    problems = []
    for path in paths:
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError as e:
            problems.append(f"{path}: {e}")
            continue
        n_bad = 0
        for ln in lines:
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except ValueError:
                n_bad += 1        # faulthandler traceback text: expected
                continue
            if not isinstance(rec, dict):
                continue
            rec["_origin"] = _origin_of(path, rec)
            rec["_file"] = path
            events.append(rec)
        if n_bad:
            problems.append(
                f"{path}: {n_bad} non-JSON line(s) skipped "
                f"(faulthandler traceback?)")
    events.sort(key=lambda r: (r.get("ts") or 0.0))
    return events, problems


def _fmt_ts(ts: Optional[float]) -> str:
    if not ts:
        return "--:--:--.---"
    frac = int((ts - int(ts)) * 1000)
    return time.strftime("%H:%M:%S", time.localtime(ts)) + f".{frac:03d}"


def _fmt_event(rec: dict) -> str:
    ev = rec.get("ev", "?")
    bits = [f"{_fmt_ts(rec.get('ts')):>12}", f"[{rec['_origin']:>7}]",
            f"{ev:<18}"]
    if ev in ("span_begin", "span_end"):
        bits.append(rec.get("name", ""))
        if ev == "span_end" and "dur_s" in rec:
            bits.append(f"dur={rec['dur_s']:.4f}s")
        if rec.get("error"):
            bits.append(f"error={rec['error']}")
        attrs = rec.get("attrs")
        if attrs:
            bits.append(" ".join(f"{k}={v}" for k, v in
                                 sorted(attrs.items())))
    elif ev == "dump":
        bits.append(f"reason={rec.get('reason')}")
        open_spans = rec.get("open_spans") or []
        if open_spans:
            bits.append("open=" +
                        ",".join(s.get("name", "?") for s in open_spans))
    else:
        for k in ("rank", "incarnation", "rc", "generation", "restart",
                  "world", "error", "pid"):
            if k in rec:
                bits.append(f"{k}={rec[k]}")
    return " ".join(str(b) for b in bits if b != "")


def _open_spans(events: List[dict]) -> dict:
    """Per origin: span begin events whose sid never saw an end — what
    each worker was doing at the end of its file."""
    by_origin: dict = {}
    for rec in events:
        o = rec["_origin"]
        ev = rec.get("ev")
        if ev == "span_begin":
            by_origin.setdefault(o, {})[rec.get("sid")] = rec
        elif ev == "span_end":
            by_origin.setdefault(o, {}).pop(rec.get("sid"), None)
    return {o: sorted(s.get("name", "?") for s in sids.values())
            for o, sids in by_origin.items() if sids}


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="paddle_tpu.observability.view",
        description="Merge flight-recorder JSONL files across "
                    "ranks/incarnations into one post-mortem timeline")
    p.add_argument("paths", nargs="+",
                   help="flight JSONL files, globs, or a log_dir")
    p.add_argument("--json", action="store_true",
                   help="emit merged records as JSONL instead of text")
    p.add_argument("--limit", type=int, default=0,
                   help="print only the LAST N timeline events")
    args = p.parse_args(argv)

    files = collect_files(args.paths)
    if not files:
        print("view: no flight files found", file=sys.stderr)
        return 1
    events, problems = load_events(files)
    for w in problems:
        print(f"view: {w}", file=sys.stderr)
    if not events:
        print("view: no parseable events", file=sys.stderr)
        return 1

    if args.json:
        for rec in events:
            print(json.dumps(rec))
        return 0

    shown = events[-args.limit:] if args.limit else events
    print(f"== timeline ({len(events)} events from {len(files)} files"
          f"{f', last {len(shown)}' if args.limit else ''}) ==")
    for rec in shown:
        print(_fmt_event(rec))

    print("\n== post-mortem ==")
    origins = sorted({r["_origin"] for r in events})
    last_ts = {o: max((r.get("ts") or 0.0) for r in events
                      if r["_origin"] == o) for o in origins}
    open_by = _open_spans(events)
    for o in origins:
        line = f"{o:>8}: last event {_fmt_ts(last_ts[o])}"
        if o in open_by:
            line += "  OPEN at end: " + ", ".join(open_by[o])
        print(line)
    dumps = [r for r in events if r.get("ev") == "dump"]
    for d in dumps:
        print(f"  dump [{d['_origin']}] reason={d.get('reason')} "
              f"at {_fmt_ts(d.get('ts'))}")
    for ev_name in ("worker_death", "relaunch", "degrade",
                    "spawn_failed"):
        for r in events:
            if r.get("ev") == ev_name:
                print(f"  {ev_name} [{r['_origin']}] rank={r.get('rank')}"
                      f" inc={r.get('incarnation')} rc={r.get('rc', '-')}"
                      f" at {_fmt_ts(r.get('ts'))}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
