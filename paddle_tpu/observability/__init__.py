"""paddle_tpu.observability — unified runtime telemetry.

One subsystem (see the per-module docstrings):

- `metrics`  — process-wide registry of counters/gauges/fixed-bucket
  histograms with labels; disarmed by default (single bool check per
  record site, the fault_injection.py discipline).
- `spans`    — `span(name, **attrs)` context manager: bounded in-memory
  ring + jax.profiler.TraceAnnotation forwarding (XProf correlation).
- `export`   — Prometheus text dump (+ optional HTTP endpoint via
  FLAGS_metrics_port), atomic JSON / append-only JSONL writers, and the
  crash flight recorder (FLAGS_flight_recorder) that leaves a
  post-mortem artifact when a trainer hangs, crashes or is killed.
- `goodput`  — the goodput ledger: step-window wall time decomposed into
  labeled productive/badput buckets + a live MFU gauge.
- `device_events` — per-execution device telemetry: jax.monitoring
  compile-duration bridge + per-executable execute accounting keyed by
  a trace-time tag (closes the trace-time-only collective caveat).
- `federation` — per-rank snapshot publishing (FLAGS_metrics_snapshot)
  + the launch supervisor's job-level merged /metrics.
- `view`     — `python -m paddle_tpu.observability.view`: merge flight
  JSONL files across ranks/incarnations into one post-mortem timeline.

Arm everything with `FLAGS_metrics=1` (env var — read at import so
subprocess chaos tests inherit it — or paddle.set_flags) or
`observability.enable()`. Instrumented call sites live in
autograd/tape (dispatch cache, via collector), distributed/{collective,
checkpoint, elastic, _net, rpc, watchdog}, utils/fault_injection (via
collector), io/prefetch, hapi/model, jit.TrainStep, inference/serving
and profiler.Profiler.
"""
from __future__ import annotations

import os
import threading

from . import (device_events, export, goodput, metrics,  # noqa: F401
               reqtrace, spans)
from .export import (append_jsonl, flight_dump,  # noqa: F401
                     install_flight_recorder, prometheus_text,
                     serve_metrics, uninstall_flight_recorder,
                     write_snapshot)
from .metrics import counter, gauge, histogram, snapshot  # noqa: F401
from .spans import span  # noqa: F401

__all__ = ["metrics", "spans", "export", "goodput", "device_events",
           "reqtrace", "enable", "enabled", "arm", "span",
           "counter", "gauge", "histogram", "snapshot", "prometheus_text",
           "write_snapshot", "append_jsonl", "serve_metrics",
           "install_flight_recorder", "uninstall_flight_recorder",
           "flight_dump", "update_device_memory_gauges"]


def enable(on: bool = True) -> None:
    """Arm (or disarm) the metrics registry and span tracing together.
    Arming also installs the jax.monitoring duration listener once (it
    bails on the armed bool when disarmed, so there is nothing to
    uninstall)."""
    metrics.enable(on)
    spans.enable(on)
    if on:
        device_events.install_listener()


def enabled() -> bool:
    return metrics.enabled()


_arm_lock = threading.Lock()
_arm_count = 0
_arm_prev = False


def arm():
    """Arm the registry+spans and return an idempotent restore()
    callable. REFCOUNTED: with two overlapping armers (a Profiler
    running across a Model.fit that carries a MetricsCallback), the
    first restore() must not disarm telemetry out from under the one
    still active — only the last restore standing reverts to the state
    captured before the first arm. The one implementation of the
    protocol, so Profiler and MetricsCallback cannot diverge."""
    global _arm_count, _arm_prev
    with _arm_lock:
        if _arm_count == 0:
            _arm_prev = metrics.enabled()
        if not metrics.enabled():
            enable(True)    # also re-arms after a direct enable(False)
        _arm_count += 1
    done = [False]

    def restore():
        global _arm_count
        with _arm_lock:
            if done[0]:
                return
            done[0] = True
            _arm_count -= 1
            if _arm_count == 0 and not _arm_prev:
                enable(False)

    return restore


# device-memory gauges (FLAGS_log_memory_stats + Profiler.step); created
# here once — consumers import the helper, not their own instruments
_G_MEM_IN_USE = metrics.gauge("device.bytes_in_use",
                              "device memory currently allocated (bytes); "
                              "unlabeled cell = host total, device=... "
                              "cells = per chip")
_G_MEM_PEAK = metrics.gauge("device.peak_bytes_in_use",
                            "peak device memory allocated (bytes); "
                            "unlabeled cell = host total, device=... "
                            "cells = per chip")


def update_device_memory_gauges():
    """Refresh device.bytes_in_use / device.peak_bytes_in_use from EVERY
    local device's memory_stats(): per-device labeled cells
    (device="tpu:0", ...) plus the unlabeled host-total cell — a
    multi-chip host no longer reports device 0 as the whole host.
    Returns {'bytes_in_use', 'peak_bytes_in_use', 'per_device'} (totals
    + the per-device map) — or None on backends without memory_stats
    (a clean no-op; CPU jaxlib returns None)."""
    try:
        import jax
        devs = jax.local_devices()
    except Exception:
        return None
    total_in = total_peak = 0
    per_device = {}
    for d in devs:
        try:
            st = d.memory_stats()
        except Exception:
            st = None
        if not st:
            continue
        in_use = int(st.get("bytes_in_use", 0))
        peak = int(st.get("peak_bytes_in_use", in_use))
        label = f"{d.platform}:{d.id}"
        per_device[label] = {"bytes_in_use": in_use,
                             "peak_bytes_in_use": peak}
        _G_MEM_IN_USE.set(in_use, device=label)
        _G_MEM_PEAK.set(peak, device=label)
        total_in += in_use
        total_peak += peak
    if not per_device:
        return None
    _G_MEM_IN_USE.set(total_in)
    _G_MEM_PEAK.set(total_peak)
    return {"bytes_in_use": total_in, "peak_bytes_in_use": total_peak,
            "per_device": per_device}


# env arming at import (the fault_injection.py pattern): subprocess chaos
# tests set these before the interpreter starts; paddle.set_flags routes
# here in-process (framework/core._apply_flag)
_FALSY_ENV = (None, "", "0", "false", "False", "off", "OFF")
if os.environ.get("FLAGS_metrics") not in _FALSY_ENV:
    enable(True)
if os.environ.get("FLAGS_span_ring_size"):
    try:
        spans.set_ring_size(int(os.environ["FLAGS_span_ring_size"]))
    except ValueError:
        pass
if os.environ.get("FLAGS_metrics_port"):
    try:
        export.serve_metrics(int(os.environ["FLAGS_metrics_port"]))
    except (ValueError, OSError):
        pass        # bad/busy port must not break `import paddle_tpu`
_flight_path = os.environ.get("FLAGS_flight_recorder")
if _flight_path:
    try:
        install_flight_recorder(_flight_path)
    except OSError:
        pass    # unwritable path must not break `import paddle_tpu`
_snapshot_path = os.environ.get("FLAGS_metrics_snapshot")
if _snapshot_path:
    try:
        from . import federation as _federation
        _federation.start_publisher(_snapshot_path)
    except Exception:
        pass    # unwritable path must not break `import paddle_tpu`
if os.environ.get("FLAGS_lock_witness") not in _FALSY_ENV:
    from . import lockwitness as _lockwitness
    _lockwitness.enable(True)
_trace_sink_path = os.environ.get("FLAGS_request_trace_sink")
if _trace_sink_path:
    try:
        reqtrace.set_sink(_trace_sink_path)
    except OSError:
        pass    # unwritable path must not break `import paddle_tpu`
