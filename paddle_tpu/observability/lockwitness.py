"""Lockdep-style runtime lock-order witness (FLAGS_lock_witness).

The threaded runtime — fleet supervisor, affinity router, membership
master, worker pools, prefetcher, watchdogs — has grown enough lock
sites that a deadlock can hide for months as a never-yet-collided pair
of nested acquisitions. This witness finds those pairs WITHOUT needing
the deadlock to fire: it wraps `threading.Lock`/`threading.RLock`
construction so every acquisition feeds a process-wide lock-ORDER
graph (the Linux lockdep idea), keyed by the lock's creation site (all
locks born at one `file:line` form one class, like lockdep lock
classes). Holding A while acquiring B records the edge A -> B; a later
acquisition that would close a cycle (B held, A wanted, A ->* B
already on record) is reported as an ORDER INVERSION — a potential
deadlock that never fired — through the metrics registry and, write-
through, the flight recorder (kernel-buffered appends survive SIGKILL,
so a drill killed mid-inversion still leaves the report on disk).

Two more runtime smells ride on the same hooks:

- held-too-long: a lock held longer than `HELD_TOO_LONG_S` (waits in
  `Condition.wait` don't count — `_release_save` drops the hold),
- blocked-under-lock: an acquisition that stalls longer than
  `BLOCKED_UNDER_LOCK_S` while the thread already holds another lock
  (the accept-loop-pinned / stalled-client signature).

Discipline (same as the metrics registry): DISARMED by default. The
default process never even installs the wrappers — `threading.Lock` is
untouched and the overhead is exactly zero. Arming (`FLAGS_lock_witness
=1`, env or `paddle.set_flags`, or `enable(True)`) swaps the
`threading.Lock`/`threading.RLock` factories once; a disarmed-but-
installed wrapper is a single module-global bool check per acquire
(guarded by tests/test_lock_witness.py). Locks created BEFORE install
stay unwitnessed — arm via env (the chaos-suite path) so the wrappers
are in place before paddle_tpu's module-level locks are born.

`Condition`/`Event`/`queue.Queue` need no patching of their own: they
construct their internal locks through the `threading.Lock`/`RLock`
module attributes at call time, so they inherit witnessed locks for
free. RLock reentrancy is instance-aware (re-acquiring a lock you
already hold records nothing), so reentrant designs — the recorder's
signal-handler RLock, metrics `_vlock` — are not false positives.
"""
from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from . import metrics

__all__ = ["enable", "enabled", "install", "uninstall", "installed",
           "report", "reset", "inversions", "HELD_TOO_LONG_S",
           "BLOCKED_UNDER_LOCK_S"]

# thresholds for the two duration smells (seconds); chaos drills and
# tests may lower them to provoke events deterministically
HELD_TOO_LONG_S = 1.0
BLOCKED_UNDER_LOCK_S = 0.5

# fast-path guard: every witnessed acquire reads this module global and
# delegates raw when False — the disarmed cost of an installed wrapper
_enabled = False
_installed = False

# originals captured at install() so uninstall() restores them exactly
_real_lock = None
_real_rlock = None

# the witness's own state locks are REAL (pre-install) locks: the graph
# update runs inside every witnessed acquire and must never recurse
# into itself
_state_lock = threading.RLock()

# acquisition-order graph over lock CLASSES (creation-site keys):
# _succ[a] = {b: first-seen info} means "a was held while b was taken"
_succ: Dict[str, Dict[str, dict]] = {}
_inversions: List[dict] = []
_reported_pairs: Set[Tuple[str, str]] = set()
_events: List[dict] = []         # held-too-long / blocked-under-lock

_tls = threading.local()

_C_INVERSIONS = metrics.counter(
    "lockwitness.inversions_total",
    "lock-order inversions (potential deadlocks) witnessed")
_C_HELD = metrics.counter(
    "lockwitness.held_too_long_total",
    "lock holds exceeding the held-too-long threshold")
_C_BLOCKED = metrics.counter(
    "lockwitness.blocked_under_lock_total",
    "acquisitions that stalled while another lock was held")


def _held() -> list:
    """This thread's stack of (wrapper, key, t_acquired)."""
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _site_key(depth: int) -> str:
    """Creation-site lock class: 'pkg/module.py:lineno' of the frame
    that called the factory (two trailing path parts keep keys stable
    across checkout roots)."""
    try:
        f = sys._getframe(depth)
    except ValueError:
        return "<unknown>"
    fn = f.f_code.co_filename.replace("\\", "/")
    parts = fn.split("/")
    return "/".join(parts[-2:]) + f":{f.f_lineno}"


def _flight(record: dict) -> None:
    """Write-through to the flight recorder (no-op when uninstalled):
    an inversion report must survive the process being SIGKILLed before
    anyone calls report()."""
    from . import export
    export.flight_event(record)


def _record_inversion(held_key: str, want_key: str, chain: list) -> None:
    pair = (want_key, held_key)
    with _state_lock:
        if pair in _reported_pairs:
            return
        _reported_pairs.add(pair)
        rec = {"ev": "lock_inversion", "ts": time.time(),
               "pid": os.getpid(),
               "held": held_key, "wanted": want_key,
               "established_order": chain,
               "thread": threading.current_thread().name}
        _inversions.append(rec)
    _C_INVERSIONS.inc()
    _flight(rec)


def _record_event(ev: str, counter, **fields) -> None:
    rec = {"ev": ev, "ts": time.time(), "pid": os.getpid(),
           "thread": threading.current_thread().name, **fields}
    with _state_lock:
        _events.append(rec)
        del _events[:-256]           # bounded: this is a smell log
    counter.inc()
    _flight(rec)


def _path(frm: str, to: str) -> Optional[list]:
    """Established-order chain frm ->* to in the acquisition graph, or
    None. Iterative DFS; the graph is tiny (one node per lock site)."""
    with _state_lock:
        succ = {k: list(v) for k, v in _succ.items()}
    stack = [(frm, [frm])]
    seen = {frm}
    while stack:
        node, chain = stack.pop()
        for nxt in succ.get(node, ()):
            if nxt == to:
                return chain + [to]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, chain + [nxt]))
    return None


def _note_acquired(wrapper: "_WitnessedLock", blocked_s: float) -> None:
    """Graph bookkeeping after a successful witnessed acquire."""
    held = _held()
    key = wrapper._key
    if held:
        if blocked_s > BLOCKED_UNDER_LOCK_S:
            _record_event(
                "lock_blocked_under_lock", _C_BLOCKED,
                wanted=key, held=[h[1] for h in held],
                blocked_s=round(blocked_s, 4))
        for _, held_key, _t in held:
            if held_key == key:
                continue         # same class nested (per-instance locks)
            # would held_key -> key close a cycle? i.e. key ->* held_key
            chain = _path(key, held_key)
            if chain is not None:
                _record_inversion(held_key, key, chain)
                continue         # keep the graph acyclic
            with _state_lock:
                edges = _succ.setdefault(held_key, {})
                if key not in edges:
                    edges[key] = {
                        "thread": threading.current_thread().name,
                        "ts": time.time()}
    held.append((wrapper, key, time.monotonic()))


def _note_released(wrapper: "_WitnessedLock") -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] is wrapper:
            _, key, t0 = held.pop(i)
            dt = time.monotonic() - t0
            if dt > HELD_TOO_LONG_S:
                _record_event("lock_held_too_long", _C_HELD,
                              lock=key, held_s=round(dt, 4))
            return


class _WitnessedLock:
    """Wrapper over one threading.Lock/RLock. Exposes the Condition
    protocol (`_release_save`/`_acquire_restore`/`_is_owned`) so
    `threading.Condition(witnessed_lock)` behaves exactly like the raw
    lock — including dropping the witness's held-entry across `wait()`
    (a condition wait is not a long hold)."""

    __slots__ = ("_inner", "_key", "_reentrant")

    def __init__(self, inner, key: str, reentrant: bool):
        self._inner = inner
        self._key = key
        self._reentrant = reentrant

    # -- core protocol -------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        if not _enabled:
            return self._inner.acquire(blocking, timeout)
        if getattr(_tls, "in_witness", False):
            return self._inner.acquire(blocking, timeout)
        if self._reentrant and any(h[0] is self for h in _held()):
            # RLock re-acquisition by the owner: no ordering event
            return self._inner.acquire(blocking, timeout)
        t0 = time.monotonic()
        got = self._inner.acquire(blocking, timeout)
        if got:
            _tls.in_witness = True
            try:
                _note_acquired(self, time.monotonic() - t0)
            finally:
                _tls.in_witness = False
        return got

    def release(self):
        if _enabled and not getattr(_tls, "in_witness", False):
            held = _held()
            n = sum(1 for h in held if h[0] is self)
            # reentrant lock: only the LAST release drops the hold
            if n and not (self._reentrant and n < self._owned_depth()):
                _tls.in_witness = True
                try:
                    _note_released(self)
                finally:
                    _tls.in_witness = False
        return self._inner.release()

    def _owned_depth(self) -> int:
        """Recursion depth of an owned RLock: parsed from the repr
        ('<locked _thread.RLock object owner=... count=N>') — the only
        portable view; 1 on any parse failure (safe: treat release as
        final)."""
        r = repr(self._inner)
        i = r.find("count=")
        if i < 0:
            return 1
        try:
            return int(r[i + 6:].split()[0].rstrip(">"))
        except ValueError:
            return 1

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    # -- Condition protocol -------------------------------------------
    def _release_save(self):
        removed = 0
        if _enabled:
            held = _held()
            for i in range(len(held) - 1, -1, -1):
                if held[i][0] is self:
                    held.pop(i)
                    removed += 1
        if hasattr(self._inner, "_release_save"):
            state = self._inner._release_save()
        else:
            self._inner.release()
            state = None
        return (state, removed)

    def _acquire_restore(self, saved):
        state, removed = saved
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        if _enabled and removed:
            held = _held()
            now = time.monotonic()
            for _ in range(removed):
                held.append((self, self._key, now))

    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _at_fork_reinit(self):
        self._inner._at_fork_reinit()
        if _enabled:
            _tls.held = []

    def __repr__(self):
        return f"<witnessed {self._key} {self._inner!r}>"


def _lock_factory():
    return _WitnessedLock(_real_lock(), _site_key(2), reentrant=False)


def _rlock_factory():
    return _WitnessedLock(_real_rlock(), _site_key(2), reentrant=True)


def install() -> None:
    """Swap the threading.Lock/RLock factories for witnessing wrappers
    (idempotent). Locks created from here on are witnessed; existing
    locks are untouched."""
    global _installed, _real_lock, _real_rlock
    if _installed:
        return
    _real_lock = threading.Lock
    _real_rlock = threading.RLock
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    _installed = True


def uninstall() -> None:
    """Restore the original factories. Wrappers already handed out keep
    working (disarmed they are one bool check), they just stop being
    created."""
    global _installed
    if not _installed:
        return
    threading.Lock = _real_lock
    threading.RLock = _real_rlock
    _installed = False


def installed() -> bool:
    return _installed


def enable(on: bool = True) -> None:
    """Arm (installing the wrappers if needed) or disarm the witness.
    Consumed by FLAGS_lock_witness."""
    global _enabled
    if on:
        install()
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def inversions() -> List[dict]:
    with _state_lock:
        return [dict(r) for r in _inversions]


def report() -> dict:
    """{'inversions': [...], 'events': [...], 'edges': n, 'locks': n} —
    the in-process view; the flight recorder holds the crash-safe one."""
    with _state_lock:
        nodes = set(_succ) | {b for v in _succ.values() for b in v}
        return {
            "inversions": [dict(r) for r in _inversions],
            "events": [dict(r) for r in _events],
            "edges": sum(len(v) for v in _succ.values()),
            "locks": len(nodes),
        }


def reset() -> None:
    """Drop the graph and all reports (test isolation)."""
    with _state_lock:
        _succ.clear()
        _inversions.clear()
        _reported_pairs.clear()
        _events.clear()
