"""paddle.audio — features + functional (ref: python/paddle/audio/:
features/layers.py Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC,
functional/window.py get_window, functional/functional.py mel utils)."""
from . import functional  # noqa: F401
from . import features  # noqa: F401
