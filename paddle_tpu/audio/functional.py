"""paddle.audio.functional (ref: python/paddle/audio/functional/ —
get_window, hz_to_mel, mel_to_hz, mel_frequencies, compute_fbank_matrix,
power_to_db, create_dct)."""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor

__all__ = ["get_window", "hz_to_mel", "mel_to_hz", "mel_frequencies",
           "fft_frequencies", "compute_fbank_matrix", "power_to_db",
           "create_dct"]


def _adt(dtype):
    from ..framework import core
    return core.convert_dtype(dtype or "float32")


def get_window(window, win_length, fftbins=True, dtype="float32"):
    if isinstance(window, tuple):
        window, *args = window
    n = win_length
    m = n if fftbins else n - 1
    t = np.arange(n)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * t / m)
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * t / m)
    elif window == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * t / m)
             + 0.08 * np.cos(4 * np.pi * t / m))
    elif window in ("rect", "boxcar", "ones"):
        w = np.ones(n)
    elif window == "bartlett":
        w = 1 - np.abs(2 * t / m - 1)
    else:
        raise ValueError(f"unknown window {window!r}")
    return Tensor(jnp.asarray(w, _adt(dtype)))


def hz_to_mel(freq, htk=False):
    if htk:
        return 2595.0 * np.log10(1.0 + np.asarray(freq) / 700.0)
    f = np.asarray(freq, np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    mel = (f - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(f >= min_log_hz,
                    min_log_mel + np.log(f / min_log_hz) / logstep, mel)


def mel_to_hz(mel, htk=False):
    if htk:
        return 700.0 * (10.0 ** (np.asarray(mel) / 2595.0) - 1.0)
    m = np.asarray(mel, np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    freq = f_min + f_sp * m
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(m >= min_log_mel,
                    min_log_hz * np.exp(logstep * (m - min_log_mel)), freq)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    mels = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk), n_mels)
    return Tensor(jnp.asarray(mel_to_hz(mels, htk), _adt(dtype)))


def fft_frequencies(sr, n_fft, dtype="float32"):
    return Tensor(jnp.linspace(0, sr / 2, 1 + n_fft // 2,
                               dtype=_adt(dtype)))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    f_max = f_max or sr / 2
    fft_f = np.linspace(0, sr / 2, 1 + n_fft // 2)
    mel_f = np.asarray(mel_to_hz(
        np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                    n_mels + 2), htk))
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    fb = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        fb *= enorm[:, None]
    return Tensor(jnp.asarray(fb, _adt(dtype)))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    s = spect.data if isinstance(spect, Tensor) else jnp.asarray(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s))
    log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return Tensor(log_spec)


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[:, None]
    dct = np.cos(math.pi / n_mels * (n + 0.5) * k)
    if norm == "ortho":
        dct[0] *= 1.0 / math.sqrt(2)
        dct *= math.sqrt(2.0 / n_mels)
    return Tensor(jnp.asarray(dct.T, _adt(dtype)))
