"""paddle.static — static-graph compatibility layer (L3 API parity).

ref: python/paddle/static/ (Program/Executor/program_guard/data) over the
ProgramDesc + InterpreterCore stack (SURVEY §3.3). TPU-native redesign:
`enable_static()` flips the tape into RECORDING mode — every op routed
through autograd.tape.apply_op appends (fn, inputs, outputs) to the current
Program while executing on placeholder zeros for shape propagation. An
`Executor.run(feed, fetch_list)` then REPLAYS the recorded DAG as one pure
function of the feeds, compiled under jax.jit and cached per feed
signature — the InterpreterCore equivalent is the XLA executable.

Static-mode training (optimizer ops inside the program) is out of scope —
use the dynamic API + jit.TrainStep, which compiles the full train step
anyway (the reason the reference needed static mode in the first place).
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import core
from ..jit import InputSpec  # noqa: F401  (paddle.static.InputSpec)
from ..tensor import Tensor

__all__ = ["Program", "program_guard", "default_main_program",
           "default_startup_program", "data", "Executor", "InputSpec",
           "save_inference_model", "load_inference_model", "name_scope",
           "cpu_places", "cuda_places", "xpu_places", "Variable", "gradients"]

_main_program: Optional["Program"] = None
_startup_program: Optional["Program"] = None


class _OpRecord:
    __slots__ = ("fn", "in_ids", "const_args", "out_ids", "name")

    def __init__(self, fn, in_ids, const_args, out_ids, name):
        self.fn = fn
        self.in_ids = in_ids          # per positional arg: var id or None
        self.const_args = const_args  # concrete values for non-var args
        self.out_ids = out_ids
        self.name = name


class Program:
    """Recorded op list + feed/fetch vars (ref ProgramDesc)."""

    def __init__(self):
        self.ops: List[_OpRecord] = []
        self.feeds: Dict[str, int] = {}       # name -> var id
        self.feed_meta: Dict[str, tuple] = {}  # name -> (shape, dtype)
        # var registry: id(tensor) -> var id, WITH a strong reference to
        # each registered Tensor — otherwise CPython id reuse after GC
        # would alias a new Tensor onto a stale var id (silently wrong
        # replay). Lifetime == Program lifetime.
        self.var_ids: Dict[int, int] = {}
        self._keepalive: List = []
        self._id = 0

    def register_var(self, t):
        self.var_ids[id(t)] = id(t)
        self._keepalive.append(t)

    def var_id(self, t):
        return self.var_ids.get(id(t))

    def clone(self, for_test=False):
        return self

    def global_block(self):
        return self

    def record(self, fn, args_ids, const_args, out_ids, name):
        self.ops.append(_OpRecord(fn, args_ids, const_args, out_ids, name))

    def reachable_ops(self, out_ids, extra_roots=()):
        """Backward reachability prune from `out_ids` (the reference
        executor's fetch pruning). Returns (ops_in_order, needed_var_ids).
        Shared by Executor replay and static.gradients (one copy of the
        replay convention)."""
        needed = set(out_ids) | set(extra_roots)
        ops = []
        for op in reversed(self.ops):
            if any(o in needed for o in op.out_ids):
                ops.append(op)
                needed.update(v for v in op.in_ids if v is not None)
        ops.reverse()
        return ops, needed

    # -- replay ------------------------------------------------------------
    def build_callable(self, fetch_ids):
        # prune by fetch reachability: unfed placeholders feeding
        # un-fetched branches are fine
        ops, needed = self.reachable_ops(fetch_ids)
        feeds = {n: vid for n, vid in self.feeds.items() if vid in needed}

        def run(feed_vals: dict):
            env: Dict[int, jax.Array] = {
                vid: jnp.asarray(feed_vals[n]) for n, vid in feeds.items()}
            for op in ops:
                args = []
                ci = 0
                for vid in op.in_ids:
                    if vid is None:   # leaf (parameter/constant): baked in
                        args.append(op.const_args[ci])
                        ci += 1
                    elif vid in env:
                        args.append(env[vid])
                    else:
                        raise KeyError(
                            f"op '{op.name}' reads a value produced outside "
                            "this Program (recorded under a different "
                            "program_guard?)")
                out = op.fn(*args)
                outs = out if isinstance(out, tuple) else (out,)
                for vid, o in zip(op.out_ids, outs):
                    env[vid] = o
            return [env[i] for i in fetch_ids]

        return run


class _StaticState:
    recording = False


_state = _StaticState()


def in_static_mode():
    return _state.recording


def _enable():
    global _main_program, _startup_program
    _state.recording = True
    from ..autograd import tape
    tape._STATIC_RECORDER = record_op
    if _main_program is None:
        _main_program = Program()
        _startup_program = Program()


def _disable():
    _state.recording = False
    from ..autograd import tape
    tape._STATIC_RECORDER = None


def default_main_program():
    global _main_program
    if _main_program is None:
        _main_program = Program()
    return _main_program


def default_startup_program():
    global _startup_program
    if _startup_program is None:
        _startup_program = Program()
    return _startup_program


class program_guard:
    """ref: static.program_guard — swap the recording target."""

    def __init__(self, main_program, startup_program=None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        global _main_program
        self._saved = _main_program
        _main_program = self.main
        return self.main

    def __exit__(self, *a):
        global _main_program
        _main_program = self._saved
        return False


def data(name, shape, dtype="float32", lod_level=0):
    """ref: static.data — feed placeholder. Executes as zeros during
    recording (shape propagation), substituted by the feed at run time."""
    prog = default_main_program()
    raw_shape = tuple(shape)
    shape = tuple(1 if (d is None or d < 0) else d for d in shape)
    arr = jnp.zeros(shape, core.convert_dtype(dtype))
    t = Tensor(arr, stop_gradient=True, name=name)
    prog.feeds[name] = id(t)
    prog.feed_meta[name] = (tuple(raw_shape), str(dtype))
    prog.register_var(t)
    return t


def var_id(t):
    return default_main_program().var_id(t)


def record_op(fn, tensor_args, datas, outs, name):
    """Called by apply_op in static mode."""
    prog = default_main_program()
    in_ids, consts = [], []
    for t, d in zip(tensor_args, datas):
        vid = prog.var_id(t) if t is not None else None
        if vid is None:
            in_ids.append(None)
            consts.append(d)
        else:
            in_ids.append(vid)
    out_ids = []
    for o in outs:
        prog.register_var(o)
        out_ids.append(id(o))
    prog.record(fn, in_ids, consts, out_ids, name)


class Executor:
    """ref: base/executor.py Executor — replay compiled under jit."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, **kw):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        fetch_ids = [program.var_id(t) if isinstance(t, Tensor) else t
                     for t in fetch_list]
        key = (id(program), len(program.ops), tuple(fetch_ids),
               tuple(sorted(feed)))
        if key not in self._cache:
            runner = program.build_callable(fetch_ids)
            self._cache[key] = jax.jit(runner)
        outs = self._cache[key]({k: np.asarray(
            v.numpy() if isinstance(v, Tensor) else v) for k, v in
            feed.items()})
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o, stop_gradient=True) for o in outs]

    def close(self):
        pass


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """ref: paddle.static.gradients (python/paddle/static/__init__.py →
    base/backward.py append_backward): appends backward computation for
    `targets` w.r.t. `inputs` to the current Program and returns the
    gradient variables (fetchable via Executor.run).

    TPU-native: instead of per-op grad-op insertion, ONE recorded op
    replays the forward subgraph as a pure function CUT at `inputs` and
    differentiates it with jax.vjp — the whole backward is a single
    traced node XLA compiles with the rest of the program (closing
    VERDICT r3 weak #8). `target_gradients` seeds the cotangents (ones
    by default); `no_grad_set` is honored by excluding those vars from
    the cut (their grads are simply not requested here, matching the
    reference's semantics of not building grads for them).
    """
    prog = default_main_program()
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    target_ids = []
    for t in targets:
        vid = prog.var_id(t)
        if vid is None:
            raise ValueError("gradients(): target not recorded in the "
                             "current Program")
        target_ids.append(vid)
    input_ids = []
    for t in inputs:
        vid = prog.var_id(t)
        if vid is None:
            raise ValueError("gradients(): input not recorded in the "
                             "current Program")
        input_ids.append(vid)

    # snapshot the forward as of this call (later-recorded ops are not
    # part of the differentiated subgraph, like append_backward)
    feeds = dict(prog.feeds)          # name -> vid
    input_set = set(input_ids)
    # prune the snapshot to the target subgraph: unrelated ops — in
    # particular PREVIOUSLY RECORDED gradients ops, whole vjps each —
    # must not replay inside this op's vjp (nested autodiff would
    # compound per gradients() call), and only feeds the subgraph reads
    # become the grad op's runtime inputs
    ops, needed = prog.reachable_ops(target_ids, extra_roots=input_set)
    feed_ids = [vid for vid in feeds.values() if vid in needed]
    seeds = None
    if target_gradients is not None:
        seeds = [None if g is None else
                 (g.data if isinstance(g, Tensor) else jnp.asarray(g))
                 for g in target_gradients]

    def _replay(env):
        for op in ops:
            if all(o in env for o in op.out_ids):
                continue
            args, ci = [], 0
            for vid in op.in_ids:
                if vid is None:
                    args.append(op.const_args[ci])
                    ci += 1
                elif vid in env:
                    args.append(env[vid])
                else:
                    break
            else:
                out = op.fn(*args)
                outs = out if isinstance(out, tuple) else (out,)
                for vid, o in zip(op.out_ids, outs):
                    # keep the cut: input vars stay the vjp primals
                    if vid not in env:
                        env[vid] = o
        return env

    def grad_fn(*feed_vals):
        base = dict(zip(feed_ids, (jnp.asarray(v) for v in feed_vals)))
        # primal values AT the cut points (feeds pass through; true
        # intermediates come from a plain forward replay)
        primal_env = _replay(dict(base))
        primals = [primal_env[vid] for vid in input_ids]

        def fwd(in_vals):
            env = dict(base)
            env.update(zip(input_ids, in_vals))
            env = _replay(env)
            return [env[t] for t in target_ids]

        outs, vjp = jax.vjp(fwd, primals)
        cts = [jnp.ones_like(o) if (seeds is None or seeds[i] is None)
               else seeds[i].astype(o.dtype)
               for i, o in enumerate(outs)]
        (grads,) = vjp(cts)
        return tuple(grads)

    grad_tensors = []
    out_ids = []
    for t in inputs:
        g = Tensor(jnp.zeros_like(t.data), stop_gradient=True,
                   name=(getattr(t, "name", None) or "x") + "@GRAD")
        prog.register_var(g)
        grad_tensors.append(g)
        out_ids.append(id(g))
    prog.record(grad_fn, feed_ids, [], out_ids, "gradients")
    return grad_tensors


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         program=None, **kw):
    """ref: static/io.py save_inference_model — exports the recorded
    program as a StableHLO artifact (same format as paddle.jit.save)."""
    from jax import export as jexport

    program = program or default_main_program()
    fetch_ids = [program.var_id(t) for t in fetch_vars]
    runner = program.build_callable(fetch_ids)
    names = [t.name for t in feed_vars]

    def fwd(*arrays):
        return tuple(runner(dict(zip(names, arrays))))

    from jax import export as _je
    abstract = []
    for i, n in enumerate(names):
        shape, dt = program.feed_meta[n]
        dt = core.convert_dtype(dt)
        if any(d is None or (isinstance(d, int) and d < 0) for d in shape):
            dims = ",".join(f"s{i}_{j}" if (d is None or d < 0) else str(d)
                            for j, d in enumerate(shape))
            abstract.append(jax.ShapeDtypeStruct(_je.symbolic_shape(dims),
                                                 dt))
        else:
            abstract.append(jax.ShapeDtypeStruct(tuple(shape), dt))
    exp = jexport.export(jax.jit(fwd))(*abstract)
    # atomic commit (tmp + fsync + os.replace) per file, .pdmodel LAST:
    # each file is individually crash-safe. The pair spans two files, so
    # a crash BETWEEN the replaces can still mix generations — the
    # .pdiparams carries the .pdmodel's sha256 and the loader verifies
    # it, turning a mixed pair into a loud error instead of silently
    # misbound feeds
    import hashlib
    import pickle

    from ..framework.io import atomic_write
    blob = exp.serialize()
    meta = {"feed_names": names,
            "model_sha256": hashlib.sha256(blob).hexdigest()}
    atomic_write(path_prefix + ".pdiparams",
                 lambda f: pickle.dump(meta, f),
                 fault_name="static.save_params")
    atomic_write(path_prefix + ".pdmodel", lambda f: f.write(blob),
                 fault_name="static.save_model")


class _FetchVar:
    """Shape/dtype handle for one output of a loaded inference program
    (the fetch-target stand-in a headless caller — e.g. the serving
    gateway — introspects instead of recorded Variables)."""

    __slots__ = ("name", "shape", "dtype")

    def __init__(self, name, shape, dtype):
        self.name = name
        self.shape = shape
        self.dtype = dtype

    def __repr__(self):
        return f"_FetchVar({self.name!r}, {self.shape}, {self.dtype})"


class _InferenceProgram:
    """A deserialized `save_inference_model` artifact, runnable with no
    Executor and no model code: `run(feed_dict)` replays the exported
    StableHLO on the named feeds and returns numpy fetches. `feed_names`
    / `fetch_vars` are the handles a serving front-end binds wire
    requests to (ISSUE 12 headless-loading satellite)."""

    def __init__(self, exported, feed_names):
        self.exported = exported
        self.feed_names = list(feed_names)
        self.fetch_vars = []
        for i, aval in enumerate(getattr(exported, "out_avals", ())):
            shape = tuple(
                d if isinstance(d, int) else str(d)
                for d in getattr(aval, "shape", ()))
            self.fetch_vars.append(_FetchVar(
                f"fetch_{i}", shape, str(getattr(aval, "dtype", "?"))))

    def run(self, feed):
        missing = [n for n in self.feed_names if n not in feed]
        if missing:
            raise KeyError(
                f"inference program missing feeds {missing}; expected "
                f"exactly {self.feed_names}")
        outs = self.exported.call(
            *[jnp.asarray(feed[n]) for n in self.feed_names])
        return [np.asarray(o) for o in outs]


def load_inference_model(path_prefix, executor=None, **kw):
    """ref: static/io.py load_inference_model. `executor` is accepted
    for API compatibility but NOT required: the returned
    `_InferenceProgram` runs headless — `prog.run({name: array})` —
    which is what lets a serving process drive the artifact without
    constructing the whole static-graph stack. Returns
    `(program, feed_names, fetch_vars)` where `fetch_vars` are
    shape/dtype handles for the program's outputs."""
    import hashlib
    import pickle

    from jax import export as jexport
    with open(path_prefix + ".pdmodel", "rb") as f:
        raw = f.read()
    with open(path_prefix + ".pdiparams", "rb") as f:
        meta = pickle.load(f)
    want = meta.get("model_sha256") if isinstance(meta, dict) else None
    if want is not None and hashlib.sha256(raw).hexdigest() != want:
        raise ValueError(
            f"torn inference-model pair at {path_prefix!r}: "
            f".pdiparams was written for a different .pdmodel (a crash "
            f"landed between the two commits) — re-export with "
            f"save_inference_model")
    exp = jexport.deserialize(raw)
    prog = _InferenceProgram(exp, meta["feed_names"])
    return prog, prog.feed_names, prog.fetch_vars


class name_scope:
    def __init__(self, prefix=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def cpu_places(device_count=None):
    return ["cpu"]


def cuda_places(device_ids=None):
    return []


def xpu_places(device_ids=None):
    return []


Variable = Tensor
