"""paddle.amp.debugging (ref: python/paddle/amp/debugging.py —
operator-stats collection, tensor checker, accuracy comparison;
python/paddle/amp/accuracy_compare.py).

TPU-native: the eager tape (autograd/tape.py) exposes an op-observer
hook; collection counts every op by compute dtype exactly where the
reference's per-ad_func AMP lists decide casts. The tensor checker
drives the same FLAGS_check_nan_inf sweep the compiled path uses.
check_numerics can append per-op stats to a JSONL dump, and
compare_accuracy diffs two such dumps (fp32 run vs low-precision run).
"""
from __future__ import annotations

import contextlib
import json
from collections import defaultdict
from enum import Enum
from typing import Optional

import jax.numpy as jnp
import numpy as np

__all__ = ["DebugMode", "TensorCheckerConfig", "enable_tensor_checker",
           "disable_tensor_checker", "check_numerics",
           "enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats",
           "compare_accuracy"]


class DebugMode(Enum):
    """ref: debugging.py DebugMode."""
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 4


# ---------------- operator stats ----------------------------------------

_stats: Optional[dict] = None


def _observer(name, outs):
    if _stats is None:
        return
    for t in outs:
        dt = getattr(getattr(t, "data", t), "dtype", None)
        if dt is None:
            continue
        dt = jnp.dtype(dt)
        if dt == jnp.float16:
            bucket = "float16"
        elif dt == jnp.bfloat16:
            bucket = "bfloat16"
        elif dt == jnp.float32:
            bucket = "float32"
        else:
            bucket = "other"
        _stats[name][bucket] += 1


def _install():
    from ..autograd import tape
    tape._OP_OBSERVER = _observer


def _uninstall():
    from ..autograd import tape
    tape._OP_OBSERVER = None


def enable_operator_stats_collection():
    """ref: debugging.py enable_operator_stats_collection — start counting
    ops per compute dtype."""
    global _stats
    _stats = defaultdict(lambda: defaultdict(int))
    _install()


def disable_operator_stats_collection():
    """Stop collecting and print the table (ref prints the same four
    dtype columns)."""
    global _stats
    _uninstall()
    stats, _stats = _stats, None
    if not stats:
        print("<---- op list ---->\n(no ops recorded)")
        return {}
    cols = ["float16", "bfloat16", "float32", "other"]
    print("<---- op list ---->")
    print(f"{'op':<28}" + "".join(f"{c:>10}" for c in cols))
    out = {}
    for op in sorted(stats):
        row = [stats[op].get(c, 0) for c in cols]
        out[op] = dict(zip(cols, row))
        print(f"{op:<28}" + "".join(f"{v:>10}" for v in row))
    return out


@contextlib.contextmanager
def collect_operator_stats():
    """ref: debugging.py collect_operator_stats context manager."""
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


# ---------------- tensor checker ----------------------------------------

class TensorCheckerConfig:
    """ref: debugging.py TensorCheckerConfig(enable, debug_mode, ...)."""

    def __init__(self, enable=True,
                 debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list
        self.skipped_op_list = skipped_op_list


def enable_tensor_checker(checker_config: TensorCheckerConfig):
    """ref: debugging.py enable_tensor_checker — turns on the per-op
    NaN/Inf sweep (the tape consumes FLAGS_check_nan_inf).
    CHECK_NAN_INF_AND_ABORT raises at the first bad op; the other modes
    warn and continue (the reference's non-abort semantics)."""
    from ..framework import core
    if checker_config.enable:
        abort = checker_config.debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT
        core.set_flags({"FLAGS_check_nan_inf": 1,
                        "FLAGS_check_nan_inf_warn_only": 0 if abort else 1})


def disable_tensor_checker():
    from ..framework import core
    core.set_flags({"FLAGS_check_nan_inf": 0,
                    "FLAGS_check_nan_inf_warn_only": 0})


# ---------------- check_numerics + accuracy compare ---------------------

def check_numerics(tensor, op_type="", var_name="", dump_path=None,
                   raise_on_nan_inf=False):
    """ref: debugging.py check_numerics — per-tensor stats + optional
    JSONL dump for compare_accuracy. Returns (num_nan, num_inf, num_zero)
    as python ints."""
    a = np.asarray(getattr(tensor, "data", tensor), np.float32)
    num_nan = int(np.isnan(a).sum())
    num_inf = int(np.isinf(a).sum())
    num_zero = int((a == 0).sum())
    finite = a[np.isfinite(a)]
    rec = {
        "op": op_type, "var": var_name,
        "dtype": str(getattr(getattr(tensor, "data", tensor), "dtype",
                             "float32")),
        "num_nan": num_nan, "num_inf": num_inf, "num_zero": num_zero,
        "min": float(finite.min()) if finite.size else 0.0,
        "max": float(finite.max()) if finite.size else 0.0,
        "mean": float(finite.mean()) if finite.size else 0.0,
    }
    if dump_path:
        with open(dump_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    if raise_on_nan_inf and (num_nan or num_inf):
        raise FloatingPointError(
            f"[check_numerics] op={op_type} var={var_name}: "
            f"{num_nan} NaN, {num_inf} Inf")
    return num_nan, num_inf, num_zero


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1.0, dump_all_ops=False):
    """ref: amp/accuracy_compare.py compare_accuracy — diff two
    check_numerics JSONL dumps (typically an fp32 run vs an amp run) and
    write an (op, var) report of max/mean deltas + nan/inf flags."""
    def load(p):
        out = {}
        with open(p) as f:
            for line in f:
                r = json.loads(line)
                out[(r["op"], r["var"])] = r
        return out

    a, b = load(dump_path), load(another_dump_path)
    rows = []
    for key in sorted(set(a) | set(b)):
        ra, rb = a.get(key), b.get(key)
        if ra is None or rb is None:
            rows.append({"op": key[0], "var": key[1],
                         "status": "missing_in_" + ("b" if rb is None
                                                   else "a")})
            continue
        max_diff = abs(ra["max"] - rb["max"])
        mean_diff = abs(ra["mean"] - rb["mean"])
        flagged = (ra["num_nan"] + rb["num_nan"]
                   + ra["num_inf"] + rb["num_inf"]) > 0
        if dump_all_ops or flagged or max_diff > 0 or mean_diff > 0:
            rows.append({"op": key[0], "var": key[1],
                         "fp32": {"min": ra["min"], "max": ra["max"],
                                  "mean": ra["mean"]},
                         "other": {"min": rb["min"], "max": rb["max"],
                                   "mean": rb["mean"]},
                         "max_diff": max_diff, "mean_diff": mean_diff,
                         "has_nan_inf": flagged})
    with open(output_filename, "w") as f:
        json.dump(rows, f, indent=1)
    return rows
