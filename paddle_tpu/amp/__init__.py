"""AMP (ref: python/paddle/amp/: auto_cast.py, grad_scaler.py:578).

TPU-native AMP: bf16-first. `auto_cast` flips a thread-local policy consumed
by Layers' matmul-class ops; `GradScaler` keeps the Paddle API but is an
identity on TPU by default — bf16 needs no loss scaling (the reference's
dynamic loss scaling targets fp16 on CUDA). fp16 mode retains real scaling.
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp
import numpy as np

from ..framework import core
from ..tensor import Tensor

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler", "is_bfloat16_supported",
           "is_float16_supported", "white_list", "black_list"]

# ref: fluid/imperative/amp_auto_cast.cc O1 lists (trimmed to the op names
# meaningful in this framework)
white_list = {"matmul", "linear", "conv2d", "conv1d", "conv3d", "einsum",
              "bmm", "mm", "attention"}
black_list = {"exp", "log", "softmax", "cross_entropy", "layer_norm", "norm",
              "mean", "sum", "cumsum", "logsumexp", "erf", "erfinv", "pow"}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"


_amp = _AmpState()


def amp_state():
    return _amp


def is_bfloat16_supported(device=None):
    return True


def is_float16_supported(device=None):
    return True


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    prev = (_amp.enabled, _amp.dtype, _amp.level)
    _amp.enabled = enable
    _amp.dtype = core.convert_dtype(dtype)
    _amp.level = level
    try:
        yield
    finally:
        _amp.enabled, _amp.dtype, _amp.level = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """O2: cast params to low precision, keep fp32 master weights in the
    optimizer (ref: amp/auto_cast.py::amp_decorate +
    fleet/utils/mix_precision_utils.py)."""
    d = core.convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    opt_single = optimizers is not None and not isinstance(optimizers, (list, tuple))
    opt_list = ([optimizers] if opt_single else list(optimizers or []))

    if level == "O2":
        excluded = tuple(excluded_layers or ())
        for m in model_list:
            for layer in m.sublayers(include_self=True):
                from ..nn.layer.norm import LayerNorm, _BatchNormBase
                if isinstance(layer, (_BatchNormBase, LayerNorm)) or \
                        (excluded and isinstance(layer, excluded)):
                    continue
                for p in layer._parameters.values():
                    if p is not None and jnp.issubdtype(p.dtype, jnp.floating):
                        for opt in opt_list:
                            if (master_weight is None or master_weight) and \
                                    any(q is p for q in opt._parameter_list):
                                opt._master_weights[id(p)] = \
                                    p.data.astype(jnp.float32)
                        p.data = p.data.astype(d)
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list,
            optimizers if opt_single else opt_list)


class GradScaler:
    """ref: python/paddle/amp/grad_scaler.py:578. With bf16 (TPU default)
    scaling is a no-op; with fp16 the dynamic-loss-scale algorithm
    (check_finite_and_unscale + update_loss_scaling kernels) is reproduced
    in jnp."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good = 0
        self._bad = 0
        self._found_inf = False

    def scale(self, var):
        if not self._enable or self._scale == 1.0:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g = p.grad.data.astype(jnp.float32) * inv
            finite = bool(jnp.all(jnp.isfinite(g)))
            found = found or not finite
            p.grad.data = g.astype(p.grad.dtype)
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if self._scale != 1.0 and not self._found_inf:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    def update(self):
        if not self._enable or not self._dynamic:
            self._found_inf = False
            return
        if self._found_inf:
            self._bad += 1
            self._good = 0
            if self._bad >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad = 0
        else:
            self._good += 1
            self._bad = 0
            if self._good >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good = 0
        self._found_inf = False

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "good": self._good,
                "bad": self._bad}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good = state.get("good", 0)
        self._bad = state.get("bad", 0)
