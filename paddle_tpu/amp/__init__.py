"""AMP (ref: python/paddle/amp/: auto_cast.py, grad_scaler.py:578).

TPU-native AMP, bf16-first.

O1 (`auto_cast`): a thread-local policy CONSUMED BY THE TAPE — every op
routed through `autograd.tape.apply_op` asks `compute_dtype(op_name)` and
casts its floating inputs to the policy dtype (white list), to float32
(black list), or leaves them alone (promote). This mirrors the reference's
generated ad_funcs, where the AMP cast is inlined before every kernel call
(ref: fluid/eager/amp_utils.h, eager_gen.py:455).

O2 (`decorate`): params cast to the low dtype with fp32 master weights kept
in the optimizer (ref: fleet/utils/mix_precision_utils.py).

`GradScaler` keeps the Paddle API (ref grad_scaler.py:578: dynamic loss
scaling via check_finite_and_unscale + update_loss_scaling) but is
implemented with traced jnp state — scale/good/bad counters are jax scalars
and the skip-on-inf decision is a `jnp.where` blend, so the whole scaler
works INSIDE a compiled TrainStep (fp16 path) instead of only in eager.
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp
import numpy as np

from ..framework import core
from ..tensor import Tensor

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler",
           "is_bfloat16_supported", "is_float16_supported",
           "is_float8_supported", "white_list", "black_list",
           "compute_dtype"]

# ref: fluid/imperative/amp_auto_cast.cc O1 lists, trimmed + extended with
# this framework's fused-op tape names (llama_attn, flash_attention, ...)
white_list = {"matmul", "linear", "conv1d", "conv2d", "conv3d",
              "conv1d_transpose", "conv2d_transpose", "conv3d_transpose",
              "einsum", "bmm", "mm", "attention", "attn", "flash_attention",
              "sdpa", "llama_attn", "llama_mlp", "bert_attn", "ernie_attn",
              "lm_head", "lm_head_tied", "addmm", "matmul_v2"}
black_list = {"exp", "log", "log2", "log10", "log1p", "softmax",
              "log_softmax", "cross_entropy", "softmax_with_cross_entropy",
              "layer_norm", "rms_norm", "norm", "mean", "sum", "cumsum",
              "logsumexp", "erf", "erfinv", "pow", "square", "reciprocal",
              "rsqrt", "acos", "asin", "cosh", "sinh", "tan", "atan2",
              "softplus", "cdist", "dist", "renorm", "group_norm",
              "instance_norm", "batch_norm", "sigmoid_cross_entropy",
              "nll_loss", "kl_div", "smooth_l1_loss", "mse_loss"}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_amp = _AmpState()


def amp_state():
    return _amp


def compute_dtype(op_name: str):
    """The dtype apply_op should cast this op's float inputs to, or None.

    White-listed ops run in the autocast dtype, black-listed ops in float32,
    everything else is left to jnp promotion semantics ("promote" mode).
    Matching is exact first, then on '_'-separated tokens of the tape name
    (so "bert_attn" hits via "attn", "decoder_scan" hits nothing).
    """
    if not _amp.enabled or _amp.level != "O1":
        return None
    name = op_name or ""
    white = white_list | _amp.custom_white
    black = black_list | _amp.custom_black
    if name in black:
        return jnp.float32
    if name in white:
        return _amp.dtype
    toks = set(name.split("_"))
    if toks & black:
        return jnp.float32
    if toks & white:
        return _amp.dtype
    return None


def is_bfloat16_supported(device=None):
    return True


def is_float16_supported(device=None):
    return True


def is_float8_supported(device=None):
    """fp8-e4m3 availability on this jax/backend — the same probe that
    gates the quantized collectives' fp8 wire mode (ISSUE 8; the
    scale/cast plumbing is shared in paddle_tpu/quantization/comm.py)."""
    from ..quantization import comm as _qcomm
    return _qcomm.supports_fp8()


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    prev = (_amp.enabled, _amp.dtype, _amp.level, _amp.custom_white,
            _amp.custom_black)
    _amp.enabled = enable
    _amp.dtype = core.convert_dtype(dtype)
    _amp.level = level
    _amp.custom_white = set(custom_white_list or ())
    _amp.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_amp.enabled, _amp.dtype, _amp.level, _amp.custom_white,
         _amp.custom_black) = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """O2: cast params to low precision, keep fp32 master weights in the
    optimizer (ref: amp/auto_cast.py::amp_decorate +
    fleet/utils/mix_precision_utils.py)."""
    d = core.convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    opt_single = optimizers is not None and not isinstance(optimizers, (list, tuple))
    opt_list = ([optimizers] if opt_single else list(optimizers or []))

    if level == "O2":
        excluded = tuple(excluded_layers or ())
        for m in model_list:
            for layer in m.sublayers(include_self=True):
                from ..nn.layer.norm import LayerNorm, _BatchNormBase
                if isinstance(layer, (_BatchNormBase, LayerNorm)) or \
                        (excluded and isinstance(layer, excluded)):
                    continue
                for p in layer._parameters.values():
                    if p is not None and jnp.issubdtype(p.dtype, jnp.floating):
                        for opt in opt_list:
                            if (master_weight is None or master_weight) and \
                                    any(q is p for q in opt._parameter_list):
                                opt._master_weights[id(p)] = \
                                    p.data.astype(jnp.float32)
                        p.data = p.data.astype(d)
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list,
            optimizers if opt_single else opt_list)


class GradScaler:
    """Dynamic loss scaling with traced state (ref grad_scaler.py:578).

    State (`scale`, `good`/`bad` counters, `found_inf`) are jax scalars and
    every update is a jnp expression, so scale/unscale/step/update all trace
    cleanly inside a compiled TrainStep. The skip-update-on-inf semantic is
    a `jnp.where` blend of pre/post-step parameters and optimizer state —
    numerically identical to the reference's conditional skip.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._incr_ratio = float(incr_ratio)
        self._decr_ratio = float(decr_ratio)
        self._incr_every = int(incr_every_n_steps)
        self._decr_every = int(decr_every_n_nan_or_inf)
        self._dynamic = use_dynamic_loss_scaling
        self._state = {
            "scale": jnp.asarray(float(init_loss_scaling) if enable else 1.0,
                                 jnp.float32),
            "good": jnp.asarray(0, jnp.int32),
            "bad": jnp.asarray(0, jnp.int32),
            "found_inf": jnp.asarray(False, jnp.bool_),
        }
        self._unscaled = False

    # -- traced-state plumbing (TrainStep threads this like opt state) ------
    def _get_traced_state(self):
        return dict(self._state)

    def _set_traced_state(self, st):
        self._state = dict(st)

    @property
    def _scale(self):
        return self._state["scale"]

    @property
    def _found_inf(self):
        return self._state["found_inf"]

    def scale(self, var):
        if not self._enable:
            return var
        return var * Tensor(self._state["scale"].astype(
            var.dtype if jnp.issubdtype(var.dtype, jnp.floating)
            else jnp.float32), stop_gradient=True)

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = (1.0 / self._state["scale"])
        found = jnp.asarray(False, jnp.bool_)
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g = p.grad.data.astype(jnp.float32) * inv
            found = found | ~jnp.all(jnp.isfinite(g))
            p.grad.data = g.astype(p.grad.dtype)
        self._state["found_inf"] = found
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not self._unscaled:
            self.unscale_(optimizer)
        found = self._state["found_inf"]
        # snapshot, run the update, then blend back where inf was found —
        # trace-compatible equivalent of "skip optimizer.step() on inf".
        # prime() first so lazily-created accumulators exist at their TRUE
        # initial values (e.g. Adagrad's initial_accumulator) before the
        # snapshot — otherwise a skipped first step would blend them to 0.
        if hasattr(optimizer, "prime"):
            optimizer.prime()
        old_params = [(p, p.data) for p in optimizer._parameter_list]
        old_state = dict(optimizer._state)
        old_master = dict(optimizer._master_weights)
        optimizer.step()
        for k, new in optimizer._state.items():
            old = old_state.get(k)
            if old is None:
                old = jnp.zeros_like(new)
            optimizer._state[k] = jnp.where(found, old, new)
        for p, old in old_params:
            p.data = jnp.where(found, old, p.data)
        for k, new in optimizer._master_weights.items():
            old = old_master.get(k)
            if old is not None:
                optimizer._master_weights[k] = jnp.where(found, old, new)
        self._unscaled = False

    def update(self):
        if not self._enable:
            return
        st = self._state
        if not self._dynamic:
            st["found_inf"] = jnp.asarray(False, jnp.bool_)
            return
        found = st["found_inf"]
        bad = jnp.where(found, st["bad"] + 1, jnp.asarray(0, jnp.int32))
        good = jnp.where(found, jnp.asarray(0, jnp.int32), st["good"] + 1)
        shrink = bad >= self._decr_every
        grow = good >= self._incr_every
        scale = st["scale"]
        scale = jnp.where(shrink,
                          jnp.maximum(scale * self._decr_ratio, 1.0), scale)
        scale = jnp.where(grow, scale * self._incr_ratio, scale)
        st["scale"] = scale
        st["bad"] = jnp.where(shrink, 0, bad)
        st["good"] = jnp.where(grow, 0, good)
        st["found_inf"] = jnp.asarray(False, jnp.bool_)

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return float(np.asarray(self._state["scale"]))

    def state_dict(self):
        return {"scale": float(np.asarray(self._state["scale"])),
                "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "good": int(np.asarray(self._state["good"])),
                "bad": int(np.asarray(self._state["bad"]))}

    def load_state_dict(self, state):
        self._state["scale"] = jnp.asarray(
            state.get("scale", self.get_init_loss_scaling()), jnp.float32)
        self._state["good"] = jnp.asarray(state.get("good", 0), jnp.int32)
        self._state["bad"] = jnp.asarray(state.get("bad", 0), jnp.int32)


from . import debugging  # noqa: E402,F401  (ref: paddle.amp.debugging)
