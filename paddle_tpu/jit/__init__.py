"""paddle_tpu.jit — dygraph-to-compiled bridge.

Replaces the reference's THREE graph-capture systems
(ref: python/paddle/jit/dy2static AST transforms, jit/sot bytecode tracing,
and the static Program/Executor stack, ~70k LoC combined) with one
mechanism: the eager vjp-tape runs unmodified under `jax.jit` tracing, so a
whole Paddle-style train step — forward, `loss.backward()`,
`optimizer.step()` — traces into ONE XLA executable. No graph breaks, no
bytecode guards; Python control flow is resolved at trace time exactly like
SOT's static path.

`to_static(layer)`     — compiled forward (inference / eval)
`TrainStep(model, opt, fn)` — compiled full training step (fwd+bwd+update)
"""
from __future__ import annotations

import functools
import itertools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..framework import core
from ..observability import device_events as _devev
from ..observability import goodput as _goodput
from ..observability import metrics as _om
from ..tensor import Tensor

__all__ = ["to_static", "not_to_static", "TrainStep", "train_step", "save",
           "load", "ignore_module", "enable_to_static", "InputSpec",
           "TranslatedLayer"]

_to_static_enabled = True


def enable_to_static(flag: bool):
    global _to_static_enabled
    _to_static_enabled = bool(flag)


def _tree_unbox(x):
    """Tensor -> array, pass through everything else (pytree-mapped)."""
    return jax.tree_util.tree_map(
        lambda v: v.data if isinstance(v, Tensor) else v, x,
        is_leaf=lambda v: isinstance(v, Tensor))


def _tree_box(x):
    return jax.tree_util.tree_map(
        lambda v: Tensor(v) if isinstance(v, jax.Array) else v, x)


def capture_state(model):
    """Split a model's state into (trainable params, everything else) as
    raw arrays — shared by TrainStep and the auto-parallel Engine."""
    from ..tensor import Parameter
    params, buffers = {}, {}
    for k, t in model.state_dict().items():
        if isinstance(t, Parameter) and not t.stop_gradient:
            params[k] = t.data
        else:
            buffers[k] = t.data
    return params, buffers


class StaticFunction:
    """Compiled wrapper over a Layer (or bound layer method)."""

    def __init__(self, function, layer=None, input_spec=None):
        self._fn = function
        self._layer = layer
        if layer is None and hasattr(function, "__self__"):
            from ..nn.layer.layers import Layer
            if isinstance(function.__self__, Layer):
                self._layer = function.__self__
        self._compiled = None
        self._input_spec = input_spec
        self._fallback = False
        self._sot = None
        self._ast_fn = None           # dy2static-lowered variant
        self._ast_tried = False

    def _build(self, fn=None):
        layer = self._layer
        fn = fn or self._fn

        @functools.partial(jax.jit)
        def compiled(state, key, args, kwargs):
            def run():
                with core.rng_key_context(key):
                    with core.no_grad_guard():
                        out = fn(*_tree_box(args), **_tree_box(kwargs))
                    new_state = ({k: t.data for k, t in layer.state_dict().items()}
                                 if layer is not None else {})
                    return _tree_unbox(out), new_state
            if layer is not None:
                with layer.use_state(state):
                    return run()
            return run()

        self._compiled = compiled

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled or self._fallback:
            return self._fn(*args, **kwargs)
        if self._sot is not None:     # split at a recorded graph break
            from .sot import SotCaptureError
            try:
                return self._sot(*args, **kwargs)
            except SotCaptureError:
                # machinery failure (guard thrash, non-replayable op) —
                # user exceptions propagate unchanged
                self._sot = None
                self._fallback = True
                return self._fn(*args, **kwargs)
        if self._compiled is None:
            self._build()
        state = ({k: t.data for k, t in self._layer.state_dict().items()}
                 if self._layer is not None else {})
        key = core.next_rng_key()
        try:
            out, new_state = self._compiled(state, key, _tree_unbox(args),
                                            _tree_unbox(kwargs))
        except (jax.errors.TracerBoolConversionError,
                jax.errors.ConcretizationTypeError,
                jax.errors.TracerIntegerConversionError,
                jax.errors.TracerArrayConversionError,
                jax.errors.NonConcreteBooleanIndexError) as e:
            # 1st recovery: dy2static AST lowering (ref transformers/
            # ifelse_transformer.py + while_loop_transformer.py) — rewrite
            # the Python if/while into lax.cond/lax.while_loop so the
            # whole function STAYS one executable with no per-branch or
            # per-trip-count respecialization (VERDICT r3 #5).
            if not self._ast_tried:
                self._ast_tried = True
                from .dy2static import ast_rewrite
                try:
                    self._ast_fn = ast_rewrite(self._fn)
                except Exception:
                    self._ast_fn = None
                if self._ast_fn is not None:
                    try:
                        self._build(self._ast_fn)
                        out, new_state = self._compiled(
                            state, key, _tree_unbox(args),
                            _tree_unbox(kwargs))
                        if self._layer is not None:
                            sd = self._layer.state_dict()
                            for k, v in new_state.items():
                                if k in sd:
                                    sd[k].data = v
                        return _tree_box(out)
                    except Exception:
                        # unloweable after all (shape-varying carry,
                        # name errors): rebuild the original and fall
                        # through to the SOT fragment path
                        self._ast_fn = None
                        self._build()
            # 2nd recovery: SOT graph break (ref jit/sot/
            # opcode_executor.py): split at the unsupported construct
            # and stitch compiled fragments around the host-side value
            # pull instead of de-optimizing the whole function to eager.
            # Guarded specializations re-capture when the pulled value
            # takes the other branch.
            from .sot import SotCaptureError, SubgraphProgram
            import warnings
            warnings.warn(
                f"to_static: data-dependent control flow broke whole-"
                f"function tracing ({type(e).__name__}); splitting into "
                "compiled sub-graph fragments at the break (ref SOT "
                "graph-break semantics)", stacklevel=2)
            self._sot = SubgraphProgram(self._fn, self._layer)
            try:
                return self._sot(*args, **kwargs)
            except SotCaptureError:
                # not replayable (rng/state mutation in capture):
                # permanent eager fallback, as before round 3
                self._sot = None
                self._fallback = True
                return self._fn(*args, **kwargs)
        if self._layer is not None:
            sd = self._layer.state_dict()
            for k, v in new_state.items():
                if k in sd:
                    sd[k].data = v
        return _tree_box(out)

    @property
    def forward(self):
        return self


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """ref: python/paddle/jit/api.py::to_static. Decorator or call."""
    from ..nn.layer.layers import Layer

    def decorate(f):
        if isinstance(f, Layer):
            static = StaticFunction(f.forward, layer=f, input_spec=input_spec)
            f.forward = static
            return f
        return StaticFunction(f, input_spec=input_spec)

    if function is None:
        return decorate
    return decorate(function)


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    return None


def _quant_sync_grads(model, ef, axis, nranks, cfg):
    """Quantized data-parallel gradient sync (ISSUE 8): inside the
    shard_map-wrapped step body, replace every trainable param's LOCAL
    grad with the blockwise-quantized mean over the `axis` shards
    (collective.grad_sync_all_reduce — the explicit EQuARX chain that
    stands in for the implicit GSPMD psum). `ef` carries this shard's
    error-feedback residuals ((1, padded) slices of the dp-sharded
    state); returns the updated residual tree."""
    from ..distributed import collective as _coll
    from ..tensor import Parameter
    new_ef = dict(ef or {})
    for k, t in model.state_dict().items():
        if not (isinstance(t, Parameter) and not t.stop_gradient):
            continue
        g = t.grad
        if g is None:
            continue
        garr = g.data if isinstance(g, Tensor) else g
        res = ef[k].reshape(-1) if ef and k in ef else None
        synced, new_res = _coll.grad_sync_all_reduce(
            garr, axis=axis, nranks=nranks, cfg=cfg, residual=res)
        t.grad = Tensor(synced)
        if new_res is not None and ef and k in ef:
            new_ef[k] = new_res.reshape(ef[k].shape)
    return new_ef


# per-rank optimizer-state footprint of a compiled TrainStep (ISSUE 16):
# recorded once per build, after the first step materializes the state —
# the ZeRO HBM saving (and any regression) is visible in /metrics
_OPT_STATE_BYTES = _om.gauge(
    "train.opt_state_bytes",
    "per-rank optimizer-state bytes of a compiled TrainStep by executable")


def _per_rank_nbytes(arr):
    """Bytes ONE rank holds of `arr`: the addressable-shard size for
    sharded jax Arrays (ZeRO state slices), the full buffer for
    replicated/host arrays."""
    try:
        if isinstance(arr, jax.Array) and len(arr.sharding.device_set) > 1:
            shards = arr.addressable_shards
            if shards:
                return int(shards[0].data.nbytes)
    except Exception:
        pass
    return int(getattr(arr, "nbytes", 0) or 0)


def _zero_sharded_update(model, opt, ef, axis, nranks, stage, cfg, block):
    """ZeRO-1/2 weight update (arxiv 2004.13336), inside the
    shard_map-wrapped step body after backward: every trainable param's
    LOCAL grad is mean-reduce-scattered over `axis`
    (collective.zero_grad_reduce_scatter — quantized phase-1 chain when
    `cfg` is armed), the optimizer update runs on THIS rank's flat
    (s,)-shard of the param with shard-shaped accumulator state (lazily
    zeros_like(w_shard) — 1/nranks the replicated footprint), and the
    updated shards are all-gathered back to the replicated param
    (collective.zero_param_all_gather, always exact). The flat layout is
    quantization/comm.py's shard_sizes(numel, nranks, block) contract —
    padding at the tail, so padded lanes carry zero grads and zero
    moments and never reach the unpadded weights. Returns the updated
    error-feedback residual tree (quantized wire only)."""
    from ..distributed import collective as _coll
    from ..quantization import comm as _qcomm
    from ..tensor import Parameter
    opt._step_count += 1
    lr = opt.get_lr()
    new_ef = dict(ef or {})
    for k, t in model.state_dict().items():
        if not (isinstance(t, Parameter) and not t.stop_gradient):
            continue
        g = t.grad
        if g is None:
            continue
        garr = g.data if isinstance(g, Tensor) else g
        res = ef[k].reshape(-1) if ef and k in ef else None
        shard_g, new_res = _coll.zero_grad_reduce_scatter(
            garr, axis=axis, nranks=nranks, stage=stage, block=block,
            cfg=cfg, residual=res)
        numel = int(t.data.size)
        s, padded = _qcomm.shard_sizes(numel, nranks, block)
        w_flat = jnp.pad(t.data.ravel(), (0, padded - numel))
        start = jax.lax.axis_index(axis) * s
        w_shard = jax.lax.dynamic_slice(w_flat, (start,), (s,))
        gs = shard_g.astype(w_shard.dtype)
        plr = lr * t.optimize_attr.get("learning_rate", 1.0) \
            if hasattr(t, "optimize_attr") else lr
        if t.regularizer is not None:
            gs = gs + t.regularizer(w_shard)
        new_shard = opt._apply_one(t, w_shard, gs, plr).astype(w_shard.dtype)
        full = _coll.zero_param_all_gather(new_shard, axis=axis)
        t.data = full[:numel].reshape(t.data.shape)
        if new_res is not None and ef and k in ef:
            new_ef[k] = new_res.reshape(ef[k].shape)
    return new_ef


def resolve_remat_policy(policy):
    """Map TrainStep's remat_policy= knob onto a jax.checkpoint policy.

    None             -> jax.checkpoint's own default (save nothing,
                        recompute everything) — bitwise the pre-knob remat
    "save_matmul_outputs" (the TrainStep default) ->
                        save_only_these_names over the
                        checkpoint_name-stamped matmul outputs
                        (models.llama.MATMUL_CHECKPOINT_NAMES); models
                        that stamp no names degrade to the save-nothing
                        default
    "nothing"        -> nothing_saveable (explicit recompute-everything)
    "dots"           -> checkpoint_dots (save every unnamed matmul too)
    callable         -> passed through (any jax.checkpoint_policies
                        predicate)

    Policies change memory/recompute placement only, never values.
    """
    if policy is None or callable(policy):
        return policy
    if policy == "save_matmul_outputs":
        from ..models.llama import MATMUL_CHECKPOINT_NAMES
        return jax.checkpoint_policies.save_only_these_names(
            *MATMUL_CHECKPOINT_NAMES)
    if policy in ("nothing", "recompute_all"):
        return jax.checkpoint_policies.nothing_saveable
    if policy == "dots":
        return jax.checkpoint_policies.checkpoint_dots
    raise ValueError(
        f"TrainStep: unknown remat_policy {policy!r} — expected None, "
        f"'save_matmul_outputs', 'nothing', 'dots' or a "
        f"jax.checkpoint_policies callable")


# ordinal suffixes for TrainStep executable tags (see _exec_tag)
_TRAIN_STEP_TAGS = itertools.count(1)


class TrainStep:
    """One-call compiled training step: forward + backward + optimizer update
    in a single XLA executable (the TPU-native answer to the reference's
    Program+InterpreterCore pipeline, ref SURVEY §3.3).

    step_fn: callable(*batch_tensors) -> loss Tensor; must route all model
    calls through `model` and set grads only via the tape.

    Optional `shard`: a paddle_tpu.distributed.ShardingPlan that places
    params/optimizer state/batch on a mesh (GSPMD partitioning).

    Optional `accumulate_steps=k` (ref: the GradientMerge meta-optimizer
    pass, fleet/meta_optimizers/gradient_merge_optimizer.py): the batch
    is split into k micro-batches on its leading axis and a lax.scan
    inside the SAME executable accumulates gradients across them, with
    ONE optimizer update at the end — activation memory drops ~k-fold
    while the optimizer sees the full global batch. The reference
    replays the program k times and conditions the update on a step
    counter; under XLA the scan keeps it a single compiled step with no
    host round-trips. Requires batch leading dims divisible by k;
    incompatible with a GradScaler (bf16 training needs no loss
    scaling — pass scaler=None).
    """

    def __init__(self, model, optimizer, step_fn, scaler=None, shard=None,
                 donate=True, accumulate_steps=1,
                 remat_policy="save_matmul_outputs"):
        self.model = model
        self.optimizer = optimizer
        self.step_fn = step_fn
        self.scaler = scaler
        self.shard = shard
        if shard is not None and hasattr(shard, "attach_model"):
            shard.attach_model(model)
        if shard is not None and getattr(shard, "grad_sync", None):
            if scaler is not None:
                raise ValueError(
                    "quantized grad sync (ShardingPlan(grad_sync=...)) is "
                    "incompatible with a GradScaler: the chain reduces "
                    "unscaled f32 gradients (bf16 training does not need "
                    "loss scaling)")
            if int(accumulate_steps) > 1:
                raise ValueError(
                    "quantized grad sync does not compose with "
                    "accumulate_steps > 1 yet — the gradient-merge scan "
                    "owns the backward/update interleaving")
        if shard is not None and getattr(shard, "zero", 0):
            if scaler is not None:
                raise ValueError(
                    "the ZeRO sharded update (ShardingPlan(zero=...)) is "
                    "incompatible with a GradScaler: the reduce-scatter "
                    "chain works on unscaled f32 gradients (bf16 training "
                    "does not need loss scaling)")
            if int(accumulate_steps) > 1:
                raise ValueError(
                    "the ZeRO sharded update does not compose with "
                    "accumulate_steps > 1 yet — the gradient-merge scan "
                    "owns the backward/update interleaving")
            if getattr(optimizer, "_grad_clip", None) is not None:
                raise ValueError(
                    "the ZeRO sharded update does not support grad_clip "
                    "yet: global-norm clipping needs a cross-shard norm "
                    "before the per-shard update")
            if getattr(optimizer, "_master_weights", None):
                raise ValueError(
                    "the ZeRO sharded update does not compose with amp O2 "
                    "master weights yet (fp8/f32 master-weight sharding is "
                    "a planned follow-on) — use amp level O1 or zero=0")
            from ..optimizer.optimizer import ASGD, LBFGS, Lamb
            if isinstance(optimizer, (Lamb, ASGD, LBFGS)):
                raise ValueError(
                    f"the ZeRO sharded update supports elementwise "
                    f"per-shard optimizers only; "
                    f"{type(optimizer).__name__} needs whole-parameter "
                    f"reductions (trust ratios / multi-row state) — use "
                    f"zero=0 or an Adam-family/SGD optimizer")
        # make the plan visible to DataLoader prefetchers so batches
        # stage straight into the mesh layout (io/prefetch.py picks up
        # the active plan's batch_spec at iteration time). Latest step
        # wins: an unsharded TrainStep clears a predecessor's plan so
        # loaders don't keep staging into a dead job's mesh layout
        from ..io import prefetch as _prefetch
        _prefetch.set_active_plan(shard)
        self._compiled = None
        self._donate = donate
        # jax.checkpoint policy armed while the step traces (consumed by
        # the models' remat sites via core.current_remat_policy). The
        # default saves the checkpoint_name-stamped matmul outputs so
        # norms/activations recompute instead of living across the
        # backward; models that stamp no names degrade to
        # jax.checkpoint's save-nothing default — bitwise the old remat
        self._remat_policy = resolve_remat_policy(remat_policy)
        self._key_base = None     # per-instance RNG base (see __call__)
        # stable executable tag stamped at trace time: per-execution
        # device telemetry (xla.dispatch_seconds, per-execution collective
        # counts) and compile attribution key on it. First instance is
        # plain "train_step" so single-step jobs need no label juggling.
        n = next(_TRAIN_STEP_TAGS)
        self._exec_tag = "train_step" if n == 1 else f"train_step_{n}"
        self._step_flops = None   # executable cost_analysis FLOPs (MFU)
        self._accum = int(accumulate_steps)
        self._quant = None        # (axis, nranks, CommQuantConfig) at build
        # (axis, nranks, zero_stage, cfg_or_None, block) at build
        self._zero = None
        self._ef_state = None     # error-feedback residuals (dp-sharded)
        self._opt_state_bytes = None  # per-rank bytes, set after build step
        if self._accum > 1 and scaler is not None:
            raise ValueError(
                "accumulate_steps > 1 is incompatible with a GradScaler: "
                "micro-grads are merged unscaled inside one executable "
                "(bf16 training does not need loss scaling)")

    def _capture_state(self):
        return capture_state(self.model)

    def _ensure_ef_state(self, params):
        """Allocate the error-feedback residual tree on first use: one
        zero (nranks, padded) f32 array per trainable param, sharded on
        the sync axis so each dp shard carries its OWN residual across
        steps (optimizer-adjacent state — it is this TrainStep's, not
        the optimizer dict's, because it is per-rank rather than
        replicated). Empty when error feedback is off (or the ZeRO wire
        is exact)."""
        if self._quant is not None:
            axis, nranks, cfg = self._quant
        else:
            axis, nranks, _stage, cfg, _block = self._zero
        if cfg is None or not cfg.error_feedback:
            return {}
        if self._ef_state is None:
            import numpy as _np
            from jax.sharding import NamedSharding, PartitionSpec as _P

            from ..quantization import comm as _qcomm
            sharding = NamedSharding(self.shard.mesh, _P(axis))
            self._ef_state = {
                k: jax.device_put(
                    _np.zeros(
                        (nranks,
                         _qcomm.shard_sizes(v.size, nranks, cfg.block)[1]),
                        _np.float32), sharding)
                for k, v in params.items()}
        return self._ef_state

    def _build(self):
        model = self.model
        opt = self.optimizer
        step_fn = self.step_fn
        scaler = self.scaler
        accum = self._accum
        # quantized grad sync arms at BUILD time so the kill switch
        # (FLAGS_quant_collectives=0) restores the plain GSPMD-psum
        # compile path bitwise, opted-in plan or not
        quant = None
        # the ZeRO sharded update likewise arms at BUILD time
        # (FLAGS_zero=0 restores the replicated compile paths bitwise);
        # when armed it OWNS the step body — grad_sync then only selects
        # the wire mode of the ZeRO reduce-scatter
        zero = None
        if self.shard is not None and getattr(self.shard, "zero", 0) and \
                self.shard.zero_armed():
            axis, nranks = self.shard.quant_sync_axis()
            if getattr(opt, "_master_weights", None):
                raise ValueError(
                    "the ZeRO sharded update does not compose with amp O2 "
                    "master weights yet — use amp level O1 or zero=0")
            cfg = self.shard.zero_wire_config()
            zero = (axis, nranks, self.shard.zero, cfg,
                    self.shard.zero_block())
        elif self.shard is not None and \
                getattr(self.shard, "grad_sync", None) and \
                core.get_bool_flag("FLAGS_quant_collectives", True):
            from ..quantization import comm as _qcomm
            axis, nranks = self.shard.quant_sync_axis()
            cfg = _qcomm.resolve_config(
                self.shard.grad_sync, self.shard.grad_sync_block,
                self.shard.grad_sync_error_feedback)
            quant = (axis, nranks, cfg)
        self._quant = quant
        self._zero = zero

        def run_accum(batch, key):
            """Gradient-merge path: lax.scan over k micro-batches, grads
            accumulated as the carry, one optimizer update at the end.
            Runs under model.use_state, so sd tensors are the traced
            params."""
            from ..tensor import Tensor as _TT
            sd = model.state_dict()
            pkeys = [k for k, t in sd.items()
                     if not getattr(t, "stop_gradient", True)]
            ptensors = [sd[k] for k in pkeys]
            pset = set(pkeys)
            # non-trainable state (BatchNorm running stats, …) mutates
            # during forward; thread it through the scan carry so body
            # tracers never leak into the outer trace and the final
            # values are the k-th micro-step's, same as k eager steps
            btensors = [t for k, t in sd.items() if k not in pset]

            def split_leading(x):
                if x.shape[0] % accum:
                    raise ValueError(
                        f"accumulate_steps={accum} must divide the batch "
                        f"leading dim {x.shape[0]}")
                return x.reshape((accum, x.shape[0] // accum) + x.shape[1:])

            micro = jax.tree_util.tree_map(split_leading, batch)
            mkeys = jax.random.key_data(jax.random.split(key, accum))
            zero = [jnp.zeros_like(p.data) for p in ptensors]
            # which params the loss actually reaches is STATIC (the scan
            # body traces once); record it so untouched params keep
            # grad=None and are skipped by opt.step() exactly like the
            # non-accumulating path (no spurious weight-decay updates)
            touched = set()

            def body(carry, xs):
                acc, loss_sum, bufs = carry
                mb, mk = xs
                for t, b in zip(btensors, bufs):
                    t.data = b
                with core.rng_key_context(jax.random.wrap_key_data(mk)):
                    loss = step_fn(*_tree_box(mb))
                    loss.backward()
                new_acc = []
                for i, (a, p) in enumerate(zip(acc, ptensors)):
                    g = p.grad
                    if g is None:
                        new_acc.append(a)
                    else:
                        touched.add(i)
                        gd = g.data if isinstance(g, _TT) else g
                        new_acc.append(a + gd.astype(a.dtype))
                opt.clear_grad(set_to_zero=False)
                return (new_acc,
                        loss_sum + loss.data.astype(jnp.float32),
                        [t.data for t in btensors]), None

            (grads, loss_sum, final_bufs), _ = jax.lax.scan(
                body, (zero, jnp.float32(0),
                       [t.data for t in btensors]), (micro, mkeys))
            for t, b in zip(btensors, final_bufs):
                t.data = b
            inv_k = 1.0 / accum
            for i, (p, g) in enumerate(zip(ptensors, grads)):
                if i in touched:
                    p.grad = _TT((g * inv_k).astype(g.dtype))
            opt.step()
            return _TT(loss_sum * inv_k)

        def _pure_body(params, buffers, opt_state, master, scaler_state,
                       step_i, lr, key, batch, ef=None):
            # key travels as raw uint32 key-data (host numpy — typed PRNG
            # keys are committed device arrays, which a multi-process
            # mesh jit cannot accept); rewrap to a typed key here. The
            # per-step stream derives from the step counter IN-TRACE
            # (domain-tagged so it cannot collide with the eager
            # fold_in(counter) stream) — no per-call device RNG work.
            key = jax.random.wrap_key_data(key)
            key = jax.random.fold_in(
                jax.random.fold_in(key, 0x54524E), step_i)
            if quant is not None or zero is not None:
                # per-shard randomness: the body runs once per dp shard
                # (shard_map), each on its own batch slice — distinct
                # dropout masks per shard, like the GSPMD global mask
                key = jax.random.fold_in(
                    key, jax.lax.axis_index((quant or zero)[0]))
            state = {}
            state.update(params)
            state.update(buffers)
            saved_state = opt._state
            saved_step = opt._step_count
            saved_master = opt._master_weights
            saved_lr = opt._lr
            saved_scaler = (scaler._get_traced_state()
                            if scaler is not None else None)
            with model.use_state(state):
                with core.rng_key_context(key):
                    opt._state = dict(opt_state)
                    opt._step_count = step_i
                    opt._master_weights = dict(master)
                    # ALWAYS run the compiled update off the per-call lr
                    # argument: __call__ evaluates scheduler/value on the
                    # host each step. Keeping a scheduler object here
                    # would bake float(scheduler()) at TRACE time — the
                    # schedule would silently never reach the weights.
                    opt._lr = lr
                    if scaler is not None:
                        scaler._set_traced_state(scaler_state)
                    try:
                        new_ef = ef
                        if zero is not None:
                            # ZeRO sharded update: backward yields LOCAL
                            # grads (per-shard body); the rs -> shard
                            # update -> ag sequence replaces opt.step()
                            loss = step_fn(*_tree_box(batch))
                            loss.backward()
                            new_ef = _zero_sharded_update(
                                model, opt, ef, zero[0], zero[1],
                                zero[2], zero[3], zero[4])
                        elif quant is not None:
                            # quantized DP sync: the body is per-shard
                            # (shard_map) so backward yields LOCAL
                            # grads; the explicit quantized chain is
                            # their mean before the update
                            loss = step_fn(*_tree_box(batch))
                            loss.backward()
                            new_ef = _quant_sync_grads(
                                model, ef, quant[0], quant[1], quant[2])
                            opt.step()
                        elif scaler is not None:
                            loss = step_fn(*_tree_box(batch))
                            scaler.scale(loss).backward()
                            scaler.step(opt)
                            scaler.update()
                        elif accum > 1:
                            loss = run_accum(batch, key)
                        else:
                            loss = step_fn(*_tree_box(batch))
                            loss.backward()
                            opt.step()
                        # in-trace: drop grads entirely — zero-filled
                        # grads here would be traced values leaking out
                        opt.clear_grad(set_to_zero=False)
                        sd = model.state_dict()
                        new_params = {k: sd[k].data for k in params}
                        new_buffers = {k: sd[k].data for k in buffers}
                        new_opt_state = dict(opt._state)
                        new_master = dict(opt._master_weights)
                        new_scaler = (scaler._get_traced_state()
                                      if scaler is not None else {})
                    finally:
                        opt._state = saved_state
                        opt._step_count = saved_step
                        opt._master_weights = saved_master
                        opt._lr = saved_lr
                        if scaler is not None:
                            scaler._set_traced_state(saved_scaler)
            if quant is not None or zero is not None:
                # global loss = mean of the per-shard means; float
                # buffers (BatchNorm running stats) likewise averaged so
                # the replicated outputs are well-defined — each shard
                # saw only its batch slice
                axis = (quant or zero)[0]
                new_buffers = {
                    k: (jax.lax.pmean(v, axis)
                        if jnp.issubdtype(v.dtype, jnp.floating) else v)
                    for k, v in new_buffers.items()}
                return (jax.lax.pmean(loss.data, axis), new_params,
                        new_buffers, new_opt_state, new_master,
                        new_scaler, new_ef)
            return (loss.data, new_params, new_buffers, new_opt_state,
                    new_master, new_scaler)

        remat_pol = self._remat_policy

        def pure(params, buffers, opt_state, master, scaler_state, step_i,
                 lr, key, batch, ef=None):
            # arm the jax.checkpoint policy for THIS trace — the models'
            # remat sites (_scan_stack/_recompute_stack) read it via
            # core.current_remat_policy() while the body traces
            with core.remat_policy_guard(remat_pol):
                return _pure_body(params, buffers, opt_state, master,
                                  scaler_state, step_i, lr, key, batch, ef)

        # FLAGS_eager_delete_tensor_gb < 0 disables buffer donation (the
        # reference's eager-deletion kill switch maps to donation here);
        # FLAGS_max_inplace_grad_add > 0 is the explicit opt-IN for
        # in-place grad-buffer reuse and overrides that veto
        flag_gb = core.get_flag("FLAGS_eager_delete_tensor_gb", 0.0)
        force_inplace = int(float(
            core.get_flag("FLAGS_max_inplace_grad_add", 0) or 0)) > 0
        donate_ok = self._donate and (
            force_inplace or float(flag_gb or 0.0) >= 0.0)
        donate = (0, 1, 2, 3) if donate_ok else ()
        if zero is not None:
            # ef (arg 9) is consumed and returned every step, like quant
            zdonate = donate + (9,) if donate_ok else ()
            self._compiled = self.shard.compile_zero_train_step(
                pure, zdonate)
        elif quant is not None:
            # the error-feedback residual tree (arg 9) is donated too:
            # it is consumed and returned every step
            qdonate = donate + (9,) if donate_ok else ()
            self._compiled = self.shard.compile_quantized_train_step(
                pure, qdonate)
        elif self.shard is not None:
            self._compiled = self.shard.compile_train_step(pure, donate)
        else:
            self._compiled = jax.jit(pure, donate_argnums=donate)

    def __call__(self, *batch):
        if self._compiled is None:
            # materialize optimizer state before the first trace: otherwise
            # the state tree widens after step 1 and the whole step
            # recompiles (minutes for large models). NOT under an armed
            # ZeRO plan: priming would allocate the full replicated
            # state the mode exists to avoid — the body creates
            # shard-shaped slots inside the first step instead (one
            # extra compile, 1/nranks the state HBM from step 0 on)
            zero_pending = (self.shard is not None
                            and getattr(self.shard, "zero", 0)
                            and self.shard.zero_armed())
            if hasattr(self.optimizer, "prime") and not zero_pending:
                self.optimizer.prime()
            self._build()
        opt = self.optimizer
        params, buffers = self._capture_state()
        # host scalars, not committed device arrays: on a multi-PROCESS
        # mesh jit can place numpy inputs into replicated shardings but
        # cannot reshard a single-local-device jax array onto devices it
        # does not own
        import numpy as _np
        lr = _np.float32(opt.get_lr())
        # opt.step() inside the compiled fn performs the +1 itself
        step_i = _np.int32(opt._step_count)
        if core._rng.stack:
            # an active rng_key_context must keep steering compiled-step
            # randomness (the fleet TP rng-tracker pattern): split the
            # context key per call, as before
            key = _np.asarray(jax.random.key_data(core.next_rng_key()))
        else:
            if self._key_base is None:
                # one fold of the globally-advancing eager counter per
                # TrainStep INSTANCE: distinct streams for successive
                # TrainSteps even when their step counters overlap,
                # deterministic under paddle.seed, and base-cache
                # invalidation (seed / set_rng_state) is respected
                self._key_base = _np.asarray(
                    jax.random.key_data(core.next_rng_key()))
                self._key_base_src = core.base_rng_key_data()
            elif self._key_base_src is not core.base_rng_key_data():
                self._key_base = _np.asarray(
                    jax.random.key_data(core.next_rng_key()))
                self._key_base_src = core.base_rng_key_data()
            key = self._key_base
        batch_arrays = _tree_unbox(batch)
        if self.shard is not None and hasattr(self.shard, "reshard_batch"):
            # committed prefetched batches must match the compiled batch
            # in_shardings — see ShardingPlan.reshard_batch
            batch_arrays = self.shard.reshard_batch(batch_arrays)
        scaler_state = (self.scaler._get_traced_state()
                        if self.scaler is not None else {})
        bench = core.get_bool_flag("FLAGS_benchmark")
        if bench:
            import time as _time
            _t0 = _time.perf_counter()
        armed = _om.enabled()
        call_args = (params, buffers, dict(opt._state),
                     dict(opt._master_weights), scaler_state,
                     step_i, lr, key, batch_arrays)
        if self._quant is not None or self._zero is not None:
            call_args = call_args + (self._ensure_ef_state(params),)
        if armed and self._step_flops is None:
            # must run BEFORE the call: args 0-3 are donated by it
            self._step_flops = self._lower_flops(call_args)
        if armed:
            # execution window: xla.dispatch_seconds{executable=tag} +
            # per-execution collective counts replayed from the tag's
            # trace-time composition (observability/device_events.py)
            with _devev.execution(self._exec_tag):
                outs = self._compiled(*call_args)
        else:
            outs = self._compiled(*call_args)
        if self._quant is not None or self._zero is not None:
            (loss, new_params, new_buffers, new_opt_state, new_master,
             new_scaler, new_ef) = outs
            if new_ef:
                self._ef_state = new_ef
        else:
            (loss, new_params, new_buffers, new_opt_state, new_master,
             new_scaler) = outs
        sd = self.model.state_dict()
        for k, v in new_params.items():
            sd[k].data = v
        for k, v in new_buffers.items():
            sd[k].data = v
        opt._state = dict(new_opt_state)
        opt._master_weights = dict(new_master)
        if self._opt_state_bytes is None:
            # the build step materialized every state slot (primed, or
            # shard-created under ZeRO) — record the per-rank footprint
            self._opt_state_bytes = self.opt_state_bytes_per_rank()
            if armed:
                _OPT_STATE_BYTES.set(self._opt_state_bytes,
                                     executable=self._exec_tag)
        if self.scaler is not None:
            self.scaler._set_traced_state(new_scaler)
        opt._step_count += 1
        if bench:
            import sys as _sys
            jax.block_until_ready(loss)
            print(f"TrainStep[{opt._step_count}]: "
                  f"{(_time.perf_counter() - _t0) * 1e3:.2f} ms",
                  file=_sys.stderr)
        if core.get_bool_flag("FLAGS_log_memory_stats"):
            # real device.memory_stats() readings, mirrored into the
            # metrics registry gauges (device.bytes_in_use /
            # device.peak_bytes_in_use); backends without memory_stats
            # (CPU jaxlib returns None) no-op cleanly — no zeros printed
            import sys as _sys
            from .. import observability as _obs
            mem = _obs.update_device_memory_gauges()
            if mem is not None:
                print(f"TrainStep[{opt._step_count}] memory: "
                      f"in_use={mem['bytes_in_use']} "
                      f"peak={mem['peak_bytes_in_use']}",
                      file=_sys.stderr)
        if core.get_bool_flag("FLAGS_check_nan_inf"):
            # compiled-path sweep: values can't be branched on at trace
            # time, so the check runs on the step RESULT; rerun in eager
            # mode for per-op localization (tape._check_nan_inf)
            import numpy as _np
            if not _np.isfinite(_np.asarray(loss)).all():
                raise FloatingPointError(
                    "NaN or Inf in TrainStep loss (FLAGS_check_nan_inf). "
                    "Rerun the step eagerly (without TrainStep) to get the "
                    "failing op's name.")
            bad = [k for k, v in new_params.items()
                   if jnp.issubdtype(v.dtype, jnp.floating)
                   and not _np.isfinite(_np.asarray(v)).all()]
            if bad:
                raise FloatingPointError(
                    f"NaN or Inf in updated parameters {bad[:5]} "
                    "(FLAGS_check_nan_inf)")
        if armed:
            # close this step's goodput window: whatever the window's
            # wall wasn't attributed (data wait, host pulls, compile,
            # checkpoint/elastic stalls) is productive device-execute;
            # the executable's own FLOPs feed the live MFU gauge
            _goodput.step_boundary(flops=self._step_flops)
        return Tensor(loss)

    def opt_state_bytes_per_rank(self):
        """Bytes of optimizer state (accumulators + amp master weights)
        ONE rank holds: sharded ZeRO slots count a single shard,
        replicated slots their full buffer. Also exported as the
        train.opt_state_bytes gauge once per build."""
        opt = self.optimizer
        return sum(_per_rank_nbytes(v) for v in opt._state.values()) + \
            sum(_per_rank_nbytes(v) for v in opt._master_weights.values())

    def _lower_flops(self, call_args):
        """The executable's own FLOP count via lowered.cost_analysis()
        (the distributed/auto_parallel/cost_model.py seam) — one extra
        abstract trace, paid only on the first ARMED call."""
        try:
            with _devev.tagged(self._exec_tag):
                lowered = self._compiled.lower(*call_args)
            ca = lowered.cost_analysis() or {}
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            return float(ca.get("flops", 0.0) or 0.0)
        except Exception:
            return 0.0


def train_step(model, optimizer, step_fn, **kw):
    return TrainStep(model, optimizer, step_fn, **kw)


class InputSpec:
    """ref: paddle.static.InputSpec — shape/dtype signature for export."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name


def save(layer, path, input_spec=None, **configs):
    """ref: paddle.jit.save (python/paddle/jit/api.py). Persists BOTH the
    weights (`path.pdparams`) and, when `input_spec` is given, a serialized
    StableHLO program (`path.pdmodel` via jax.export) — the TPU-native
    inference artifact: `jit.load` runs it WITHOUT the model's Python code,
    like the reference's saved Program + TranslatedLayer."""
    from ..framework import io as fio
    fio.save(layer.state_dict(), path + ".pdparams")
    if input_spec is None:
        return
    from jax import export as jexport

    from ..framework import core

    state = {k: t.data for k, t in layer.state_dict().items()}

    def fwd(state, *inputs):
        with layer.use_state(state), core.no_grad_guard():
            out = layer(*_tree_box(list(inputs)))
        return _tree_unbox(out)

    # dynamic dims (None/-1) export as symbolic shapes so the artifact
    # accepts any size there (jax.export shape polymorphism)
    abstract = []
    for i, s in enumerate(input_spec):
        dt = core.convert_dtype(getattr(s, "dtype", "float32"))
        if any(d is None or d == -1 for d in s.shape):
            dims = ",".join(
                f"b{i}_{j}" if (d is None or d == -1) else str(d)
                for j, d in enumerate(s.shape))
            abstract.append(jax.ShapeDtypeStruct(
                jexport.symbolic_shape(dims), dt))
        else:
            abstract.append(jax.ShapeDtypeStruct(tuple(s.shape), dt))
    state_abs = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), state)
    try:   # portable artifact when every op lowers for both platforms
        exp = jexport.export(jax.jit(fwd), platforms=("cpu", "tpu"))(
            state_abs, *abstract)
    except Exception as e:
        import warnings
        warnings.warn(
            f"jit.save: multi-platform (cpu+tpu) lowering failed "
            f"({type(e).__name__}: {str(e)[:200]}); exporting for the "
            f"current backend only — the artifact will not load on other "
            "platforms", stacklevel=2)
        exp = jexport.export(jax.jit(fwd))(state_abs, *abstract)
    from ..framework.io import atomic_write
    blob = exp.serialize()
    # atomic commit: a crash mid-serialize must not tear the inference
    # artifact or destroy the previous one (ROADMAP lint-coverage item)
    atomic_write(path + ".pdmodel", lambda f: f.write(blob))


class TranslatedLayer:
    """Runs an exported program without model code (ref: jit/translated_layer)."""

    def __init__(self, exported, state):
        self._exported = exported
        self._state = state

    def __call__(self, *inputs):
        arrs = [x.data if isinstance(x, Tensor) else jnp.asarray(x)
                for x in inputs]
        out = self._exported.call(self._state, *arrs)
        return jax.tree_util.tree_map(
            lambda a: Tensor(a, stop_gradient=True), out)

    forward = __call__

    def state_dict(self):
        return {k: Tensor(v, stop_gradient=True)
                for k, v in self._state.items()}

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("exported inference programs cannot be trained")


def load(path, **configs):
    """paddle.jit.load: with a .pdmodel artifact returns a TranslatedLayer
    (callable, no model code needed); otherwise the raw state dict."""
    import os

    from ..framework import io as fio
    state = fio.load(path + ".pdparams")
    if not os.path.exists(path + ".pdmodel"):
        return state
    from jax import export as jexport
    with open(path + ".pdmodel", "rb") as f:
        exp = jexport.deserialize(f.read())
    arrs = {k: (v.data if isinstance(v, Tensor) else jnp.asarray(v))
            for k, v in state.items()}
    return TranslatedLayer(exp, arrs)
