"""SOT-style sub-graph capture with graph breaks
(ref: python/paddle/jit/sot/ — opcode_executor.py splits a function at
unsupported constructs and stitches compiled fragments around eager
gaps; function_graph.py holds the captured fragments; guards re-
specialize when a guarded value changes).

TPU-native translation: instead of a bytecode interpreter, capture uses
the tape's op stream. One instrumented EAGER run records every apply_op
(fn, inputs, outputs) plus every GRAPH BREAK — a point where Python
pulled a concrete value out of a Tensor (bool/int/float/item/numpy), the
exact construct that kills whole-function tracing. The op log is then
segmented at the breaks and each segment compiled as ONE jitted replay
fragment. Later calls run fragment -> pull guard value -> fragment; when
a pulled value diverges from the recorded one (the other side of a
data-dependent branch), the call re-captures a new specialization for
that guard path — the reference's guard/specialize semantics.

A function with a data-dependent `if` therefore runs as 2 compiled
fragments + a host-side branch, NOT whole-function eager (VERDICT r2
item 7)."""
from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..framework import core
from ..tensor import Tensor

__all__ = ["SubgraphProgram", "GraphBreak", "SotCaptureError"]


class SotCaptureError(RuntimeError):
    """Capture/replay machinery failure (NOT a user-function error):
    the caller should de-optimize to eager. User exceptions raised by
    the function itself propagate unchanged."""


# per-signature specialization cap: a guard that varies every call
# (e.g. an exact float pulled from real data) would otherwise recapture
# per call and pin every intermediate buffer forever
_MAX_SPECS = 8


class GraphBreak:
    """One recorded concrete-value pull (the break + its guard)."""
    __slots__ = ("op_index", "tensor", "kind", "value")

    def __init__(self, op_index, tensor, kind, value):
        self.op_index = op_index
        self.tensor = tensor
        self.kind = kind
        self.value = value


class _Capture:
    """Instrumented eager run artifacts: op log + breaks + io maps."""

    def __init__(self):
        # op log entries: (fn, arg_tensors(list|None), const_datas, outs)
        self.ops: List[Tuple] = []
        self.breaks: List[GraphBreak] = []


_active: Optional[_Capture] = None


def _record_op(fn, tensor_args, datas, outs, name):
    if _active is not None:
        _active.ops.append((fn, list(tensor_args), list(datas),
                            list(outs)))


_PULLS = ("__bool__", "__float__", "__int__", "__index__", "item",
          "numpy", "__array__")


@contextlib.contextmanager
def _instrument():
    """Route tape ops to the capture log and hook Tensor's concrete-value
    pulls as graph-break events."""
    global _active
    from ..autograd import tape
    cap = _Capture()
    _active = cap
    saved_rec = tape._STATIC_RECORDER
    tape._STATIC_RECORDER = _record_op
    saved = {m: getattr(Tensor, m) for m in _PULLS}

    def hook(method):
        orig = saved[method]

        def wrapped(self, *a, **kw):
            out = orig(self, *a, **kw)
            if _active is not None:
                guard = out
                if method in ("numpy", "__array__"):
                    guard = np.asarray(out).copy()
                _active.breaks.append(GraphBreak(
                    len(_active.ops), self, method, guard))
            return out
        return wrapped

    try:
        for m in _PULLS:
            setattr(Tensor, m, hook(m))
        yield cap
    finally:
        for m, f in saved.items():
            setattr(Tensor, m, f)
        tape._STATIC_RECORDER = saved_rec
        _active = None


def _guard_equal(a, b) -> bool:
    """Pulled-value guard comparison. Floating values compare with a
    tight tolerance, NOT bitwise: the captured value came from eager
    op-by-op execution while replay re-derives it from the fused
    compiled fragment, and XLA fusion legitimately changes rounding
    (observed 3e-7 relative drift on a 24-layer stack — bitwise
    equality made every replay respecialize). The tolerance is kept
    tight (~30x the observed drift): wider would replay a stale
    specialization for genuinely different values near a branch
    threshold. Integer/bool values compare exactly (they often feed
    shapes and trip counts)."""
    a = np.asarray(a)
    b = np.asarray(b)
    if a.shape != b.shape:
        return False
    if np.issubdtype(a.dtype, np.floating) \
            or np.issubdtype(a.dtype, np.complexfloating):
        return bool(np.allclose(a, b, rtol=1e-5, atol=1e-8,
                                equal_nan=True))
    return bool(np.array_equal(a, b))


class _Fragment:
    """One compiled replay segment of the op log."""

    def __init__(self, ops, input_ids, output_ids):
        self.input_ids = list(input_ids)
        self.output_ids = list(output_ids)
        entries = []
        for fn, tensor_args, datas, outs in ops:
            arg_ids = [id(t) if t is not None else None
                       for t in tensor_args]
            out_ids = [id(t) for t in outs]
            entries.append((fn, arg_ids, datas, out_ids))

        def replay(vals):
            env = dict(zip(self.input_ids, vals))
            for fn, arg_ids, datas, out_ids in entries:
                args = [env[i] if i is not None and i in env else d
                        for i, d in zip(arg_ids, datas)]
                out = fn(*args)
                outs = out if isinstance(out, tuple) else (out,)
                for oid, o in zip(out_ids, outs):
                    env[oid] = o
            return [env[i] for i in self.output_ids]

        self._compiled = jax.jit(replay)

    def __call__(self, env: Dict[int, Any]):
        vals = self._compiled([env[i] for i in self.input_ids])
        env.update(zip(self.output_ids, vals))


class _Spec:
    """One guard-path specialization: fragments + expected pull values."""

    def __init__(self, cap: _Capture, arg_ids: Dict[int, Tuple],
                 param_ids: Dict[int, str], out_tree):
        self.breaks = cap.breaks
        self.out_tree = out_tree              # pytree with id markers
        self.arg_ids = arg_ids                # tensor id -> arg path
        self.param_ids = param_ids            # tensor id -> param name
        self.consts: Dict[int, Any] = {}      # frozen external tensors
        self.n_fragments = 0
        self.fragments: List[_Fragment] = []
        self.frag_breaks: List[List[GraphBreak]] = []
        self._build(cap)

    def _build(self, cap):
        produced: Dict[int, int] = {}         # tensor id -> op index
        for idx, (_, _, _, outs) in enumerate(cap.ops):
            for t in outs:
                produced.setdefault(id(t), idx)
        # classify externals; freeze anything not an arg/param
        for fn, tensor_args, datas, outs in cap.ops:
            for t in tensor_args:
                if t is None:
                    continue
                tid = id(t)
                if (tid not in produced and tid not in self.arg_ids
                        and tid not in self.param_ids
                        and tid not in self.consts):
                    self.consts[tid] = t.data
        # segment boundaries: first break at-or-after each op index
        bounds = sorted({b.op_index for b in self.breaks
                         if 0 < b.op_index < len(cap.ops)})
        seg_edges = [0] + bounds + [len(cap.ops)]
        # ids needed later (by later segments, breaks, or outputs)
        needed_after: Dict[int, set] = {}
        out_leaf_ids = {tid for tid in jax.tree_util.tree_leaves(
            self.out_tree) if isinstance(tid, int)}
        for si in range(len(seg_edges) - 1):
            lo, hi = seg_edges[si], seg_edges[si + 1]
            later_use = set()
            for fn, tensor_args, datas, outs in cap.ops[hi:]:
                later_use |= {id(t) for t in tensor_args if t is not None}
            later_use |= {id(b.tensor) for b in self.breaks
                          if b.op_index >= hi}
            later_use |= out_leaf_ids
            seg_ops = cap.ops[lo:hi]
            seg_produced = {id(t) for _, _, _, outs in seg_ops
                            for t in outs}
            seg_consumed = set()
            for fn, tensor_args, datas, outs in seg_ops:
                seg_consumed |= {id(t) for t in tensor_args
                                 if t is not None}
            # ids are object identities, so anything consumed but not
            # produced inside the segment comes from outside it
            inputs = seg_consumed - seg_produced
            outputs = sorted(seg_produced & later_use)
            self.fragments.append(
                _Fragment(seg_ops, sorted(inputs), outputs))
            # guards evaluated after this fragment: pulls recorded while
            # ops (lo, hi] had run
            self.frag_breaks.append(
                [b for b in self.breaks if lo < b.op_index <= hi])
        # pulls of raw inputs before any op ran: guard them up front
        self.pre_breaks = [b for b in self.breaks if b.op_index == 0]
        self.n_fragments = len(self.fragments)

    def seed_env(self, arg_leaves: Dict[Tuple, Any], params: Dict[str, Any]
                 ) -> Dict[int, Any]:
        env = dict(self.consts)
        for tid, path in self.arg_ids.items():
            env[tid] = arg_leaves[path]
        for tid, pname in self.param_ids.items():
            env[tid] = params[pname]
        return env

    @staticmethod
    def _check(b: GraphBreak, env) -> bool:
        tid = id(b.tensor)
        if tid not in env:
            return False                   # pulled value not replayable
        actual = np.asarray(env[tid])
        if b.kind in ("numpy", "__array__"):
            return _guard_equal(actual, b.value)
        if b.kind == "item":
            return _guard_equal(actual.item()
                                if actual.size == 1 else actual, b.value)
        if b.kind == "__bool__":
            return bool(actual) == b.value
        if b.kind == "__float__":
            return _guard_equal(float(actual), b.value)
        return int(actual) == b.value

    def run(self, arg_leaves, params):
        """Execute fragments, checking pull guards between them.
        Returns (ok, out_env): ok=False on the first guard mismatch."""
        env = self.seed_env(arg_leaves, params)
        for b in self.pre_breaks:
            if not self._check(b, env):
                return False, None
        for frag, brs in zip(self.fragments, self.frag_breaks):
            frag(env)
            for b in brs:
                if not self._check(b, env):
                    return False, None
        return True, env

    def outputs(self, env):
        return jax.tree_util.tree_map(
            lambda leaf: (Tensor(env[leaf], stop_gradient=True)
                          if isinstance(leaf, int) else leaf),
            self.out_tree)


class SubgraphProgram:
    """Guarded fragment cache for one function (ref FunctionGraph +
    guard layer in jit/sot)."""

    def __init__(self, fn, layer=None):
        self.fn = fn
        self.layer = layer
        self._specs: Dict[Tuple, List[_Spec]] = {}
        self.last_path = None          # 'fragments' | 'capture'
        self._param_cache = None       # (struct_version, state items)

    # -- signatures ---------------------------------------------------------
    @staticmethod
    def _flatten(args, kwargs):
        """Tensor is itself a registered pytree node — flatten WITHOUT
        is_leaf would descend into it, yielding raw arrays that (a) miss
        the Tensor checks below (inputs silently frozen as consts) and
        (b) get repr()'d into the signature: full array printing per
        call plus a fresh capture+compile for every distinct input VALUE
        (measured 123x call overhead before this fix)."""
        return jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda v: isinstance(v, Tensor))

    def _sig(self, args, kwargs):
        leaves, treedef = self._flatten(args, kwargs)
        sig = [str(treedef)]
        for leaf in leaves:
            if isinstance(leaf, Tensor):
                sig.append(("T", tuple(leaf.shape), str(leaf.data.dtype)))
            elif isinstance(leaf, (jax.Array, np.ndarray)):
                # raw arrays are captured as CONSTS (frozen values), so
                # the signature must fingerprint the value — but a full
                # sha1 made every call O(array bytes) (ref SOT guards
                # are O(guards)). Hash a BOUNDED strided sample: exact
                # for arrays <= 4096 elems, head/tail/stride beyond —
                # real data that differs virtually always differs there
                # (documented tradeoff: a value changed ONLY between
                # sample points replays the stale const). Only the
                # sample is materialized to host — never the full leaf
                # (a jax.Array const would otherwise pay a full
                # device->host copy per call).
                import hashlib
                size = int(np.prod(leaf.shape)) if leaf.shape else 1
                flat = leaf.reshape(-1)
                if size > 4096:
                    step = max(size // 2048, 1)
                    parts = [np.asarray(flat[:1024]),
                             np.asarray(flat[::step]),
                             np.asarray(flat[-1024:])]
                    payload = b"".join(
                        np.ascontiguousarray(p).tobytes() for p in parts)
                else:
                    payload = np.ascontiguousarray(
                        np.asarray(flat)).tobytes()
                sig.append(("A", tuple(leaf.shape), str(leaf.dtype),
                            hashlib.sha1(payload).hexdigest()))
            else:
                sig.append(("P", repr(leaf)))
        return tuple(sig)

    def _arg_leaves(self, args, kwargs):
        out = {}
        leaves, _ = self._flatten(args, kwargs)
        for i, leaf in enumerate(leaves):
            if isinstance(leaf, Tensor):
                out[(i,)] = leaf.data
        return out

    def _params(self):
        """Per-call param map. state_dict() walks the whole module tree
        (string prefix joins per tensor) — far too slow to redo every
        replay on a large model — so the (name, Tensor) ITEMS are
        cached and invalidated by the global layer structure version
        (bumped on add/remove/replace; optimizer steps and
        set_state_dict mutate Tensor.data in place and keep the cache
        valid)."""
        if self.layer is None:
            return {}
        from ..nn.layer.layers import struct_version
        ver = struct_version()
        if self._param_cache is None or self._param_cache[0] != ver:
            self._param_cache = (
                ver, tuple(self.layer.state_dict().items()))
        return {k: t.data for k, t in self._param_cache[1]}

    # -- capture ------------------------------------------------------------
    def _capture(self, args, kwargs):
        arg_ids = {}
        leaves, _ = self._flatten(args, kwargs)
        for i, leaf in enumerate(leaves):
            if isinstance(leaf, Tensor):
                arg_ids[id(leaf)] = (i,)
        param_ids = {}
        pre_state = {}
        if self.layer is not None:
            for k, t in self.layer.state_dict().items():
                param_ids[id(t)] = k
                pre_state[k] = t.data
        from ..framework.core import _rng
        rng_before = (_rng.counter, len(_rng.stack))
        with _instrument() as cap, core.no_grad_guard():
            out = self.fn(*args, **kwargs)
        # replay-safety guards: a capture that consumed RNG (dropout
        # masks baked into closures) or mutated layer state in Python
        # (BatchNorm running stats) would replay stale values — refuse
        # and let the caller de-optimize to eager
        if (_rng.counter, len(_rng.stack)) != rng_before:
            raise SotCaptureError(
                "function consumed RNG during capture (dropout?); "
                "fragment replay would repeat the same mask")
        if self.layer is not None:
            for k, t in self.layer.state_dict().items():
                if k in pre_state and t.data is not pre_state[k]:
                    raise SotCaptureError(
                        f"layer state {k!r} mutated during capture; "
                        "replay would not re-apply it")
        out_tree = jax.tree_util.tree_map(
            lambda v: id(v) if isinstance(v, Tensor) else v, out,
            is_leaf=lambda v: isinstance(v, Tensor))
        # keep Tensor objects alive so ids stay unique
        spec = _Spec(cap, arg_ids, param_ids, out_tree)
        spec._keepalive = ([t for op in cap.ops for t in op[3]]
                          + [b.tensor for b in cap.breaks])
        return spec, out

    def __call__(self, *args, **kwargs):
        sig = self._sig(args, kwargs)
        arg_leaves = self._arg_leaves(args, kwargs)
        params = self._params()
        for spec in self._specs.get(sig, []):
            ok, env = spec.run(arg_leaves, params)
            if ok:
                self.last_path = "fragments"
                return spec.outputs(env)
        # no cached guard path matches: capture a new specialization
        if len(self._specs.get(sig, [])) >= _MAX_SPECS:
            raise SotCaptureError(
                f"guard thrash: {_MAX_SPECS} specializations for one "
                "signature — pulled values vary per call; de-optimize")
        spec, out = self._capture(args, kwargs)
        self._specs.setdefault(sig, []).append(spec)
        self.last_path = "capture"
        return out

    @property
    def n_specs(self):
        return sum(len(v) for v in self._specs.values())
