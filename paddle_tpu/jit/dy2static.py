"""AST-level control-flow lowering for to_static
(ref: python/paddle/jit/dy2static/transformers/ifelse_transformer.py and
while_loop_transformer.py — the reference rewrites Python `if`/`while`
over tensors into graph control-flow ops so the WHOLE function stays one
program).

TPU-native: the rewrite targets `lax.cond` / `lax.while_loop`. Each
`while`/`if` becomes a pair of local closures (cond/body or true/false)
plus a call to a runtime helper that dispatches at execution time:
a concrete (python) condition keeps plain Python semantics; a traced
tensor condition lowers to the lax primitive — so a data-dependent loop
compiles into ONE executable with no per-trip-count respecialization
(VERDICT r3 #5). `break`/`continue` in a while body lower via carried
done/skip flags (ref: dy2static/transformers/break_continue_transformer
.py rewrites them into bool flag variables + guarded blocks): the loop
condition becomes `not brk and test`, statements after a potential
break/continue are wrapped in a flag-guarded `if`, and the flags join
the lax.while_loop carry. Top-level `for i in range(...)` (int-literal
step, builtin range only) rewrites into the same while form with an
increment-first body, so tensor trip counts and break/continue work
there too. Constructs the rewrite cannot lower soundly
(return in the body, attribute/subscript stores, loop else-clauses,
a carried name first bound inside the loop body — nothing to seed the
lax carry with, the reference papers over this with UndefinedVar
dummies) are left untouched and fall to the SOT fragment path.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import List, Optional, Set

import jax

__all__ = ["ast_rewrite", "run_while", "run_if"]

_RT_NAME = "__paddle_ds_rt__"


# ---------------- runtime helpers ------------------------------------------

def _is_tensorish(v):
    from ..tensor import Tensor
    return isinstance(v, (Tensor, jax.Array)) or hasattr(v, "aval")


def _unbox(v):
    from ..tensor import Tensor
    return v.data if isinstance(v, Tensor) else v


def _unbox_tree(vs):
    from ..tensor import Tensor
    return jax.tree_util.tree_map(
        lambda v: v.data if isinstance(v, Tensor) else v, vs,
        is_leaf=lambda v: isinstance(v, Tensor))


def _rebox_like(vals, templates):
    from ..tensor import Tensor
    out = []
    for v, t in zip(vals, templates):
        out.append(Tensor(v, stop_gradient=True)
                   if isinstance(t, Tensor) else v)
    return tuple(out)


def _concrete_bool(c):
    """bool(c) if c is concrete; None if it is a tracer."""
    try:
        return bool(_unbox(c))
    except (jax.errors.TracerBoolConversionError,
            jax.errors.ConcretizationTypeError):
        return None


def run_while(cond_fn, body_fn, vars_tuple):
    """`while cond: body` over carried `vars_tuple`. Traced tensor
    condition -> lax.while_loop (one executable); concrete -> Python.
    A condition that STARTS concrete but turns traced mid-loop (a
    lowered break flag becomes a tensor after the first lax.cond)
    continues under lax from the current carry — the already-run
    iterations stay unrolled in the trace."""
    cb = _concrete_bool(cond_fn(*vars_tuple))
    while cb:
        # concrete condition: plain Python loop (eager or static-trip)
        vars_tuple = tuple(body_fn(*vars_tuple))
        cb = _concrete_bool(cond_fn(*vars_tuple))
    if cb is not None:
        return vars_tuple
    templates = vars_tuple

    def cond(vs):
        return _unbox(cond_fn(*_rebox_like(vs, templates))).reshape(())

    def body(vs):
        out = body_fn(*_rebox_like(vs, templates))
        return tuple(_unbox(v) for v in out)

    init = tuple(_unbox(v) for v in vars_tuple)
    out = jax.lax.while_loop(cond, body, init)
    return _rebox_like(out, templates)


def loop_not_done(brk, test_thunk):
    """`not brk and test` — the while condition including the lowered
    break flag. `test_thunk` is LAZY: a concrete taken break must not
    evaluate the test again (the original `while` never evaluates its
    test after a break — it may only be valid pre-break, e.g. an index
    bound). On the traced path both operands evaluate, as lax control
    flow inherently does."""
    b = _unbox(brk)
    if not _is_tensorish(b):
        if bool(b):
            return False          # short-circuit: break already taken
        return test_thunk()
    t = _unbox(test_thunk())
    import jax.numpy as jnp
    return jnp.logical_and(
        jnp.logical_not(jnp.asarray(b).reshape(())),
        jnp.asarray(t).reshape(()))


def not_any(*flags):
    """`not (f1 or f2 or ...)` — guard for statements following a
    potential break/continue. Mixed python/tensor operands supported."""
    vals = [_unbox(f) for f in flags]
    if any(_is_tensorish(v) for v in vals):
        import jax.numpy as jnp
        acc = jnp.asarray(False)
        for v in vals:
            acc = jnp.logical_or(acc, jnp.asarray(v).reshape(()))
        return jnp.logical_not(acc)
    return not any(bool(v) for v in vals)


def run_if(cond, true_fn, false_fn, vars_tuple):
    """`if cond: ... else: ...` assigning into `vars_tuple`. Traced
    tensor condition -> lax.cond; concrete -> Python branch."""
    cb = _concrete_bool(cond)
    if cb is not None:
        return tuple((true_fn if cb else false_fn)(*vars_tuple))
    templates = vars_tuple

    def mk(branch):
        def f(vs):
            out = branch(*_rebox_like(vs, templates))
            return tuple(_unbox(v) for v in out)
        return f

    init = tuple(_unbox(v) for v in vars_tuple)
    out = jax.lax.cond(_unbox(cond).reshape(()), mk(true_fn),
                       mk(false_fn), init)
    return _rebox_like(out, templates)


# ---------------- AST analysis ---------------------------------------------

class _NameCollector(ast.NodeVisitor):
    """Assigned / loaded names of a statement list, NOT descending into
    nested function/lambda bodies (their locals are their own)."""

    def __init__(self, allow_bc=False):
        self.stores: Set[str] = set()
        self.loads: Set[str] = set()
        self.unsupported = False
        self._allow_bc = allow_bc     # break/continue handled separately

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Store):
            self.stores.add(node.id)
        elif isinstance(node.ctx, ast.Load):
            self.loads.add(node.id)

    def visit_Attribute(self, node):
        if isinstance(node.ctx, ast.Store):
            self.unsupported = True       # object mutation can't lower
        self.generic_visit(node)

    def visit_Subscript(self, node):
        if isinstance(node.ctx, ast.Store):
            self.unsupported = True
        self.generic_visit(node)

    def visit_Break(self, node):
        if not self._allow_bc:
            self.unsupported = True

    def visit_Continue(self, node):
        if not self._allow_bc:
            self.unsupported = True

    def visit_Return(self, node):
        self.unsupported = True

    def visit_FunctionDef(self, node):
        self.stores.add(node.name)        # binds the name only

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass


def _analyze(stmts: List[ast.stmt], allow_bc=False):
    c = _NameCollector(allow_bc=allow_bc)
    for s in stmts:
        c.visit(s)
    return c


def _locally_initialized_flags(stmts: List[ast.stmt]) -> Set[str]:
    """Flag names whose `= False` pre-init lives INSIDE these
    statements — i.e. flags of a construct fully contained here. Such
    flags must not join an enclosing construct's carry (they are
    unbound before it). Only this module emits False-constant assigns
    to __ds_brk_/__ds_cont_ names, so the pattern is unambiguous."""
    out: Set[str] = set()
    for s in ast.walk(ast.Module(body=list(stmts), type_ignores=[])):
        if (isinstance(s, ast.Assign) and len(s.targets) == 1
                and isinstance(s.targets[0], ast.Name)
                and s.targets[0].id.startswith(("__ds_brk_",
                                                "__ds_cont_"))
                and isinstance(s.value, ast.Constant)
                and s.value.value is False):
            out.add(s.targets[0].id)
    return out


# ---------------- break/continue pre-lowering ------------------------------

def _contains_raw_loop(stmts: List[ast.stmt]) -> bool:
    """Any un-lowered for/while remaining in these statements (not
    inside nested function bodies, which own their locals). Such a
    loop stores names that are typically body-local — carrying them
    would reference unbound names before the enclosing loop."""
    for s in stmts:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        if isinstance(s, (ast.While, ast.For, ast.AsyncFor)):
            return True
        for field in ("body", "orelse", "finalbody"):
            inner = getattr(s, field, None)
            if inner and _contains_raw_loop(inner):
                return True
    return False


def _has_break_continue(stmts: List[ast.stmt]) -> bool:
    """Break/Continue belonging to THIS loop level (descends into ifs
    and try blocks, never into nested loops or function defs)."""
    for s in stmts:
        if isinstance(s, (ast.Break, ast.Continue)):
            return True
        if isinstance(s, (ast.While, ast.For, ast.AsyncFor,
                          ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        for field in ("body", "orelse", "finalbody"):
            inner = getattr(s, field, None)
            if inner and _has_break_continue(inner):
                return True
    return False


def _flag_assign(name: str, value: bool) -> ast.Assign:
    return ast.Assign(
        targets=[ast.Name(id=name, ctx=ast.Store())],
        value=ast.Constant(value=value))


def _guard_call(brk: str, cont: str) -> ast.expr:
    return ast.Call(
        func=ast.Attribute(value=ast.Name(id=_RT_NAME, ctx=ast.Load()),
                           attr="not_any", ctx=ast.Load()),
        args=[ast.Name(id=brk, ctx=ast.Load()),
              ast.Name(id=cont, ctx=ast.Load())],
        keywords=[])


def _rewrite_break_continue(stmts: List[ast.stmt], brk: str, cont: str):
    """Replace break/continue with flag stores and wrap every statement
    that could execute after one in a flag guard (ref:
    break_continue_transformer.py BreakContinueTransformer). Returns
    (new_stmts, contains_bc)."""
    out: List[ast.stmt] = []
    for idx, s in enumerate(stmts):
        if isinstance(s, ast.Break):
            out.append(_flag_assign(brk, True))
            return out, True              # rest of the list is dead
        if isinstance(s, ast.Continue):
            out.append(_flag_assign(cont, True))
            return out, True
        if isinstance(s, ast.If):
            tb, t_bc = _rewrite_break_continue(s.body, brk, cont)
            fb, f_bc = _rewrite_break_continue(s.orelse, brk, cont)
            if t_bc or f_bc:
                out.append(ast.If(test=s.test, body=tb, orelse=fb))
                rest, _ = _rewrite_break_continue(stmts[idx + 1:],
                                                  brk, cont)
                if rest:
                    out.append(ast.If(test=_guard_call(brk, cont),
                                      body=rest, orelse=[]))
                return out, True
        out.append(s)
    return out, False


# ---------------- the transformer ------------------------------------------

class _CtrlFlow(ast.NodeTransformer):
    def __init__(self, allow_range_lowering=True):
        self.n = 0
        self.rewrote = False
        # for-range lowering is sound only for TOP-LEVEL loops: the
        # synthesized iterator/seed assignments live inside an
        # enclosing construct's body and would join its carry unbound
        self._depth = 0
        self._allow_range = allow_range_lowering

    def _visit_children(self, node):
        self._depth += 1
        try:
            self.generic_visit(node)
        finally:
            self._depth -= 1

    def _carried(self, analyses, keep_flags=True) -> Optional[List[str]]:
        stores: Set[str] = set()
        for a in analyses:
            if a.unsupported:
                return None
            stores |= a.stores
        # __ds_* closure names never carry. Break/continue flags are
        # ordinary state for the construct that OWNS them (an if inside
        # the loop must carry them; keep_flags=True), but an ENCLOSING
        # loop must not — an inner loop's flags are stored-before-
        # loaded within the enclosing body and dead after it, and
        # carrying them would reference names unbound before the loop.
        names = sorted(
            n for n in stores
            if not n.startswith("__ds_")
            or (keep_flags and n.startswith(("__ds_brk_",
                                             "__ds_cont_"))))
        return names or None

    def _closure(self, name: str, carried: List[str],
                 body: List[ast.stmt], ret_names: List[str]):
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in carried],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[])
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in ret_names],
            ctx=ast.Load()))
        return ast.FunctionDef(name=name, args=args, body=body + [ret],
                               decorator_list=[], returns=None)

    def _helper_call(self, helper: str, head_args, carried: List[str]):
        call = ast.Call(
            func=ast.Attribute(value=ast.Name(id=_RT_NAME, ctx=ast.Load()),
                               attr=helper, ctx=ast.Load()),
            args=head_args + [ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Load()) for n in carried],
                ctx=ast.Load())],
            keywords=[])
        target = ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Store())
                                 for n in carried], ctx=ast.Store())
        return ast.Assign(targets=[target], value=call)

    def visit_While(self, node: ast.While):
        # break/continue pre-lowering must run BEFORE generic_visit so
        # the guard ifs it synthesizes get lax-lowered like any other
        # if — but only when the body is otherwise lowerable: an
        # attribute/subscript store or return must keep the ORIGINAL
        # loop so it falls to SOT (lowering just the flags would trace
        # the side effect once and bake a leaked tracer)
        pre: List[ast.stmt] = []
        flags: List[str] = []
        test = node.test
        if not node.orelse and _has_break_continue(node.body) \
                and not _analyze(node.body, allow_bc=True).unsupported:
            i = self.n
            self.n += 1
            brk, cont = f"__ds_brk_{i}", f"__ds_cont_{i}"
            new_body, _ = _rewrite_break_continue(node.body, brk, cont)
            if _has_break_continue(new_body):
                # a break/continue inside a `with`/`try` survived the
                # rewrite (it only descends into ifs) — lowering now
                # would emit a bare `break` outside any loop; keep the
                # original node so it falls to SOT
                self._visit_children(node)
                return node
            # cont resets every iteration; brk persists in the carry.
            # The original test is wrapped in a LAZY thunk: a taken
            # break must not evaluate it again (see loop_not_done).
            thunk = ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                                   kwonlyargs=[], kw_defaults=[],
                                   kwarg=None, defaults=[]),
                body=node.test)
            node = ast.While(
                test=ast.Call(
                    func=ast.Attribute(
                        value=ast.Name(id=_RT_NAME, ctx=ast.Load()),
                        attr="loop_not_done", ctx=ast.Load()),
                    args=[ast.Name(id=brk, ctx=ast.Load()), thunk],
                    keywords=[]),
                body=[_flag_assign(cont, False)] + new_body,
                orelse=[])
            test = node.test
            pre = [_flag_assign(brk, False), _flag_assign(cont, False)]
            flags = [brk, cont]
        self._visit_children(node)
        if node.orelse:
            return node
        if _contains_raw_loop(node.body):
            # an un-lowered nested loop stores body-local names the
            # carry would reference unbound before this loop — keep
            # Python semantics (whole-trace unroll or SOT)
            return node
        body_a = _analyze(node.body)
        test_a = _analyze([ast.Expr(value=test)])
        carried = self._carried([body_a], keep_flags=False)
        if carried is None and flags:
            carried = []
        if carried is None or test_a.unsupported:
            return node
        carried = sorted(set(carried) | set(flags))
        i = self.n
        self.n += 1
        cond_fn = self._closure(
            f"__ds_cond_{i}", carried,
            [], [])
        # cond returns the test expression directly
        cond_fn.body = [ast.Return(value=node.test)]
        body_fn = self._closure(f"__ds_body_{i}", carried, node.body,
                                carried)
        assign = self._helper_call(
            "run_while",
            [ast.Name(id=f"__ds_cond_{i}", ctx=ast.Load()),
             ast.Name(id=f"__ds_body_{i}", ctx=ast.Load())], carried)
        self.rewrote = True
        return pre + [cond_fn, body_fn, assign]

    def visit_For(self, node: ast.For):
        """Lower `for <name> in range(...)` to the while form (ref:
        dy2static/transformers/loop_transformer.py) so tensor trip
        counts compile into lax.while_loop and break/continue reuse the
        flag lowering. The increment runs at the TOP of the body
        (iterator seeded at start-step) so a lowered `continue` — which
        guards every statement after it — cannot skip the increment.
        Non-range iterables, tuple targets, for/else, and dynamic
        step signs keep Python semantics."""
        a = node.iter.args if isinstance(node.iter, ast.Call) else None

        def const_int(n):
            # range steps must be INT literals (a float step is a
            # TypeError in real range); negative literals parse as
            # UnaryOp(USub, Constant)
            if isinstance(n, ast.Constant) and isinstance(n.value, int) \
                    and not isinstance(n.value, bool):
                return n.value
            if isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.USub):
                v = const_int(n.operand)
                return -v if v is not None else None
            return None

        step_node = (a[2] if a is not None and len(a) == 3
                     else ast.Constant(value=1))
        step_val = const_int(step_node)
        if (node.orelse or not isinstance(node.target, ast.Name)
                or not isinstance(node.iter, ast.Call)
                or not isinstance(node.iter.func, ast.Name)
                or node.iter.func.id != "range"
                or node.iter.keywords
                or a is None or not 1 <= len(a) <= 3
                or any(isinstance(x, ast.Starred) for x in a)
                or step_val in (None, 0)
                or self._depth > 0 or not self._allow_range):
            self._visit_children(node)
            return node
        start = a[0] if len(a) >= 2 else ast.Constant(value=0)
        stop = a[1] if len(a) >= 2 else a[0]
        k = self.n
        self.n += 1
        # single-underscore prefix: these are ORDINARY loop state that
        # must join the while carry (the __ds_ prefix is excluded from
        # carries as closure-name namespace)
        it, stop_n = f"_ds_it_{k}", f"_ds_stop_{k}"

        def name(n, ctx):
            return ast.Name(id=n, ctx=ctx)

        def step_const():
            return ast.Constant(value=step_val)

        cmp_op = ast.Lt() if step_val > 0 else ast.Gt()
        seed = ast.BinOp(left=start, op=ast.Sub(), right=step_const())
        # the target must be bound before the loop (it joins the while
        # carry) — but ONLY seed it when currently unbound: an empty
        # range must leave a pre-existing binding untouched, and a
        # prior of another dtype must stay visible (a lax carry
        # mismatch fails LOUDLY and to_static falls back — better than
        # silently replacing the value). Known deviation (the
        # reference's UndefinedVar dummies behave the same way): a
        # previously-UNBOUND target read after an EMPTY range sees
        # start-step instead of raising UnboundLocalError.
        target_seed = ast.Try(
            body=[ast.Expr(value=name(node.target.id, ast.Load()))],
            handlers=[ast.ExceptHandler(
                type=ast.Name(id="NameError", ctx=ast.Load()),
                name=None,
                body=[ast.Assign(
                    targets=[name(node.target.id, ast.Store())],
                    value=name(it, ast.Load()))])],
            orelse=[], finalbody=[])
        init = [
            ast.Assign(targets=[name(it, ast.Store())], value=seed),
            ast.Assign(targets=[name(stop_n, ast.Store())], value=stop),
            target_seed,
        ]
        body = [
            ast.Assign(targets=[name(it, ast.Store())],
                       value=ast.BinOp(left=name(it, ast.Load()),
                                       op=ast.Add(),
                                       right=step_const())),
            ast.Assign(targets=[name(node.target.id, ast.Store())],
                       value=name(it, ast.Load())),
        ] + node.body
        test = ast.Compare(
            left=ast.BinOp(left=name(it, ast.Load()), op=ast.Add(),
                           right=step_const()),
            ops=[cmp_op], comparators=[name(stop_n, ast.Load())])
        wh = ast.While(test=test, body=body, orelse=[])
        lowered = self.visit_While(wh)
        return init + (lowered if isinstance(lowered, list)
                       else [lowered])

    def visit_If(self, node: ast.If):
        self._visit_children(node)
        body_a = _analyze(node.body)
        else_a = _analyze(node.orelse)
        carried = self._carried([body_a, else_a])
        if carried is None:
            return node
        # flags of constructs fully inside this if (their False-init
        # lives in a branch) are unbound before it — drop them from
        # the carry; an ENCLOSING loop's flags (stored via `= True`
        # only) stay
        local = (_locally_initialized_flags(node.body)
                 | _locally_initialized_flags(node.orelse))
        if local:
            carried = [n for n in carried if n not in local]
            if not carried:
                # nothing escapes this if; leave it to the fallback
                return node
        i = self.n
        self.n += 1
        t_fn = self._closure(f"__ds_true_{i}", carried, node.body, carried)
        f_fn = self._closure(f"__ds_false_{i}", carried,
                             node.orelse or [ast.Pass()], carried)
        assign = self._helper_call(
            "run_if",
            [node.test,
             ast.Name(id=f"__ds_true_{i}", ctx=ast.Load()),
             ast.Name(id=f"__ds_false_{i}", ctx=ast.Load())], carried)
        self.rewrote = True
        return [t_fn, f_fn, assign]


def ast_rewrite(fn):
    """Rewrite fn's while/if statements into lax-lowered helper calls.
    Returns the transformed callable, or None when nothing was rewritten
    or the source is unavailable (builtins, exec'd code, lambdas)."""
    bound_self = getattr(fn, "__self__", None)
    raw = fn.__func__ if bound_self is not None else fn
    try:
        src = textwrap.dedent(inspect.getsource(raw))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return None
    fndef = tree.body[0]
    if not isinstance(fndef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    fndef.decorator_list = []
    # for-range lowering assumes `range` is the builtin — a local,
    # closure, or module-global shadow would be silently mis-lowered
    code = raw.__code__
    range_is_builtin = ("range" not in code.co_varnames
                        and "range" not in code.co_freevars
                        and "range" not in raw.__globals__)
    tr = _CtrlFlow(allow_range_lowering=range_is_builtin)
    tr.visit(fndef)
    if not tr.rewrote:
        return None
    # wrap in a factory so the original closure cells rebind as args
    free = list(raw.__code__.co_freevars)
    factory = ast.FunctionDef(
        name="__ds_factory__",
        args=ast.arguments(posonlyargs=[],
                           args=[ast.arg(arg=n) for n in free],
                           vararg=None, kwonlyargs=[], kw_defaults=[],
                           kwarg=None, defaults=[]),
        body=[fndef, ast.Return(value=ast.Name(id=fndef.name,
                                               ctx=ast.Load()))],
        decorator_list=[], returns=None)
    mod = ast.Module(body=[factory], type_ignores=[])
    ast.fix_missing_locations(mod)
    from . import dy2static as _rt
    glb = dict(raw.__globals__)
    glb[_RT_NAME] = _rt
    code = compile(mod, filename=f"<dy2static {raw.__name__}>",
                   mode="exec")
    ns: dict = {}
    exec(code, glb, ns)
    cells = ([c.cell_contents for c in (raw.__closure__ or ())]
             if free else [])
    new_fn = ns["__ds_factory__"](*cells)
    new_fn = functools.wraps(raw)(new_fn)
    if bound_self is not None:
        new_fn = new_fn.__get__(bound_self, type(bound_self))
    return new_fn
