"""Communication-quantization plumbing (EQuARX, arxiv 2506.17615).

Shared scale/zero-point helpers for every low-precision byte-mover in
the framework, so the wire format is decided in ONE place:

  * the quantized collectives behind `distributed/collective.py`
    (blockwise absmax over flat payloads, int8 / fp8-e4m3 wire dtypes,
    the two-phase reduce_scatter -> all_gather chain's quantize points);
  * the weight-only int8 serving path (`inference/serving.py`
    `quantize_state_int8` — per-output-channel absmax, same rounding
    and clipping rules as the wire path);
  * AMP capability probes (`paddle_tpu.amp.is_float8_supported`).

Everything here is pure jnp and trace-safe: the collective chain calls
these INSIDE shard_map/jit bodies.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax.numpy as jnp

#: wire modes -> (qmax, wire dtype name). int8 is symmetric [-127, 127]
#: (the -128 code is unused so negation round-trips); fp8-e4m3 has no
#: shared exponent, absmax scaling maps the block max onto +-448 (the
#: e4m3fn finite max) and the cast does the rounding.
_QMAX = {"int8": 127.0, "fp8": 448.0}

MODES = tuple(_QMAX)

# floor for absmax so all-zero blocks quantize to exact zeros instead
# of dividing by zero (any positive value works: 0/scale == 0)
_EPS = 1e-30

_fp8_supported: Optional[bool] = None


def supports_fp8() -> bool:
    """True when this jax ships float8_e4m3fn and the backend can cast
    to it (the fp8 wire mode's availability gate; also the probe behind
    `paddle_tpu.amp.is_float8_supported`)."""
    global _fp8_supported
    if _fp8_supported is None:
        try:
            jnp.zeros((2,), jnp.float32).astype(jnp.float8_e4m3fn)
            _fp8_supported = True
        except (AttributeError, TypeError, RuntimeError):
            _fp8_supported = False
    return _fp8_supported


def qmax(mode: str) -> float:
    if mode not in _QMAX:
        raise ValueError(
            f"unknown comm-quant mode {mode!r}; expected one of {MODES}")
    return _QMAX[mode]


def wire_dtype(mode: str):
    """The dtype actually put on the wire for `mode` (1 byte/element
    for both supported modes)."""
    qmax(mode)
    if mode == "fp8":
        if not supports_fp8():
            raise ValueError(
                "fp8 communication quantization needs jnp.float8_e4m3fn "
                "(unavailable on this jax) — use mode='int8'")
        return jnp.float8_e4m3fn
    return jnp.int8


@dataclass(frozen=True)
class CommQuantConfig:
    """Resolved wire format of one quantized collective: `mode` picks
    the element dtype, `block` the absmax-scale granularity (one f32
    scale per `block` contiguous elements of the flattened payload),
    `error_feedback` whether the caller carries a compensation residual
    across calls."""
    mode: str = "int8"
    block: int = 256
    error_feedback: bool = False

    def __post_init__(self):
        qmax(self.mode)
        if self.block < 1:
            raise ValueError(f"block must be >= 1, got {self.block}")

    @property
    def wire_bytes_per_element(self) -> float:
        """Wire cost per payload element: 1 quantized byte + this
        element's share of its block's f32 scale."""
        return 1.0 + 4.0 / self.block


def resolve_config(mode=None, block=None,
                   error_feedback: bool = False) -> CommQuantConfig:
    """Fill unset knobs from the flag registry (`mode=True` means "the
    default mode"): block defaults to FLAGS_quant_collectives_block."""
    from ..framework import core
    if mode is None or mode is True:
        mode = "int8"
    if block is None:
        block = int(float(core.get_flag("FLAGS_quant_collectives_block",
                                        256) or 256))
    return CommQuantConfig(mode=str(mode), block=int(block),
                           error_feedback=bool(error_feedback))


def shard_sizes(numel: int, nranks: int, block: int) -> Tuple[int, int]:
    """(per-shard elements, padded total) for an `numel`-element payload
    split across `nranks`: the shard is rounded up to a whole number of
    scale blocks so every rank quantizes aligned blocks. Shared by the
    collective chain and the error-feedback state allocator in
    jit.TrainStep — both must agree on the padded layout."""
    shard = -(-numel // nranks)
    shard = -(-shard // block) * block
    return shard, shard * nranks


def quantize_blocks(x, block: int, mode: str):
    """Blockwise absmax quantization of `x` (..., S) with S % block == 0.

    Returns (q, scales): q has x's shape in the wire dtype, scales is
    (..., S // block) float32 with scale = absmax / qmax per block —
    dequantization is `q * scale` elementwise over blocks."""
    qm = qmax(mode)
    lead, s = x.shape[:-1], x.shape[-1]
    if s % block:
        raise ValueError(f"last dim {s} not a multiple of block {block}")
    b = x.astype(jnp.float32).reshape(lead + (s // block, block))
    scales = jnp.maximum(jnp.max(jnp.abs(b), axis=-1), _EPS) / qm
    y = b / scales[..., None]
    if mode == "int8":
        q = jnp.clip(jnp.round(y), -qm, qm).astype(jnp.int8)
    else:
        q = y.astype(wire_dtype(mode))
    return q.reshape(x.shape), scales


def dequantize_blocks(q, scales, block: int):
    """Inverse of quantize_blocks: float32 result of q's shape."""
    lead, s = q.shape[:-1], q.shape[-1]
    b = q.astype(jnp.float32).reshape(lead + (s // block, block))
    return (b * scales[..., None]).reshape(q.shape)


def channelwise_absmax_int8(arr, axis: int = 0):
    """Per-channel absmax int8 quantization (the weight-only serving
    rule: one f32 scale per output channel, keepdims so `q * scale`
    broadcasts back). Returns (q_int8, scale_f32)."""
    a32 = arr.astype(jnp.float32)
    scale = jnp.max(jnp.abs(a32), axis=axis, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(a32 / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_channelwise(q, scale, dtype):
    """Inverse of channelwise_absmax_int8 in the target compute dtype
    (in-trace: XLA fuses the convert + scale into the consuming dot)."""
    return (q.astype(jnp.float32) * scale).astype(dtype)
