"""paddle.quantization — QAT/PTQ (ref: python/paddle/quantization/ —
QuantConfig, QAT with FakeQuant observers, PTQ with calibration
observers).

TPU-native: fake-quant is a straight-through-estimator quantize/dequantize
pair that XLA folds into the surrounding ops; int8 deployment on TPU means
feeding the quantized weights to XLA as int8 with dequant scales (the
reference's conversion pass); this module implements the training-time
surface: observers, QAT wrapping, PTQ calibration, convert()."""
from __future__ import annotations

from typing import Dict, List, Optional, Type

import jax
import jax.numpy as jnp

from ..autograd.tape import apply_op
from ..nn.layer.layers import Layer
from ..ops._helpers import to_tensor_like
from ..tensor import Tensor

from . import comm  # noqa: F401  (communication quantization plumbing)
from .comm import (  # noqa: F401
    CommQuantConfig, channelwise_absmax_int8, dequantize_blocks,
    dequantize_channelwise, quantize_blocks, supports_fp8,
)

__all__ = ["QuantConfig", "QAT", "PTQ", "AbsmaxObserver",
           "MovingAverageObserver", "FakeQuant", "QuantedLinear",
           "quant_dequant", "comm", "CommQuantConfig", "quantize_blocks",
           "dequantize_blocks", "channelwise_absmax_int8",
           "dequantize_channelwise", "supports_fp8"]


import functools


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fake_quant(v, s, qmax):
    q = jnp.clip(jnp.round(v / s * qmax), -qmax - 1, qmax)
    return q / qmax * s


def _fq_fwd(v, s, qmax):
    return _fake_quant(v, s, qmax), ()


def _fq_bwd(qmax, res, g):   # straight-through estimator
    return (g, None)


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


def quant_dequant(x, scale, bits=8):
    """STE fake quant: round(x/scale*qmax)/qmax*scale with identity grad."""
    qmax = 2.0 ** (bits - 1) - 1
    xt = to_tensor_like(x)
    sc = scale.data if isinstance(scale, Tensor) else jnp.asarray(scale)
    return apply_op(lambda a: _fake_quant(a, sc, qmax), xt,
                    name="fake_quant")


class AbsmaxObserver:
    """ref quantization/observers/abs_max.py — per-tensor absmax scale.

    Stateless update rule: `update(state, x) -> new_state` is a pure jnp
    expression, so observation works under jit tracing (TrainStep / hapi
    compiled fit) — the state itself lives in a FakeQuant buffer that the
    compiled step threads through functionally (ADVICE r1: the old
    float()-based observer broke under tracing).
    """

    def __init__(self, quant_bits=8):
        self.bits = quant_bits

    def init_state(self):
        return jnp.zeros((), jnp.float32)

    def update(self, state, a):
        return jnp.maximum(state, jnp.abs(a).max().astype(jnp.float32))

    def scale(self, state):
        return jnp.maximum(state, 1e-8)


class MovingAverageObserver(AbsmaxObserver):
    def __init__(self, quant_bits=8, momentum=0.9):
        super().__init__(quant_bits)
        self.momentum = momentum

    def update(self, state, a):
        cur = jnp.abs(a).max().astype(jnp.float32)
        # state == 0 means "no observation yet": seed with the first value
        ema = self.momentum * state + (1 - self.momentum) * cur
        return jnp.where(state == 0, cur, ema)


class FakeQuant(Layer):
    def __init__(self, observer=None, bits=8):
        super().__init__()
        self.observer = observer or AbsmaxObserver(bits)
        self.bits = bits
        self.register_buffer(
            "observer_state", Tensor(self.observer.init_state(),
                                     stop_gradient=True))

    def forward(self, x):
        xt = to_tensor_like(x)
        if self.training:
            new_state = self.observer.update(self.observer_state.data, xt.data)
            self.observer_state.data = new_state
        s = self.observer.scale(self.observer_state.data)
        return quant_dequant(xt, s, self.bits)


class QuantedLinear(Layer):
    """Linear with weight+activation fake-quant (ref nn/quant layers)."""

    def __init__(self, linear, w_bits=8, a_bits=8):
        super().__init__()
        self.inner = linear
        self.w_fq = FakeQuant(bits=w_bits)
        self.a_fq = FakeQuant(bits=a_bits)

    def forward(self, x):
        from ..nn import functional as F
        x = self.a_fq(x)
        w = self.w_fq(self.inner.weight)
        return F.linear(x, w, self.inner.bias)


class QuantConfig:
    """ref quantization/config.py — maps layer types to quant wrappers."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._type_map: Dict[Type[Layer], Type[Layer]] = {}
        from ..nn.layer.common import Linear
        self._type_map[Linear] = QuantedLinear

    def add_type_config(self, layer_type, activation=None, weight=None,
                        wrapper=None):
        if wrapper is not None:
            self._type_map[layer_type] = wrapper


def _wrap_layers(model: Layer, cfg: QuantConfig):
    from ..nn.layer.layers import bump_struct_version
    for name, child in list(model._sub_layers.items()):
        wrapper = cfg._type_map.get(type(child))
        if wrapper is not None:
            model._sub_layers[name] = wrapper(child)
            bump_struct_version()
        else:
            _wrap_layers(child, cfg)
    return model


class QAT:
    """ref quantization/qat.py — quantize-aware-training wrapper."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer, inplace=False):
        return _wrap_layers(model, self.config)

    def convert(self, model: Layer, inplace=False):
        """Fold observers: freeze scales (deployment handled by XLA int8)."""
        model.eval()
        return model


class PTQ:
    """ref quantization/ptq.py — post-training calibration."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer, inplace=False):
        m = _wrap_layers(model, self.config)
        m.train()   # observers active during calibration passes
        return m

    def convert(self, model: Layer, inplace=False):
        model.eval()
        return model
