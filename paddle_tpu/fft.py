"""paddle.fft (ref: python/paddle/fft.py over pocketfft; here jnp.fft → XLA)."""
from __future__ import annotations

import jax.numpy as jnp

from .autograd.tape import apply_op
from .ops._helpers import to_tensor_like

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2", "ifft2",
           "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn", "fftfreq",
           "rfftfreq", "fftshift", "ifftshift"]


def _norm(norm):
    return norm if norm in ("ortho", "forward") else "backward"


def _mk1(jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op(lambda a: jfn(a, n=n, axis=axis, norm=_norm(norm)),
                        to_tensor_like(x))
    return op


def _mk2(jfn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return apply_op(lambda a: jfn(a, s=s, axes=tuple(axes), norm=_norm(norm)),
                        to_tensor_like(x))
    return op


def _mkn(jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        ax = tuple(axes) if axes is not None else None
        return apply_op(lambda a: jfn(a, s=s, axes=ax, norm=_norm(norm)),
                        to_tensor_like(x))
    return op


fft = _mk1(jnp.fft.fft)
ifft = _mk1(jnp.fft.ifft)
rfft = _mk1(jnp.fft.rfft)
irfft = _mk1(jnp.fft.irfft)
hfft = _mk1(jnp.fft.hfft)
ihfft = _mk1(jnp.fft.ihfft)
fft2 = _mk2(jnp.fft.fft2)
ifft2 = _mk2(jnp.fft.ifft2)
rfft2 = _mk2(jnp.fft.rfft2)
irfft2 = _mk2(jnp.fft.irfft2)
fftn = _mkn(jnp.fft.fftn)
ifftn = _mkn(jnp.fft.ifftn)
rfftn = _mkn(jnp.fft.rfftn)
irfftn = _mkn(jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .framework import core
    from .tensor import Tensor
    out = jnp.fft.fftfreq(n, d)
    if dtype is not None:
        out = out.astype(core.convert_dtype(dtype))
    return Tensor(out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .framework import core
    from .tensor import Tensor
    out = jnp.fft.rfftfreq(n, d)
    if dtype is not None:
        out = out.astype(core.convert_dtype(dtype))
    return Tensor(out)


def fftshift(x, axes=None, name=None):
    return apply_op(lambda a: jnp.fft.fftshift(a, axes=axes), to_tensor_like(x))


def ifftshift(x, axes=None, name=None):
    return apply_op(lambda a: jnp.fft.ifftshift(a, axes=axes), to_tensor_like(x))
