"""Op-surface tail: the remaining reference YAML forward ops
(ref: paddle/phi/api/yaml/ops.yaml + legacy_ops.yaml — tracked by
tools/op_coverage.py; python API anchors cited per op)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.tape import apply_op
from ..framework import core
from ..tensor import Tensor
from ._helpers import to_tensor_like, unwrap

__all__ = [
    "add_n", "trace", "reverse", "fill", "fill_diagonal",
    "fill_diagonal_tensor", "renorm", "clip_by_norm", "check_numerics",
    "logsigmoid", "bce_loss", "huber_loss", "kldiv_loss", "dirichlet",
    "top_p_sampling", "gather_tree", "identity_loss", "temporal_shift",
    "sequence_mask",
    "index_select_strided", "tensor_unfold", "view_dtype", "view_shape",
    "trans_layout", "full_int_array", "segment_pool", "fold",
]


def add_n(inputs, name=None):
    """ref: python/paddle/tensor/math.py add_n (sum_op)."""
    ts = [to_tensor_like(t) for t in inputs]
    return apply_op(lambda *xs: sum(xs[1:], xs[0]), *ts, name="add_n")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    """ref: python/paddle/tensor/math.py trace."""
    return apply_op(
        lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2),
        to_tensor_like(x), name="trace")


def reverse(x, axis, name=None):
    """ref legacy reverse == flip."""
    from .manipulation import flip
    return flip(x, axis)


def fill(x, value, name=None):
    """In-place fill (ref fill kernel). Functional under the hood."""
    t = to_tensor_like(x)
    t.data = jnp.full_like(t.data, value)
    return t


def fill_diagonal(x, value, offset=0, wrap=False, name=None):
    """ref: tensor/manipulation.py fill_diagonal_."""
    t = to_tensor_like(x)

    def f(a):
        n = min(a.shape[-2], a.shape[-1])
        i = jnp.arange(n - abs(offset))
        r = i + max(-offset, 0)
        c = i + max(offset, 0)
        return a.at[..., r, c].set(value)

    return apply_op(f, t, name="fill_diagonal")


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    """ref: fill_diagonal_tensor — write tensor y along the diagonal."""
    t = to_tensor_like(x)
    yv = to_tensor_like(y)

    def f(a, b):
        a2 = jnp.moveaxis(a, (dim1, dim2), (-2, -1))
        n = min(a2.shape[-2], a2.shape[-1])
        i = jnp.arange(n - abs(offset))
        r = i + max(-offset, 0)
        c = i + max(offset, 0)
        a2 = a2.at[..., r, c].set(b.astype(a.dtype))
        return jnp.moveaxis(a2, (-2, -1), (dim1, dim2))

    return apply_op(f, t, yv, name="fill_diagonal_tensor")


def renorm(x, p, axis, max_norm, name=None):
    """ref: tensor/math.py renorm — clamp per-slice p-norm to max_norm."""
    t = to_tensor_like(x)

    def f(a):
        moved = jnp.moveaxis(a, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
        scale = jnp.where(norms > max_norm,
                          max_norm / jnp.maximum(norms, 1e-12), 1.0)
        out = flat * scale[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)

    return apply_op(f, t, name="renorm")


def clip_by_norm(x, max_norm, name=None):
    """ref: phi clip_by_norm kernel (nn/clip.py)."""
    t = to_tensor_like(x)

    def f(a):
        n = jnp.sqrt(jnp.sum(a.astype(jnp.float32) ** 2))
        scale = jnp.where(n > max_norm, max_norm / jnp.maximum(n, 1e-12), 1.0)
        return (a.astype(jnp.float32) * scale).astype(a.dtype)

    return apply_op(f, t, name="clip_by_norm")


def check_numerics(x, op_type="", var_name="", message="", stack_height_limit=-1,
                   output_dir="", name=None):
    """ref: check_numerics kernel — raises on nan/inf (eager)."""
    t = to_tensor_like(x)
    from ..autograd.tape import _check_nan_inf
    label = " ".join(s for s in (op_type, var_name, message) if s) \
        or "check_numerics"
    _check_nan_inf(label, (t.data,))
    return t


def logsigmoid(x, name=None):
    from ..nn.functional import log_sigmoid
    return log_sigmoid(x)


def bce_loss(input, label, name=None):
    from ..nn.functional import binary_cross_entropy
    return binary_cross_entropy(input, label, reduction="none")


def huber_loss(input, label, delta=1.0, name=None):
    """ref: phi huber_loss kernel."""
    a, b = to_tensor_like(input), to_tensor_like(label)

    def f(x, y):
        r = jnp.abs(x - y)
        return jnp.where(r <= delta, 0.5 * r * r,
                         delta * (r - 0.5 * delta))

    return apply_op(f, a, b, name="huber_loss")


def kldiv_loss(x, target, reduction="mean", log_target=False, name=None):
    from ..nn.functional import kl_div
    return kl_div(x, target, reduction=reduction, log_target=log_target)


def dirichlet(alpha, name=None):
    """ref: paddle.distribution dirichlet op — one draw per leading row."""
    a = unwrap(to_tensor_like(alpha))
    key = core.next_rng_key()
    g = jax.random.gamma(key, a)
    out = g / jnp.sum(g, axis=-1, keepdims=True)
    return Tensor(out, stop_gradient=True)


def top_p_sampling(x, ps, threshold=None, topp_seed=None, seed=-1,
                   k=0, mode="truncated", return_top=False, name=None):
    """ref: phi top_p_sampling — nucleus sampling over last-dim logits.
    x: [B, V] probabilities or logits; ps: [B] cumulative-probability cap.
    Returns (values, indices) of the sampled token (paddle signature)."""
    lg = unwrap(to_tensor_like(x)).astype(jnp.float32)
    p_cap = jnp.reshape(unwrap(to_tensor_like(ps)).astype(jnp.float32), (-1,))
    probs = jax.nn.softmax(lg, axis=-1)
    sort_idx = jnp.argsort(-probs, axis=-1)
    sort_p = jnp.take_along_axis(probs, sort_idx, axis=-1)
    cum = jnp.cumsum(sort_p, axis=-1)
    keep = cum - sort_p < p_cap[:, None]     # always keep the top token
    if threshold is not None:
        # absolute probability floor, effective together with ps
        thr = jnp.reshape(unwrap(to_tensor_like(threshold))
                          .astype(jnp.float32), (-1, 1))
        keep = keep & (sort_p >= thr)
    if k and int(k) > 0:
        keep = keep & (jnp.arange(sort_p.shape[-1])[None, :] < int(k))
    keep = keep.at[:, 0].set(True)           # never filter the argmax
    if mode != "truncated" or return_top:
        import warnings
        warnings.warn("top_p_sampling: mode!='truncated' / return_top "
                      "are accepted for kernel-signature parity but not "
                      "implemented; sampling uses the truncated "
                      "distribution", UserWarning)
    filt = jnp.where(keep, sort_p, 0.0)
    filt = filt / jnp.maximum(filt.sum(-1, keepdims=True), 1e-12)
    key = (jax.random.PRNGKey(seed) if seed >= 0 else core.next_rng_key())
    choice = jax.random.categorical(key, jnp.log(jnp.maximum(filt, 1e-12)))
    idx = jnp.take_along_axis(sort_idx, choice[:, None], axis=-1)
    val = jnp.take_along_axis(probs, idx, axis=-1)
    return (Tensor(val, stop_gradient=True),
            Tensor(idx.astype(jnp.int64), stop_gradient=True))


def gather_tree(ids, parents, name=None):
    """ref: phi gather_tree — reconstruct beam-search paths.
    ids/parents: [max_time, batch, beam]."""
    iv = unwrap(to_tensor_like(ids)).astype(jnp.int32)
    pv = unwrap(to_tensor_like(parents)).astype(jnp.int32)
    T = iv.shape[0]

    def step(carry, t):
        beams = carry                       # [batch, beam] current beam ids
        tok = jnp.take_along_axis(iv[t], beams, axis=1)
        par = jnp.take_along_axis(pv[t], beams, axis=1)
        return par, tok

    last = jnp.broadcast_to(jnp.arange(iv.shape[2])[None, :],
                            iv.shape[1:]).astype(jnp.int32)
    _, toks = jax.lax.scan(step, last, jnp.arange(T - 1, -1, -1))
    return Tensor(jnp.flip(toks, axis=0), stop_gradient=True)


def identity_loss(x, reduction="none", name=None):
    t = to_tensor_like(x)
    red = {0: "sum", 1: "mean", 2: "none",
           "sum": "sum", "mean": "mean", "none": "none"}[reduction]
    if red == "none":
        return apply_op(lambda a: a, t, name="identity_loss")
    fn = jnp.sum if red == "sum" else jnp.mean
    return apply_op(lambda a: fn(a), t, name="identity_loss")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """ref: phi temporal_shift kernel (TSM video models)."""
    t = to_tensor_like(x)

    def f(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        NT, C, H, W = a.shape
        N = NT // seg_num
        a = a.reshape(N, seg_num, C, H, W)
        c1 = int(C * shift_ratio)
        c2 = int(C * 2 * shift_ratio)
        fwd = jnp.pad(a[:, 1:, :c1], ((0, 0), (0, 1), (0, 0), (0, 0), (0, 0)))
        bwd = jnp.pad(a[:, :-1, c1:c2],
                      ((0, 0), (1, 0), (0, 0), (0, 0), (0, 0)))
        keep = a[:, :, c2:]
        out = jnp.concatenate([fwd, bwd, keep], axis=2).reshape(NT, C, H, W)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return apply_op(f, t, name="temporal_shift")


def index_select_strided(x, index, axis=0, name=None):
    from .manipulation import index_select
    return index_select(x, index, axis)


def tensor_unfold(x, axis, size, step, name=None):
    from .manipulation import unfold
    return unfold(x, axis, size, step)


def view_dtype(x, dtype, name=None):
    from .manipulation import view
    return view(x, dtype)


def view_shape(x, shape, name=None):
    from .manipulation import view
    return view(x, shape)


def trans_layout(x, perm, name=None):
    from .manipulation import transpose
    return transpose(x, perm)


def full_int_array(value, dtype="int64", name=None):
    from .creation import to_tensor
    return to_tensor(np.asarray(value, core.convert_dtype(dtype)))


def segment_pool(x, segment_ids, pooltype="SUM", name=None):
    """ref: phi segment_pool — dispatches to geometric segment ops."""
    from .. import geometric as G
    fn = {"SUM": G.segment_sum, "MEAN": G.segment_mean,
          "MAX": G.segment_max, "MIN": G.segment_min}[pooltype.upper()]
    return fn(x, segment_ids)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """ref: nn/functional/fold (col2im, inverse of unfold)."""
    from ..nn.functional import fold as _fold
    return _fold(x, output_sizes, kernel_sizes, strides, paddings, dilations)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """ref: paddle.nn.functional.sequence_mask (phi sequence_mask op):
    lengths [..., ] -> mask [..., maxlen] with 1 where position < length.
    maxlen=None uses x.max() — that makes the OUTPUT SHAPE data-
    dependent, so under jit pass an explicit maxlen (graph-break
    semantics otherwise: the value is pulled to the host)."""
    t = to_tensor_like(x)
    if maxlen is None:
        maxlen = int(np.asarray(unwrap(t)).max())

    # canonicalize int64 -> int32 quietly (x64 mode is off by default;
    # an astype(int64) would warn-and-truncate per call)
    out_dt = jnp.int32 if str(dtype) in ("int64", "long") else jnp.dtype(dtype)

    def f(lens):
        pos = jnp.arange(int(maxlen))
        m = pos[None, :] < lens.reshape(-1, 1)
        m = m.reshape(tuple(lens.shape) + (int(maxlen),))
        return m.astype(out_dt)

    return apply_op(f, t, name="sequence_mask")
