"""Elementwise & scalar math ops (ref: python/paddle/tensor/math.py,
paddle/phi/kernels/elementwise_*; XLA fuses these — no hand-fusion needed)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.tape import apply_op
from ..framework import core
from ..tensor import Tensor
from ._helpers import make_binary, make_unary, to_tensor_like, unwrap

_UNARY = {
    "abs": jnp.abs, "acos": jnp.arccos, "acosh": jnp.arccosh,
    "asin": jnp.arcsin, "asinh": jnp.arcsinh, "atan": jnp.arctan,
    "atanh": jnp.arctanh, "ceil": jnp.ceil, "cos": jnp.cos,
    "cosh": jnp.cosh, "digamma": jax.scipy.special.digamma,
    "erf": jax.scipy.special.erf, "erfinv": jax.scipy.special.erfinv,
    "exp": jnp.exp, "expm1": jnp.expm1, "floor": jnp.floor,
    "frac": lambda x: x - jnp.trunc(x),
    "i0": lambda x: jax.scipy.special.i0(x), "i0e": lambda x: jax.scipy.special.i0e(x),
    "i1": lambda x: jax.scipy.special.i1(x), "i1e": lambda x: jax.scipy.special.i1e(x),
    "lgamma": jax.scipy.special.gammaln, "log": jnp.log, "log10": jnp.log10,
    "log1p": jnp.log1p, "log2": jnp.log2,
    "neg": jnp.negative, "reciprocal": lambda x: 1.0 / x,
    "round": jnp.round, "rsqrt": jax.lax.rsqrt, "sigmoid": jax.nn.sigmoid,
    "sign": jnp.sign, "sin": jnp.sin, "sinh": jnp.sinh,
    "sqrt": jnp.sqrt, "square": jnp.square, "tan": jnp.tan, "tanh": jnp.tanh,
    "trunc": jnp.trunc, "angle": jnp.angle, "conj": jnp.conj,
    "deg2rad": jnp.deg2rad, "rad2deg": jnp.rad2deg,
}

_BINARY = {
    "add": jnp.add, "subtract": jnp.subtract, "multiply": jnp.multiply,
    "divide": jnp.divide, "floor_divide": jnp.floor_divide,
    "mod": jnp.mod, "remainder": jnp.mod, "floor_mod": jnp.mod,
    "pow": jnp.power, "maximum": jnp.maximum, "minimum": jnp.minimum,
    "fmax": jnp.fmax, "fmin": jnp.fmin, "atan2": jnp.arctan2,
    "logaddexp": jnp.logaddexp, "hypot": jnp.hypot,
    "copysign": jnp.copysign, "nextafter": jnp.nextafter,
    "heaviside": jnp.heaviside, "gcd": jnp.gcd, "lcm": jnp.lcm,
    "ldexp": jnp.ldexp,
    "bitwise_and": jnp.bitwise_and, "bitwise_or": jnp.bitwise_or,
    "bitwise_xor": jnp.bitwise_xor,
    "bitwise_left_shift": jnp.left_shift, "bitwise_right_shift": jnp.right_shift,
}

_g = globals()
for _name, _fn in _UNARY.items():
    _g[_name] = make_unary(_fn, _name)
for _name, _fn in _BINARY.items():
    _g[_name] = make_binary(_fn, _name)

__all__ = list(_UNARY) + list(_BINARY) + [
    "bitwise_not", "clip", "scale", "stanh", "multiplex", "addmm",
    "lerp", "nan_to_num", "trapezoid", "diff", "cumsum", "cumprod",
    "cummax", "cummin", "logcumsumexp", "isfinite", "isinf", "isnan",
    "increment", "divide_no_nan", "rsub",
    "inner", "outer", "kron", "logit", "exp2", "signbit",
    "polygamma", "gammaln", "gammainc", "gammaincc", "sinc",
]


def bitwise_not(x, out=None, name=None):
    return apply_op(jnp.bitwise_not, to_tensor_like(x))


def clip(x, min=None, max=None, name=None):
    mn = unwrap(min) if min is not None else None
    mx = unwrap(max) if max is not None else None
    return apply_op(lambda a: jnp.clip(a, mn, mx), to_tensor_like(x), name="clip")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s, b = unwrap(scale), unwrap(bias)
    if bias_after_scale:
        out = apply_op(lambda a: a * s + b, to_tensor_like(x), name="scale")
    else:
        out = apply_op(lambda a: (a + b) * s, to_tensor_like(x), name="scale")
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op(lambda a: scale_b * jnp.tanh(scale_a * a), to_tensor_like(x))


def multiplex(inputs, index, name=None):
    ts = [to_tensor_like(t) for t in inputs]
    idx = to_tensor_like(index)
    return apply_op(
        lambda i, *xs: jnp.take_along_axis(
            jnp.stack(xs, 0), i.reshape(1, -1, *([1] * (xs[0].ndim - 1))).astype(jnp.int32), axis=0
        )[0],
        idx, *ts, name="multiplex")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op(lambda i, a, b: beta * i + alpha * (a @ b),
                    to_tensor_like(input), to_tensor_like(x), to_tensor_like(y),
                    name="addmm")


def lerp(x, y, weight, name=None):
    if isinstance(weight, (int, float)):
        return apply_op(lambda a, b: a + weight * (b - a),
                        to_tensor_like(x), to_tensor_like(y), name="lerp")
    return apply_op(lambda a, b, w: a + w * (b - a),
                    to_tensor_like(x), to_tensor_like(y), to_tensor_like(weight),
                    name="lerp")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_op(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf),
                    to_tensor_like(x))


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = to_tensor_like(y)
    if x is not None:
        return apply_op(lambda yy, xx: jax.scipy.integrate.trapezoid(yy, xx, axis=axis),
                        y, to_tensor_like(x))
    d = 1.0 if dx is None else dx
    return apply_op(lambda yy: jax.scipy.integrate.trapezoid(yy, dx=d, axis=axis), y)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [to_tensor_like(x)]
    pre = ap = None
    if prepend is not None:
        pre = len(args); args.append(to_tensor_like(prepend))
    if append is not None:
        ap = len(args); args.append(to_tensor_like(append))

    def f(*xs):
        kw = {}
        if pre is not None:
            kw["prepend"] = xs[pre]
        if ap is not None:
            kw["append"] = xs[ap]
        return jnp.diff(xs[0], n=n, axis=axis, **kw)
    return apply_op(f, *args, name="diff")


def cumsum(x, axis=None, dtype=None, name=None):
    d = core.convert_dtype(dtype)
    return apply_op(lambda a: jnp.cumsum(a, axis=axis, dtype=d), to_tensor_like(x))


def cumprod(x, dim=None, dtype=None, name=None):
    d = core.convert_dtype(dtype)
    return apply_op(lambda a: jnp.cumprod(a, axis=dim, dtype=d), to_tensor_like(x))


def _cummaxmin(x, axis, dtype, fn):
    x = to_tensor_like(x)
    d = core.convert_dtype(dtype) or jnp.int32
    flat = axis is None
    ax = 0 if axis is None else axis

    def f(a):
        a = a.ravel() if flat else a
        axx = ax % a.ndim
        cm = fn(a, axis=axx)
        eq = a == cm  # positions achieving the running extremum
        ar = jnp.arange(a.shape[axx]).reshape(
            [-1 if i == axx else 1 for i in range(a.ndim)])
        idx = jax.lax.cummax(jnp.where(eq, jnp.broadcast_to(ar, a.shape), -1),
                             axis=axx)
        return cm, idx

    vals, idx = apply_op(f, x, n_outputs=2, name="cummaxmin")
    return vals, Tensor(idx.data.astype(d))


def cummax(x, axis=None, dtype="int64", name=None):
    return _cummaxmin(x, axis, dtype, jax.lax.cummax)


def cummin(x, axis=None, dtype="int64", name=None):
    return _cummaxmin(x, axis, dtype, jax.lax.cummin)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    def f(a):
        if dtype is not None:
            from ..framework import core as _core
            a = a.astype(_core.convert_dtype(dtype))
        if axis is None:
            a = a.ravel()
            ax = 0
        else:
            ax = axis
        m = jax.lax.cummax(a, axis=ax)
        return jnp.log(jnp.cumsum(jnp.exp(a - m), axis=ax)) + m
    return apply_op(f, to_tensor_like(x))


isfinite = make_unary(jnp.isfinite, "isfinite")
isinf = make_unary(jnp.isinf, "isinf")
isnan = make_unary(jnp.isnan, "isnan")


def increment(x, value=1.0, name=None):
    x._inplace_from(apply_op(lambda a: a + value, x, name="increment"))
    return x


def divide_no_nan(x, y, name=None):
    return apply_op(lambda a, b: jnp.where(b == 0, 0.0, a / jnp.where(b == 0, 1.0, b)),
                    to_tensor_like(x), to_tensor_like(y))


def rsub(x, y, alpha=1.0):
    return apply_op(lambda a, b: b - alpha * a, to_tensor_like(x), to_tensor_like(y))


def inner(x, y, name=None):
    return apply_op(lambda a, b: jnp.inner(a, b), to_tensor_like(x), to_tensor_like(y))


def outer(x, y, name=None):
    return apply_op(lambda a, b: jnp.outer(a, b), to_tensor_like(x), to_tensor_like(y))


def kron(x, y, name=None):
    return apply_op(jnp.kron, to_tensor_like(x), to_tensor_like(y))


def logit(x, eps=None, name=None):
    def f(a):
        if eps is not None:
            a = jnp.clip(a, eps, 1.0 - eps)
        return jnp.log(a / (1.0 - a))
    return apply_op(f, to_tensor_like(x))


def exp2(x, name=None):
    return apply_op(jnp.exp2, to_tensor_like(x))


signbit = make_unary(jnp.signbit, "signbit")


def sinc(x, name=None):
    return apply_op(jnp.sinc, to_tensor_like(x))


def polygamma(x, n, name=None):
    return apply_op(lambda a: jax.scipy.special.polygamma(n, a), to_tensor_like(x))


def gammaln(x, name=None):
    return apply_op(jax.scipy.special.gammaln, to_tensor_like(x))


def gammainc(x, y, name=None):
    return apply_op(jax.scipy.special.gammainc, to_tensor_like(x), to_tensor_like(y))


def gammaincc(x, y, name=None):
    return apply_op(jax.scipy.special.gammaincc, to_tensor_like(x), to_tensor_like(y))
