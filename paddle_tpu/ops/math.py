"""Elementwise & scalar math ops (ref: python/paddle/tensor/math.py,
paddle/phi/kernels/elementwise_*; XLA fuses these — no hand-fusion needed)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.tape import apply_op
from ..framework import core
from ..tensor import Tensor
from ._helpers import make_binary, make_unary, to_tensor_like, unwrap

_UNARY = {
    "abs": jnp.abs, "acos": jnp.arccos, "acosh": jnp.arccosh,
    "asin": jnp.arcsin, "asinh": jnp.arcsinh, "atan": jnp.arctan,
    "atanh": jnp.arctanh, "ceil": jnp.ceil, "cos": jnp.cos,
    "cosh": jnp.cosh, "digamma": jax.scipy.special.digamma,
    "erf": jax.scipy.special.erf, "erfinv": jax.scipy.special.erfinv,
    "exp": jnp.exp, "expm1": jnp.expm1, "floor": jnp.floor,
    "frac": lambda x: x - jnp.trunc(x),
    "i0": lambda x: jax.scipy.special.i0(x), "i0e": lambda x: jax.scipy.special.i0e(x),
    "i1": lambda x: jax.scipy.special.i1(x), "i1e": lambda x: jax.scipy.special.i1e(x),
    "lgamma": jax.scipy.special.gammaln, "log": jnp.log, "log10": jnp.log10,
    "log1p": jnp.log1p, "log2": jnp.log2,
    "neg": jnp.negative, "reciprocal": lambda x: 1.0 / x,
    "round": jnp.round, "rsqrt": jax.lax.rsqrt, "sigmoid": jax.nn.sigmoid,
    "sign": jnp.sign, "sin": jnp.sin, "sinh": jnp.sinh,
    "sqrt": jnp.sqrt, "square": jnp.square, "tan": jnp.tan, "tanh": jnp.tanh,
    "trunc": jnp.trunc, "angle": jnp.angle, "conj": jnp.conj,
    "deg2rad": jnp.deg2rad, "rad2deg": jnp.rad2deg,
}

_BINARY = {
    "add": jnp.add, "subtract": jnp.subtract, "multiply": jnp.multiply,
    "divide": jnp.divide, "floor_divide": jnp.floor_divide,
    "mod": jnp.mod, "remainder": jnp.mod, "floor_mod": jnp.mod,
    "pow": jnp.power, "maximum": jnp.maximum, "minimum": jnp.minimum,
    "fmax": jnp.fmax, "fmin": jnp.fmin, "atan2": jnp.arctan2,
    "logaddexp": jnp.logaddexp, "hypot": jnp.hypot,
    "copysign": jnp.copysign, "nextafter": jnp.nextafter,
    "heaviside": jnp.heaviside, "gcd": jnp.gcd, "lcm": jnp.lcm,
    "ldexp": jnp.ldexp,
    "bitwise_and": jnp.bitwise_and, "bitwise_or": jnp.bitwise_or,
    "bitwise_xor": jnp.bitwise_xor,
    "bitwise_left_shift": jnp.left_shift, "bitwise_right_shift": jnp.right_shift,
}

_g = globals()
for _name, _fn in _UNARY.items():
    _g[_name] = make_unary(_fn, _name)
for _name, _fn in _BINARY.items():
    _g[_name] = make_binary(_fn, _name)

__all__ = list(_UNARY) + list(_BINARY) + [
    "bitwise_not", "clip", "scale", "stanh", "multiplex", "addmm",
    "lerp", "nan_to_num", "trapezoid", "diff", "cumsum", "cumprod",
    "cummax", "cummin", "logcumsumexp", "isfinite", "isinf", "isnan",
    "increment", "divide_no_nan", "rsub",
    "inner", "outer", "kron", "logit", "exp2", "signbit",
    "polygamma", "gammaln", "gammainc", "gammaincc", "sinc",
]


def bitwise_not(x, out=None, name=None):
    return apply_op(jnp.bitwise_not, to_tensor_like(x))


def _clip_k(a, *, mn, mx):
    return jnp.clip(a, mn, mx)


def clip(x, min=None, max=None, name=None):
    mn = unwrap(min) if min is not None else None
    mx = unwrap(max) if max is not None else None
    return apply_op(_clip_k, to_tensor_like(x), name="clip", mn=mn, mx=mx)


def _scale_bias_after_k(a, *, s, b):
    return a * s + b


def _scale_bias_before_k(a, *, s, b):
    return (a + b) * s


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s, b = unwrap(scale), unwrap(bias)
    k = _scale_bias_after_k if bias_after_scale else _scale_bias_before_k
    out = apply_op(k, to_tensor_like(x), name="scale", s=s, b=b)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def _stanh_k(a, *, sa, sb):
    return sb * jnp.tanh(sa * a)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op(_stanh_k, to_tensor_like(x), sa=scale_a, sb=scale_b)


def multiplex(inputs, index, name=None):
    ts = [to_tensor_like(t) for t in inputs]
    idx = to_tensor_like(index)
    return apply_op(
        lambda i, *xs: jnp.take_along_axis(
            jnp.stack(xs, 0), i.reshape(1, -1, *([1] * (xs[0].ndim - 1))).astype(jnp.int32), axis=0
        )[0],
        idx, *ts, name="multiplex")


def _addmm_k(i, a, b, *, beta, alpha):
    return beta * i + alpha * (a @ b)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op(_addmm_k, to_tensor_like(input), to_tensor_like(x),
                    to_tensor_like(y), name="addmm", beta=beta, alpha=alpha)


def _lerp_scalar_k(a, b, *, w):
    return a + w * (b - a)


def _lerp_k(a, b, w):
    return a + w * (b - a)


def lerp(x, y, weight, name=None):
    if isinstance(weight, (int, float)):
        return apply_op(_lerp_scalar_k, to_tensor_like(x), to_tensor_like(y),
                        name="lerp", w=weight)
    return apply_op(_lerp_k, to_tensor_like(x), to_tensor_like(y),
                    to_tensor_like(weight), name="lerp")


def _nan_to_num_k(a, *, nan, posinf, neginf):
    return jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_op(_nan_to_num_k, to_tensor_like(x), nan=nan, posinf=posinf,
                    neginf=neginf)


def _trapezoid_x_k(yy, xx, *, ax):
    return jax.scipy.integrate.trapezoid(yy, xx, axis=ax)


def _trapezoid_dx_k(yy, *, dx, ax):
    return jax.scipy.integrate.trapezoid(yy, dx=dx, axis=ax)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = to_tensor_like(y)
    if x is not None:
        return apply_op(_trapezoid_x_k, y, to_tensor_like(x), ax=int(axis))
    return apply_op(_trapezoid_dx_k, y, dx=1.0 if dx is None else dx,
                    ax=int(axis))


def _diff_k(*xs, pre, ap, n, ax):
    kw = {}
    if pre is not None:
        kw["prepend"] = xs[pre]
    if ap is not None:
        kw["append"] = xs[ap]
    return jnp.diff(xs[0], n=n, axis=ax, **kw)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [to_tensor_like(x)]
    pre = ap = None
    if prepend is not None:
        pre = len(args); args.append(to_tensor_like(prepend))
    if append is not None:
        ap = len(args); args.append(to_tensor_like(append))
    return apply_op(_diff_k, *args, name="diff", pre=pre, ap=ap, n=int(n),
                    ax=int(axis))


def _cumsum_k(a, *, ax, dt):
    return jnp.cumsum(a, axis=ax, dtype=dt)


def cumsum(x, axis=None, dtype=None, name=None):
    return apply_op(_cumsum_k, to_tensor_like(x), ax=axis,
                    dt=core.convert_dtype(dtype))


def _cumprod_k(a, *, ax, dt):
    return jnp.cumprod(a, axis=ax, dtype=dt)


def cumprod(x, dim=None, dtype=None, name=None):
    return apply_op(_cumprod_k, to_tensor_like(x), ax=dim,
                    dt=core.convert_dtype(dtype))


def _cummaxmin_k(a, *, which, flat, ax):
    fn = jax.lax.cummax if which == "max" else jax.lax.cummin
    a = a.ravel() if flat else a
    axx = ax % a.ndim
    cm = fn(a, axis=axx)
    eq = a == cm  # positions achieving the running extremum
    ar = jnp.arange(a.shape[axx]).reshape(
        [-1 if i == axx else 1 for i in range(a.ndim)])
    idx = jax.lax.cummax(jnp.where(eq, jnp.broadcast_to(ar, a.shape), -1),
                         axis=axx)
    return cm, idx


def _cummaxmin(x, axis, dtype, which):
    x = to_tensor_like(x)
    d = core.convert_dtype(dtype) or jnp.int32
    vals, idx = apply_op(_cummaxmin_k, x, n_outputs=2, name="cummaxmin",
                         which=which, flat=axis is None,
                         ax=0 if axis is None else int(axis))
    return vals, Tensor(idx.data.astype(d))


def cummax(x, axis=None, dtype="int64", name=None):
    return _cummaxmin(x, axis, dtype, "max")


def cummin(x, axis=None, dtype="int64", name=None):
    return _cummaxmin(x, axis, dtype, "min")


def _logcumsumexp_k(a, *, ax, dt):
    if dt is not None:
        a = a.astype(dt)
    if ax is None:
        a = a.ravel()
        ax = 0
    m = jax.lax.cummax(a, axis=ax)
    return jnp.log(jnp.cumsum(jnp.exp(a - m), axis=ax)) + m


def logcumsumexp(x, axis=None, dtype=None, name=None):
    return apply_op(_logcumsumexp_k, to_tensor_like(x), ax=axis,
                    dt=core.convert_dtype(dtype))


isfinite = make_unary(jnp.isfinite, "isfinite")
isinf = make_unary(jnp.isinf, "isinf")
isnan = make_unary(jnp.isnan, "isnan")


def _add_scalar_k(a, *, v):
    return a + v


def increment(x, value=1.0, name=None):
    x._inplace_from(apply_op(_add_scalar_k, x, name="increment", v=value))
    return x


def divide_no_nan(x, y, name=None):
    return apply_op(lambda a, b: jnp.where(b == 0, 0.0, a / jnp.where(b == 0, 1.0, b)),
                    to_tensor_like(x), to_tensor_like(y))


def _rsub_k(a, b, *, alpha):
    return b - alpha * a


def rsub(x, y, alpha=1.0):
    return apply_op(_rsub_k, to_tensor_like(x), to_tensor_like(y), alpha=alpha)


def inner(x, y, name=None):
    return apply_op(lambda a, b: jnp.inner(a, b), to_tensor_like(x), to_tensor_like(y))


def outer(x, y, name=None):
    return apply_op(lambda a, b: jnp.outer(a, b), to_tensor_like(x), to_tensor_like(y))


def kron(x, y, name=None):
    return apply_op(jnp.kron, to_tensor_like(x), to_tensor_like(y))


def _logit_k(a, *, eps):
    if eps is not None:
        a = jnp.clip(a, eps, 1.0 - eps)
    return jnp.log(a / (1.0 - a))


def logit(x, eps=None, name=None):
    return apply_op(_logit_k, to_tensor_like(x), eps=eps)


def exp2(x, name=None):
    return apply_op(jnp.exp2, to_tensor_like(x))


signbit = make_unary(jnp.signbit, "signbit")


def sinc(x, name=None):
    return apply_op(jnp.sinc, to_tensor_like(x))


def _polygamma_k(a, *, n):
    return jax.scipy.special.polygamma(n, a)


def polygamma(x, n, name=None):
    return apply_op(_polygamma_k, to_tensor_like(x), n=n)


def gammaln(x, name=None):
    return apply_op(jax.scipy.special.gammaln, to_tensor_like(x))


def gammainc(x, y, name=None):
    return apply_op(jax.scipy.special.gammainc, to_tensor_like(x), to_tensor_like(y))


def gammaincc(x, y, name=None):
    return apply_op(jax.scipy.special.gammaincc, to_tensor_like(x), to_tensor_like(y))
