"""einsum (ref: python/paddle/tensor/einsum.py ~1k LoC of parsing —
here XLA's dot_general via jnp.einsum does the planning)."""
from __future__ import annotations

import jax.numpy as jnp

from ..autograd.tape import apply_op
from ._helpers import to_tensor_like

__all__ = ["einsum"]


def einsum(equation, *operands, name=None):
    if len(operands) == 1 and isinstance(operands[0], (list, tuple)):
        operands = tuple(operands[0])
    ts = [to_tensor_like(o) for o in operands]
    return apply_op(lambda *xs: jnp.einsum(equation, *xs, optimize="optimal"),
                    *ts, name="einsum")
