"""Op surface (ref: paddle/phi/api/yaml/ops.yaml ~570 ops + python/paddle/tensor).

Every op is a jnp/lax composition routed through the autograd tape
(`apply_op`), replacing the reference's generated C++ API + phi kernels
(ref: paddle/phi/api/yaml/generator/api_gen.py). XLA replaces kernel
selection / data transform / fusion passes.
"""
from .creation import *      # noqa: F401,F403
from .tensor_array import *  # noqa: F401,F403
from .math import *          # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *         # noqa: F401,F403
from .reduction import *     # noqa: F401,F403
from .search import *        # noqa: F401,F403
from .linalg_ops import *    # noqa: F401,F403
from .random_ops import *    # noqa: F401,F403
from .einsum_ops import *    # noqa: F401,F403
from .extra import *         # noqa: F401,F403
from .tail import *          # noqa: F401,F403

# generated in-place `<op>_` variants over everything defined above
from . import inplace as _inplace
_generated_inplace = _inplace.install(globals())
globals().update(_generated_inplace)

# install them (and the method-shaped tail ops) as Tensor methods too —
# the reference exposes both spellings (paddle.tanh_(t) and t.tanh_())
from ..tensor import Tensor as _Tensor
for _n, _f in _generated_inplace.items():
    if not hasattr(_Tensor, _n):
        setattr(_Tensor, _n, _f)
for _n in ("frexp", "sgn", "index_fill", "multigammaln",
           "cumulative_trapezoid", "tolist"):
    if not hasattr(_Tensor, _n):
        setattr(_Tensor, _n, globals()[_n])
del _Tensor, _n, _f

from . import patch_methods  # noqa: F401  (installs Tensor methods/operators)
