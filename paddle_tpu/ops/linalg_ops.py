"""Linear-algebra ops (ref: python/paddle/tensor/linalg.py + paddle.linalg).

matmul maps to the MXU via XLA dot_general; bf16 accumulation in f32 is the
TPU-native default.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.tape import apply_op
from ..framework import core
from ..tensor import Tensor
from ._helpers import to_tensor_like, unwrap

__all__ = [
    "matmul", "mm", "bmm", "dot", "mv", "dist", "cross", "cholesky",
    "cholesky_solve", "cholesky_inverse", "matrix_power", "matrix_transpose",
    "qr", "svd", "svdvals", "svd_lowrank", "pca_lowrank", "eig", "eigh",
    "eigvals", "eigvalsh", "det", "slogdet", "inverse", "pinv", "solve",
    "triangular_solve", "lstsq", "lu", "lu_unpack", "lu_solve", "matrix_rank",
    "multi_dot", "cond", "corrcoef", "cov", "householder_product",
    "matrix_exp", "vecdot", "vander", "ormqr",
]


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply_op(f, to_tensor_like(x), to_tensor_like(y), name="matmul")


def mm(input, mat2, name=None):
    return matmul(input, mat2)


def bmm(x, y, name=None):
    return apply_op(jnp.matmul, to_tensor_like(x), to_tensor_like(y), name="bmm")


def dot(x, y, name=None):
    return apply_op(lambda a, b: jnp.sum(a * b, axis=-1),
                    to_tensor_like(x), to_tensor_like(y), name="dot")


def vecdot(x, y, axis=-1, name=None):
    return apply_op(lambda a, b: jnp.sum(a * b, axis=axis),
                    to_tensor_like(x), to_tensor_like(y))


def mv(x, vec, name=None):
    return apply_op(jnp.matmul, to_tensor_like(x), to_tensor_like(vec), name="mv")


def dist(x, y, p=2, name=None):
    def f(a, b):
        d = (a - b).ravel()
        if p == 0:
            return jnp.sum(d != 0).astype(a.dtype)
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == float("-inf"):
            return jnp.min(jnp.abs(d))
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)
    return apply_op(f, to_tensor_like(x), to_tensor_like(y), name="dist")


def cross(x, y, axis=9, name=None):
    x, y = to_tensor_like(x), to_tensor_like(y)
    if axis == 9:
        ax = next((i for i, s in enumerate(x.shape) if s == 3), -1)
    else:
        ax = axis
    return apply_op(lambda a, b: jnp.cross(a, b, axis=ax), x, y, name="cross")


def cholesky(x, upper=False, name=None):
    def f(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L
    return apply_op(f, to_tensor_like(x), name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    def f(b, chol):
        return jax.scipy.linalg.cho_solve((chol, not upper), b)
    return apply_op(f, to_tensor_like(x), to_tensor_like(y), name="cholesky_solve")


def cholesky_inverse(x, upper=False, name=None):
    def f(chol):
        n = chol.shape[-1]
        eye = jnp.eye(n, dtype=chol.dtype)
        return jax.scipy.linalg.cho_solve((chol, not upper), eye)
    return apply_op(f, to_tensor_like(x))


def matrix_power(x, n, name=None):
    return apply_op(lambda a: jnp.linalg.matrix_power(a, n), to_tensor_like(x))


def matrix_transpose(x, name=None):
    return apply_op(lambda a: jnp.swapaxes(a, -1, -2), to_tensor_like(x))


def qr(x, mode="reduced", name=None):
    out = apply_op(lambda a: tuple(jnp.linalg.qr(a, mode=mode)),
                   to_tensor_like(x), n_outputs=2 if mode != "r" else 1, name="qr")
    return out


def svd(x, full_matrices=False, name=None):
    return apply_op(
        lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)),
        to_tensor_like(x), n_outputs=3, name="svd")


def svdvals(x, name=None):
    return apply_op(lambda a: jnp.linalg.svd(a, compute_uv=False), to_tensor_like(x))


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    x = to_tensor_like(x)
    a = x.data if M is None else x.data - unwrap(M)
    m, n = a.shape[-2:]
    q = min(q, m, n)
    key = core.next_rng_key()
    G = jax.random.normal(key, a.shape[:-2] + (n, q), dtype=a.dtype)
    Y = a @ G
    Q, _ = jnp.linalg.qr(Y)
    for _ in range(niter):
        Z = jnp.swapaxes(a, -1, -2) @ Q
        Q2, _ = jnp.linalg.qr(Z)
        Y = a @ Q2
        Q, _ = jnp.linalg.qr(Y)
    B = jnp.swapaxes(Q, -1, -2) @ a
    U, S, Vh = jnp.linalg.svd(B, full_matrices=False)
    return Tensor(Q @ U), Tensor(S), Tensor(jnp.swapaxes(Vh, -1, -2))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    x = to_tensor_like(x)
    m, n = x.data.shape[-2:]
    if q is None:
        q = min(6, m, n)
    a = x.data
    if center:
        a = a - a.mean(axis=-2, keepdims=True)
    return svd_lowrank(Tensor(a), q=q, niter=niter)


def eig(x, name=None):
    a = np.asarray(unwrap(x))
    w, v = np.linalg.eig(a)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigvals(x, name=None):
    a = np.asarray(unwrap(x))
    return Tensor(jnp.asarray(np.linalg.eigvals(a)))


def eigh(x, UPLO="L", name=None):
    return apply_op(lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)),
                    to_tensor_like(x), n_outputs=2, name="eigh")


def eigvalsh(x, UPLO="L", name=None):
    return apply_op(lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), to_tensor_like(x))


def det(x, name=None):
    return apply_op(jnp.linalg.det, to_tensor_like(x), name="det")


def slogdet(x, name=None):
    out = apply_op(lambda a: tuple(jnp.linalg.slogdet(a)), to_tensor_like(x),
                   n_outputs=2, name="slogdet")
    return out


def inverse(x, name=None):
    return apply_op(jnp.linalg.inv, to_tensor_like(x), name="inverse")


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op(lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian),
                    to_tensor_like(x), name="pinv")


def solve(x, y, name=None):
    return apply_op(jnp.linalg.solve, to_tensor_like(x), to_tensor_like(y),
                    name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply_op(f, to_tensor_like(x), to_tensor_like(y),
                    name="triangular_solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    a, b = unwrap(x), unwrap(y)
    sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
    return Tensor(sol), Tensor(res), Tensor(jnp.asarray(rank)), Tensor(sv)


def lu(x, pivot=True, get_infos=False, name=None):
    if not pivot:
        # LAPACK getrf (and the reference GPU kernel) always pivots;
        # silently returning pivoted factors for pivot=False would be a
        # wrong decomposition
        raise NotImplementedError(
            "lu(pivot=False) is not supported (the underlying "
            "factorization always partial-pivots)")
    lu_mat, piv = jax.scipy.linalg.lu_factor(unwrap(x))
    outs = [Tensor(lu_mat), Tensor(piv.astype(jnp.int32) + 1)]
    if get_infos:
        outs.append(Tensor(jnp.zeros((), jnp.int32)))
    return tuple(outs)


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True, name=None):
    lu_mat = unwrap(lu_data)
    piv = unwrap(lu_pivots) - 1
    n = lu_mat.shape[-2]
    L = U = P = None
    if unpack_ludata:
        L = jnp.tril(lu_mat, -1) + jnp.eye(n, lu_mat.shape[-1],
                                           dtype=lu_mat.dtype)
        L = L[..., :, : min(lu_mat.shape[-2:])]
        U = jnp.triu(lu_mat)[..., : min(lu_mat.shape[-2:]), :]
    if unpack_pivots:
        pv = np.asarray(piv)
        batch = pv.shape[:-1]
        pv2 = pv.reshape(-1, pv.shape[-1])
        eyes = []
        for row in pv2:
            perm = np.arange(n)
            for i, p in enumerate(row):
                perm[i], perm[p] = perm[p], perm[i]
            eyes.append(np.eye(n)[perm].T)
        P = jnp.asarray(
            np.stack(eyes).reshape(batch + (n, n))).astype(lu_mat.dtype)
    # paddle returns (P, L, U) with None placeholders for skipped parts
    return (Tensor(P) if P is not None else None,
            Tensor(L) if L is not None else None,
            Tensor(U) if U is not None else None)


def lu_solve(b, lu_data, lu_pivots, trans=0, name=None):
    return Tensor(jax.scipy.linalg.lu_solve(
        (unwrap(lu_data), unwrap(lu_pivots) - 1), unwrap(b), trans=trans))


def matrix_rank(x, tol=None, hermitian=False, atol=None, rtol=None, name=None):
    a = unwrap(x)
    if hermitian:
        s = jnp.abs(jnp.linalg.eigvalsh(a))
    else:
        s = jnp.linalg.svd(a, compute_uv=False)
    if tol is None and atol is None and rtol is None:
        tol_v = s.max(-1, keepdims=True) * max(a.shape[-2:]) * jnp.finfo(s.dtype).eps
    else:
        t = tol if tol is not None else atol if atol is not None else 0.0
        tol_v = jnp.asarray(unwrap(t))
        while tol_v.ndim < s.ndim:
            tol_v = tol_v[..., None]
    return Tensor(jnp.sum(s > tol_v, axis=-1).astype(jnp.int64))


def multi_dot(x, name=None):
    ts = [to_tensor_like(t) for t in x]
    return apply_op(lambda *xs: jnp.linalg.multi_dot(xs), *ts, name="multi_dot")


def cond(x, p=None, name=None):
    return apply_op(lambda a: jnp.linalg.cond(a, p=p), to_tensor_like(x))


def corrcoef(x, rowvar=True, ddof=False, name=None):
    return apply_op(lambda a: jnp.corrcoef(a, rowvar=rowvar), to_tensor_like(x))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = unwrap(fweights) if fweights is not None else None
    aw = unwrap(aweights) if aweights is not None else None
    return apply_op(
        lambda a: jnp.cov(a, rowvar=rowvar, bias=not ddof, fweights=fw, aweights=aw),
        to_tensor_like(x), name="cov")


def householder_product(x, tau, name=None):
    def f(a, t):
        m, n = a.shape[-2:]
        k = t.shape[-1]
        Q = jnp.broadcast_to(jnp.eye(m, dtype=a.dtype), a.shape[:-2] + (m, m))
        for i in range(k):
            v = a[..., :, i]
            v = jnp.where(jnp.arange(m) < i, 0.0, v)
            v = v.at[..., i].set(1.0)
            Qv = jnp.einsum("...ij,...j->...i", Q, v)
            Q = Q - t[..., i][..., None, None] * Qv[..., :, None] * v[..., None, :]
        return Q[..., :, :n]
    return apply_op(f, to_tensor_like(x), to_tensor_like(tau))


def matrix_exp(x, name=None):
    return apply_op(jax.scipy.linalg.expm, to_tensor_like(x))


def vander(x, n=None, increasing=False, name=None):
    return apply_op(lambda a: jnp.vander(a, N=n, increasing=increasing),
                    to_tensor_like(x))


def ormqr(x, tau, other, left=True, transpose=False, name=None):
    Q = householder_product(x, tau)
    def f(q, o):
        qq = jnp.swapaxes(q, -1, -2) if transpose else q
        return (qq @ o) if left else (o @ qq)
    return apply_op(f, Q, to_tensor_like(other))
