"""Shape/layout manipulation ops (ref: python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.tape import apply_op
from ..framework import core
from ..tensor import Tensor
from ._helpers import static_int, to_tensor_like, unwrap

__all__ = [
    "reshape", "reshape_", "transpose", "flatten", "squeeze", "squeeze_",
    "unsqueeze", "unsqueeze_", "concat", "stack", "split", "tensor_split",
    "chunk", "unbind", "tile", "expand", "expand_as", "broadcast_to",
    "broadcast_tensors", "gather", "gather_nd", "scatter", "scatter_nd",
    "scatter_nd_add", "index_select", "index_sample", "index_add", "index_put",
    "masked_select", "masked_fill", "masked_scatter", "where", "roll", "flip",
    "rot90", "slice", "strided_slice", "crop", "repeat_interleave",
    "take_along_axis", "put_along_axis", "pad", "cast", "flatten_",
    "unstack", "unique", "unique_consecutive", "nonzero", "moveaxis",
    "swapaxes", "take", "tensordot", "as_complex", "as_real", "view", "view_as",
    "atleast_1d", "atleast_2d", "atleast_3d", "diagonal", "diag_embed",
    "diagonal_scatter", "fill_diagonal_", "shard_index", "t",
    "unfold", "as_strided", "select_scatter", "slice_scatter", "column_stack",
    "row_stack", "hstack", "vstack", "dstack", "dsplit", "hsplit", "vsplit",
    "bucketize", "searchsorted", "histogram", "histogramdd", "bincount",
    "block_diag", "cdist",
]


def _axes(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(static_int(a) for a in axis)
    return static_int(axis)


# Op bodies live at module level with shape/axis parameters as keyword-only
# static kwargs: a per-call closure (`lambda a: jnp.reshape(a, shape)`) gets
# a fresh function object every call, which defeats the eager dispatch cache
# (tape.apply_op keys on callable code identity + statics). Enforced by
# tools/check_apply_op_closures.py.

def _reshape_k(a, *, shape):
    return jnp.reshape(a, shape)


def reshape(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = [int(v) for v in np.asarray(shape.data)]
    else:
        shape = [static_int(s) for s in shape]
    return apply_op(_reshape_k, to_tensor_like(x), name="reshape",
                    shape=tuple(shape))


def reshape_(x, shape, name=None):
    return x._inplace_from(reshape(x, shape))


def _view_dtype_k(a, *, dt):
    return a.view(dt)


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return apply_op(_view_dtype_k, to_tensor_like(x),
                    dt=core.convert_dtype(shape_or_dtype))


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def _transpose_k(a, *, perm):
    return jnp.transpose(a, perm)


def transpose(x, perm=None, name=None):
    return apply_op(_transpose_k, to_tensor_like(x), name="transpose",
                    perm=_axes(perm))


def t(x, name=None):
    x = to_tensor_like(x)
    if x.ndim < 2:
        return x.clone()
    return apply_op(jnp.transpose, x, name="t")


def _moveaxis_k(a, *, src, dst):
    return jnp.moveaxis(a, src, dst)


def moveaxis(x, source, destination, name=None):
    return apply_op(_moveaxis_k, to_tensor_like(x),
                    src=_axes(source), dst=_axes(destination))


def _swapaxes_k(a, *, a0, a1):
    return jnp.swapaxes(a, a0, a1)


def swapaxes(x, axis0, axis1, name=None):
    return apply_op(_swapaxes_k, to_tensor_like(x),
                    a0=static_int(axis0), a1=static_int(axis1))


def _flatten_k(a, *, s, e):
    shape = list(a.shape[:s]) + [-1] + list(a.shape[e + 1:])
    return jnp.reshape(a, shape)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = to_tensor_like(x)
    nd = max(x.ndim, 1)
    return apply_op(_flatten_k, x, name="flatten",
                    s=start_axis % nd, e=stop_axis % nd)


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    return x._inplace_from(flatten(x, start_axis, stop_axis))


def _squeeze_k(a, *, ax):
    if ax is None:
        return jnp.squeeze(a)
    keep = tuple(i for i in ax if a.shape[i % a.ndim] == 1)
    return jnp.squeeze(a, axis=keep) if keep else a


def squeeze(x, axis=None, name=None):
    ax = _axes(axis)
    if isinstance(ax, int):
        ax = (ax,)
    return apply_op(_squeeze_k, to_tensor_like(x), name="squeeze", ax=ax)


def squeeze_(x, axis=None, name=None):
    return x._inplace_from(squeeze(x, axis))


def _unsqueeze_k(a, *, ax):
    out = a
    for i in sorted(ax):
        out = jnp.expand_dims(out, i)
    return out


def unsqueeze(x, axis, name=None):
    ax = _axes(axis)
    if isinstance(ax, int):
        ax = (ax,)
    return apply_op(_unsqueeze_k, to_tensor_like(x), name="unsqueeze",
                    ax=tuple(ax))


def unsqueeze_(x, axis, name=None):
    return x._inplace_from(unsqueeze(x, axis))


def _concat_k(*xs, ax):
    return jnp.concatenate(xs, axis=ax)


def concat(x, axis=0, name=None):
    ts = [to_tensor_like(t) for t in x]
    return apply_op(_concat_k, *ts, name="concat", ax=static_int(axis))


def _stack_k(*xs, ax):
    return jnp.stack(xs, axis=ax)


def stack(x, axis=0, name=None):
    ts = [to_tensor_like(t) for t in x]
    return apply_op(_stack_k, *ts, name="stack", ax=static_int(axis))


def hstack(x, name=None):
    return apply_op(lambda *xs: jnp.hstack(xs), *[to_tensor_like(t) for t in x])


def vstack(x, name=None):
    return apply_op(lambda *xs: jnp.vstack(xs), *[to_tensor_like(t) for t in x])


def dstack(x, name=None):
    return apply_op(lambda *xs: jnp.dstack(xs), *[to_tensor_like(t) for t in x])


def column_stack(x, name=None):
    return apply_op(lambda *xs: jnp.column_stack(xs), *[to_tensor_like(t) for t in x])


row_stack = vstack


def split(x, num_or_sections, axis=0, name=None):
    x = to_tensor_like(x)
    ax = static_int(axis)
    dim = x.data.shape[ax]
    if isinstance(num_or_sections, int):
        n = num_or_sections
        if dim % n != 0:
            raise ValueError(
                f"split: axis {ax} size {dim} not divisible by {n} "
                "(use tensor_split/chunk for uneven splits)")
        sizes = [dim // n] * n
    else:
        sizes = [static_int(s) for s in num_or_sections]
        minus = [i for i, s in enumerate(sizes) if s in (-1, None)]
        if minus:
            rest = dim - sum(s for s in sizes if s not in (-1, None))
            sizes[minus[0]] = rest
    offsets = [int(o) for o in np.cumsum([0] + sizes[:-1])]
    n_out = len(sizes)
    out = apply_op(_split_k, x, n_outputs=n_out, name="split",
                   offsets=tuple(offsets), sizes=tuple(int(s) for s in sizes),
                   ax=ax)
    return list(out) if isinstance(out, tuple) else [out]


def _split_k(a, *, offsets, sizes, ax):
    return tuple(jax.lax.slice_in_dim(a, o, o + s, axis=ax)
                 for o, s in zip(offsets, sizes))


def tensor_split(x, num_or_indices, axis=0, name=None):
    x = to_tensor_like(x)
    ax = static_int(axis)
    dim = x.data.shape[ax]
    if isinstance(num_or_indices, int):
        n = num_or_indices
        base, rem = divmod(dim, n)
        sizes = [base + (1 if i < rem else 0) for i in range(n)]
        return split(x, sizes, axis=ax)
    idx = [0] + [static_int(i) for i in num_or_indices] + [dim]
    sizes = [b - a for a, b in zip(idx[:-1], idx[1:])]
    return split(x, sizes, axis=ax)


def chunk(x, chunks, axis=0, name=None):
    # uneven sizes allowed: remainder spread over the leading chunks
    return tensor_split(x, chunks, axis)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def hsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=1)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def _unbind_k(a, *, ax, n):
    return tuple(jax.lax.index_in_dim(a, i, axis=ax, keepdims=False)
                 for i in range(n))


def unbind(x, axis=0, name=None):
    x = to_tensor_like(x)
    ax = static_int(axis)
    n = x.data.shape[ax]
    out = apply_op(_unbind_k, x, n_outputs=n, name="unbind", ax=ax, n=n)
    return list(out) if isinstance(out, tuple) else [out]


unstack = unbind


def _tile_k(a, *, reps):
    return jnp.tile(a, reps)


def tile(x, repeat_times, name=None):
    if isinstance(repeat_times, Tensor):
        repeat_times = [int(v) for v in np.asarray(repeat_times.data)]
    reps = tuple(static_int(r) for r in repeat_times)
    return apply_op(_tile_k, to_tensor_like(x), name="tile", reps=reps)


def _expand_k(a, *, shape):
    tgt = list(shape)
    off = len(tgt) - a.ndim
    for i in range(a.ndim):
        if tgt[off + i] in (-1, None):
            tgt[off + i] = a.shape[i]
    return jnp.broadcast_to(a, tgt)


def expand(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = [int(v) for v in np.asarray(shape.data)]
    shape = tuple(static_int(s) for s in shape)
    return apply_op(_expand_k, to_tensor_like(x), name="expand", shape=shape)


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    ts = [to_tensor_like(t) for t in inputs]
    return list(apply_op(lambda *xs: tuple(jnp.broadcast_arrays(*xs)),
                         *ts, n_outputs=len(ts), name="broadcast_tensors"))


def _cast_k(a, *, dt):
    return a.astype(dt)


def cast(x, dtype, name=None):
    return apply_op(_cast_k, to_tensor_like(x), name="cast",
                    dt=core.convert_dtype(dtype))


def _gather_k(a, i, *, ax):
    return jnp.take(a, i.astype(jnp.int32).ravel(), axis=ax)


def gather(x, index, axis=0, name=None):
    return apply_op(_gather_k, to_tensor_like(x), to_tensor_like(index),
                    name="gather", ax=static_int(axis))


def gather_nd(x, index, name=None):
    def f(a, idx):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        return a[tuple(jnp.moveaxis(idx, -1, 0))] if k > 0 else a
    return apply_op(f, to_tensor_like(x), to_tensor_like(index), name="gather_nd")


def _scatter_k(a, i, u, *, overwrite):
    i = i.astype(jnp.int32).ravel()
    if overwrite:
        return a.at[i].set(u)
    z = a.at[i].set(jnp.zeros_like(u))
    return z.at[i].add(u)


def scatter(x, index, updates, overwrite=True, name=None):
    return apply_op(_scatter_k, to_tensor_like(x), to_tensor_like(index),
                    to_tensor_like(updates), name="scatter",
                    overwrite=bool(overwrite))


def scatter_(x, index, updates, overwrite=True, name=None):
    return x._inplace_from(scatter(x, index, updates, overwrite))


def scatter_nd_add(x, index, updates, name=None):
    def f(a, idx, u):
        idx = idx.astype(jnp.int32)
        return a.at[tuple(jnp.moveaxis(idx, -1, 0))].add(u)
    return apply_op(f, to_tensor_like(x), to_tensor_like(index),
                    to_tensor_like(updates), name="scatter_nd_add")


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros
    z = zeros(shape, dtype=updates.dtype if isinstance(updates, Tensor) else None)
    return scatter_nd_add(z, index, updates)


def index_select(x, index, axis=0, name=None):
    return apply_op(_gather_k, to_tensor_like(x), to_tensor_like(index),
                    name="index_select", ax=static_int(axis))


def index_sample(x, index):
    def f(a, i):
        return jnp.take_along_axis(a, i.astype(jnp.int32), axis=1)
    return apply_op(f, to_tensor_like(x), to_tensor_like(index), name="index_sample")


def _index_add_k(a, i, v, *, ax):
    i = i.astype(jnp.int32).ravel()
    am = jnp.moveaxis(a, ax, 0)
    vm = jnp.moveaxis(v, ax, 0)
    return jnp.moveaxis(am.at[i].add(vm), 0, ax)


def index_add(x, index, axis, value, name=None):
    return apply_op(_index_add_k, to_tensor_like(x), to_tensor_like(index),
                    to_tensor_like(value), name="index_add",
                    ax=static_int(axis))


def _index_put_k(a, v, *idx, accumulate):
    idx = tuple(i.astype(jnp.int32) if jnp.issubdtype(i.dtype, jnp.integer) else i
                for i in idx)
    return a.at[idx].add(v) if accumulate else a.at[idx].set(v)


def index_put(x, indices, value, accumulate=False, name=None):
    idx_ts = [to_tensor_like(i) for i in indices]
    return apply_op(_index_put_k, to_tensor_like(x), to_tensor_like(value),
                    *idx_ts, name="index_put", accumulate=bool(accumulate))


def _masked_select_k(a, idx, *, shape):
    return jnp.take(jnp.broadcast_to(a, shape).ravel(), idx)


def masked_select(x, mask, name=None):
    # dynamic output shape: host-sync (eager only), like the reference's
    # D2H copy in the masked_select kernel
    x, mask = to_tensor_like(x), to_tensor_like(mask)
    shape = jnp.broadcast_shapes(x.data.shape, mask.data.shape)
    mb = np.broadcast_to(np.asarray(mask.data), shape)
    idx = np.nonzero(mb.ravel())[0]
    return apply_op(_masked_select_k, x, idx, name="masked_select",
                    shape=tuple(shape))


def _masked_fill_k(a, m, *, v):
    return jnp.where(m, jnp.asarray(v, a.dtype), a)


def masked_fill(x, mask, value, name=None):
    return apply_op(_masked_fill_k, to_tensor_like(x), to_tensor_like(mask),
                    name="masked_fill", v=unwrap(value))


def _masked_scatter_k(a, v, pos):
    flat = a.ravel()
    return flat.at[pos].set(v.ravel()[: pos.shape[0]]).reshape(a.shape)


def masked_scatter(x, mask, value, name=None):
    x, mask, value = to_tensor_like(x), to_tensor_like(mask), to_tensor_like(value)
    mb = np.asarray(jnp.broadcast_to(mask.data, x.data.shape)).ravel()
    pos = np.nonzero(mb)[0]
    return apply_op(_masked_scatter_k, x, value, pos, name="masked_scatter")


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply_op(lambda c, a, b: jnp.where(c, a, b),
                    to_tensor_like(condition), to_tensor_like(x), to_tensor_like(y),
                    name="where")


def nonzero(x, as_tuple=False):
    # HOST op by nature: the output SHAPE depends on the values, so it
    # cannot trace into jit / record into a static Program (same class:
    # histogram/histogramdd/bincount auto-range). Deliberately not
    # tape-routed — using it inside to_static triggers the concrete-
    # value graph break, which is the correct behavior.
    arr = np.asarray(unwrap(x))
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i).reshape(-1, 1)) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1)))


def _roll_k(a, *, sh, ax):
    return jnp.roll(a, sh, axis=ax)


def roll(x, shifts, axis=None, name=None):
    sh = tuple(shifts) if isinstance(shifts, (list, tuple)) else static_int(shifts)
    return apply_op(_roll_k, to_tensor_like(x), name="roll",
                    sh=sh, ax=_axes(axis))


def _flip_k(a, *, ax):
    return jnp.flip(a, axis=ax)


def flip(x, axis, name=None):
    return apply_op(_flip_k, to_tensor_like(x), name="flip", ax=_axes(axis))


def _rot90_k(a, *, k, axes):
    return jnp.rot90(a, k=k, axes=axes)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op(_rot90_k, to_tensor_like(x), k=static_int(k),
                    axes=tuple(axes))


def _slice_k(a, *, axes, starts, ends):
    out = a
    for ax, st, en in zip(axes, starts, ends):
        n = out.shape[ax]
        st2 = max(st + n, 0) if st < 0 else min(st, n)
        en2 = max(en + n, 0) if en < 0 else min(en, n)
        out = jax.lax.slice_in_dim(out, st2, en2, axis=ax)
    return out


def slice(input, axes, starts, ends):
    return apply_op(_slice_k, to_tensor_like(input), name="slice",
                    axes=tuple(static_int(a) for a in axes),
                    starts=tuple(static_int(s) for s in starts),
                    ends=tuple(static_int(e) for e in ends))


def _strided_slice_k(a, *, axes, starts, ends, strides):
    import builtins
    idx = [builtins.slice(None)] * a.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = builtins.slice(st, en, sd)
    return a[tuple(idx)]


def strided_slice(x, axes, starts, ends, strides, name=None):
    return apply_op(_strided_slice_k, to_tensor_like(x), name="strided_slice",
                    axes=tuple(static_int(a) for a in axes),
                    starts=tuple(static_int(s) for s in starts),
                    ends=tuple(static_int(e) for e in ends),
                    strides=tuple(static_int(s) for s in strides))


def _crop_k(a, *, offs, shp):
    return jax.lax.dynamic_slice(a, offs, shp)


def crop(x, shape=None, offsets=None, name=None):
    x = to_tensor_like(x)
    shp = [static_int(s) for s in (shape if shape is not None else x.shape)]
    offs = [static_int(o) for o in (offsets if offsets is not None else [0] * x.ndim)]
    for i, s in enumerate(shp):
        if s in (-1, None):
            shp[i] = x.shape[i] - offs[i]
    return apply_op(_crop_k, x, name="crop", offs=tuple(offs), shp=tuple(shp))


def _repeat_var_k(a, reps, *, ax, total):
    return jnp.repeat(a, reps, axis=ax, total_repeat_length=total)


def _repeat_k(a, *, reps, ax):
    return jnp.repeat(a, reps, axis=ax)


def repeat_interleave(x, repeats, axis=None, name=None):
    x = to_tensor_like(x)
    ax = _axes(axis)
    if isinstance(repeats, Tensor):
        reps = np.asarray(repeats.data)
        total = int(reps.sum())
        return apply_op(_repeat_var_k, x, jnp.asarray(reps),
                        name="repeat_interleave", ax=ax, total=total)
    return apply_op(_repeat_k, x, name="repeat_interleave",
                    reps=static_int(repeats), ax=ax)


def _take_along_axis_k(a, i, *, ax):
    return jnp.take_along_axis(a, i.astype(jnp.int32), axis=ax)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply_op(_take_along_axis_k, to_tensor_like(arr),
                    to_tensor_like(indices), name="take_along_axis",
                    ax=static_int(axis))


def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    return apply_op(_put_along_axis_k, to_tensor_like(arr),
                    to_tensor_like(indices), to_tensor_like(values),
                    name="put_along_axis", ax=static_int(axis),
                    reduce=reduce, include_self=bool(include_self))


def _put_along_axis_k(a, i, v, *, ax, reduce, include_self):
    i = i.astype(jnp.int32)
    v = jnp.broadcast_to(jnp.asarray(v, a.dtype), i.shape)
    if reduce == "assign":
        return jnp.put_along_axis(a, i, v, axis=ax, inplace=False)
    mode = {"add": "add", "multiply": "multiply", "mul": "multiply",
            "amin": "min", "amax": "max", "mean": "add"}[reduce]
    # scatter via .at on the moved axis
    am = jnp.moveaxis(a, ax, 0)
    im = jnp.moveaxis(i, ax, 0)
    vm = jnp.moveaxis(v, ax, 0)
    grid = jnp.meshgrid(*[jnp.arange(s) for s in im.shape], indexing="ij")
    full_idx = (im,) + tuple(grid[1:])
    if not include_self:
        # targets are re-initialized to the reduce identity: arr's
        # prior values at scattered positions are excluded
        if reduce in ("amin", "amax"):
            if jnp.issubdtype(am.dtype, jnp.integer):
                info = jnp.iinfo(am.dtype)
                init = info.max if reduce == "amin" else info.min
            else:
                init = jnp.inf if reduce == "amin" else -jnp.inf
        else:
            init = {"add": 0, "multiply": 1, "mul": 1,
                    "mean": 0}[reduce]
        am = am.at[full_idx].set(jnp.asarray(init, am.dtype))
    upd = getattr(am.at[full_idx], mode)(vm)
    if reduce == "mean":
        cnt = jnp.zeros(am.shape, jnp.float32).at[full_idx].add(1.0)
        base = jnp.zeros_like(cnt) if not include_self \
            else jnp.ones_like(cnt)
        denom = jnp.maximum(cnt + base, 1.0)
        scattered = cnt > 0
        upd = jnp.where(scattered,
                        (upd.astype(jnp.float32) / denom).astype(
                            upd.dtype),
                        upd)
    return jnp.moveaxis(upd, 0, ax)


def take(x, index, mode="raise", name=None):
    x, index = to_tensor_like(x), to_tensor_like(index)
    if mode == "raise":
        # honor the raise contract when indices are concrete (eager path);
        # under tracing fall back to clip like jnp
        try:
            iv = np.asarray(index.data)
            n = int(np.prod(x.data.shape))
            if iv.size and (iv.min() < -n or iv.max() >= n):
                raise IndexError(
                    f"take: index out of range for tensor with {n} elements "
                    f"(got min={iv.min()}, max={iv.max()})")
        except (TypeError, jax.errors.TracerArrayConversionError):
            pass
    m = "clip" if mode == "raise" else mode
    return apply_op(_take_k, x, index, name="take", m=m)


def _take_k(a, i, *, m):
    return jnp.take(a.ravel(), i.astype(jnp.int32), mode=m)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = to_tensor_like(x)
    if isinstance(pad, Tensor):
        pad = [int(v) for v in np.asarray(pad.data)]
    pad = [static_int(p) for p in pad]
    nd = x.ndim
    if len(pad) == 2 * nd:
        cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle/torch convention: first (before, after) pair applies to the
        # LAST spatial dim, the next pair to the one before it, etc.
        pairs = [(pad[i], pad[i + 1]) for i in range(0, len(pad), 2)]
        cfg = [(0, 0)] * nd
        if data_format.endswith("C") and nd >= 3:  # NHWC/NLC/NDHWC
            spatial = list(range(1, nd - 1))
        else:
            spatial = list(range(2, nd))
        for d, pr in zip(reversed(spatial), pairs):
            cfg[d] = pr
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
             "edge": "edge", "circular": "wrap", "wrap": "wrap"}[mode]
    return apply_op(_pad_k, x, name="pad", cfg=tuple(tuple(p) for p in cfg),
                    jmode=jmode, value=value)


def _pad_k(a, *, cfg, jmode, value):
    if jmode == "constant":
        return jnp.pad(a, cfg, mode="constant", constant_values=value)
    return jnp.pad(a, cfg, mode=jmode)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    arr = np.asarray(unwrap(x))
    res = np.unique(arr, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    from ..framework import core as _core
    idt = _core.convert_dtype(dtype or "int64")   # index/inverse/counts dtype
    outs = [Tensor(jnp.asarray(r if i == 0 else r.astype(idt)))
            for i, r in enumerate(res)]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    arr = np.asarray(unwrap(x))
    if axis is None:
        arr = arr.ravel()
        ax = 0
    else:
        ax = axis
    n = arr.shape[ax]
    if n == 0:
        from ..framework import core as _core
        idt = _core.convert_dtype(dtype or "int64")
        outs = [Tensor(jnp.asarray(arr))]
        if return_inverse:
            outs.append(Tensor(jnp.zeros((0,), idt)))
        if return_counts:
            outs.append(Tensor(jnp.zeros((0,), idt)))
    else:
        sl = [np.s_[:]] * arr.ndim
        sl[ax] = np.s_[1:]
        sl0 = [np.s_[:]] * arr.ndim
        sl0[ax] = np.s_[:-1]
        neq = (arr[tuple(sl)] != arr[tuple(sl0)])
        while neq.ndim > 1:
            neq = neq.any(axis=-1 if ax == 0 else 0)
        keep = np.concatenate([[True], neq])
        out = np.compress(keep, arr, axis=ax)
        outs = [Tensor(jnp.asarray(out))]
        from ..framework import core as _core
        idt = _core.convert_dtype(dtype or "int64")
        if return_inverse:
            inv = np.cumsum(keep) - 1
            outs.append(Tensor(jnp.asarray(inv.astype(idt))))
        if return_counts:
            idx = np.nonzero(keep)[0]
            counts = np.diff(np.append(idx, n))
            outs.append(Tensor(jnp.asarray(counts.astype(idt))))
    return outs[0] if len(outs) == 1 else tuple(outs)


def atleast_1d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_1d, to_tensor_like(t)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_2d, to_tensor_like(t)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_3d, to_tensor_like(t)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def _diagonal_k(a, *, offset, axis1, axis2):
    return jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(_diagonal_k, to_tensor_like(x), name="diagonal",
                    offset=static_int(offset), axis1=static_int(axis1),
                    axis2=static_int(axis2))


def _diag_embed_k(a, *, offset, dim1, dim2):
    n = a.shape[-1] + abs(offset)
    base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
    i = jnp.arange(a.shape[-1])
    r = i + max(-offset, 0)
    c = i + max(offset, 0)
    out = base.at[..., r, c].set(a)
    nd = out.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    perm = [d for d in range(nd) if d not in (nd - 2, nd - 1)]
    # place last two dims at (dim1, dim2)
    order = []
    src = iter(perm)
    for d in range(nd):
        if d == d1:
            order.append(nd - 2)
        elif d == d2:
            order.append(nd - 1)
        else:
            order.append(next(src))
    return jnp.transpose(out, order)


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    return apply_op(_diag_embed_k, to_tensor_like(input), name="diag_embed",
                    offset=static_int(offset), dim1=static_int(dim1),
                    dim2=static_int(dim2))


def _diagonal_scatter_k(a, b, *, offset, axis1, axis2):
    i = jnp.arange(b.shape[-1])
    r = i + max(-offset, 0)
    c = i + max(offset, 0)
    am = jnp.moveaxis(a, (axis1, axis2), (0, 1))
    bm = jnp.moveaxis(b, -1, 0)
    return jnp.moveaxis(am.at[r, c].set(bm), (0, 1), (axis1, axis2))


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(_diagonal_scatter_k, to_tensor_like(x), to_tensor_like(y),
                    name="diagonal_scatter", offset=static_int(offset),
                    axis1=static_int(axis1), axis2=static_int(axis2))


def _fill_diag_wrap_k(a, *, start, step, value, nr, nc):
    idx = jnp.arange(start, nr * nc, step)
    return a.reshape(-1).at[idx].set(value).reshape(nr, nc)


def _fill_diag_k(a, *, n, offset, value):
    i = jnp.arange(n - abs(offset))
    r = i + max(-offset, 0)
    c = i + max(offset, 0)
    return a.at[..., r, c].set(value)


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    if wrap and x.ndim == 2 and x.shape[0] > x.shape[1]:
        # tall matrix + wrap: the diagonal restarts every (ncols+1)
        # flat positions (ref fill_diagonal_ wrap semantics)
        nr, nc = x.shape
        start = offset if offset >= 0 else -offset * nc
        new = apply_op(_fill_diag_wrap_k, x, name="fill_diagonal_",
                       start=int(start), step=nc + 1, value=value,
                       nr=nr, nc=nc)
        return x._inplace_from(new)
    n = min(x.shape[-2], x.shape[-1])
    new = apply_op(_fill_diag_k, x, name="fill_diagonal_",
                   n=n, offset=static_int(offset), value=value)
    return x._inplace_from(new)


def _shard_index_k(i, *, size, shard_id, ignore_value):
    shard = i // size
    return jnp.where(shard == shard_id, i % size, ignore_value)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    return apply_op(_shard_index_k, to_tensor_like(input), name="shard_index",
                    size=index_num // nshards, shard_id=static_int(shard_id),
                    ignore_value=static_int(ignore_value))


def _unfold_k(a, *, ax, size, step):
    n = a.shape[ax]
    starts = list(range(0, n - size + 1, step))
    parts = [jax.lax.slice_in_dim(a, s, s + size, axis=ax) for s in starts]
    return jnp.stack(parts, axis=ax if ax >= 0 else a.ndim + ax)


def _unfold_move_k(a, *, ax):
    return jnp.moveaxis(a, ax + 1, -1)


def unfold(x, axis, size, step, name=None):
    ax = static_int(axis)
    out = apply_op(_unfold_k, to_tensor_like(x), name="unfold",
                   ax=ax, size=static_int(size), step=static_int(step))
    # paddle returns windows appended as last dim
    return apply_op(_unfold_move_k, out, ax=ax)


def _as_strided_k(a, idx):
    return a.ravel()[idx]


def as_strided(x, shape, stride, offset=0, name=None):
    idx = np.full(tuple(shape), offset, dtype=np.int64)
    for d, (s, st) in enumerate(zip(shape, stride)):
        r = np.arange(s) * st
        idx = idx + r.reshape([-1 if i == d else 1 for i in range(len(shape))])
    return apply_op(_as_strided_k, to_tensor_like(x), jnp.asarray(idx),
                    name="as_strided")


def _select_scatter_k(a, v, *, ax, index):
    return jnp.moveaxis(jnp.moveaxis(a, ax, 0).at[index].set(v), 0, ax)


def select_scatter(x, values, axis, index, name=None):
    return apply_op(_select_scatter_k, to_tensor_like(x),
                    to_tensor_like(values), ax=static_int(axis),
                    index=static_int(index))


def _slice_scatter_k(a, v, *, axes, starts, ends, strides):
    import builtins
    idx = [builtins.slice(None)] * a.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = builtins.slice(st, en, sd)
    return a.at[tuple(idx)].set(v)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    return apply_op(_slice_scatter_k, to_tensor_like(x), to_tensor_like(value),
                    axes=tuple(static_int(a) for a in axes),
                    starts=tuple(static_int(s) for s in starts),
                    ends=tuple(static_int(e) for e in ends),
                    strides=tuple(static_int(s) for s in strides))


def as_complex(x, name=None):
    return apply_op(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), to_tensor_like(x))


def as_real(x, name=None):
    return apply_op(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1),
                    to_tensor_like(x))


def _tensordot_k(a, b, *, axes):
    return jnp.tensordot(a, b, axes=axes)


def tensordot(x, y, axes=2, name=None):
    if isinstance(axes, Tensor):
        axes = np.asarray(axes.data).tolist()
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(a) if isinstance(a, (list, tuple)) else a for a in axes)
    return apply_op(_tensordot_k, to_tensor_like(x), to_tensor_like(y),
                    name="tensordot", axes=axes)


def _bucketize_k(ss, xx, *, side, dt):
    return jnp.searchsorted(ss, xx, side=side).astype(dt)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    d = jnp.int32 if out_int32 else core.convert_dtype("int64")
    return apply_op(_bucketize_k, to_tensor_like(sorted_sequence),
                    to_tensor_like(x), name="bucketize", side=side, dt=d)


def _searchsorted_1d(s, x, side):
    return jnp.searchsorted(s, x, side=side)


def _searchsorted_k(ss, v, *, side, dt):
    if ss.ndim == 1:
        out = jnp.searchsorted(ss, v, side=side)
    else:
        out = jax.vmap(functools.partial(_searchsorted_1d, side=side))(
            ss.reshape(-1, ss.shape[-1]), v.reshape(-1, v.shape[-1])
        ).reshape(v.shape)
    return out.astype(dt)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    # paddle returns int64 unless out_int32 (matching bucketize above)
    d = jnp.int32 if out_int32 else core.convert_dtype("int64")
    return apply_op(_searchsorted_k, to_tensor_like(sorted_sequence),
                    to_tensor_like(values), name="searchsorted",
                    side=side, dt=d)


def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None):
    arr = unwrap(input)
    # paddle's min==max==0 sentinel means "use the data range", which is
    # jnp.histogram's range=None default — computed on device, no host
    # sync (float(jnp.min(arr)) here cost two blocking round-trips and
    # broke tracing)
    rng = None if (min == 0 and max == 0) else (float(min), float(max))
    h, _ = jnp.histogram(arr.ravel(), bins=bins, range=rng,
                         weights=(unwrap(weight).ravel()
                                  if weight is not None else None),
                         density=density)
    # int64 counts only for the plain unweighted histogram: weighted bin
    # sums are fractional (paddle returns float there) and an int cast
    # would floor sub-1.0 bins to zero
    return Tensor(h if (density or weight is not None)
                  else h.astype(jnp.int64))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    arr = np.asarray(unwrap(x))
    h, edges = np.histogramdd(arr, bins=bins, range=ranges, density=density,
                              weights=np.asarray(unwrap(weights)) if weights is not None else None)
    return Tensor(jnp.asarray(h)), [Tensor(jnp.asarray(e)) for e in edges]


def bincount(x, weights=None, minlength=0, name=None):
    arr = np.asarray(unwrap(x))
    length = max(minlength, int(arr.max()) + 1 if arr.size else 0)
    w = unwrap(weights) if weights is not None else None
    return Tensor(jnp.bincount(jnp.asarray(arr), weights=w, length=length))


def block_diag(inputs, name=None):
    ts = [to_tensor_like(t) for t in inputs]
    return apply_op(lambda *xs: jax.scipy.linalg.block_diag(*xs), *ts)


def _cdist_k(a, b, *, p):
    diff = a[..., :, None, :] - b[..., None, :, :]
    if p == 2.0:
        return jnp.sqrt(jnp.maximum(jnp.sum(diff * diff, -1), 1e-30))
    if p == float("inf"):
        return jnp.max(jnp.abs(diff), -1)
    return jnp.sum(jnp.abs(diff) ** p, -1) ** (1.0 / p)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary", name=None):
    return apply_op(_cdist_k, to_tensor_like(x), to_tensor_like(y),
                    name="cdist", p=float(p))
