"""TensorArray ops (ref: paddle/fluid/framework/lod_tensor_array.h
LoDTensorArray + python/paddle/tensor/array.py — create_array,
array_write, array_read, array_length).

TPU-native position: the reference's TensorArray exists to serve
variable-length control flow in the static graph executor. Under JAX that
role belongs to lax.scan carries with static shapes; the eager API here
is a real list-backed container for host-side collection (the same way
dygraph paddle treats a TensorArray as a python list — ref
python/paddle/tensor/array.py:25 "In dygraph mode, a list of tensors").
"""
from __future__ import annotations

from typing import List, Optional

from ..tensor import Tensor
from ._helpers import to_tensor_like

__all__ = ["TensorArray", "create_array", "array_write", "array_read",
           "array_length", "array_pop"]


class TensorArray(list):
    """List of Tensors with the reference's array-op surface."""

    def write(self, i: int, x) -> "TensorArray":
        return array_write(x, i, array=self)

    def read(self, i: int) -> Tensor:
        return array_read(self, i)

    def length(self) -> int:
        return len(self)

    def pop(self, i: int = -1) -> Tensor:
        return array_pop(self, i)


def create_array(dtype="float32", initialized_list=None) -> TensorArray:
    """ref: array.py create_array."""
    arr = TensorArray()
    for t in (initialized_list or ()):
        arr.append(to_tensor_like(t))
    return arr


def _idx(i) -> int:
    if isinstance(i, Tensor):
        # required sync: a TensorArray index addresses a python list, so
        # a tensor index must concretize — one scalar pull per access
        return int(i.numpy().reshape(()))  # graft-lint: disable=host-sync
    return int(i)


def array_write(x, i, array: Optional[TensorArray] = None) -> TensorArray:
    """ref: array.py array_write — write x at index i (appending allowed
    only at i == len, the reference's constraint)."""
    if array is None:
        array = TensorArray()
    i = _idx(i)
    x = to_tensor_like(x)
    if i < 0:
        raise IndexError(
            f"array_write index must be >= 0, got {i} (the reference "
            "constrains writes to 0 <= i <= len)")
    if i < len(array):
        array[i] = x
    elif i == len(array):
        array.append(x)
    else:
        raise IndexError(
            f"array_write index {i} beyond array length {len(array)} "
            "(only in-place or append writes allowed)")
    return array


def array_read(array: TensorArray, i) -> Tensor:
    """ref: array.py array_read."""
    return array[_idx(i)]


def array_length(array: TensorArray) -> Tensor:
    """ref: array.py array_length — returns an integer scalar Tensor
    (int32 under JAX's default x32 mode)."""
    import jax.numpy as jnp
    return Tensor(jnp.asarray(len(array)), stop_gradient=True)


def array_pop(array: TensorArray, i=-1) -> Tensor:
    """ref: manipulation.py array_pop."""
    return list.pop(array, _idx(i))
