"""Top-level API tail (ref: python/paddle/__init__.py exports with no
existing equivalent here: finfo/iinfo/dtype, shape/rank/tolist,
broadcast_shape, combinations, pdist, cumulative_trapezoid, frexp, sgn,
multigammaln, index_fill, is_* dtype queries, batch, flops, places,
LazyGuard, rng-state accessors)."""
from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.tape import apply_op
from ..tensor import Tensor
from ._helpers import to_tensor_like, unwrap

__all__ = ["finfo", "iinfo", "dtype", "shape", "rank", "tolist",
           "broadcast_shape", "combinations", "pdist",
           "cumulative_trapezoid", "frexp", "sgn", "multigammaln",
           "index_fill", "is_complex",
           "is_floating_point", "is_integer", "batch", "flops",
           "CPUPlace", "CUDAPlace", "CUDAPinnedPlace", "LazyGuard",
           "disable_signal_handler", "get_rng_state", "set_rng_state",
           "get_cuda_rng_state", "set_cuda_rng_state", "check_shape",
           "summary"]

dtype = jnp.dtype  # ref: paddle.dtype


def finfo(dt):
    """ref: paddle.finfo — float type limits."""
    return jnp.finfo(dt)


def iinfo(dt):
    """ref: paddle.iinfo — integer type limits."""
    return jnp.iinfo(dt)


def shape(x):
    """ref: paddle.shape — runtime shape as an int tensor."""
    return Tensor(jnp.asarray(unwrap(to_tensor_like(x)).shape),
                  stop_gradient=True)


def rank(x):
    """ref: paddle.rank."""
    return Tensor(jnp.asarray(unwrap(to_tensor_like(x)).ndim),
                  stop_gradient=True)


def tolist(x):
    """ref: paddle.tolist."""
    return np.asarray(unwrap(to_tensor_like(x))).tolist()


def broadcast_shape(x_shape, y_shape):
    """ref: paddle.broadcast_shape."""
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def combinations(x, r=2, with_replacement=False, name=None):
    """ref: paddle.combinations — r-combinations of a 1-D tensor."""
    import itertools

    arr = unwrap(to_tensor_like(x))
    n = arr.shape[0]
    gen = (itertools.combinations_with_replacement(range(n), r)
           if with_replacement else itertools.combinations(range(n), r))
    idx = np.asarray(list(gen), np.int64).reshape(-1, r)
    return Tensor(arr[jnp.asarray(idx)], stop_gradient=True)


def pdist(x, p=2.0, name=None):
    """ref: paddle.pdist — condensed pairwise distances of [N, D]."""
    def f(a):
        af = a if jnp.issubdtype(a.dtype, jnp.floating) \
            else a.astype(jnp.float32)
        diff = af[:, None, :] - af[None, :, :]
        if p == 2.0:
            sq = (diff ** 2).sum(-1)
            # exact 0 for duplicate rows, grad-safe sqrt elsewhere
            d = jnp.where(sq > 0,
                          jnp.sqrt(jnp.where(sq > 0, sq, 1.0)), 0.0)
        elif p == float("inf"):
            d = jnp.abs(diff).max(-1)                # Chebyshev
        elif p == 0.0:
            d = (jnp.abs(diff) > 0).sum(-1).astype(af.dtype)  # Hamming
        else:
            d = (jnp.abs(diff) ** p).sum(-1) ** (1.0 / p)
        n = a.shape[0]
        iu = jnp.triu_indices(n, k=1)
        return d[iu]

    return apply_op(f, to_tensor_like(x), name="pdist")


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """ref: paddle.cumulative_trapezoid — x and dx are mutually
    exclusive; 1-D x broadcasts against n-D y along `axis` (the
    reference's supported shapes)."""
    if x is not None and dx is not None:
        raise ValueError("cumulative_trapezoid: pass either x or dx, "
                         "not both (reference contract)")
    args = [to_tensor_like(y)]
    if x is not None:
        args.append(to_tensor_like(x))

    def f(yv, *rest):
        if not jnp.issubdtype(yv.dtype, jnp.floating):
            yv = yv.astype(jnp.float32)   # preserve f64 inputs as-is
        ax = axis % yv.ndim
        y0 = jax.lax.slice_in_dim(yv, 0, yv.shape[ax] - 1, axis=ax)
        y1 = jax.lax.slice_in_dim(yv, 1, yv.shape[ax], axis=ax)
        if rest:
            xv = rest[0]
            if not jnp.issubdtype(xv.dtype, jnp.floating):
                xv = xv.astype(jnp.float32)
            if xv.ndim == 1 and yv.ndim > 1:
                d = jnp.diff(xv)
                view = [1] * yv.ndim
                view[ax] = d.shape[0]
                d = d.reshape(view)
            else:
                xax = axis % xv.ndim
                d = jnp.diff(xv, axis=xax)
        else:
            d = dx if dx is not None else 1.0
        return jnp.cumsum((y0 + y1) / 2.0 * d, axis=ax)

    return apply_op(f, *args, name="cumulative_trapezoid")


def frexp(x, name=None):
    """ref: paddle.frexp -> (mantissa, exponent)."""
    def f(a):
        m, e = jnp.frexp(a)
        return m, e.astype(jnp.float32)

    return apply_op(f, to_tensor_like(x), n_outputs=2, name="frexp")


def sgn(x, name=None):
    """ref: paddle.sgn — sign for reals, unit phasor for complex."""
    def f(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0.0 + 0.0j, a / mag)
        return jnp.sign(a)

    return apply_op(f, to_tensor_like(x), name="sgn")


def multigammaln(x, p, name=None):
    """ref: paddle.multigammaln — log multivariate gamma."""
    def f(a):
        af = a if jnp.issubdtype(a.dtype, jnp.floating) \
            else a.astype(jnp.float32)
        const = p * (p - 1) / 4.0 * _math.log(_math.pi)
        terms = sum(jax.scipy.special.gammaln(af - i / 2.0)
                    for i in range(p))
        return const + terms

    return apply_op(f, to_tensor_like(x), name="multigammaln")


def index_fill(x, index, axis, value, name=None):
    """ref: paddle.index_fill — fill rows/slices at `index` along axis."""
    def f(a, idx):
        moved = jnp.moveaxis(a, axis, 0)
        filled = moved.at[idx.astype(jnp.int32)].set(value)
        return jnp.moveaxis(filled, 0, axis)

    return apply_op(f, to_tensor_like(x), to_tensor_like(index),
                    name="index_fill")


def is_complex(x):
    return jnp.issubdtype(unwrap(to_tensor_like(x)).dtype,
                          jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(unwrap(to_tensor_like(x)).dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(unwrap(to_tensor_like(x)).dtype, jnp.integer)


def batch(reader, batch_size, drop_last=False):
    """ref: paddle.batch — wrap a sample reader into a batch reader."""
    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader


def flops(net, input_size, custom_ops=None, print_detail=False):
    """ref: paddle.flops — model forward FLOPs; measured by XLA's own
    cost analysis of the traced forward (exact, not a per-layer table)."""
    from ..framework import core

    state = {k: t.data for k, t in net.state_dict().items()}
    x = jnp.zeros(tuple(input_size), jnp.float32)

    def fwd(state, xv):
        with net.use_state(state), core.no_grad_guard():
            out = net(Tensor(xv))
            return out.data if isinstance(out, Tensor) else out[0].data

    ca = jax.jit(fwd).lower(state, x).cost_analysis() or {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    total = int(ca.get("flops", 0) or 0)
    if print_detail:
        print(f"Total FLOPs: {total}")
    return total


def summary(net, input_size=None, dtypes=None, input=None):
    """ref: paddle.summary — delegate to hapi Model.summary; a concrete
    `input` tensor supplies the shape when input_size is absent."""
    from ..hapi import Model

    if input_size is None and input is not None:
        input_size = tuple(unwrap(to_tensor_like(input)).shape)
    return Model(net).summary(input_size=input_size, dtype=dtypes)


# ---- places (ref: paddle.CPUPlace / CUDAPlace — device handles; under
# one-controller JAX a place is just a device lookup) ----

class CPUPlace:
    def __repr__(self):
        return "Place(cpu)"

    def __eq__(self, o):
        return isinstance(o, CPUPlace)

    def __hash__(self):
        return hash("cpu_place")


class CUDAPlace:
    """Accepted for API compat; maps to the accelerator device."""

    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return f"Place(accelerator:{self.device_id})"

    def __eq__(self, o):
        return isinstance(o, CUDAPlace) and o.device_id == self.device_id

    def __hash__(self):
        return hash(("cuda_place", self.device_id))


class CUDAPinnedPlace:
    def __repr__(self):
        return "Place(pinned)"

    def __eq__(self, o):
        return isinstance(o, CUDAPinnedPlace)

    def __hash__(self):
        return hash("pinned_place")


class LazyGuard:
    """ref: paddle.LazyGuard — defers parameter materialization. Param
    init here is already cheap functional jnp init on trace; the guard is
    a documented no-op context."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def disable_signal_handler():
    """ref: paddle.disable_signal_handler — no custom handlers here."""


def check_shape(x):  # legacy debugging helper
    return shape(x)


def get_rng_state(device=None):
    """ref: paddle.get_rng_state."""
    from ..framework import core

    return core.get_rng_state()


def set_rng_state(state, device=None):
    from ..framework import core

    core.set_rng_state(state)


get_cuda_rng_state = get_rng_state
set_cuda_rng_state = set_rng_state
