"""Comparison & logical ops (ref: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor
from ._helpers import to_tensor_like, unwrap

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "logical_and", "logical_or", "logical_not", "logical_xor",
    "equal_all", "allclose", "isclose", "is_tensor", "is_empty",
]


def _cmp(fn):
    # through the tape (apply_op), NOT a bare Tensor(...) construction:
    # bypassing the tape makes comparisons invisible to the static
    # Program recorder and to SOT fragment capture — both would then
    # freeze the comparison RESULT as a constant and replay stale
    # branches when inputs change (round-4 capture-soundness fix)
    def op(x, y, name=None):
        from ..autograd import tape
        return tape.apply_op(fn, x, y, name=fn.__name__)
    return op


equal = _cmp(jnp.equal)
not_equal = _cmp(jnp.not_equal)
greater_than = _cmp(jnp.greater)
greater_equal = _cmp(jnp.greater_equal)
less_than = _cmp(jnp.less)
less_equal = _cmp(jnp.less_equal)
logical_and = _cmp(jnp.logical_and)
logical_or = _cmp(jnp.logical_or)
logical_xor = _cmp(jnp.logical_xor)


def logical_not(x, out=None, name=None):
    from ..autograd import tape
    return tape.apply_op(jnp.logical_not, x, name="logical_not")


def equal_all(x, y, name=None):
    from ..autograd import tape
    return tape.apply_op(jnp.array_equal, x, y, name="equal_all")


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    from ..autograd import tape
    return tape.apply_op(
        lambda a, b: jnp.allclose(a, b, rtol=float(rtol),
                                  atol=float(atol), equal_nan=equal_nan),
        x, y, name="allclose")


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    from ..autograd import tape
    return tape.apply_op(
        lambda a, b: jnp.isclose(a, b, rtol=float(rtol),
                                 atol=float(atol), equal_nan=equal_nan),
        x, y, name="isclose")


def is_tensor(x):
    return isinstance(x, Tensor)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(int(np.prod(unwrap(x).shape)) == 0))
