"""In-place op variants (ref: python/paddle/tensor/* `<op>_` functions —
paddle's dygraph inplace API, e.g. math.py tanh_ / manipulation.py
scatter_).

TPU-native position: XLA arrays are immutable; "in-place" in the eager
tape means REBINDING the Tensor's underlying array (donation/aliasing
inside compiled steps is XLA's job). That preserves the API contract the
reference documents — the input tensor object itself now holds the
result — including paddle's restriction that inplace ops on tensors that
require grad inside autograd regions are the caller's responsibility.
"""
from __future__ import annotations

from typing import Callable, Dict

__all__: list = []  # populated by _install below

# base-op name -> generated `<name>_`
_INPLACE_BASES = [
    "abs", "acos", "addmm", "atan", "bitwise_and", "bitwise_left_shift",
    "bitwise_not", "bitwise_or", "bitwise_right_shift", "bitwise_xor",
    "cast", "ceil", "clip", "copysign", "cos", "cumprod", "cumsum",
    "digamma", "divide", "equal", "erf", "exp", "expm1", "fill",
    "floor", "floor_divide", "floor_mod", "frac", "gammainc", "gammaincc",
    "gammaln", "gcd", "greater_equal", "greater_than", "hypot", "i0",
    "lcm", "ldexp", "less_equal", "less_than", "lgamma", "log", "log10",
    "log2", "logical_and", "logical_not", "logical_or", "logical_xor",
    "logit", "masked_fill", "masked_scatter", "mod", "multigammaln",
    "multiply", "nan_to_num", "neg", "not_equal", "polygamma", "pow",
    "reciprocal", "remainder", "renorm", "round", "rsqrt", "scale",
    "scatter", "sigmoid", "sin", "sinh", "sqrt", "square", "subtract",
    "t", "tan", "tanh", "transpose", "tril", "triu", "trunc", "uniform",
    "add", "flatten", "reshape", "squeeze", "unsqueeze",
    "index_fill", "index_add", "index_put",
]


# ops whose inplace form legitimately changes the view shape
_SHAPE_CHANGING = {"reshape", "flatten", "squeeze", "unsqueeze", "t",
                   "transpose", "cast"}


def _make(base: Callable, name: str):
    allow_reshape = base.__name__ in _SHAPE_CHANGING

    def op_(x, *args, **kwargs):
        out = base(x, *args, **kwargs)
        # reject broadcast ENLARGEMENT (more elements than x) — numel
        # comparison still permits legal view changes like cumsum_'s
        # axis=None flatten
        if not allow_reshape and out.data.size > x.data.size:
            raise ValueError(
                f"{name}: in-place result shape {tuple(out.data.shape)} "
                f"broadcast-enlarges input {tuple(x.data.shape)} — the "
                "reference rejects shape-growing inplace ops")
        # rebind: the input tensor object now holds the result (dtype may
        # change, e.g. comparison inplace variants — same as the reference
        # dygraph behavior)
        x.data = out.data
        x.stop_gradient = getattr(out, "stop_gradient", x.stop_gradient)
        return x

    op_.__name__ = name
    op_.__doc__ = (f"In-place variant of `{base.__name__}` "
                   f"(ref: paddle.{base.__name__}_). Rebinds the input "
                   "tensor's array to the result.")
    return op_


def install(namespace: Dict) -> Dict[str, Callable]:
    """Generate `<op>_` for every available base op in `namespace`;
    the caller installs the returned map as module globals AND Tensor
    methods."""
    out = {}

    base_where = namespace.get("where")
    if base_where is not None:
        def where_(condition, x=None, y=None):
            """In-place where: mutates X (ref tensor/search.py where_ —
            'inplaced with input x'), NOT the condition tensor."""
            out_t = base_where(condition, x, y)
            x.data = out_t.data
            x.stop_gradient = getattr(out_t, "stop_gradient",
                                      x.stop_gradient)
            return x

        out["where_"] = where_
    for base_name in _INPLACE_BASES:
        base = namespace.get(base_name)
        if base is None or not callable(base):
            continue
        name = base_name + "_"
        if name in namespace:      # a hand-written variant wins
            continue
        out[name] = _make(base, name)
    __all__.extend(out.keys())
    return out
