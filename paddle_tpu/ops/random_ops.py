"""Random ops (ref: python/paddle/tensor/random.py).

TPU-native: counter-based JAX PRNG keys from the framework key-stack, so the
same code is reproducible eagerly and traceable under jit (the reference's
stateful phi Generator has no compiled-mode story; this does).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.tape import apply_op
from ..framework import core
from ..tensor import Tensor
from ._helpers import to_tensor_like, unwrap

__all__ = [
    "rand", "randn", "randint", "randint_like", "uniform", "normal",
    "standard_normal", "gaussian", "randperm", "multinomial", "bernoulli",
    "poisson", "exponential_", "binomial", "standard_gamma", "log_normal",
    "uniform_", "normal_", "cauchy_", "geometric_",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in np.asarray(shape.data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    # required sync: paddle's API accepts tensor shape entries, but the
    # output shape must be concrete python ints before dispatch
    return tuple(int(unwrap(s)) if not isinstance(s, int) else s for s in shape)  # graft-lint: disable=host-sync


def rand(shape, dtype=None, name=None):
    d = core.convert_dtype(dtype) or core.get_default_dtype()
    return Tensor(jax.random.uniform(core.next_rng_key(), _shape(shape), d))


def randn(shape, dtype=None, name=None):
    d = core.convert_dtype(dtype) or core.get_default_dtype()
    return Tensor(jax.random.normal(core.next_rng_key(), _shape(shape), d))


standard_normal = randn


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    d = core.convert_dtype(dtype) or core.get_default_dtype()
    key = jax.random.key(seed) if seed else core.next_rng_key()
    return Tensor(jax.random.normal(key, _shape(shape), d) * std + mean)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    d = core.convert_dtype(dtype)
    # required sync only when tensor bounds are passed (API compat);
    # jax.random.randint wants concrete min/max for dtype bounds checks
    return Tensor(jax.random.randint(core.next_rng_key(), _shape(shape),
                                     int(unwrap(low)), int(unwrap(high)), d))  # graft-lint: disable=host-sync


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = to_tensor_like(x)
    if high is None:
        low, high = 0, low
    d = core.convert_dtype(dtype) or x.dtype
    out = jax.random.randint(core.next_rng_key(), tuple(x.shape), int(low), int(high),
                             jnp.int32)
    return Tensor(out.astype(d))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    d = core.convert_dtype(dtype) or core.get_default_dtype()
    key = jax.random.key(seed) if seed else core.next_rng_key()
    # required sync only when tensor bounds are passed (API compat)
    return Tensor(jax.random.uniform(key, _shape(shape), d,
                                     minval=float(unwrap(min)),   # graft-lint: disable=host-sync
                                     maxval=float(unwrap(max))))  # graft-lint: disable=host-sync


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = unwrap(mean) if isinstance(mean, Tensor) else mean
        s = unwrap(std) if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(jax.random.normal(core.next_rng_key(), shp,
                                        core.get_default_dtype()) * s + m)
    shp = _shape(shape) if shape is not None else ()
    return Tensor(jax.random.normal(core.next_rng_key(), shp,
                                    core.get_default_dtype()) * std + mean)


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    return Tensor(jnp.exp(normal(mean, std, shape).data))


def randperm(n, dtype="int64", name=None):
    d = core.convert_dtype(dtype)
    return Tensor(jax.random.permutation(core.next_rng_key(), int(n)).astype(d))


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = to_tensor_like(x)
    p = x.data / jnp.sum(x.data, axis=-1, keepdims=True)
    key = core.next_rng_key()
    if replacement:
        out = jax.random.categorical(key, jnp.log(jnp.maximum(p, 1e-30)),
                                     shape=(num_samples,) + p.shape[:-1])
        out = jnp.moveaxis(out, 0, -1)
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(key, p.shape)
        scores = jnp.log(jnp.maximum(p, 1e-30)) + g
        _, out = jax.lax.top_k(scores, num_samples)
    return Tensor(out.astype(jnp.int64))


def bernoulli(x, name=None):
    x = to_tensor_like(x)
    u = jax.random.uniform(core.next_rng_key(), tuple(x.shape))
    return Tensor((u < x.data).astype(x.dtype))


def poisson(x, name=None):
    x = to_tensor_like(x)
    return Tensor(jax.random.poisson(core.next_rng_key(), x.data,
                                     dtype=jnp.int32).astype(x.dtype))


def binomial(count, prob, name=None):
    c, p = unwrap(count), unwrap(prob)
    out = jax.random.binomial(core.next_rng_key(), c.astype(jnp.float32),
                              p.astype(jnp.float32))
    return Tensor(out.astype(jnp.int64))


def standard_gamma(x, name=None):
    x = to_tensor_like(x)
    return Tensor(jax.random.gamma(core.next_rng_key(), x.data))


def exponential_(x, lam=1.0, name=None):
    u = jax.random.uniform(core.next_rng_key(), tuple(x.shape),
                           x.dtype if jnp.issubdtype(x.dtype, jnp.floating)
                           else jnp.float32, minval=1e-7, maxval=1.0)
    x.data = (-jnp.log(u) / lam).astype(x.dtype)
    return x


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else core.next_rng_key()
    x.data = jax.random.uniform(key, tuple(x.shape), x.dtype, minval=min, maxval=max)
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x.data = jax.random.normal(core.next_rng_key(), tuple(x.shape), x.dtype) * std + mean
    return x


def cauchy_(x, loc=0, scale=1, name=None):
    u = jax.random.uniform(core.next_rng_key(), tuple(x.shape), x.dtype,
                           minval=1e-6, maxval=1 - 1e-6)
    x.data = loc + scale * jnp.tan(jnp.pi * (u - 0.5))
    return x


def geometric_(x, probs, name=None):
    u = jax.random.uniform(core.next_rng_key(), tuple(x.shape), jnp.float32,
                           minval=1e-7, maxval=1.0)
    x.data = (jnp.ceil(jnp.log(u) / jnp.log1p(-probs))).astype(x.dtype)
    return x
