"""Search/sort ops (ref: python/paddle/tensor/search.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.tape import apply_op
from ..framework import core
from ..tensor import Tensor
from ._helpers import to_tensor_like, unwrap

__all__ = ["argmax", "argmin", "argsort", "sort", "topk", "kthvalue"]


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = core.convert_dtype(dtype)
    kd = keepdim if axis is not None else False
    return apply_op(
        lambda a: jnp.argmax(a, axis=axis, keepdims=kd).astype(d),
        to_tensor_like(x), name="argmax")


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = core.convert_dtype(dtype)
    kd = keepdim if axis is not None else False
    return apply_op(
        lambda a: jnp.argmin(a, axis=axis, keepdims=kd).astype(d),
        to_tensor_like(x), name="argmin")


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    return apply_op(
        lambda a: jnp.argsort(-a if descending else a, axis=axis,
                              stable=stable or descending
                              ).astype(jnp.int64),
        to_tensor_like(x), name="argsort")


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        out = jnp.sort(a, axis=axis)
        return jnp.flip(out, axis=axis) if descending else out
    if descending:
        # stable descending must mirror argsort ordering; sort values by index
        idx = argsort(x, axis=axis, descending=True, stable=stable)
        return apply_op(lambda a: jnp.take_along_axis(a, idx.data.astype(jnp.int32),
                                                      axis=axis),
                        to_tensor_like(x), name="sort")
    return apply_op(f, to_tensor_like(x), name="sort")


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    x = to_tensor_like(x)
    if isinstance(k, Tensor):
        k = int(np.asarray(k.data))
    ax = (axis if axis is not None else -1) % max(x.ndim, 1)
    def f(a):
        am = jnp.moveaxis(a, ax, -1)
        if largest:
            v, i = jax.lax.top_k(am, k)
        else:
            v, i = jax.lax.top_k(-am, k)
            v = -v
        return jnp.moveaxis(v, -1, ax), jnp.moveaxis(i, -1, ax)
    vals, idx = apply_op(f, x, n_outputs=2, name="topk")
    return vals, Tensor(idx.data.astype(jnp.int64))


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = to_tensor_like(x)
    ax = axis % x.ndim
    def f(a):
        s = jnp.sort(a, axis=ax)
        v = jnp.take(s, jnp.asarray([k - 1]), axis=ax)
        return v if keepdim else jnp.squeeze(v, ax)
    vals = apply_op(f, x, name="kthvalue")
    si = jnp.argsort(x.data, axis=ax)
    idx = jnp.take(si, jnp.asarray([k - 1]), axis=ax)
    if not keepdim:
        idx = jnp.squeeze(idx, ax)
    return vals, Tensor(idx.astype(jnp.int64))
