"""Tensor creation ops (ref: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.tape import apply_op
from ..framework import core
from ..tensor import Parameter, Tensor
from ._helpers import static_int, to_tensor_like, unwrap

__all__ = [
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye",
    "diag", "diagflat", "tril", "triu", "meshgrid", "assign", "clone",
    "complex", "real", "imag", "tril_indices", "triu_indices",
    "create_parameter", "numel", "polar",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in np.asarray(shape.data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(static_int(s) for s in shape)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    dtype = core.convert_dtype(dtype)
    if isinstance(data, Tensor):
        arr = data.data
    else:
        arr = jnp.asarray(data)
    if dtype is not None and arr.dtype != dtype:
        arr = arr.astype(dtype)
    elif dtype is None and np.issubdtype(arr.dtype, np.floating) and not isinstance(data, (Tensor, jax.Array)):
        arr = arr.astype(core.get_default_dtype())
    return Tensor(arr, stop_gradient=stop_gradient)


def zeros(shape, dtype=None, name=None):
    dtype = core.convert_dtype(dtype) or core.get_default_dtype()
    return Tensor(jnp.zeros(_shape(shape), dtype))


def ones(shape, dtype=None, name=None):
    dtype = core.convert_dtype(dtype) or core.get_default_dtype()
    return Tensor(jnp.ones(_shape(shape), dtype))


def full(shape, fill_value, dtype=None, name=None):
    dtype = core.convert_dtype(dtype)
    fill_value = unwrap(fill_value)
    if dtype is None:
        dtype = core.get_default_dtype() if isinstance(fill_value, float) else None
    return Tensor(jnp.full(_shape(shape), fill_value, dtype))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return Tensor(jnp.zeros_like(unwrap(x), dtype=core.convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones_like(unwrap(x), dtype=core.convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor(jnp.full_like(unwrap(x), unwrap(fill_value),
                                dtype=core.convert_dtype(dtype)))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start, end, step = unwrap(start), unwrap(end), unwrap(step)
    dtype = core.convert_dtype(dtype)
    return Tensor(jnp.arange(start, end, step, dtype=dtype))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(unwrap(start), unwrap(stop), static_int(num),
                               dtype=core.convert_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(unwrap(start), unwrap(stop), static_int(num),
                               base=unwrap(base), dtype=core.convert_dtype(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    dtype = core.convert_dtype(dtype) or core.get_default_dtype()
    return Tensor(jnp.eye(static_int(num_rows),
                          static_int(num_columns) if num_columns is not None else None,
                          dtype=dtype))


def diag(x, offset=0, padding_value=0, name=None):
    x = to_tensor_like(x)
    if padding_value == 0 or x.ndim == 2:
        return apply_op(lambda a: jnp.diag(a, k=offset), x, name="diag")
    return apply_op(
        lambda a: jnp.where(jnp.eye(a.shape[0] + abs(offset), dtype=bool, k=offset),
                            jnp.diag(a, k=offset), padding_value),
        x, name="diag")


def diagflat(x, offset=0, name=None):
    return apply_op(lambda a: jnp.diagflat(a, k=offset), to_tensor_like(x))


def tril(x, diagonal=0, name=None):
    return apply_op(lambda a: jnp.tril(a, k=diagonal), to_tensor_like(x))


def triu(x, diagonal=0, name=None):
    return apply_op(lambda a: jnp.triu(a, k=diagonal), to_tensor_like(x))


def tril_indices(row, col=None, offset=0, dtype="int64"):
    col = col if col is not None else row
    r, c = np.tril_indices(row, offset, col)
    d = core.convert_dtype(dtype)
    return Tensor(jnp.stack([jnp.asarray(r, d), jnp.asarray(c, d)]))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = col if col is not None else row
    r, c = np.triu_indices(row, offset, col)
    d = core.convert_dtype(dtype)
    return Tensor(jnp.stack([jnp.asarray(r, d), jnp.asarray(c, d)]))


def meshgrid(*args, name=None):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    tensors = [to_tensor_like(a) for a in args]
    return apply_op(lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")),
                    *tensors, n_outputs=len(tensors), name="meshgrid")


def assign(x, output=None):
    x = to_tensor_like(x)
    out = apply_op(lambda a: a + 0 if jnp.issubdtype(a.dtype, jnp.number) else a,
                   x, name="assign")
    if output is not None:
        output._inplace_from(out)
        return output
    return out


def clone(x, name=None):
    return to_tensor_like(x).clone()


def complex(real, imag, name=None):
    return apply_op(jax.lax.complex, to_tensor_like(real), to_tensor_like(imag))


def polar(abs, angle, name=None):
    return apply_op(lambda r, t: r * jnp.exp(1j * t.astype(jnp.complex64)),
                    to_tensor_like(abs), to_tensor_like(angle))


def real(x, name=None):
    return apply_op(jnp.real, to_tensor_like(x))


def imag(x, name=None):
    return apply_op(jnp.imag, to_tensor_like(x))


def numel(x, name=None):
    return Tensor(jnp.asarray(int(np.prod(unwrap(x).shape))))


def create_parameter(shape, dtype=None, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    from ..nn import initializer as I
    dtype = core.convert_dtype(dtype) or core.get_default_dtype()
    init = default_initializer
    if init is None and attr is not None and getattr(attr, "initializer", None) is not None:
        init = attr.initializer
    if init is None:
        init = I.Constant(0.0) if is_bias else I.XavierNormal()
    data = init(tuple(shape), dtype)
    return Parameter(data, name=name or "")
