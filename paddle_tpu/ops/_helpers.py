"""Shared op-definition helpers."""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.tape import apply_op
from ..framework import core
from ..tensor import Tensor


def to_tensor_like(x) -> Tensor:
    if isinstance(x, Tensor):
        return x
    return Tensor(jnp.asarray(x))


def unwrap(x):
    return x.data if isinstance(x, Tensor) else x


def unwrap_opt(x):
    """Unwrap possibly-None / scalar / Tensor into array-or-scalar."""
    if x is None:
        return None
    return x.data if isinstance(x, Tensor) else x


def static_int(x):
    """Resolve an axis/size argument that may be a 0-d Tensor."""
    if isinstance(x, Tensor):
        return int(np.asarray(x.data))
    return x


def make_unary(jfn, op_name):
    # the paddle-API `name=` kwarg (a user label) must NOT shadow the tape
    # op name — AMP lists and FLAGS_check_nan_inf key off the latter
    def op(x, name=None):
        return apply_op(jfn, to_tensor_like(x), name=op_name)
    op.__name__ = op_name
    op.__qualname__ = op_name
    op.__doc__ = f"TPU-native `paddle.{op_name}` (jnp composition)."
    return op


def make_binary(jfn, op_name):
    def op(x, y, name=None):
        return apply_op(jfn, to_tensor_like(x), to_tensor_like(y),
                        name=op_name)
    op.__name__ = op_name
    op.__qualname__ = op_name
    op.__doc__ = f"TPU-native `paddle.{op_name}` (jnp composition)."
    return op
