"""Install op functions as Tensor methods + Python operators
(ref: python/paddle/base/dygraph/tensor_patch_methods.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..autograd.tape import apply_op
from ..framework import core
from ..tensor import Tensor
from . import creation, einsum_ops, linalg_ops, logic, manipulation, math as m
from . import random_ops, reduction, search
from ._helpers import to_tensor_like

_MODULES = [m, manipulation, reduction, logic, search, linalg_ops, creation,
            random_ops, einsum_ops]

# names that collide with properties/builtins and must not be set
_SKIP = {"to_tensor", "is_tensor", "create_parameter", "meshgrid",
         "broadcast_tensors", "block_diag", "multi_dot"}


def _install():
    for mod in _MODULES:
        for name in getattr(mod, "__all__", []):
            if name in _SKIP or hasattr(Tensor, name):
                continue
            fn = getattr(mod, name)
            setattr(Tensor, name, fn)


_install()

# ---------------------------------------------------------------------------
# extra named methods
# ---------------------------------------------------------------------------

def _astype(self, dtype):
    return manipulation.cast(self, dtype)


def _cpu(self):
    return self


def _cuda(self, device_id=None, blocking=True):
    return self


def _to(self, *args, **kwargs):
    dtype = kwargs.get("dtype")
    for a in args:
        if isinstance(a, str) and a.split(":")[0] in ("cpu", "gpu", "tpu", "cuda", "xpu"):
            continue
        if a is not None and not isinstance(a, bool):
            dtype = a
    if dtype is not None:
        return manipulation.cast(self, dtype)
    return self


def _pin_memory(self):
    return self


def _add_(self, y):
    return self._inplace_from(m.add(self, y))


def _subtract_(self, y):
    return self._inplace_from(m.subtract(self, y))


def _multiply_(self, y):
    return self._inplace_from(m.multiply(self, y))


def _divide_(self, y):
    return self._inplace_from(m.divide(self, y))


def _scale_(self, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    return self._inplace_from(m.scale(self, scale, bias, bias_after_scale, act))


def _clip_(self, min=None, max=None):
    return self._inplace_from(m.clip(self, min, max))


def _mT(self):
    return manipulation.swapaxes(self, -1, -2)


Tensor.astype = _astype
Tensor.cpu = _cpu
Tensor.cuda = _cuda
Tensor.to = _to
Tensor.pin_memory = _pin_memory
Tensor.add_ = _add_
Tensor.subtract_ = _subtract_
Tensor.multiply_ = _multiply_
Tensor.divide_ = _divide_
Tensor.scale_ = _scale_
Tensor.clip_ = _clip_
Tensor.T = property(lambda self: manipulation.transpose(
    self, list(range(self.ndim))[::-1]))
Tensor.mT = property(_mT)
Tensor.cast_ = lambda self, dtype: self._inplace_from(manipulation.cast(self, dtype))
Tensor.zero_ = Tensor.zero_
Tensor.exp_ = lambda self: self._inplace_from(m.exp(self))
Tensor.sqrt_ = lambda self: self._inplace_from(m.sqrt(self))
Tensor.rsqrt_ = lambda self: self._inplace_from(m.rsqrt(self))
Tensor.reciprocal_ = lambda self: self._inplace_from(m.reciprocal(self))
Tensor.floor_ = lambda self: self._inplace_from(m.floor(self))
Tensor.ceil_ = lambda self: self._inplace_from(m.ceil(self))
Tensor.round_ = lambda self: self._inplace_from(m.round(self))
Tensor.tanh_ = lambda self: self._inplace_from(m.tanh(self))
Tensor.abs_ = lambda self: self._inplace_from(m.abs(self))

# ---------------------------------------------------------------------------
# operators
# ---------------------------------------------------------------------------

def _rev(fn):
    def op(self, other):
        return fn(to_tensor_like(other), self)
    return op


Tensor.__add__ = m.add
Tensor.__radd__ = m.add
Tensor.__sub__ = m.subtract
Tensor.__rsub__ = _rev(m.subtract)
Tensor.__mul__ = m.multiply
Tensor.__rmul__ = m.multiply
Tensor.__truediv__ = m.divide
Tensor.__rtruediv__ = _rev(m.divide)
Tensor.__floordiv__ = m.floor_divide
Tensor.__rfloordiv__ = _rev(m.floor_divide)
Tensor.__mod__ = m.mod
Tensor.__rmod__ = _rev(m.mod)
Tensor.__pow__ = m.pow
Tensor.__rpow__ = _rev(m.pow)
Tensor.__matmul__ = linalg_ops.matmul
Tensor.__rmatmul__ = _rev(linalg_ops.matmul)
Tensor.__neg__ = m.neg
Tensor.__abs__ = m.abs
Tensor.__pos__ = lambda self: self
Tensor.__invert__ = lambda self: Tensor(~self.data)
Tensor.__eq__ = logic.equal
Tensor.__ne__ = logic.not_equal
Tensor.__lt__ = logic.less_than
Tensor.__le__ = logic.less_equal
Tensor.__gt__ = logic.greater_than
Tensor.__ge__ = logic.greater_equal
Tensor.__and__ = lambda self, o: Tensor(jnp.bitwise_and(self.data, to_tensor_like(o).data))
Tensor.__or__ = lambda self, o: Tensor(jnp.bitwise_or(self.data, to_tensor_like(o).data))
Tensor.__xor__ = lambda self, o: Tensor(jnp.bitwise_xor(self.data, to_tensor_like(o).data))
Tensor.__lshift__ = lambda self, o: Tensor(jnp.left_shift(self.data, to_tensor_like(o).data))
Tensor.__rshift__ = lambda self, o: Tensor(jnp.right_shift(self.data, to_tensor_like(o).data))
