"""Reductions (ref: python/paddle/tensor/math.py sum/mean/... ,
phi/kernels/reduce_*). XLA lowers these straight to efficient TPU reductions."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.tape import apply_op
from ..framework import core
from ..tensor import Tensor
from ._helpers import to_tensor_like, unwrap

__all__ = [
    "sum", "mean", "prod", "max", "min", "amax", "amin", "std", "var",
    "median", "nanmedian", "nansum", "nanmean", "quantile", "nanquantile",
    "logsumexp", "all", "any", "count_nonzero", "mode", "norm",
]


def _axes(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    if isinstance(axis, Tensor):
        v = np.asarray(axis.data)
        return tuple(int(a) for a in v.ravel()) if v.ndim else int(v)
    return int(axis)


# keyword-only statics + a name-keyed registry keep the op body a single
# module-level function, so repeated reductions hit the eager dispatch cache
# (a per-call closure over `jfn`/`ax` would miss every time).
_REDUCE_FNS = {
    "sum": jnp.sum, "mean": jnp.mean, "prod": jnp.prod, "max": jnp.max,
    "min": jnp.min, "nansum": jnp.nansum, "nanmean": jnp.nanmean,
}


def _reduce_k(a, *, op, ax, keepdim, dt):
    out = _REDUCE_FNS[op](a, axis=ax, keepdims=keepdim)
    return out.astype(dt) if dt is not None else out


def _reduce(jfn_name, x, axis, keepdim, dtype=None, name=""):
    return apply_op(_reduce_k, to_tensor_like(x), name=name, op=jfn_name,
                    ax=_axes(axis), keepdim=bool(keepdim),
                    dt=core.convert_dtype(dtype))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    return _reduce("sum", x, axis, keepdim, dtype, "sum")


def mean(x, axis=None, keepdim=False, name=None):
    return _reduce("mean", x, axis, keepdim, None, "mean")


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return _reduce("prod", x, axis, keepdim, dtype, "prod")


def max(x, axis=None, keepdim=False, name=None):
    return _reduce("max", x, axis, keepdim, None, "max")


def min(x, axis=None, keepdim=False, name=None):
    return _reduce("min", x, axis, keepdim, None, "min")


amax = max
amin = min


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return _reduce("nansum", x, axis, keepdim, dtype, "nansum")


def nanmean(x, axis=None, keepdim=False, name=None):
    return _reduce("nanmean", x, axis, keepdim, None, "nanmean")


def _std_k(a, *, ax, dd, keepdim):
    return jnp.std(a, axis=ax, ddof=dd, keepdims=keepdim)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op(_std_k, to_tensor_like(x), name="std", ax=_axes(axis),
                    dd=1 if unbiased else 0, keepdim=bool(keepdim))


def _var_k(a, *, ax, dd, keepdim):
    return jnp.var(a, axis=ax, ddof=dd, keepdims=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op(_var_k, to_tensor_like(x), name="var", ax=_axes(axis),
                    dd=1 if unbiased else 0, keepdim=bool(keepdim))


def _median_avg_k(a, *, ax, keepdim):
    return jnp.median(a, axis=ax, keepdims=keepdim)


def _median_flat_k(b, *, k, keepdim):
    v = jnp.sort(b.ravel())[k]
    return v.reshape([1] * b.ndim) if keepdim else v


def _median_axis_k(b, *, ax, keepdim):
    kk = jnp.full([1 if i == ax % b.ndim else s for i, s in enumerate(b.shape)],
                  (b.shape[ax] - 1) // 2, jnp.int32)
    v = jnp.take_along_axis(jnp.sort(b, axis=ax), kk, axis=ax)
    return v if keepdim else jnp.squeeze(v, ax)


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    ax = _axes(axis)
    if mode == "avg":
        return apply_op(_median_avg_k, to_tensor_like(x), name="median",
                        ax=ax, keepdim=bool(keepdim))
    # mode="min": lower median (+ its index for a single-int axis —
    # upstream returns the (values, index) pair only in that case)
    x = to_tensor_like(x)
    a = x.data
    if ax is None:
        k = (a.size - 1) // 2
        return apply_op(_median_flat_k, x, name="median", k=int(k),
                        keepdim=bool(keepdim))
    val = apply_op(_median_axis_k, x, name="median", ax=ax,
                   keepdim=bool(keepdim))
    k = (a.shape[ax] - 1) // 2
    idx = jnp.take(jnp.argsort(a, axis=ax), jnp.asarray([k]), axis=ax)
    if not keepdim:
        idx = jnp.squeeze(idx, ax)
    return val, Tensor(idx.astype(jnp.int64))


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None,
              _values_only=False):
    ax = _axes(axis)
    if mode == "min" and isinstance(ax, (tuple, list)):
        # multi-axis: collapse the reduced axes to one and recurse.
        # Upstream returns (values, index) only for a single-int axis,
        # so the recursion skips the index (argsort) work entirely.
        x = to_tensor_like(x)
        axes = sorted(a % x.ndim for a in ax)
        perm = [i for i in range(x.ndim) if i not in axes] + axes
        from .manipulation import reshape, transpose
        xt = transpose(x, perm)
        lead = [xt.shape[i] for i in range(x.ndim - len(axes))]
        xt = reshape(xt, lead + [-1])
        v = nanmedian(xt, axis=-1, keepdim=False, mode="min",
                      _values_only=True)
        if keepdim:
            shp = [1 if d in axes else x.shape[d] for d in range(x.ndim)]
            v = reshape(v, shp)
        return v
    if mode == "min":
        # lower middle of the NON-NaN values + its index (median's
        # mode="min" convention; NaNs sort last so a per-slice valid
        # count picks the right order statistic)
        x = to_tensor_like(x)
        val = apply_op(_nanmedian_min_k, x, name="nanmedian", ax=ax,
                       keepdim=bool(keepdim))
        # upstream contract: the (values, index) pair only for a
        # single-int axis; axis=None returns the values alone
        if ax is None or _values_only:
            return val
        a = x.data
        valid = jnp.sum(~jnp.isnan(a), axis=ax,
                        keepdims=True).astype(jnp.int32)
        k = jnp.maximum((valid - 1) // 2, 0)
        idx = jnp.take_along_axis(jnp.argsort(a, axis=ax), k, axis=ax)
        if not keepdim:
            idx = jnp.squeeze(idx, ax)
        return val, Tensor(idx.astype(jnp.int64))
    return apply_op(_nanmedian_avg_k, to_tensor_like(x), name="nanmedian",
                    ax=ax, keepdim=bool(keepdim))


def _nanmedian_min_k(a, *, ax, keepdim):
    if ax is None:
        f = a.ravel()
        valid = jnp.sum(~jnp.isnan(f)).astype(jnp.int32)
        k = jnp.maximum((valid - 1) // 2, 0)
        v = jnp.sort(f)[k]
        return v.reshape([1] * a.ndim) if keepdim else v
    valid = jnp.sum(~jnp.isnan(a), axis=ax,
                    keepdims=True).astype(jnp.int32)
    k = jnp.maximum((valid - 1) // 2, 0)
    v = jnp.take_along_axis(jnp.sort(a, axis=ax), k, axis=ax)
    return v if keepdim else jnp.squeeze(v, ax)


def _nanmedian_avg_k(a, *, ax, keepdim):
    return jnp.nanmedian(a, axis=ax, keepdims=keepdim)


def _quantile_k(a, q, *, ax, keepdim, method):
    return jnp.quantile(a, q, axis=ax, keepdims=keepdim, method=method)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return apply_op(_quantile_k, to_tensor_like(x), jnp.asarray(unwrap(q)),
                    name="quantile", ax=_axes(axis), keepdim=bool(keepdim),
                    method=interpolation)


def _nanquantile_k(a, q, *, ax, keepdim, method):
    return jnp.nanquantile(a, q, axis=ax, keepdims=keepdim, method=method)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return apply_op(_nanquantile_k, to_tensor_like(x), jnp.asarray(unwrap(q)),
                    name="nanquantile", ax=_axes(axis), keepdim=bool(keepdim),
                    method=interpolation)


def _logsumexp_k(a, *, ax, keepdim):
    return jax.scipy.special.logsumexp(a, axis=ax, keepdims=keepdim)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply_op(_logsumexp_k, to_tensor_like(x), name="logsumexp",
                    ax=_axes(axis), keepdim=bool(keepdim))


def _all_k(a, *, ax, keepdim):
    return jnp.all(a, axis=ax, keepdims=keepdim)


def all(x, axis=None, keepdim=False, name=None):
    return apply_op(_all_k, to_tensor_like(x), name="all", ax=_axes(axis),
                    keepdim=bool(keepdim))


def _any_k(a, *, ax, keepdim):
    return jnp.any(a, axis=ax, keepdims=keepdim)


def any(x, axis=None, keepdim=False, name=None):
    return apply_op(_any_k, to_tensor_like(x), name="any", ax=_axes(axis),
                    keepdim=bool(keepdim))


def _count_nonzero_k(a, *, ax, keepdim):
    return jnp.count_nonzero(a, axis=ax, keepdims=keepdim).astype(jnp.int64)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply_op(_count_nonzero_k, to_tensor_like(x), name="count_nonzero",
                    ax=_axes(axis), keepdim=bool(keepdim))


def mode(x, axis=-1, keepdim=False, name=None):
    x = to_tensor_like(x)
    ax = int(axis) % x.ndim
    a = jnp.moveaxis(x.data, ax, -1)
    n = a.shape[-1]
    # O(n^2) pairwise-count mode: fine for the typical small reduce axis and
    # maps to one fused TPU kernel (no data-dependent shapes)
    counts = jnp.sum(a[..., :, None] == a[..., None, :], axis=-1)
    # prefer the largest value among ties, matching the reference kernel
    order = jnp.argsort(a, axis=-1)
    sc = jnp.take_along_axis(counts, order, axis=-1)
    best_sorted = n - 1 - jnp.argmax(sc[..., ::-1], axis=-1)
    pos = jnp.take_along_axis(order, best_sorted[..., None], axis=-1)
    vals_b = jnp.take_along_axis(a, pos, axis=-1)
    # index = last occurrence of modal value
    hits = a == vals_b
    ar = jnp.broadcast_to(jnp.arange(n), a.shape)
    idx = jnp.max(jnp.where(hits, ar, -1), axis=-1)
    out_val = apply_op(_mode_gather_k, x, idx, name="mode", ax=ax,
                       keepdim=bool(keepdim))
    idx_out = idx[..., None] if keepdim else idx
    if keepdim:
        idx_out = jnp.moveaxis(idx_out, -1, ax)
    return out_val, Tensor(idx_out.astype(jnp.int64))


def _squeeze_or_keep(v, ax, keepdim):
    # v has the reduced axis of size 1 at the end
    if keepdim:
        return jnp.moveaxis(v, -1, ax)
    return v[..., 0]


def _mode_gather_k(b, idx, *, ax, keepdim):
    return _squeeze_or_keep(
        jnp.take_along_axis(jnp.moveaxis(b, ax, -1), idx[..., None], axis=-1),
        ax, keepdim)


def _norm_k(a, *, p, ax, keepdim):
    if p is None or p == "fro":
        if ax is None:
            return jnp.sqrt(jnp.sum(jnp.real(a * jnp.conj(a))))
        return jnp.linalg.norm(a, ord=None, axis=ax, keepdims=keepdim)
    if p == "nuc":
        return jnp.linalg.norm(a, ord="nuc", axis=ax, keepdims=keepdim)
    if p == float("inf"):
        return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
    if p == 0:
        return jnp.sum((a != 0).astype(a.dtype), axis=ax, keepdims=keepdim)
    return jnp.sum(jnp.abs(a) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    return apply_op(_norm_k, to_tensor_like(x), name="norm", p=p,
                    ax=_axes(axis), keepdim=bool(keepdim))
