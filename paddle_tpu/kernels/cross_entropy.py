"""Fused blockwise softmax cross-entropy for large vocabularies
(ref: phi/kernels/gpu/cross_entropy_kernel.cu — the reference fuses
softmax+CE in one kernel; re-designed here flash-style for TPU).

The naive path materializes log_softmax(logits) in f32 — for a LLaMA
batch (B*S=8k, V=32k) that is a ~1 GB HBM round trip in each direction.
This kernel streams vocab blocks through VMEM with an online-softmax
accumulator (m, l) so the f32 [N, V] tensor never exists:

  forward : per token, running max m and sum-exp l over vocab blocks,
            plus the logit at the label; loss = log l + m - x[label].
  backward: dx = (exp(x - m)/l - onehot) * g, recomputed blockwise from
            the saved (m, l) residuals — same trick flash attention uses.

Grid is (token_blocks, vocab_blocks) with the vocab dimension sequential
("arbitrary") so the accumulator carries across vocab steps in VMEM
scratch. Out-of-range vocab columns (non-divisible V) are masked with
-inf; padded token rows are handled by Pallas dropping out-of-bounds
writes.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["fused_cross_entropy", "supported"]

_NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def supported(n_classes: int, min_vocab: int = 4096) -> bool:
    """Worth routing through the kernel: big-vocab CE on TPU.
    FLAGS_use_fused_ce=0 forces the plain-XLA log_softmax path (the
    per-route ablation lever; ref: phi autotune/deterministic kill
    switches)."""
    try:
        from ..framework import core
        if not core.get_bool_flag("FLAGS_use_fused_ce", False):
            return False
    except Exception:
        pass
    return _on_tpu() and n_classes >= min_vocab


def _fwd_kernel(x_ref, lbl_ref, loss_ref, m_out, l_out,
                m_s, l_s, xl_s, *, v_total, bv, ignore_index):
    import jax.experimental.pallas as pl

    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s[...], _NEG_INF)
        l_s[...] = jnp.zeros_like(l_s[...])
        xl_s[...] = jnp.zeros_like(xl_s[...])

    x = x_ref[...].astype(jnp.float32)              # [bn, bv]
    bn = x.shape[0]
    cols = j * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    x = jnp.where(cols < v_total, x, _NEG_INF)

    m_prev = m_s[...]                               # [bn, 1]
    bm = jnp.max(x, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, bm)
    l_s[...] = (l_s[...] * jnp.exp(m_prev - m_new)
                + jnp.sum(jnp.exp(x - m_new), axis=1, keepdims=True))
    m_s[...] = m_new

    lbl = lbl_ref[...]                              # [bn, 1] int32
    hit = cols == lbl
    xl_s[...] += jnp.sum(jnp.where(hit, x, 0.0), axis=1, keepdims=True)

    @pl.when(j == nv - 1)
    def _finish():
        valid = lbl != ignore_index
        loss = jnp.log(l_s[...]) + m_s[...] - xl_s[...]
        loss_ref[...] = jnp.where(valid, loss, 0.0)
        m_out[...] = m_s[...]
        l_out[...] = l_s[...]


def _bwd_kernel(x_ref, lbl_ref, m_ref, l_ref, g_ref, dx_ref,
                *, v_total, bv, ignore_index):
    import jax.experimental.pallas as pl

    j = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32)
    bn = x.shape[0]
    cols = j * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    lbl = lbl_ref[...]
    valid = (lbl != ignore_index).astype(jnp.float32)
    p = jnp.exp(x - m_ref[...]) / l_ref[...]
    onehot = (cols == lbl).astype(jnp.float32)
    g = g_ref[...] * valid
    dx = (p - onehot) * g
    dx = jnp.where(cols < v_total, dx, 0.0)
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _block_sizes(n, v, blocks=None):
    """Token/vocab block sizes: explicit override (sweeps), else the
    autotune cache winner for this (N, V) class, else the heuristic."""
    if blocks is None:
        from . import autotune
        blocks = autotune.lookup(autotune.cache_key("fused_ce", N=n, V=v))
    if blocks is not None:
        return min(blocks[0], n), min(blocks[1], v)
    bn = 256 if n >= 256 else max(8, n)
    bv = 2048 if v >= 2048 else v
    return bn, bv


def _pallas_common(n, v, bn, bv):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid = (pl.cdiv(n, bn), pl.cdiv(v, bv))
    x_spec = pl.BlockSpec((bn, bv), lambda i, j: (i, j))
    row_spec = pl.BlockSpec((bn, 1), lambda i, j: (i, 0))
    # jax >= 0.7 renamed TPUCompilerParams -> CompilerParams
    _CP = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    params = _CP(dimension_semantics=("parallel", "arbitrary"))
    return pl, pltpu, grid, x_spec, row_spec, params


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fused_cross_entropy(logits, labels, ignore_index=-100, blocks=None):
    """Per-token CE loss [N] f32 from logits [N, V] + labels [N] int.
    ignore_index rows get loss 0 (caller divides by the valid count).
    blocks: optional (bn, bv) override used by autotune sweeps."""
    loss, _ = _fwd(logits, labels, ignore_index, blocks)
    return loss


def _fwd(logits, labels, ignore_index, blocks=None):
    n, v = logits.shape
    bn, bv = _block_sizes(n, v, blocks)
    pl, pltpu, grid, x_spec, row_spec, params = _pallas_common(n, v, bn, bv)
    lbl2 = labels.astype(jnp.int32).reshape(n, 1)
    kern = functools.partial(_fwd_kernel, v_total=v, bv=bv,
                             ignore_index=ignore_index)
    out_shape = [jax.ShapeDtypeStruct((n, 1), jnp.float32)] * 3
    interpret = not _on_tpu()
    loss, m, l = pl.pallas_call(
        kern, grid=grid,
        in_specs=[x_spec, row_spec],
        out_specs=[row_spec, row_spec, row_spec],
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((bn, 1), jnp.float32)] * 3,
        compiler_params=None if interpret else params,
        interpret=interpret,
    )(logits, lbl2)
    return loss[:, 0], (logits, lbl2, m, l)


def _fwd_rule(logits, labels, ignore_index, blocks=None):
    return _fwd(logits, labels, ignore_index, blocks)


def _bwd_rule(ignore_index, blocks, res, g):
    logits, lbl2, m, l = res
    n, v = logits.shape
    bn, bv = _block_sizes(n, v, blocks)
    pl, pltpu, grid, x_spec, row_spec, params = _pallas_common(n, v, bn, bv)
    kern = functools.partial(_bwd_kernel, v_total=v, bv=bv,
                             ignore_index=ignore_index)
    interpret = not _on_tpu()
    dx = pl.pallas_call(
        kern, grid=grid,
        in_specs=[x_spec, row_spec, row_spec, row_spec, row_spec],
        out_specs=x_spec,
        out_shape=jax.ShapeDtypeStruct((n, v), logits.dtype),
        compiler_params=None if interpret else params,
        interpret=interpret,
    )(logits, lbl2, m, l, g.astype(jnp.float32).reshape(n, 1))
    return dx, None


fused_cross_entropy.defvjp(_fwd_rule, _bwd_rule)


def sweep_block_sizes(N=8192, V=32000, dtype=jnp.bfloat16,
                      candidates=None, iters=8, resweep=False):
    """On-chip (bn, bv) sweep for the fused-CE kernel; winners persist in
    the autotune cache (ref: phi/kernels/autotune/cache.cc). Tunes the
    training shape: fwd + bwd under grad."""
    from . import autotune

    if candidates is None:
        candidates = [(bn, bv)
                      for bn in (128, 256, 512) if bn <= N
                      for bv in (1024, 2048, 4096, 8192) if bv <= V]
    key = autotune.cache_key("fused_ce", N=N, V=V)
    kq = jax.random.split(jax.random.PRNGKey(0), 2)
    logits = jax.random.normal(kq[0], (N, V), dtype)
    labels = jax.random.randint(kq[1], (N,), 0, V)

    def make_fn(cand):
        def body(c, _):
            f = lambda x: fused_cross_entropy(x, labels, -100,
                                              tuple(cand)).sum()
            return c + jax.grad(f)(logits).astype(jnp.float32).sum(), None

        return jax.jit(lambda: jax.lax.scan(
            body, jnp.float32(0), None, length=iters)[0])

    return autotune.autotune(
        key, candidates, make_fn, default=list(_block_sizes(N, V)),
        iters=iters,
        sweep=True if (resweep or autotune.lookup(key) is None) else None)
