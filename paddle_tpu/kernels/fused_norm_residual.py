"""Fused residual-add + RMSNorm (ref: phi/kernels/fusion/gpu/
fused_bias_residual_layernorm; TPU-native row-blocked Pallas kernel).

The transformer residual seam `h = x + attn; a = rms_norm(h)` is two
HBM round trips when left to XLA (the custom-vjp boundary around
rms_norm blocks fusion across it). This kernel reads x and the residual
branch once, emits BOTH the summed residual stream h (needed downstream
as the next residual source) and the normalized activation y in one
VMEM pass. The backward is an analytic custom_vjp that recomputes the
rstd from the saved h instead of storing normalized activations:

  h  = x + residual                       (rounded to the stream dtype)
  y  = h * r * w,  r = rsqrt(mean(h^2) + eps)
  dh = gh + r*(gy*w) - h * r^3/H * sum(gy*w*h)    (dx = dresidual = dh)
  dw = sum_rows(gy * h * r)

The jnp fallback reproduces the unfused `(x + residual)` + rms_norm
sequence bitwise (same op order, same f32 casts), so the
FLAGS_fused_transformer=0 comparison and the interpret-mode parity
tests share one reference. Tests flip `_FORCE_PALLAS` to drive the
Pallas path through the interpreter on CPU.

Block sizes come from kernels/autotune.py (key "fused_norm", quantized
hidden-size class) — sweep via `sweep_block_sizes`.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    _HAS_TPU = True
except Exception:  # pragma: no cover
    _HAS_TPU = False

__all__ = ["fused_add_rms_norm", "supported", "sweep_block_sizes"]

# tests flip this to exercise the Pallas path through the interpreter on
# CPU (interpret mode is orders of magnitude slower than the fallback)
_FORCE_PALLAS = False


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def supported(shape) -> bool:
    """x/residual: [..., H] — Mosaic lane alignment for the compiled
    route (the fallback handles everything)."""
    return int(shape[-1]) % 128 == 0


def _size_class(h: int) -> int:
    """Quantize the hidden size to a power of two so one autotune sweep
    covers one (kernel, size-class, device) point."""
    c = 128
    while c < h:
        c *= 2
    return c


def _block_rows(rows: int, H: int, block_rows=None) -> int:
    """Rows per grid step: explicit override (sweeps), else the autotune
    winner for this hidden-size class, else min(256, rows) — shrunk to a
    divisor of the row count either way."""
    if block_rows is None:
        from . import autotune
        hit = autotune.lookup(autotune.cache_key("fused_norm",
                                                 H=_size_class(H)))
        if hit:
            block_rows = int(hit[0] if isinstance(hit, (list, tuple))
                             else hit)
    if not block_rows or block_rows <= 0:
        block_rows = 256
    block_rows = max(1, min(block_rows, rows))
    while rows % block_rows:
        block_rows -= 1
    return block_rows


def _route(shape, use_pallas):
    if use_pallas is None:
        return _HAS_TPU and supported(shape) and (_on_tpu() or _FORCE_PALLAS)
    if use_pallas and not supported(shape):
        # an EXPLICIT True must not silently time/run the fallback — a
        # sweep would record noise winners and callers would believe
        # they exercised the compiled route
        raise ValueError(
            f"fused_add_rms_norm: use_pallas=True but shape {tuple(shape)} "
            f"is not Mosaic-aligned (need H % 128 == 0)")
    return use_pallas


def _fwd_kernel(x_ref, r_ref, w_ref, y_ref, h_ref, *, eps):
    # round h to the stream dtype BEFORE normalizing — the unfused path
    # norms the rounded residual stream, and parity with it is the
    # contract the kill switch and the interpret tests check
    h = (x_ref[...].astype(jnp.float32)
         + r_ref[...].astype(jnp.float32)).astype(h_ref.dtype)
    h_ref[...] = h
    h32 = h.astype(jnp.float32)
    ms = jnp.mean(h32 * h32, axis=-1, keepdims=True)
    y_ref[...] = (h32 * jax.lax.rsqrt(ms + eps)
                  * w_ref[...].astype(jnp.float32)).astype(y_ref.dtype)


def _fwd_impl(x, residual, weight, eps, use_pallas, block_rows):
    if not _route(x.shape, use_pallas):
        # exact jnp mirror of the unfused path: Tensor add (f32 compute,
        # round to stream dtype) then the rms_norm fallback on h
        h = x + residual
        h32 = h.astype(jnp.float32)
        ms = jnp.mean(h32 * h32, axis=-1, keepdims=True)
        y = (h32 * jax.lax.rsqrt(ms + eps)
             * weight.astype(jnp.float32)).astype(x.dtype)
        return y, h
    orig_shape = x.shape
    H = orig_shape[-1]
    xf = x.reshape(-1, H)
    rf = residual.reshape(-1, H)
    rows = xf.shape[0]
    br = _block_rows(rows, H, block_rows)
    grid = (rows // br,)
    y, h = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        out_shape=(jax.ShapeDtypeStruct(xf.shape, x.dtype),
                   jax.ShapeDtypeStruct(xf.shape, x.dtype)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((br, H), lambda i: (i, 0)),
            pl.BlockSpec((br, H), lambda i: (i, 0)),
            pl.BlockSpec((H,), lambda i: (0,)),
        ],
        out_specs=(pl.BlockSpec((br, H), lambda i: (i, 0)),
                   pl.BlockSpec((br, H), lambda i: (i, 0))),
        interpret=not _on_tpu(),
    )(xf, rf, weight)
    return y.reshape(orig_shape), h.reshape(orig_shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_add_rms_norm(x, residual, weight, eps=1e-6, use_pallas=None,
                       block_rows=None):
    """x, residual: [..., H]; weight: [H]. Returns (y, h) with
    h = x + residual and y = rms_norm(h) * weight.

    use_pallas: None = auto (real TPU + aligned, or _FORCE_PALLAS via
    the interpreter), True/False forces the route; block_rows overrides
    the autotuned row block (the sweep's candidate lever)."""
    return _fwd_impl(x, residual, weight, eps, use_pallas, block_rows)


def _fused_fwd(x, residual, weight, eps, use_pallas, block_rows):
    y, h = _fwd_impl(x, residual, weight, eps, use_pallas, block_rows)
    # save h (the rounded residual stream) + weight; rstd is recomputed
    # in the backward — nothing normalized survives the forward
    return (y, h), (h, weight)


def _fused_bwd(eps, use_pallas, block_rows, res, cts):
    h, w = res
    gy, gh = cts
    H = h.shape[-1]
    h32 = h.astype(jnp.float32)
    gy32 = gy.astype(jnp.float32)
    w32 = w.astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(h32 * h32, axis=-1, keepdims=True) + eps)
    gw = gy32 * w32
    dnorm = r * gw - h32 * (r ** 3) * jnp.sum(gw * h32, axis=-1,
                                              keepdims=True) / H
    # cotangent accumulation in the stream dtype, matching the tape's
    # add of the rms_norm bwd and the downstream residual cotangent
    dh = dnorm.astype(h.dtype) + gh
    dw = jnp.sum((gy32 * h32 * r).reshape(-1, H), axis=0).astype(w.dtype)
    return dh, dh, dw


fused_add_rms_norm.defvjp(_fused_fwd, _fused_bwd)


def sweep_block_sizes(shape, dtype=jnp.bfloat16, iters=8, sweep=None):
    """Register/refresh the row-block winner for one hidden-size class
    with kernels/autotune.py (PADDLE_AUTOTUNE=1 or sweep=True; cached
    winners are consulted by _block_rows unconditionally)."""
    from . import autotune
    H = int(shape[-1])
    rows = 1
    for s in shape[:-1]:
        rows *= int(s)
    key = autotune.cache_key("fused_norm", H=_size_class(H))

    def make_fn(br):
        if br > rows:
            return None
        rng = jax.random.PRNGKey(0)
        x = jax.random.normal(rng, (rows, H), jnp.float32).astype(dtype)
        res = jax.random.normal(rng, (rows, H), jnp.float32).astype(dtype)
        w = jnp.ones((H,), jnp.float32)

        def run():
            def body(c, _):
                y, h = fused_add_rms_norm(x + c.astype(dtype), res, w,
                                          use_pallas=True, block_rows=br)
                return c + 0 * y[0, 0].astype(jnp.float32), None
            return jax.jit(lambda: jax.lax.scan(
                body, jnp.float32(0), None, length=iters))()

        return run

    return autotune.autotune(key, [32, 64, 128, 256, 512], make_fn,
                             default=_block_rows(rows, H), iters=iters,
                             sweep=sweep)
