"""Pallas block-attention kernel with softmax stats — the per-round
compute of ring attention (kernels/ring_attention.py) and the per-chunk
compute of the chunked-bias flash path (kernels/flash_attention.py).

The ring schedule needs UNNORMALIZED per-block results (m, l, o) so
rounds can merge online; the in-tree flash kernel only returns the
normalized output, which is why ring previously fell back to dense jnp
einsums (VERDICT r1 weak #7). This kernel streams k/v sub-blocks through
VMEM with an online-softmax accumulator — the s = q k^T f32 score matrix
never materializes in HBM — and carries an analytic custom VJP (einsum
recompute from the saved stats, the same fwd-kernel + analytic-VJP
pattern as kernels/rms_norm.py), so ring attention stays reverse-
differentiable through lax.scan.

Layout: q [B, Sq, H, D], k/v [B, Sk, H, D] -> m, l [B, H, Sq] f32 and
o [B, Sq, H, D] f32 (unnormalized); `mask` is an optional [Sq, Sk] bool.
`bias` is an optional ADDITIVE [B, H, Sq, Sk] f32 operand (the chunked
slice of an attention bias — alibi, relative-position, padding): entries
<= _NEG/2 are treated as masked (their p is zeroed exactly, so a fully
masked row yields l=0, o=0 like the boolean mask path). bias is
differentiable — the VJP returns ds for it.
Fully-masked rows yield (m=-1e30, l=0, o=0), which the ring merge treats
as an empty contribution.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["block_attention_stats", "supported"]

_NEG = -1e30
# tests flip this to exercise the Pallas path through the interpreter on
# CPU; production dispatch requires a real TPU (interpret mode is orders
# of magnitude slower than the jnp fallback)
_FORCE_PALLAS = False


def _block_size(s: int, which: str = "q") -> int:
    """Largest dividing block <= 512, overridable by an autotune-cache
    winner for this sequence-length class (kernels/autotune.py)."""
    from . import autotune
    hit = autotune.lookup(autotune.cache_key("block_attn", S=s))
    if hit:
        b = hit[0] if which == "q" else hit[-1]
        if s % b == 0:
            return b
    for b in (512, 256, 128):
        if s % b == 0:
            return b
    raise AssertionError(f"supported() admitted unaligned size {s}")


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def supported(q_shape, k_shape) -> bool:
    B, Sq, H, D = q_shape
    Sk = k_shape[1]
    return (Sq % 128 == 0 and Sk % 128 == 0 and D % 64 == 0
            and q_shape[2] == k_shape[2])


def _pallas_fwd(q, k, v, mask, scale, bias=None, interpret=None):
    """q [N, Sq, D]; k/v [N, Sk, D]; mask [Sq, Sk] bool or None;
    bias [N, Sq, Sk] f32 or None, with N = B*H folded into the grid's
    leading parallel dim."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, Sq, D = q.shape
    Sk = k.shape[1]
    bq = _block_size(Sq, "q")   # exact divisors — no dropped tail blocks
    bk = _block_size(Sk, "k")
    grid = (N, Sq // bq, Sk // bk)
    use_mask = mask is not None
    if not use_mask:
        mask = jnp.ones((bq, bk), jnp.bool_)
    use_bias = bias is not None
    if not use_bias:
        bias = jnp.zeros((1, bq, bk), jnp.float32)

    def kern(q_ref, k_ref, v_ref, mask_ref, bias_ref, m_out, l_out, o_out,
             m_s, l_s, o_s):
        j = pl.program_id(2)
        nk = pl.num_programs(2)

        @pl.when(j == 0)
        def _init():
            m_s[...] = jnp.full_like(m_s[...], _NEG)
            l_s[...] = jnp.zeros_like(l_s[...])
            o_s[...] = jnp.zeros_like(o_s[...])

        qb = q_ref[0].astype(jnp.float32)          # [bq, D]
        kb = k_ref[0].astype(jnp.float32)          # [bk, D]
        vb = v_ref[0].astype(jnp.float32)
        mb = mask_ref[...]
        s = (qb @ kb.T) * scale
        if use_bias:
            s = s + bias_ref[0]
            # bias-masked entries (<= _NEG/2) count as invalid
            mb = mb & (bias_ref[0] > 0.5 * _NEG)
        s = jnp.where(mb, s, _NEG)

        m_prev = m_s[...]                          # [bq, 1]
        bm = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, bm)
        # explicit zeroing: fully-masked rows must contribute l=0, o=0
        # (exp(-1e30 - (-1e30)) would otherwise be 1)
        p = jnp.where(mb, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_s[...] = l_s[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        o_s[...] = o_s[...] * alpha + p @ vb
        m_s[...] = m_new

        @pl.when(j == nk - 1)
        def _emit():
            m_out[0] = m_s[...]
            l_out[0] = l_s[...]
            o_out[0] = o_s[...]

    if interpret is None:
        interpret = not _on_tpu()
    # jax >= 0.7 renamed TPUCompilerParams -> CompilerParams
    _CP = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    params = _CP(dimension_semantics=("parallel", "parallel", "arbitrary"))
    mask_spec = (pl.BlockSpec((bq, bk), lambda n, i, j: (i, j)) if use_mask
                 else pl.BlockSpec((bq, bk), lambda n, i, j: (0, 0)))
    bias_spec = (pl.BlockSpec((1, bq, bk), lambda n, i, j: (n, i, j))
                 if use_bias
                 else pl.BlockSpec((1, bq, bk), lambda n, i, j: (0, 0, 0)))
    m, l, o = pl.pallas_call(
        kern, grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda n, i, j: (n, i, 0)),
            pl.BlockSpec((1, bk, D), lambda n, i, j: (n, j, 0)),
            pl.BlockSpec((1, bk, D), lambda n, i, j: (n, j, 0)),
            mask_spec,
            bias_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, bq, 1), lambda n, i, j: (n, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda n, i, j: (n, i, 0)),
            pl.BlockSpec((1, bq, D), lambda n, i, j: (n, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, Sq, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, Sq, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, Sq, D), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bq, 1), jnp.float32),
                        pltpu.VMEM((bq, 1), jnp.float32),
                        pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=None if interpret else params,
        interpret=interpret,
    )(q, k, v, mask, bias)
    return m[..., 0], l[..., 0], o


def _apply_bias_mask(s, mask, bias):
    """Shared score assembly: additive bias, then boolean/threshold mask.
    Returns (s, valid) with valid broadcast to s's shape."""
    valid = jnp.ones(s.shape, bool) if mask is None else \
        jnp.broadcast_to(mask[None, None], s.shape)
    if bias is not None:
        s = s + bias
        valid = valid & (bias > 0.5 * _NEG)
    return jnp.where(valid, s, _NEG), valid


def _dense_stats(q, k, v, mask, scale, bias=None):
    """jnp reference path: same contract, used for unaligned shapes."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    s, valid = _apply_bias_mask(s, mask, bias)
    m = jnp.max(s, axis=-1)
    p = jnp.where(valid, jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return m, l, o


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 6))
def block_attention_stats(q, k, v, mask, scale, bias=None, use_pallas=None):
    """(m [B,H,Sq], l [B,H,Sq], o [B,Sq,H,D] f32, unnormalized) for one
    ring round / bias chunk. Differentiable in q/k/v/bias; mask is
    non-differentiable. use_pallas: None = auto (real TPU + aligned),
    True/False forces the route (the chunked-bias caller decides once
    per call site so cross-platform lowering tests can pin it)."""
    return _stats_fwd_impl(q, k, v, mask, scale, bias, use_pallas)


def _stats_fwd_impl(q, k, v, mask, scale, bias=None, use_pallas=None):
    B, Sq, H, D = q.shape
    explicit = use_pallas is True
    if use_pallas is None:
        use_pallas = supported(q.shape, k.shape) and (_on_tpu()
                                                      or _FORCE_PALLAS)
    if use_pallas and supported(q.shape, k.shape):
        # an EXPLICIT True (lowering tests / the TPU bias route) compiles
        # the real Mosaic kernel even when tracing off-chip; the
        # _FORCE_PALLAS auto route keeps the interpreter for CPU CI
        interpret = None if not explicit else False
        fold = lambda x: jnp.swapaxes(x, 1, 2).reshape(
            B * H, x.shape[1], D)
        bias_f = None
        if bias is not None:
            bias_f = jnp.broadcast_to(
                bias.astype(jnp.float32),
                (B, H, Sq, k.shape[1])).reshape(B * H, Sq, k.shape[1])
        m, l, o = _pallas_fwd(fold(q), fold(k), fold(v), mask, scale,
                              bias_f, interpret=interpret)
        m = m.reshape(B, H, Sq)
        l = l.reshape(B, H, Sq)
        o = jnp.swapaxes(o.reshape(B, H, Sq, D), 1, 2)
        return m, l, o
    return _dense_stats(q, k, v, mask, scale, bias)


def _stats_fwd(q, k, v, mask, scale, bias, use_pallas):
    out = _stats_fwd_impl(q, k, v, mask, scale, bias, use_pallas)
    m = out[0]
    return out, (q, k, v, mask, bias, m)


def _stats_bwd(scale, use_pallas, res, cts):
    """Analytic VJP with m treated as stop-gradient (the merged, final
    attention output is invariant to the stabilizer):
      dp[q,k] = do[q]·v[k] + dl[q];  ds = p * dp
      dq = ds k * scale; dk = ds^T q * scale; dv = p^T do; dbias = ds.
    p is recomputed from the saved m — one [Sq, Sk] block per ring round
    / bias chunk, never the full sequence."""
    q, k, v, mask, bias, m = res
    ct_m, ct_l, ct_o = cts
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
    s, valid = _apply_bias_mask(s, mask, bias)
    p = jnp.where(valid, jnp.exp(s - m[..., None]), 0.0)
    do = ct_o.astype(jnp.float32)                       # [B,Sq,H,D]
    dp = jnp.einsum("bqhd,bkhd->bhqk", do, vf) + ct_l[..., None]
    ds = p * dp
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, kf) * scale
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf) * scale
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, do)
    dbias = None
    if bias is not None:
        # reduce ds over the broadcast dims of the given bias shape
        dbias = ds
        for ax in range(4):
            if bias.shape[ax] == 1 and ds.shape[ax] != 1:
                dbias = dbias.sum(axis=ax, keepdims=True)
        dbias = dbias.astype(bias.dtype)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            None, dbias)


block_attention_stats.defvjp(_stats_fwd, _stats_bwd)
