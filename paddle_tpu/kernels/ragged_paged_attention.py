"""Ragged paged attention — mixed prefill-chunk + decode rows in ONE
kernel invocation over the paged KV pool (ref: "Ragged Paged Attention",
arxiv 2604.15464 — the TPU-native kernel behind chunked-prefill
continuous batching; the reference's serving analog is
block_multihead_attention's mixed-phase decode driven by
analysis_predictor Run).

Contract: queries arrive PACKED — `q [total_q_tokens, nh, d]` holds every
sequence's rows back to back; per-sequence row metadata
`(q_start, q_len, kv_len)` (i32[num_seqs]) says which rows belong to
sequence s (rows q_start[s] .. q_start[s]+q_len[s]) and how many KV
tokens the sequence holds AFTER this step's keys were scattered into the
pool. A decode row is simply q_len == 1; a prefill chunk is q_len > 1;
an idle slot is q_len == 0. Row t of sequence s sits at absolute
position kv_len[s] - q_len[s] + (t - q_start[s]) and attends causally to
KV positions <= its own, gathered through the per-sequence block table
`page_table` (i32[num_seqs, pages_per_seq]) into the shared
`[kvh, n_pages, page, d]` page pool (page 0 is the engine's scratch
page; unused table entries are 0).

Two routes, same contract (the block_attention.py discipline):
  * a Pallas kernel — per-sequence q blocks stream KV one PAGE at a time
    through VMEM with the online-softmax accumulator idiom from
    block_attention.py; the per-sequence page gather rides the
    PrefetchScalarGridSpec index map (the ragged-index idiom of the
    in-tree paged_attention kernel), so the kernel never materializes a
    dense per-sequence cache;
  * an exact jnp fallback (CPU / unaligned shapes).
Tests flip `_FORCE_PALLAS` to drive the Pallas path through the
interpreter on CPU; production dispatch requires a real TPU.
Block sizes come from kernels/autotune.py (key "ragged_paged_attn").
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

__all__ = ["ragged_paged_attention", "supported"]

_NEG = -1e30
# tests flip this to exercise the Pallas path through the interpreter on
# CPU (interpret mode is orders of magnitude slower than the fallback)
_FORCE_PALLAS = False


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def supported(q_shape, pages_shape) -> bool:
    """q: [T, nh, d]; pages: [kvh, n_pages, page, d] — Mosaic-alignment
    gate for the compiled route (the fallback handles everything)."""
    T, nh, d = q_shape
    kvh, _, page, d2 = pages_shape
    return (d == d2 and d % 64 == 0 and page % 8 == 0 and nh % kvh == 0)


def _block_q(total_q: int) -> int:
    """q-block rows per grid step: autotune winner for this packed-size
    class when recorded (kernels/autotune.py), else the largest
    power-of-two block <= min(total_q rounded up, 128). Any value works —
    q is padded up to a block multiple — so the sweep is free to explore."""
    from . import autotune
    hit = autotune.lookup(autotune.cache_key("ragged_paged_attn",
                                             T=_size_class(total_q)))
    if hit:
        b = int(hit[0] if isinstance(hit, (list, tuple)) else hit)
        if b > 0 and (b & (b - 1)) == 0:
            return b
    return min(128, _size_class(total_q))


def _size_class(total_q: int) -> int:
    """Quantize the packed row count to a power of two so one autotune
    sweep covers one (kernel, size-class, device) point."""
    c = 8
    while c < total_q:
        c *= 2
    return c


def _row_ids(T, q_start, q_len):
    """Packed-row bookkeeping shared by both routes: for each row t,
    (sequence id, local index within the sequence, membership bool)."""
    t = jnp.arange(T)
    member = ((t[:, None] >= q_start[None, :])
              & (t[:, None] < (q_start + q_len)[None, :]))
    sid = jnp.argmax(member, axis=1).astype(jnp.int32)
    valid = jnp.any(member, axis=1)
    local = t - q_start[sid]
    return sid, local, valid


def ragged_paged_attention(q, k_pages, v_pages, q_start, q_len, kv_len,
                           page_table, scale=None, use_pallas=None,
                           block_q=None):
    """Packed ragged causal attention over the paged KV pool.

    q: [T, nh, d] packed rows; k/v_pages: [kvh, n_pages, page, d];
    q_start/q_len/kv_len: i32[num_seqs]; page_table:
    i32[num_seqs, pages_per_seq]. Returns [T, nh, d] in q.dtype (f32
    math); rows belonging to no sequence come back zero.
    use_pallas: None = auto (real TPU + aligned, or _FORCE_PALLAS via
    the interpreter), True/False forces the route; block_q overrides the
    autotuned q-block (the sweep's candidate lever)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if use_pallas is None:
        use_pallas = (supported(q.shape, k_pages.shape)
                      and (_on_tpu() or _FORCE_PALLAS))
    elif use_pallas and not supported(q.shape, k_pages.shape):
        # an EXPLICIT True must not silently time/run the fallback — a
        # sweep would record noise winners and callers would believe
        # they exercised the compiled route
        raise ValueError(
            f"ragged_paged_attention: use_pallas=True but shapes are not "
            f"Mosaic-aligned (q {q.shape}, pages {k_pages.shape}: need "
            f"d % 64 == 0, page % 8 == 0, nh % kvh == 0)")
    if use_pallas:
        return _pallas_path(q, k_pages, v_pages, q_start, q_len, kv_len,
                            page_table, scale,
                            interpret=not _on_tpu(), block_q=block_q)
    return _dense_fallback(q, k_pages, v_pages, q_start, q_len, kv_len,
                           page_table, scale)


def _dense_fallback(q, k_pages, v_pages, q_start, q_len, kv_len,
                    page_table, scale):
    """Exact jnp reference: gather each row's sequence KV dense, one
    causal softmax per row. Memory is O(T * pages_per_seq * page).

    Float-op ORDER deliberately mirrors paged_attention._dense_fallback
    (q scaled in input dtype, -inf masking, jax.nn.softmax before the
    value contraction): a decode row here is bitwise-identical to the
    single-token decode kernel's fallback, so the chunked engine's
    greedy argmax cannot flip against the bucketed one at bf16
    near-ties."""
    T, nh, d = q.shape
    kvh, _, page, _ = k_pages.shape
    B, ppmax = page_table.shape
    S = ppmax * page
    sid, local, valid_row = _row_ids(T, q_start, q_len)
    pos = kv_len[sid] - q_len[sid] + local               # abs position
    q = q * scale                                        # pre-scale, q dtype

    def gather(pages):                                   # -> [B, S, kvh, d]
        x = pages[:, page_table]          # [kvh, B, ppmax, page, d]
        x = jnp.moveaxis(x, 0, 3)         # [B, ppmax, page, kvh, d]
        return x.reshape(B, S, kvh, d)

    k = gather(k_pages)[sid]                             # [T, S, kvh, d]
    v = gather(v_pages)[sid]
    rep = nh // kvh
    if rep > 1:
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("thd,tshd->ths", q.astype(jnp.float32),
                   k.astype(jnp.float32))
    kv_pos = jnp.arange(S)
    mask = ((kv_pos[None, :] <= pos[:, None])
            & (kv_pos[None, :] < kv_len[sid][:, None])
            & valid_row[:, None])
    s = jnp.where(mask[:, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("ths,tshd->thd", p, v.astype(jnp.float32))
    # fully-masked rows (padding / idle slots) softmax to nan: drop them
    o = jnp.where(valid_row[:, None, None], o, 0.0)
    return o.astype(q.dtype)


def _pallas_path(q, k_pages, v_pages, q_start, q_len, kv_len, page_table,
                 scale, interpret, block_q=None):
    """Repack rows per sequence (padded to a q block), run the kernel on
    grid (seq, head, q_block, kv_page), unpack back to packed rows. The
    repack/unpack gathers fuse into the surrounding jit."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, nh, d = q.shape
    kvh, n_pages, page, _ = k_pages.shape
    B, ppmax = page_table.shape
    rep = nh // kvh
    bq = int(block_q) if block_q else _block_q(T)
    q_pad = -(-T // bq) * bq

    # per-sequence padded repack: row i of sequence s = packed row
    # q_start[s] + min(i, q_len[s]-1) (clamped duplicates are masked off
    # inside the kernel by the row < q_len predicate)
    i = jnp.arange(q_pad)
    safe = jnp.maximum(q_len, 1)
    rows = q_start[:, None] + jnp.minimum(i[None, :], safe[:, None] - 1)
    rows = jnp.clip(rows, 0, T - 1)
    qp = jnp.moveaxis(q[rows], 2, 1)                 # [B, nh, q_pad, d]

    grid = (B, nh, q_pad // bq, ppmax)

    def kern(ql_ref, kl_ref, pt_ref, q_ref, k_ref, v_ref, o_ref,
             m_s, l_s, acc):
        s = pl.program_id(0)
        qi = pl.program_id(2)
        j = pl.program_id(3)
        nk = pl.num_programs(3)

        @pl.when(j == 0)
        def _init():
            m_s[...] = jnp.full_like(m_s[...], _NEG)
            l_s[...] = jnp.zeros_like(l_s[...])
            acc[...] = jnp.zeros_like(acc[...])

        qln = ql_ref[s]
        kln = kl_ref[s]
        qb = q_ref[0, 0].astype(jnp.float32)         # [bq, d]
        kb = k_ref[0, 0].astype(jnp.float32)         # [page, d]
        vb = v_ref[0, 0].astype(jnp.float32)
        row = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
        pos = kln - qln + row                        # abs position [bq, 1]
        col = j * page + jax.lax.broadcasted_iota(jnp.int32, (bq, page), 1)
        valid = (row < qln) & (col <= pos) & (col < kln)
        sc = jnp.dot(qb, kb.T,
                     preferred_element_type=jnp.float32) * scale
        sc = jnp.where(valid, sc, _NEG)
        m_prev = m_s[...]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=1, keepdims=True))
        # explicit zeroing: fully-masked rows must contribute l=0, o=0
        p = jnp.where(valid, jnp.exp(sc - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_s[...] = l_s[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc[...] = acc[...] * alpha + jnp.dot(
            p, vb, preferred_element_type=jnp.float32)
        m_s[...] = m_new

        @pl.when(j == nk - 1)
        def _emit():
            l = l_s[...]
            o_ref[0, 0] = jnp.where(
                l > 0.0, acc[...] / jnp.where(l > 0.0, l, 1.0), 0.0)

    # the per-sequence page gather rides the index map: kv grid step j
    # fetches pool page page_table[s, j] (0 = the engine's scratch page
    # for table slots past the sequence's pages — masked off above)
    q_spec = pl.BlockSpec((1, 1, bq, d),
                          lambda s, h, qi, j, ql, kl, pt: (s, h, qi, 0))
    kv_spec = pl.BlockSpec(
        (1, 1, page, d),
        lambda s, h, qi, j, ql, kl, pt: (h // rep, pt[s, j], 0, 0))
    out_spec = pl.BlockSpec((1, 1, bq, d),
                            lambda s, h, qi, j, ql, kl, pt: (s, h, qi, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=out_spec,
        scratch_shapes=[pltpu.VMEM((bq, 1), jnp.float32),
                        pltpu.VMEM((bq, 1), jnp.float32),
                        pltpu.VMEM((bq, d), jnp.float32)],
    )
    # jax >= 0.7 renamed TPUCompilerParams -> CompilerParams
    _CP = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    params = _CP(dimension_semantics=("parallel", "parallel", "parallel",
                                      "arbitrary"))
    out = pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, nh, q_pad, d), jnp.float32),
        compiler_params=None if interpret else params,
        interpret=interpret,
    )(q_len.astype(jnp.int32), kv_len.astype(jnp.int32),
      page_table.astype(jnp.int32), qp, k_pages, v_pages)

    # unpack [B, nh, q_pad, d] -> packed [T, nh, d]
    sid, local, valid_row = _row_ids(T, q_start, q_len)
    local = jnp.clip(local, 0, q_pad - 1)
    o = jnp.moveaxis(out, 1, 2)[sid, local]          # [T, nh, d]
    o = jnp.where(valid_row[:, None, None], o, 0.0)
    return o.astype(q.dtype)


def sweep_block_sizes(q_shape, pages_shape, ppmax=8, iters=8, sweep=None):
    """Register/refresh the q-block winner for one packed-size class with
    kernels/autotune.py (PADDLE_AUTOTUNE=1 or sweep=True; cached winners
    are consulted by _block_q unconditionally)."""
    from . import autotune
    T, nh, d = q_shape
    kvh, n_pages, page, _ = pages_shape
    key = autotune.cache_key("ragged_paged_attn", T=_size_class(T))

    def make_fn(bq):
        if bq > _size_class(T):
            return None
        rng = jax.random.PRNGKey(0)
        q = jax.random.normal(rng, q_shape, jnp.float32)
        kp = jax.random.normal(rng, pages_shape, jnp.float32)
        vp = jax.random.normal(rng, pages_shape, jnp.float32)
        B = max(1, T // 4)
        q_len = jnp.full((B,), T // B, jnp.int32)
        q_start = jnp.arange(B, dtype=jnp.int32) * (T // B)
        kv_len = q_len + page
        pt = jnp.tile(jnp.arange(1, ppmax + 1, dtype=jnp.int32) % n_pages,
                      (B, 1))

        def run():
            def body(c, _):
                o = ragged_paged_attention(q + c, kp, vp, q_start, q_len,
                                           kv_len, pt, use_pallas=True,
                                           block_q=bq)
                return c + 0 * o[0, 0, 0], None
            return jax.jit(lambda: jax.lax.scan(
                body, jnp.float32(0), None, length=iters))()

        return run

    return autotune.autotune(key, [8, 16, 32, 64, 128], make_fn,
                             default=_block_q(T), iters=iters, sweep=sweep)
