"""Rotary position embedding (ref: phi fused_rope kernel,
python/paddle/incubate/nn/functional/fused_rotary_position_embedding.py).

Pure-jnp rotate-half formulation — XLA fuses the mul/adds into surrounding
matmuls, so a bespoke Pallas kernel buys nothing here (measured pattern on
TPU); cos/sin caches are precomputed once per (seq, dim).
"""
from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=32)
def _cos_sin_cache(seq_len: int, dim: int, base: float, dtype_str: str):
    # host-side numpy so cached values are concrete constants — caching
    # device arrays here would leak tracers when called under jit/remat
    inv_freq = 1.0 / (base ** (np.arange(0, dim, 2, dtype=np.float32) / dim))
    t = np.arange(seq_len, dtype=np.float32)
    freqs = np.outer(t, inv_freq)                  # [S, dim/2]
    emb = np.concatenate([freqs, freqs], axis=-1)  # [S, dim]
    return np.cos(emb), np.sin(emb)


def _rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def fused_qkv_rope(a, w_qkv, num_heads, num_kv_heads, head_dim,
                   position_ids=None, base=10000.0, seq_len=None):
    """Fused QKV+RoPE prologue: one wide projection, then rope applied
    to the q/k slices in-register via the cos/sin cache — no separate
    narrow matmuls, no standalone elementwise pass over q and k.

    a: [B, S, H] (or [S, H] packed rows); w_qkv:
    [H, (num_heads + 2*num_kv_heads) * head_dim] with q|k|v column
    layout (the fuse_attention_qkv checkpoint layout). Returns
    (q, k, v) shaped [..., heads, head_dim] with rope already applied
    to q and k. position_ids/seq_len follow apply_rope (packed [S]
    rows get a broadcast batch dim internally)."""
    from jax.ad_checkpoint import checkpoint_name
    nh, kvh, d = num_heads, num_kv_heads, head_dim
    qkv = checkpoint_name(a @ w_qkv, "llama_qkv")
    lead = qkv.shape[:-1]
    q = qkv[..., :nh * d].reshape(*lead, nh, d)
    k = qkv[..., nh * d:(nh + kvh) * d].reshape(*lead, kvh, d)
    v = qkv[..., (nh + kvh) * d:].reshape(*lead, kvh, d)
    if a.ndim == 2:                      # packed rows: [S, H]
        pids = None if position_ids is None else position_ids[None]
        q4, k4 = apply_rope(q[None], k[None], position_ids=pids,
                            base=base, seq_len=seq_len)
        return q4[0], k4[0], v
    q, k = apply_rope(q, k, position_ids=position_ids, base=base,
                      seq_len=seq_len)
    return q, k, v


def apply_rope(q, k, position_ids=None, base=10000.0, seq_len=None):
    """q, k: [B, S, H, D] -> rotated (same shapes), f32 math, input dtype out.

    seq_len: table length when position_ids may exceed q's length (KV-cache
    decode, where q holds 1 token at an arbitrary absolute position)."""
    S, D = q.shape[1], q.shape[-1]
    if position_ids is not None and seq_len is not None:
        S = int(seq_len)
    cos, sin = _cos_sin_cache(S, D, base, "f32")
    if position_ids is not None:
        cos = jnp.take(cos, position_ids, axis=0)  # [B, S, D]
        sin = jnp.take(sin, position_ids, axis=0)
        cos = cos[:, :, None, :]
        sin = sin[:, :, None, :]
    else:
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    q_out = qf * cos + _rotate_half(qf) * sin
    k_out = kf * cos + _rotate_half(kf) * sin
    return q_out.astype(q.dtype), k_out.astype(k.dtype)
