"""Kernel block-size autotuning with a persisted cache
(ref: paddle/phi/kernels/autotune/cache.cc + auto_tune_base.h — the
reference keys tuned kernel configs by shape signature and caches them
process-wide; here the cache also persists across processes as JSON so
one sweep serves every later run on the same device kind).

Design for the TPU tunnel: a single kernel launch costs ~4 ms of relay
latency, so candidates are timed by running the op inside one jitted
`lax.scan` loop (amortizes launch overhead) and synchronized with a
host transfer (`float(x)`), which is the only reliable barrier over the
tunnel. Sweeps run only when explicitly enabled (PADDLE_AUTOTUNE=1) or
when `sweep=True` is passed — never silently during training; cached
winners are consulted unconditionally.

Layered lookup:
  1. in-process memo
  2. user cache file (PADDLE_AUTOTUNE_CACHE, default
     ~/.paddle_tpu_autotune.json) — written by sweeps
  3. shipped defaults (kernels/autotune_defaults.json) — curated
     winners measured on real hardware, committed to the repo
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
from typing import Any, Callable, Dict, Optional, Sequence

__all__ = ["lookup", "record", "autotune", "cache_key", "device_kind"]

_lock = threading.Lock()
_memo: Dict[str, Any] = {}
_user_cache: Optional[Dict[str, Any]] = None
_defaults: Optional[Dict[str, Any]] = None

_DEFAULTS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "autotune_defaults.json")


def _user_cache_path() -> str:
    return os.environ.get(
        "PADDLE_AUTOTUNE_CACHE",
        os.path.join(os.path.expanduser("~"), ".paddle_tpu_autotune.json"))


def device_kind() -> str:
    """Normalized device tag the cache is keyed under ('cpu' off-TPU)."""
    try:
        import jax
        d = jax.devices()[0]
        if d.platform != "tpu":
            return d.platform
        return getattr(d, "device_kind", "tpu").lower().replace(" ", "")
    except Exception:
        return "cpu"


def cache_key(kernel: str, **shape_attrs) -> str:
    """Stable key: kernel name + sorted shape/config attrs + device kind.
    Keep attrs coarse (powers of two already quantize naturally) so one
    sweep covers one (kernel, shape-class, device) point."""
    parts = [kernel, device_kind()]
    parts += [f"{k}={shape_attrs[k]}" for k in sorted(shape_attrs)]
    return ":".join(parts)


def _load(path: str) -> Dict[str, Any]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def lookup(key: str):
    """Best-known config for `key`, or None. Never sweeps.
    FLAGS_use_autotune=False disables tuned configs entirely (heuristic
    defaults only — the reference's global autotune kill switch)."""
    try:
        from ..framework import core
        if not core.get_bool_flag("FLAGS_use_autotune", True):
            return None
    except Exception:
        pass
    global _user_cache, _defaults
    with _lock:
        if key in _memo:
            return _memo[key]
        if _user_cache is None:
            _user_cache = _load(_user_cache_path())
        if _defaults is None:
            _defaults = _load(_DEFAULTS_PATH)
        for store in (_user_cache, _defaults):
            if key in store:
                _memo[key] = store[key]["best"]
                return _memo[key]
    return None


def _update_file(path: str, mutate) -> Dict[str, Any]:
    """Cross-PROCESS-safe read-modify-write of the user cache (advisor
    r3: two parallel sweep processes sharing PADDLE_AUTOTUNE_CACHE must
    not drop each other's winners): an fcntl flock serializes
    reload -> mutate -> atomic replace; where flock is unavailable the
    reload-merge still shrinks the race to the write itself (instead of
    trusting a stale in-memory snapshot)."""
    lock_path = path + ".lock"
    lf = None
    try:
        lf = open(lock_path, "a+")
        import fcntl
        fcntl.flock(lf, fcntl.LOCK_EX)
    except (OSError, ImportError):
        pass
    try:
        disk = _load(path)
        out = mutate(disk)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(out, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            pass
        return out
    finally:
        if lf is not None:
            lf.close()       # releases the flock


def record(key: str, best, timings_ms: Optional[Dict[str, float]] = None):
    """Persist a sweep winner to the user cache (merge-on-write under an
    OS-level lock, atomic rename)."""
    global _user_cache
    path = _user_cache_path()
    entry: Dict[str, Any] = {"best": best}
    if timings_ms:
        entry["timings_ms"] = {k: round(v, 4)
                               for k, v in timings_ms.items()}
    with _lock:
        def mutate(disk):
            disk[key] = entry
            return disk

        _user_cache = _update_file(path, mutate)
        _memo[key] = best


def forget(key: str):
    """Drop a cache entry (memo + user file) — sweep repair path."""
    global _user_cache
    path = _user_cache_path()
    with _lock:
        _memo.pop(key, None)

        def mutate(disk):
            disk.pop(key, None)
            return disk

        _user_cache = _update_file(path, mutate)


class _CandidateTimeout(Exception):
    """A candidate blew its wall budget (lost tunnel compile, wedged
    executor) — skip it; never let one candidate stall the sweep."""


@contextlib.contextmanager
def _candidate_deadline():
    """SIGALRM-armed context for one candidate's compile+measure. A
    remote-compile request over the axon tunnel can be silently dropped
    (observed r4: the CE sweep's first candidate blocked 40+ min on a
    Python socket wait); a per-candidate wall budget turns that into a
    skipped candidate. Main-thread only — elsewhere it degrades to a
    no-op. Limitation: SIGALRM only interrupts Python-level waits; a
    block inside jaxlib's C++ client fires the handler only when the C
    call returns, so pair sweeps with a process-level watchdog (bench.py
    _arm_wall_watchdog) for full coverage."""
    import signal

    if not hasattr(signal, "SIGALRM"):
        yield  # no-op where SIGALRM doesn't exist (Windows)
        return
    try:
        budget = int(os.environ.get(
            "PADDLE_AUTOTUNE_CANDIDATE_TIMEOUT", "300"))
    except ValueError:
        import sys
        print("autotune: malformed PADDLE_AUTOTUNE_CANDIDATE_TIMEOUT "
              f"{os.environ['PADDLE_AUTOTUNE_CANDIDATE_TIMEOUT']!r}; "
              "using 300", file=sys.stderr)
        budget = 300
    if (budget <= 0 or threading.current_thread()
            is not threading.main_thread()):
        yield
        return

    def on_alarm(signum, frame):
        raise _CandidateTimeout()

    import time as _time
    old_handler = signal.signal(signal.SIGALRM, on_alarm)
    armed_at = _time.monotonic()
    prev_remaining = signal.alarm(0)
    if prev_remaining:
        # never postpone a sooner outer deadline (bench.py whole-run
        # watchdog): the candidate budget is capped by what's left of it
        budget = min(budget, prev_remaining)
    signal.alarm(budget)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old_handler)
        if prev_remaining:
            # an outer whole-run watchdog (bench.py) was armed: re-arm
            # what's left of its budget rather than silently disarming it
            elapsed = int(_time.monotonic() - armed_at)
            signal.alarm(max(prev_remaining - elapsed, 1))


def _time_candidate(fn: Callable[[], Any], iters: int) -> float:
    """Median-of-3 wall time (ms per iteration) of a jitted loop."""
    import time

    import jax
    fn()  # compile + warm
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = fn()
        # host transfer is the only reliable sync over the axon tunnel
        jax.tree_util.tree_map(
            lambda x: float(x.reshape(-1)[0]) if hasattr(x, "reshape") else x,
            out)
        times.append((time.perf_counter() - t0) / iters)
    times.sort()
    return times[1] * 1e3


def sweeps_enabled() -> bool:
    if os.environ.get("PADDLE_AUTOTUNE", "0") == "1":
        return True
    try:  # flag consumers (ref FLAGS_use_autotune / exhaustive search)
        from ..framework import core
        if not core.get_bool_flag("FLAGS_use_autotune", True):
            return False
        return core.get_bool_flag("FLAGS_cudnn_exhaustive_search")
    except Exception:
        return False


def autotune(key: str, candidates: Sequence[Any],
             make_fn: Callable[[Any], Optional[Callable[[], Any]]],
             default: Any, iters: int = 8, sweep: Optional[bool] = None):
    """Return the best config for `key`.

    make_fn(candidate) returns a zero-arg callable running the op with
    that config (typically a jitted lax.scan loop of `iters` steps), or
    None / raises to skip the candidate. Cached winners are returned
    without running anything UNLESS sweep=True is passed explicitly
    (tools re-tuning after a kernel change must be able to re-measure);
    sweep=None means "sweep only if PADDLE_AUTOTUNE=1 and nothing is
    cached". Sweeps run only on a real accelerator (interpret-mode
    timings are meaningless), and a sweep where every candidate failed
    records NOTHING — the default must not masquerade as a winner.
    """
    forced = sweep is True
    hit = lookup(key)
    if hit is not None and not forced:
        return hit
    if sweep is None:
        sweep = sweeps_enabled()
    if not sweep or device_kind() == "cpu":
        return hit if hit is not None else default
    timings: Dict[str, float] = {}
    best, best_t = default, float("inf")
    for cand in candidates:
        try:
            with _candidate_deadline():
                fn = make_fn(cand)
                if fn is None:
                    continue
                t = _time_candidate(fn, iters)
        except _CandidateTimeout:
            import sys
            print(f"autotune: candidate {cand} for {key} exceeded "
                  "PADDLE_AUTOTUNE_CANDIDATE_TIMEOUT — skipped",
                  file=sys.stderr)
            continue
        except Exception:
            continue  # candidate doesn't compile/fit — skip
        timings[str(cand)] = t
        if t < best_t:
            best, best_t = cand, t
    if timings:
        record(key, best, timings)
    return best
