"""Pallas TPU kernels (SURVEY §7.2): the fused ops XLA won't fuse well.

Replaces the reference's CUDA fusion zoo (phi/kernels/fusion/gpu/*,
fused_attention_op.cu, fused_rms_norm, cutlass attention) with TPU-native
Pallas kernels. Import is lazy/defensive: on CPU test meshes the jnp
fallbacks in nn.functional are used instead.
"""
from . import flash_attention  # noqa: F401
from . import fused_norm_residual  # noqa: F401
from . import rms_norm  # noqa: F401
from . import rope  # noqa: F401
from . import swiglu  # noqa: F401
