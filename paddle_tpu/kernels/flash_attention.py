"""Flash attention on TPU (ref: phi/kernels/gpu/flash_attn_kernel.cu +
third_party flashattn — re-designed for TPU, not ported).

Strategy: use the tuned in-tree Pallas TPU kernel
(jax.experimental.pallas.ops.tpu.flash_attention) when on TPU and shapes are
tile-aligned; it implements the same online-softmax blocked algorithm as
FlashAttention-2 with MXU-shaped (block_q x block_k) tiles and VMEM
double-buffering. Causal masking is handled natively by the kernel (blocks
above the diagonal are skipped, so causal is FASTER, not gated out), and
padding masks map onto the kernel's segment-id mechanism. A custom
ring-attention kernel for the `sep` axis lives in ring_attention.py
(reference has NO equivalent — SURVEY §5 long-context).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

# lane width is 128; the kernel pads smaller head dims, profitable down to 64
_MIN_HEAD_DIM = 64
_SEQ_ALIGN = 128


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def supported(q_shape, k_shape, causal_or_none: bool,
              has_padding_mask: bool = False) -> bool:
    """True when flash_attention_bshd will hit the Pallas kernel.

    `causal_or_none`: mask is either causal or absent (anything else —
    arbitrary additive masks — must go through `bias=`, which we route to
    the dense path). Padding masks are fine (segment ids).
    """
    del has_padding_mask  # handled via segment ids — no longer gated out
    if not _on_tpu():
        return False
    if not causal_or_none:
        return False
    B, Sq, H, D = q_shape
    Sk = k_shape[1]
    # kernel pads D <= 128 up to the lane width; above that it requires an
    # exact multiple of 128 (so 192/320 must take the dense fallback)
    d_ok = (D % 64 == 0) if D <= 128 else (D % 128 == 0)
    return (d_ok and Sq % _SEQ_ALIGN == 0
            and Sk % _SEQ_ALIGN == 0 and q_shape[2] == k_shape[2])


def _block_sizes(Sq, Sk):
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes
    bq = min(512, Sq)
    bk = min(512, Sk)
    return BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
        block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq,
    )


@functools.partial(jax.jit, static_argnames=("causal", "scale"))
def flash_attention_bshd(q, k, v, causal=False, scale=None, padding_mask=None):
    """[batch, seq, heads, dim] in/out (paddle flash_attn layout).

    padding_mask: optional [batch, kv_seq] bool/int array, True/1 = valid
    token. Lowered to the kernel's segment-id masking (pad tokens get a
    distinct segment so nothing attends to or from them).
    """
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        SegmentIds, flash_attention)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)  # BHSD
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    Sq, Sk = qt.shape[2], kt.shape[2]
    seg = None
    if padding_mask is not None:
        kv_seg = jnp.where(padding_mask.astype(bool), 1, 0).astype(jnp.int32)
        if Sq == Sk:
            q_seg = kv_seg
        else:
            q_seg = jnp.ones((q.shape[0], Sq), jnp.int32)
        seg = SegmentIds(q=q_seg, kv=kv_seg)
    out = flash_attention(qt, kt, vt, segment_ids=seg, causal=causal,
                          sm_scale=scale, block_sizes=_block_sizes(Sq, Sk))
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)
