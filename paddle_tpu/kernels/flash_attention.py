"""Flash attention on TPU (ref: phi/kernels/gpu/flash_attn_kernel.cu +
third_party flashattn — re-designed for TPU, not ported; the reference
kernel's MQA/GQA + bias support is matched here, flash_attn_kernel.cu
accepts num_heads_k != num_heads and an attn additive mask).

Three routes, all Pallas:
- MHA (q_heads == kv_heads): the tuned in-tree TPU flash kernel
  (jax.experimental.pallas.ops.tpu.flash_attention) — online-softmax
  MXU-shaped tiles, native causal block skipping, segment-id padding
  masks, and an additive-bias operand (`ab`) for arbitrary masks.
- GQA/MQA causal/full without bias: the splash kernel in MQA mode,
  vmapped over kv heads with q grouped [kv_heads, group, Sq, D] — no
  materialized kv repeat, and block-sparse causal skipping.
- GQA with bias: kv heads broadcast to q heads (autodiff sums the kv
  grads over the group), then the MHA route — still the flash kernel,
  never the O(S^2) dense fallback.

Block sizes come from the autotune cache (kernels/autotune.py) when a
sweep has recorded a winner for the shape class, else a 512 heuristic.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

# lane width is 128; the kernel pads smaller head dims, profitable down to 64
_MIN_HEAD_DIM = 64
_SEQ_ALIGN = 128


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def supported(q_shape, k_shape, causal_or_none: bool,
              has_padding_mask: bool = False,
              has_bias: bool = False) -> bool:
    """True when flash_attention_bshd will hit a Pallas kernel.

    `causal_or_none`: mask is either causal or absent. Arbitrary
    additive masks route through `bias=` (the kernel's ab operand), so
    pass has_bias=True for those instead of returning False. Padding
    masks map to segment ids. GQA/MQA (q_heads a multiple of kv_heads)
    is first-class.
    """
    del has_padding_mask  # handled via segment ids — never gated out
    try:
        from ..framework import core
        if not core.get_bool_flag("FLAGS_use_flash_attention", True):
            # per-route kill switch / ablation lever (ref: the
            # reference's flash enable toggles)
            return False
    except Exception:
        pass
    if not _on_tpu():
        return False
    if not causal_or_none and not has_bias:
        return False  # non-causal non-bias masks must come in as bias
    B, Sq, Hq, D = q_shape
    Hk = k_shape[2]
    Sk = k_shape[1]
    # kernel pads D <= 128 up to the lane width; above that it requires an
    # exact multiple of 128 (so 192/320 must take the dense fallback)
    d_ok = (D % 64 == 0) if D <= 128 else (D % 128 == 0)
    return (d_ok and Sq % _SEQ_ALIGN == 0 and Sk % _SEQ_ALIGN == 0
            and Hq % Hk == 0)


def _block_sizes(Sq, Sk, D, causal, blocks=None):
    """Flash BlockSizes: explicit override (sweeps), else the autotune
    cache winner for this shape class, else 512-square."""
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

    from . import autotune
    if blocks is None:
        default = (min(512, Sq), min(512, Sk))
        key = autotune.cache_key("flash", Sq=Sq, Sk=Sk, D=D,
                                 causal=int(causal))
        blocks = autotune.lookup(key) or default
    bq, bk = min(blocks[0], Sq), min(blocks[1], Sk)
    return BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
        block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq,
    )


def _splash_block_sizes(Sq, Sk, D, blocks=None):
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk)

    from . import autotune
    if blocks is None:
        default = (min(512, Sq), min(512, Sk))
        key = autotune.cache_key("splash", Sq=Sq, Sk=Sk, D=D)
        blocks = autotune.lookup(key) or default
    bq, bk = min(blocks[0], Sq), min(blocks[1], Sk)
    return sk.BlockSizes(block_q=bq, block_kv=bk, block_kv_compute=bk,
                         block_q_dkv=bq, block_kv_dkv=bk,
                         block_kv_dkv_compute=bk,
                         block_q_dq=bq, block_kv_dq=bk)


def _splash_gqa(qt, kt, vt, causal, scale, padding_mask, interpret=False,
                blocks=None, segments=None):
    """GQA via splash MQA mode: qt [B, Hq, Sq, D], kt/vt [B, Hk, Sk, D].
    No kv repeat materializes; the group dim rides the kernel's q-head
    axis (is_mqa=True shares one kv head across it). `segments` overrides
    the padding-mask-derived segment ids with explicit (q_seg [B, Sq],
    kv_seg [B, Sk]) int32 arrays — the packed-varlen route."""
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk)
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_mask as sm)

    B, Hq, Sq, D = qt.shape
    Hk, Sk = kt.shape[1], kt.shape[2]
    group = Hq // Hk
    mask_cls = sm.CausalMask((Sq, Sk)) if causal else sm.FullMask((Sq, Sk))
    mask = sm.MultiHeadMask([mask_cls] * group)
    kernel = sk.make_splash_mqa_single_device(
        mask, block_sizes=_splash_block_sizes(Sq, Sk, D, blocks),
        interpret=interpret)
    # splash takes pre-scaled q and no sm_scale argument
    qg = (qt * scale).reshape(B, Hk, group, Sq, D)
    seg = None
    if segments is not None:
        seg = sk.SegmentIds(q=segments[0].astype(jnp.int32),
                            kv=segments[1].astype(jnp.int32))
    elif padding_mask is not None:
        kv_seg = jnp.where(padding_mask.astype(bool), 1, 0).astype(jnp.int32)
        q_seg = kv_seg if Sq == Sk else jnp.ones((B, Sq), jnp.int32)
        seg = sk.SegmentIds(q=q_seg, kv=kv_seg)
    # vmap over batch, then kv heads (q grouped per kv head)
    run = jax.vmap(  # batch
        jax.vmap(kernel, in_axes=(0, 0, 0, None)),  # kv heads
        in_axes=(0, 0, 0, 0))
    out = run(qg, kt, vt, seg)  # [B, Hk, group, Sq, D]
    return out.reshape(B, Hq, Sq, D)


_NEG = -1e30


def _bias_chunk(kind, params, pos_q, pos_k, B, H, causal, padding_mask):
    """[B, H, len(pos_q), len(pos_k)] f32 bias chunk generated ON THE FLY
    (never the full [B, H, Sq, Sk]):

    - "alibi":    params = slopes [H]; bias = -slope * (i - j) on the
                  causal triangle (the standard ALiBi form), -slope*|i-j|
                  when not causal.
    - "rel_table": params = (table [H, 2R+1], R); bias = table[h,
                  clip(j - i, -R, R) + R] — T5-style learned relative
                  position bias, differentiable through the gather.
    - "dense":    params = array broadcastable to [B, H, Sq, Sk]; the
                  chunk is SLICED from it, so only narrow inputs (e.g.
                  [B, 1, 1, Sk]) stay narrow; a caller-materialized
                  [Sq, Sk] bias is already the caller's footprint.

    Causal and per-batch padding masks fold in as _NEG entries (the
    block-stats kernel zeroes them exactly)."""
    lq, lk = pos_q.shape[0], pos_k.shape[0]
    if kind == "alibi":
        slopes = params.astype(jnp.float32).reshape(-1)
        dist = (pos_q[:, None] - pos_k[None, :]).astype(jnp.float32)
        if not causal:
            dist = jnp.abs(dist)
        bias = -slopes[:, None, None] * dist                # [H, lq, lk]
        bias = jnp.broadcast_to(bias[None], (B, H, lq, lk))
    elif kind == "rel_table":
        table, R = params
        idx = jnp.clip(pos_k[None, :] - pos_q[:, None], -R, R) + R
        bias = jnp.take(table.astype(jnp.float32), idx,
                        axis=1)                             # [H, lq, lk]
        bias = jnp.broadcast_to(bias[None], (B, H, lq, lk))
    elif kind == "dense":
        arr = params.astype(jnp.float32)
        while arr.ndim < 4:
            arr = arr[None]
        sl_q = arr[:, :, pos_q] if arr.shape[2] != 1 else arr
        sl = sl_q[:, :, :, pos_k] if arr.shape[3] != 1 else sl_q
        bias = jnp.broadcast_to(sl, (B, H, lq if arr.shape[2] != 1 else 1,
                                     lk if arr.shape[3] != 1 else 1))
        bias = jnp.broadcast_to(bias, (B, H, lq, lk))
    else:
        raise ValueError(f"unknown bias kind {kind!r}")
    if causal:
        bias = jnp.where(pos_q[None, None, :, None]
                         >= pos_k[None, None, None, :], bias, _NEG)
    if padding_mask is not None:
        valid = padding_mask.astype(bool)[:, None, None, pos_k]
        bias = jnp.where(valid, bias, _NEG)
    return bias


def _merge_stats(m1, l1, o1, m2, l2, o2):
    """Online-softmax merge of two unnormalized partials (the ring merge):
    m/l [B, H, Sq]; o [B, Sq, H, D]."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    l = l1 * a1 + l2 * a2
    a1t = jnp.swapaxes(a1, 1, 2)[..., None]
    a2t = jnp.swapaxes(a2, 1, 2)[..., None]
    o = o1 * a1t + o2 * a2t
    return m, l, o


def flash_attention_biased(q, k, v, kind, params, causal=False, scale=None,
                           padding_mask=None, chunk=None, use_pallas=None):
    """Blockwise-bias flash attention, BSHD in/out (VERDICT r3 #3a/#3c;
    ref: flash_attn_kernel.cu streams the attn bias blockwise in-kernel).

    Scans KV in `chunk`-sized slices; each chunk's bias is GENERATED (or
    sliced) on the fly and fed to the block-stats kernel
    (kernels/block_attention.py — Pallas on TPU, jnp elsewhere), partials
    merged online. Peak bias footprint is O(B*H*Sq*chunk), never
    O(B*H*Sq*Sk); GQA repeats kv per-CHUNK only (chunk-bounded, exactly
    what a fused kernel's group-shared kv block read does). The scan body
    is rematerialized so chunk biases are not saved for backward.
    """
    from .block_attention import block_attention_stats
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    B, Sq, Hq, D = q.shape
    Sk, Hk = k.shape[1], k.shape[2]
    group = Hq // Hk
    if chunk is None:
        from . import autotune
        hit = autotune.lookup(autotune.cache_key("chunked_bias", Sk=Sk,
                                                 D=D))
        chunk = int(hit[0]) if hit else 512
    C = min(chunk, Sk)
    n_chunks = -(-Sk // C)
    pad = n_chunks * C - Sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        pm = (padding_mask.astype(bool) if padding_mask is not None
              else jnp.ones((B, Sk), bool))
        padding_mask = jnp.pad(pm, ((0, 0), (0, pad)))
    pos_q = jnp.arange(Sq)

    def body(carry, ci):
        m, l, o = carry
        start = ci * C
        kc = jax.lax.dynamic_slice_in_dim(k, start, C, axis=1)
        vc = jax.lax.dynamic_slice_in_dim(v, start, C, axis=1)
        if group > 1:
            kc = jnp.broadcast_to(
                kc[:, :, :, None], (B, C, Hk, group, D)).reshape(
                    B, C, Hq, D)
            vc = jnp.broadcast_to(
                vc[:, :, :, None], (B, C, Hk, group, D)).reshape(
                    B, C, Hq, D)
        pos_k = start + jnp.arange(C)
        bias_c = _bias_chunk(kind, params, pos_q, pos_k, B, Hq, causal,
                             padding_mask)
        mc, lc, oc = block_attention_stats(q, kc, vc, None, scale, bias_c,
                                           use_pallas)
        return _merge_stats(m, l, o, mc, lc, oc), None

    m0 = jnp.full((B, Hq, Sq), _NEG, jnp.float32)
    l0 = jnp.zeros((B, Hq, Sq), jnp.float32)
    o0 = jnp.zeros((B, Sq, Hq, D), jnp.float32)
    # dynamic-slice positions must be traced for a fori-style scan; remat
    # keeps chunk biases out of the residuals
    (m, l, o), _ = jax.lax.scan(
        jax.checkpoint(body), (m0, l0, o0), jnp.arange(n_chunks))
    lt = jnp.swapaxes(l, 1, 2)[..., None]
    out = o / jnp.maximum(lt, 1e-30)
    return out.astype(q.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "interpret", "blocks"))
def flash_attention_bshd(q, k, v, causal=False, scale=None,
                         padding_mask=None, bias=None, interpret=False,
                         blocks=None):
    """[batch, seq, heads, dim] in/out (paddle flash_attn layout).

    padding_mask: optional [batch, kv_seq] bool/int array, True/1 = valid
    token — lowered to segment-id masking. bias: optional additive mask
    broadcastable to [batch, heads, Sq, Sk] — streamed CHUNKWISE through
    the block-stats kernel (flash_attention_biased): the f32
    [B, H, Sq, Sk] score-shaped buffer the kernel ab operand would need
    is never materialized, and narrow biases (e.g. [B, 1, 1, Sk]) are
    sliced narrow per chunk. GQA/MQA (q heads a multiple of kv heads) is
    handled without materializing a kv repeat on either route (splash-MQA
    when bias is None; per-chunk broadcast otherwise).
    """
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        SegmentIds, flash_attention)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])

    if bias is not None:
        # chunked-bias route — BSHD end to end, no transposes needed
        B, Sq, Hq, D = q.shape
        Sk = k.shape[1]
        use_pallas = None
        if _on_tpu() and not (Sq % 128 == 0 and Sk % 128 == 0
                              and D % 64 == 0):
            use_pallas = False
        elif _on_tpu():
            use_pallas = True
        return flash_attention_biased(
            q, k, v, "dense", bias, causal=causal, scale=scale,
            padding_mask=padding_mask, use_pallas=use_pallas)

    qt = jnp.swapaxes(q, 1, 2)  # BHSD
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    B, Hq, Sq, D = qt.shape
    Hk, Sk = kt.shape[1], kt.shape[2]

    if Hq != Hk:
        out = _splash_gqa(qt, kt, vt, causal, scale, padding_mask,
                          interpret=interpret, blocks=blocks)
        return jnp.swapaxes(out, 1, 2).astype(q.dtype)

    seg = None
    if padding_mask is not None:
        kv_seg = jnp.where(padding_mask.astype(bool), 1, 0).astype(jnp.int32)
        q_seg = kv_seg if Sq == Sk else jnp.ones((B, Sq), jnp.int32)
        seg = SegmentIds(q=q_seg, kv=kv_seg)
    out = flash_attention(qt, kt, vt, segment_ids=seg, causal=causal,
                          sm_scale=scale,
                          block_sizes=_block_sizes(Sq, Sk, D, causal,
                                                   blocks))
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def sweep_block_sizes(Sq=2048, Sk=2048, D=128, H=16, B=4, causal=True,
                      kv_heads=None, dtype=jnp.bfloat16, candidates=None,
                      iters=8, resweep=False):
    """On-chip block-size sweep; winners persist in the autotune cache
    (ref: phi/kernels/autotune/cache.cc). Run from bench tooling with
    PADDLE_AUTOTUNE=1, never during training. kv_heads != H tunes the
    splash GQA route (its own cache key) — the route a GQA model will
    actually take. resweep=True re-measures over a cached winner."""
    from . import autotune

    if candidates is None:
        candidates = [(bq, bk)
                      for bq in (256, 512, 1024) if bq <= Sq
                      for bk in (256, 512, 1024) if bk <= Sk]
    Hk = kv_heads or H
    if Hk != H:
        key = autotune.cache_key("splash", Sq=Sq, Sk=Sk, D=D)
    else:
        key = autotune.cache_key("flash", Sq=Sq, Sk=Sk, D=D,
                                 causal=int(causal))
    kq = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq[0], (B, Sq, H, D), dtype)
    k = jax.random.normal(kq[1], (B, Sk, Hk, D), dtype)
    v = jax.random.normal(kq[2], (B, Sk, Hk, D), dtype)

    def make_fn(cand):
        bq, bk = cand
        if Sq % bq or Sk % bk:
            return None

        def body(c, _):
            # grad-through to tune fwd+bwd together (training shape);
            # blocks as a static arg forces a fresh trace per candidate
            f = lambda q_: flash_attention_bshd(
                q_, k, v, causal=causal,
                blocks=(bq, bk)).astype(jnp.float32).sum()
            return c + jax.grad(f)(q).astype(jnp.float32).sum(), None

        loop = jax.jit(lambda: jax.lax.scan(
            body, jnp.float32(0), None, length=iters)[0])
        return loop

    return autotune.autotune(
        key, candidates, make_fn,
        default=[min(512, Sq), min(512, Sk)], iters=iters,
        sweep=True if (resweep or autotune.lookup(key) is None) else None)


def packed_supported(total_q, total_k, n_heads_q, n_heads_k, D) -> bool:
    """Varlen PACKED route eligibility (ref flash_attn_varlen /
    flash_attn_unpadded kernel): the packed total length pads up to the
    128 alignment, so any total works on TPU; only head-dim rules and
    the GQA group structure (q heads a multiple of kv heads — the splash
    kernel's MQA mode carries packed GQA) gate it."""
    try:
        from ..framework import core
        if not core.get_bool_flag("FLAGS_use_flash_attention", True):
            return False  # same kill switch as supported()
    except Exception:
        pass
    if not _on_tpu():
        return False
    d_ok = (D % 64 == 0) if D <= 128 else (D % 128 == 0)
    return d_ok and n_heads_q % n_heads_k == 0


def flash_attention_packed(q, k, v, seg_q, seg_kv, causal=False,
                           scale=None):
    """Packed-varlen flash attention: q/k/v [total, H, D] holding many
    sequences back-to-back; seg_q/seg_kv int32 [total] sequence ids
    (1-based; 0 = padding). Runs the flash kernel with batch 1 and
    segment-id masking — cross-sequence attention is masked by segment,
    and GLOBAL causal + segments equals per-sequence causal because
    packing preserves intra-sequence order (valid for self-attention
    layouts where q and kv share the packing). GQA/MQA (Hq a multiple of
    Hk) rides the splash kernel's MQA mode with the same segment ids —
    no kv repeat materializes (VERDICT r3 #3b; ref flash_attn_unpadded
    supports GQA, phi/kernels/gpu/flash_attn_kernel.cu).
    """
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        SegmentIds, flash_attention)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    Tq, Hq, D = q.shape
    Tk, Hk = k.shape[0], k.shape[1]
    if Hq != Hk:
        # splash causal masks require square score shapes: pad q and kv
        # to the same aligned total (self-attention packings have Tq==Tk)
        T = max(Tq, Tk)
        T += (-T) % _SEQ_ALIGN
        pad_q, pad_k = T - Tq, T - Tk
    else:
        pad_q = (-Tq) % _SEQ_ALIGN
        pad_k = (-Tk) % _SEQ_ALIGN
    qp = jnp.pad(q, ((0, pad_q), (0, 0), (0, 0)))
    kp = jnp.pad(k, ((0, pad_k), (0, 0), (0, 0)))
    vp = jnp.pad(v, ((0, pad_k), (0, 0), (0, 0)))
    sq = jnp.pad(seg_q.astype(jnp.int32), (0, pad_q))   # pad -> seg 0
    sk = jnp.pad(seg_kv.astype(jnp.int32), (0, pad_k))
    qt = jnp.swapaxes(qp, 0, 1)[None]     # [1, H, T, D]
    kt = jnp.swapaxes(kp, 0, 1)[None]
    vt = jnp.swapaxes(vp, 0, 1)[None]
    if Hq != Hk:
        out = _splash_gqa(qt, kt, vt, causal, scale, None,
                          segments=(sq[None], sk[None]))
        out = jnp.swapaxes(out[0], 0, 1)[:Tq]
        return out.astype(q.dtype)
    out = flash_attention(
        qt, kt, vt, segment_ids=SegmentIds(q=sq[None], kv=sk[None]),
        causal=causal, sm_scale=scale,
        block_sizes=_block_sizes(qt.shape[2], kt.shape[2], D, causal))
    out = jnp.swapaxes(out[0], 0, 1)[:Tq]
    return out.astype(q.dtype)
