"""Flash attention on TPU (ref: phi/kernels/gpu/flash_attn_kernel.cu +
third_party flashattn — re-designed for TPU, not ported; the reference
kernel's MQA/GQA + bias support is matched here, flash_attn_kernel.cu
accepts num_heads_k != num_heads and an attn additive mask).

Three routes, all Pallas:
- MHA (q_heads == kv_heads): the tuned in-tree TPU flash kernel
  (jax.experimental.pallas.ops.tpu.flash_attention) — online-softmax
  MXU-shaped tiles, native causal block skipping, segment-id padding
  masks, and an additive-bias operand (`ab`) for arbitrary masks.
- GQA/MQA causal/full without bias: the splash kernel in MQA mode,
  vmapped over kv heads with q grouped [kv_heads, group, Sq, D] — no
  materialized kv repeat, and block-sparse causal skipping.
- GQA with bias: kv heads broadcast to q heads (autodiff sums the kv
  grads over the group), then the MHA route — still the flash kernel,
  never the O(S^2) dense fallback.

Block sizes come from the autotune cache (kernels/autotune.py) when a
sweep has recorded a winner for the shape class, else a 512 heuristic.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

# lane width is 128; the kernel pads smaller head dims, profitable down to 64
_MIN_HEAD_DIM = 64
_SEQ_ALIGN = 128


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def supported(q_shape, k_shape, causal_or_none: bool,
              has_padding_mask: bool = False,
              has_bias: bool = False) -> bool:
    """True when flash_attention_bshd will hit a Pallas kernel.

    `causal_or_none`: mask is either causal or absent. Arbitrary
    additive masks route through `bias=` (the kernel's ab operand), so
    pass has_bias=True for those instead of returning False. Padding
    masks map to segment ids. GQA/MQA (q_heads a multiple of kv_heads)
    is first-class.
    """
    del has_padding_mask  # handled via segment ids — never gated out
    if not _on_tpu():
        return False
    if not causal_or_none and not has_bias:
        return False  # non-causal non-bias masks must come in as bias
    B, Sq, Hq, D = q_shape
    Hk = k_shape[2]
    Sk = k_shape[1]
    # kernel pads D <= 128 up to the lane width; above that it requires an
    # exact multiple of 128 (so 192/320 must take the dense fallback)
    d_ok = (D % 64 == 0) if D <= 128 else (D % 128 == 0)
    return (d_ok and Sq % _SEQ_ALIGN == 0 and Sk % _SEQ_ALIGN == 0
            and Hq % Hk == 0)


def _block_sizes(Sq, Sk, D, causal, blocks=None):
    """Flash BlockSizes: explicit override (sweeps), else the autotune
    cache winner for this shape class, else 512-square."""
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

    from . import autotune
    if blocks is None:
        default = (min(512, Sq), min(512, Sk))
        key = autotune.cache_key("flash", Sq=Sq, Sk=Sk, D=D,
                                 causal=int(causal))
        blocks = autotune.lookup(key) or default
    bq, bk = min(blocks[0], Sq), min(blocks[1], Sk)
    return BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
        block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq,
    )


def _splash_block_sizes(Sq, Sk, D, blocks=None):
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk)

    from . import autotune
    if blocks is None:
        default = (min(512, Sq), min(512, Sk))
        key = autotune.cache_key("splash", Sq=Sq, Sk=Sk, D=D)
        blocks = autotune.lookup(key) or default
    bq, bk = min(blocks[0], Sq), min(blocks[1], Sk)
    return sk.BlockSizes(block_q=bq, block_kv=bk, block_kv_compute=bk,
                         block_q_dkv=bq, block_kv_dkv=bk,
                         block_kv_dkv_compute=bk,
                         block_q_dq=bq, block_kv_dq=bk)


def _splash_gqa(qt, kt, vt, causal, scale, padding_mask, interpret=False,
                blocks=None):
    """GQA via splash MQA mode: qt [B, Hq, Sq, D], kt/vt [B, Hk, Sk, D].
    No kv repeat materializes; the group dim rides the kernel's q-head
    axis (is_mqa=True shares one kv head across it)."""
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk)
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_mask as sm)

    B, Hq, Sq, D = qt.shape
    Hk, Sk = kt.shape[1], kt.shape[2]
    group = Hq // Hk
    mask_cls = sm.CausalMask((Sq, Sk)) if causal else sm.FullMask((Sq, Sk))
    mask = sm.MultiHeadMask([mask_cls] * group)
    kernel = sk.make_splash_mqa_single_device(
        mask, block_sizes=_splash_block_sizes(Sq, Sk, D, blocks),
        interpret=interpret)
    # splash takes pre-scaled q and no sm_scale argument
    qg = (qt * scale).reshape(B, Hk, group, Sq, D)
    seg = None
    if padding_mask is not None:
        kv_seg = jnp.where(padding_mask.astype(bool), 1, 0).astype(jnp.int32)
        q_seg = kv_seg if Sq == Sk else jnp.ones((B, Sq), jnp.int32)
        seg = sk.SegmentIds(q=q_seg, kv=kv_seg)
    # vmap over batch, then kv heads (q grouped per kv head)
    run = jax.vmap(  # batch
        jax.vmap(kernel, in_axes=(0, 0, 0, None)),  # kv heads
        in_axes=(0, 0, 0, 0))
    out = run(qg, kt, vt, seg)  # [B, Hk, group, Sq, D]
    return out.reshape(B, Hq, Sq, D)


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "interpret", "blocks"))
def flash_attention_bshd(q, k, v, causal=False, scale=None,
                         padding_mask=None, bias=None, interpret=False,
                         blocks=None):
    """[batch, seq, heads, dim] in/out (paddle flash_attn layout).

    padding_mask: optional [batch, kv_seq] bool/int array, True/1 = valid
    token — lowered to segment-id masking. bias: optional additive mask
    broadcastable to [batch, heads, Sq, Sk] — streamed blockwise through
    the kernel's ab operand (never a dense-softmax fallback). The kernel
    requires ab at FULL [B, H, Sq, Sk] f32, so a broadcast-narrow bias
    is materialized here; that matches the dense path's score-matrix
    footprint while keeping flash compute, but pure kv padding should
    come in as padding_mask (segment ids), not bias. GQA/MQA (q heads a
    multiple of kv heads) is handled without materializing a kv repeat
    when bias is None.
    """
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        SegmentIds, flash_attention)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)  # BHSD
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    B, Hq, Sq, D = qt.shape
    Hk, Sk = kt.shape[1], kt.shape[2]

    if Hq != Hk and bias is None:
        out = _splash_gqa(qt, kt, vt, causal, scale, padding_mask,
                          interpret=interpret, blocks=blocks)
        return jnp.swapaxes(out, 1, 2).astype(q.dtype)

    if Hq != Hk:
        # bias path needs the MHA kernel: broadcast kv over the group
        # (cheap reshape-broadcast; autodiff reduces kv grads over it)
        group = Hq // Hk
        kt = jnp.broadcast_to(kt[:, :, None], (B, Hk, group, Sk, D)
                              ).reshape(B, Hq, Sk, D)
        vt = jnp.broadcast_to(vt[:, :, None], (B, Hk, group, Sk, D)
                              ).reshape(B, Hq, Sk, D)

    seg = None
    if padding_mask is not None:
        kv_seg = jnp.where(padding_mask.astype(bool), 1, 0).astype(jnp.int32)
        q_seg = kv_seg if Sq == Sk else jnp.ones((B, Sq), jnp.int32)
        seg = SegmentIds(q=q_seg, kv=kv_seg)
    ab = None
    if bias is not None:
        ab = jnp.broadcast_to(bias.astype(jnp.float32),
                              (B, Hq, Sq, Sk))
    out = flash_attention(qt, kt, vt, ab=ab, segment_ids=seg, causal=causal,
                          sm_scale=scale,
                          block_sizes=_block_sizes(Sq, Sk, D, causal,
                                                   blocks))
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def sweep_block_sizes(Sq=2048, Sk=2048, D=128, H=16, B=4, causal=True,
                      kv_heads=None, dtype=jnp.bfloat16, candidates=None,
                      iters=8, resweep=False):
    """On-chip block-size sweep; winners persist in the autotune cache
    (ref: phi/kernels/autotune/cache.cc). Run from bench tooling with
    PADDLE_AUTOTUNE=1, never during training. kv_heads != H tunes the
    splash GQA route (its own cache key) — the route a GQA model will
    actually take. resweep=True re-measures over a cached winner."""
    from . import autotune

    if candidates is None:
        candidates = [(bq, bk)
                      for bq in (256, 512, 1024) if bq <= Sq
                      for bk in (256, 512, 1024) if bk <= Sk]
    Hk = kv_heads or H
    if Hk != H:
        key = autotune.cache_key("splash", Sq=Sq, Sk=Sk, D=D)
    else:
        key = autotune.cache_key("flash", Sq=Sq, Sk=Sk, D=D,
                                 causal=int(causal))
    kq = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq[0], (B, Sq, H, D), dtype)
    k = jax.random.normal(kq[1], (B, Sk, Hk, D), dtype)
    v = jax.random.normal(kq[2], (B, Sk, Hk, D), dtype)

    def make_fn(cand):
        bq, bk = cand
        if Sq % bq or Sk % bk:
            return None

        def body(c, _):
            # grad-through to tune fwd+bwd together (training shape);
            # blocks as a static arg forces a fresh trace per candidate
            f = lambda q_: flash_attention_bshd(
                q_, k, v, causal=causal,
                blocks=(bq, bk)).astype(jnp.float32).sum()
            return c + jax.grad(f)(q).astype(jnp.float32).sum(), None

        loop = jax.jit(lambda: jax.lax.scan(
            body, jnp.float32(0), None, length=iters)[0])
        return loop

    return autotune.autotune(
        key, candidates, make_fn,
        default=[min(512, Sq), min(512, Sk)], iters=iters,
        sweep=True if (resweep or autotune.lookup(key) is None) else None)


def packed_supported(total_q, total_k, n_heads_q, n_heads_k, D) -> bool:
    """Varlen PACKED route eligibility (ref flash_attn_varlen /
    flash_attn_unpadded kernel): the packed total length pads up to the
    128 alignment, so any total works on TPU; only head-dim rules and
    MHA (packed GQA falls back) gate it."""
    if not _on_tpu():
        return False
    d_ok = (D % 64 == 0) if D <= 128 else (D % 128 == 0)
    return d_ok and n_heads_q == n_heads_k


def flash_attention_packed(q, k, v, seg_q, seg_kv, causal=False,
                           scale=None):
    """Packed-varlen flash attention: q/k/v [total, H, D] holding many
    sequences back-to-back; seg_q/seg_kv int32 [total] sequence ids
    (1-based; 0 = padding). Runs the flash kernel with batch 1 and
    segment-id masking — cross-sequence attention is masked by segment,
    and GLOBAL causal + segments equals per-sequence causal because
    packing preserves intra-sequence order (valid for self-attention
    layouts where q and kv share the packing).
    """
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        SegmentIds, flash_attention)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    Tq, H, D = q.shape
    Tk = k.shape[0]
    pad_q = (-Tq) % _SEQ_ALIGN
    pad_k = (-Tk) % _SEQ_ALIGN
    qp = jnp.pad(q, ((0, pad_q), (0, 0), (0, 0)))
    kp = jnp.pad(k, ((0, pad_k), (0, 0), (0, 0)))
    vp = jnp.pad(v, ((0, pad_k), (0, 0), (0, 0)))
    sq = jnp.pad(seg_q.astype(jnp.int32), (0, pad_q))   # pad -> seg 0
    sk = jnp.pad(seg_kv.astype(jnp.int32), (0, pad_k))
    qt = jnp.swapaxes(qp, 0, 1)[None]     # [1, H, T, D]
    kt = jnp.swapaxes(kp, 0, 1)[None]
    vt = jnp.swapaxes(vp, 0, 1)[None]
    out = flash_attention(
        qt, kt, vt, segment_ids=SegmentIds(q=sq[None], kv=sk[None]),
        causal=causal, sm_scale=scale,
        block_sizes=_block_sizes(qt.shape[2], kt.shape[2], D, causal))
    out = jnp.swapaxes(out[0], 0, 1)[:Tq]
    return out.astype(q.dtype)
