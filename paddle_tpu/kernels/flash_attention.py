"""Flash attention on TPU (ref: phi/kernels/gpu/flash_attn_kernel.cu +
third_party flashattn — re-designed for TPU, not ported).

Strategy: use the tuned in-tree Pallas TPU kernel
(jax.experimental.pallas.ops.tpu.flash_attention) when on TPU and shapes are
tile-aligned; it implements the same online-softmax blocked algorithm as
FlashAttention-2 with MXU-shaped (block_q x block_k) tiles and VMEM
double-buffering. A custom ring-attention kernel for the `sep` axis lives in
ring_attention.py (reference has NO equivalent — SURVEY §5 long-context).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

_MIN_HEAD_DIM = 128  # lane width; smaller head_dims pad poorly


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def supported(q_shape, k_shape, no_mask: bool) -> bool:
    if not _on_tpu():
        return False
    if not no_mask:
        return False
    B, Sq, H, D = q_shape
    Sk = k_shape[1]
    # kernel wants seq multiples of the block size and head_dim % 128 == 0
    return (D % _MIN_HEAD_DIM == 0 and Sq % 128 == 0 and Sk % 128 == 0
            and q_shape[2] == k_shape[2])


@functools.partial(jax.jit, static_argnames=("causal", "scale"))
def flash_attention_bshd(q, k, v, causal=False, scale=None):
    """[batch, seq, heads, dim] in/out (paddle flash_attn layout)."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes, flash_attention)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)  # BHSD
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    Sq, Sk = qt.shape[2], kt.shape[2]
    bq = min(512, Sq)
    bk = min(512, Sk)
    sizes = BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
        block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq,
    )
    out = flash_attention(qt, kt, vt, causal=causal, sm_scale=scale,
                          block_sizes=sizes)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)
