"""Fused SwiGLU MLP prologue (ref: phi/kernels/fusion/gpu/
fused_gate_attention + fused_bias_act; TPU-native blockwise Pallas
kernel with the silu(g)*u epilogue fused into the gate/up matmul).

The unfused MLP materializes `gu = a @ w_gate_up` — a [T, 2M] tensor
(4H-wide at llama ratios) that exists only to be split, activated and
multiplied — an HBM round trip XLA does not reliably elide across the
autograd seam. Here the gate/up products are streamed block-by-block
through VMEM: each (row-block, column-block) grid step computes
g = a·wg and u = a·wu for one [bt, bm] tile in f32, applies
silu(g) * u in-register, and writes only the [T, M] activation out.
The backward is two Pallas kernels with opposite accumulation orders —
da accumulates over column blocks, dw_gate_up over row blocks — each
recomputing its g/u tile from (a, w) so the [T, 2M] intermediate never
hits HBM in either direction.

The jnp fallback computes the exact unfused expression
`silu(gu[..., :M]) * gu[..., M:]`, and the fallback backward is
jax.vjp of that expression, so FLAGS_fused_transformer=0 parity and
interpret-mode tests share one reference. Tests flip `_FORCE_PALLAS`
to drive the Pallas path through the interpreter on CPU.

Block sizes come from kernels/autotune.py (key "swiglu", quantized
H/M size classes) — sweep via `sweep_block_sizes`.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_TPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_TPU = False

__all__ = ["swiglu", "supported", "sweep_block_sizes"]

# tests flip this to exercise the Pallas path through the interpreter on
# CPU (interpret mode is orders of magnitude slower than the fallback)
_FORCE_PALLAS = False


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def supported(a_shape, w_shape) -> bool:
    """a: [..., H]; w_gate_up: [H, 2M] — Mosaic-alignment gate for the
    compiled route (the fallback handles everything)."""
    H, M2 = int(w_shape[0]), int(w_shape[1])
    M = M2 // 2
    return (int(a_shape[-1]) == H and M2 == 2 * M
            and H % 128 == 0 and M % 128 == 0)


def _size_class(n: int) -> int:
    c = 128
    while c < n:
        c *= 2
    return c


def _blocks(T: int, M: int, blocks=None):
    """(row-block, column-block) per grid step: explicit override
    (sweeps), else the autotune winner for this size class, else
    (256, 512) — each shrunk to a divisor of its extent."""
    if blocks is None:
        from . import autotune
        hit = autotune.lookup(autotune.cache_key(
            "swiglu", M=_size_class(M)))
        if hit and isinstance(hit, (list, tuple)) and len(hit) == 2:
            blocks = (int(hit[0]), int(hit[1]))
    if blocks is None:
        blocks = (256, 512)
    bt, bm = blocks
    bt = max(1, min(int(bt), T))
    while T % bt:
        bt -= 1
    bm = max(1, min(int(bm), M))
    while M % bm:
        bm -= 1
    return bt, bm


def _route(a_shape, w_shape, use_pallas):
    if use_pallas is None:
        return (_HAS_TPU and supported(a_shape, w_shape)
                and (_on_tpu() or _FORCE_PALLAS))
    if use_pallas and not supported(a_shape, w_shape):
        # an EXPLICIT True must not silently time/run the fallback
        raise ValueError(
            f"swiglu: use_pallas=True but shapes are not Mosaic-aligned "
            f"(a {tuple(a_shape)}, w_gate_up {tuple(w_shape)}: need "
            f"a[-1] == H, H % 128 == 0, (2M)/2 % 128 == 0)")
    return use_pallas


def _ref(a, w_gate_up):
    """The exact unfused expression (LlamaMLP's fused-weight path)."""
    m = w_gate_up.shape[-1] // 2
    gu = a @ w_gate_up
    return jax.nn.silu(gu[..., :m]) * gu[..., m:]


def _gu_tile(a_ref, wg_ref, wu_ref):
    a = a_ref[...]
    g = jnp.dot(a, wg_ref[...], preferred_element_type=jnp.float32)
    u = jnp.dot(a, wu_ref[...], preferred_element_type=jnp.float32)
    return g, u


def _fwd_kernel(a_ref, wg_ref, wu_ref, o_ref):
    g, u = _gu_tile(a_ref, wg_ref, wu_ref)
    o_ref[...] = (jax.nn.silu(g) * u).astype(o_ref.dtype)


def _dgu_tile(a_ref, wg_ref, wu_ref, do_ref):
    """Recompute the g/u tile and turn the output cotangent into the
    gate/up cotangents (silu'(g) = s + g*s*(1-s), s = sigmoid(g))."""
    g, u = _gu_tile(a_ref, wg_ref, wu_ref)
    do = do_ref[...].astype(jnp.float32)
    s = jax.nn.sigmoid(g)
    dg = do * u * (s + g * s * (1.0 - s))
    du = do * (g * s)
    return dg, du


def _bwd_da_kernel(a_ref, wg_ref, wu_ref, do_ref, da_ref, acc_ref, *, nm):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    dg, du = _dgu_tile(a_ref, wg_ref, wu_ref, do_ref)
    dims = (((1,), (1,)), ((), ()))          # contract the M-block axis
    acc_ref[...] += (
        jax.lax.dot_general(dg, wg_ref[...], dims,
                            preferred_element_type=jnp.float32)
        + jax.lax.dot_general(du, wu_ref[...], dims,
                              preferred_element_type=jnp.float32))

    @pl.when(j == nm - 1)
    def _emit():
        da_ref[...] = acc_ref[...].astype(da_ref.dtype)


def _bwd_dw_kernel(a_ref, wg_ref, wu_ref, do_ref, dwg_ref, dwu_ref,
                   accg_ref, accu_ref, *, nt):
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        accg_ref[...] = jnp.zeros_like(accg_ref)
        accu_ref[...] = jnp.zeros_like(accu_ref)

    dg, du = _dgu_tile(a_ref, wg_ref, wu_ref, do_ref)
    a = a_ref[...]
    dims = (((0,), (0,)), ((), ()))          # contract the row-block axis
    accg_ref[...] += jax.lax.dot_general(
        a, dg, dims, preferred_element_type=jnp.float32)
    accu_ref[...] += jax.lax.dot_general(
        a, du, dims, preferred_element_type=jnp.float32)

    @pl.when(t == nt - 1)
    def _emit():
        dwg_ref[...] = accg_ref[...].astype(dwg_ref.dtype)
        dwu_ref[...] = accu_ref[...].astype(dwu_ref.dtype)


def _fwd_impl(a, w_gate_up, use_pallas, blocks):
    if not _route(a.shape, w_gate_up.shape, use_pallas):
        return _ref(a, w_gate_up)
    orig_shape = a.shape
    H = orig_shape[-1]
    M = w_gate_up.shape[-1] // 2
    af = a.reshape(-1, H)
    T = af.shape[0]
    bt, bm = _blocks(T, M, blocks)
    nm = M // bm
    out = pl.pallas_call(
        _fwd_kernel,
        out_shape=jax.ShapeDtypeStruct((T, M), a.dtype),
        grid=(T // bt, nm),
        in_specs=[
            pl.BlockSpec((bt, H), lambda i, j: (i, 0)),
            pl.BlockSpec((H, bm), lambda i, j: (0, j)),
            pl.BlockSpec((H, bm), lambda i, j, nm=nm: (0, j + nm)),
        ],
        out_specs=pl.BlockSpec((bt, bm), lambda i, j: (i, j)),
        interpret=not _on_tpu(),
    )(af, w_gate_up, w_gate_up)
    return out.reshape(orig_shape[:-1] + (M,))


def _bwd_impl(a, w_gate_up, g, use_pallas, blocks):
    if not _route(a.shape, w_gate_up.shape, use_pallas):
        # autodiff of the exact unfused expression — bitwise the
        # FLAGS_fused_transformer=0 tape on CPU
        _, vjp = jax.vjp(_ref, a, w_gate_up)
        return vjp(g)
    orig_shape = a.shape
    H = orig_shape[-1]
    M = w_gate_up.shape[-1] // 2
    af = a.reshape(-1, H)
    gf = g.reshape(-1, M)
    T = af.shape[0]
    bt, bm = _blocks(T, M, blocks)
    nt, nm = T // bt, M // bm
    scratch = pltpu.VMEM if _HAS_TPU and pltpu is not None else None
    da = pl.pallas_call(
        functools.partial(_bwd_da_kernel, nm=nm),
        out_shape=jax.ShapeDtypeStruct((T, H), a.dtype),
        grid=(nt, nm),
        in_specs=[
            pl.BlockSpec((bt, H), lambda i, j: (i, 0)),
            pl.BlockSpec((H, bm), lambda i, j: (0, j)),
            pl.BlockSpec((H, bm), lambda i, j, nm=nm: (0, j + nm)),
            pl.BlockSpec((bt, bm), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((bt, H), lambda i, j: (i, 0)),
        scratch_shapes=[scratch((bt, H), jnp.float32)],
        interpret=not _on_tpu(),
    )(af, w_gate_up, w_gate_up, gf)
    dwg, dwu = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, nt=nt),
        out_shape=(jax.ShapeDtypeStruct((H, M), w_gate_up.dtype),
                   jax.ShapeDtypeStruct((H, M), w_gate_up.dtype)),
        grid=(nm, nt),
        in_specs=[
            pl.BlockSpec((bt, H), lambda m, t: (t, 0)),
            pl.BlockSpec((H, bm), lambda m, t: (0, m)),
            pl.BlockSpec((H, bm), lambda m, t, nm=nm: (0, m + nm)),
            pl.BlockSpec((bt, bm), lambda m, t: (t, m)),
        ],
        out_specs=(pl.BlockSpec((H, bm), lambda m, t: (0, m)),
                   pl.BlockSpec((H, bm), lambda m, t: (0, m))),
        scratch_shapes=[scratch((H, bm), jnp.float32),
                        scratch((H, bm), jnp.float32)],
        interpret=not _on_tpu(),
    )(af, w_gate_up, w_gate_up, gf)
    dw = jnp.concatenate([dwg, dwu], axis=-1)
    return da.reshape(orig_shape), dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def swiglu(a, w_gate_up, use_pallas=None, blocks=None):
    """a: [..., H]; w_gate_up: [H, 2M] (gate columns first). Returns
    silu(a @ w_gate) * (a @ w_up): [..., M]. The down projection stays
    outside — its input is the kernel's output, already in HBM.

    use_pallas: None = auto (real TPU + aligned, or _FORCE_PALLAS via
    the interpreter), True/False forces the route; blocks overrides the
    autotuned (row, column) blocks (the sweep's candidate lever)."""
    return _fwd_impl(a, w_gate_up, use_pallas, blocks)


def _swiglu_fwd(a, w_gate_up, use_pallas, blocks):
    return _fwd_impl(a, w_gate_up, use_pallas, blocks), (a, w_gate_up)


def _swiglu_bwd(use_pallas, blocks, res, g):
    a, w_gate_up = res
    return _bwd_impl(a, w_gate_up, g, use_pallas, blocks)


swiglu.defvjp(_swiglu_fwd, _swiglu_bwd)


def sweep_block_sizes(a_shape, w_shape, dtype=jnp.bfloat16, iters=8,
                      sweep=None):
    """Register/refresh the (row, column) block winner for one size
    class with kernels/autotune.py (PADDLE_AUTOTUNE=1 or sweep=True;
    cached winners are consulted by _blocks unconditionally). Times the
    fwd+bwd pair under jax.grad — the backward's two accumulation
    kernels dominate and must share the winner."""
    from . import autotune
    H, M2 = int(w_shape[0]), int(w_shape[1])
    M = M2 // 2
    rows = 1
    for s in a_shape[:-1]:
        rows *= int(s)
    key = autotune.cache_key("swiglu", M=_size_class(M))

    def make_fn(cand):
        bt, bm = cand
        if bt > rows or bm > M:
            return None
        rng = jax.random.PRNGKey(0)
        a = jax.random.normal(rng, (rows, H), jnp.float32).astype(dtype)
        w = jax.random.normal(rng, (H, M2), jnp.float32).astype(dtype)

        def loss(a_, w_):
            return jnp.sum(swiglu(a_, w_, use_pallas=True,
                                  blocks=(bt, bm)).astype(jnp.float32))

        def run():
            def body(c, _):
                da, dw = jax.grad(loss, argnums=(0, 1))(
                    a * (1 + 0 * c).astype(dtype), w)
                return c + 0 * da[0, 0].astype(jnp.float32), None
            return jax.jit(lambda: jax.lax.scan(
                body, jnp.float32(0), None, length=iters))()

        return run

    return autotune.autotune(
        key, [(128, 128), (128, 512), (256, 256), (256, 512), (512, 512)],
        make_fn, default=_blocks(rows, M), iters=iters, sweep=sweep)
