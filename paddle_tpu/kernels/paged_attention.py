"""Paged-KV decode attention (ref: the reference's paged decode kernels —
block_multihead_attention under phi/kernels/fusion/gpu/ and
masked_multihead_attention / fused_multi_transformer_op.cu decode mode).

TPU-native: wraps the in-tree Pallas paged-attention kernel
(jax.experimental.pallas.ops.tpu.paged_attention) for single-token decode
over a paged KV cache, with a dense jnp fallback (CPU / unaligned shapes).
The page table layout matches the reference's block tables: per-sequence
page indices into a global page pool.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

__all__ = ["decode_attention", "paged_decode_attention", "paginate_cache",
           "supported"]

_PAGE = 16  # tokens per page (multiple of the sublane tile)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def supported(q_shape, pages_shape) -> bool:
    """q: [B, nh, d]; pages: [kvh, n_pages, page, d]."""
    if not _on_tpu():
        return False
    B, nh, d = q_shape
    kvh, n_pages, page, d2 = pages_shape
    return (d == d2 and d % 64 == 0 and page % 8 == 0
            and nh % kvh == 0)


def paginate_cache(cache_k, cache_v, page_size=_PAGE):
    """[B, S_max, kvh, d] contiguous cache -> (k_pages, v_pages,
    page_indices) in the kernel's [kvh, total_pages, page, d] pool layout
    with the identity block table."""
    B, S, kvh, d = cache_k.shape
    assert S % page_size == 0, f"S_max {S} must be a page multiple"
    ppseq = S // page_size

    def to_pages(c):
        # [B, S, kvh, d] -> [kvh, B*ppseq, page, d]
        x = c.reshape(B, ppseq, page_size, kvh, d)
        x = jnp.moveaxis(x, 3, 0)                 # [kvh, B, ppseq, page, d]
        return x.reshape(kvh, B * ppseq, page_size, d)

    page_indices = (jnp.arange(B)[:, None] * ppseq
                    + jnp.arange(ppseq)[None, :]).astype(jnp.int32)
    return to_pages(cache_k), to_pages(cache_v), page_indices


def paged_decode_attention(q, k_pages, v_pages, lengths, page_indices,
                           scale=None):
    """One decode step over a paged cache.

    q: [B, nh, d]; k/v_pages: [kvh, total_pages, page, d];
    lengths: i32[B] valid tokens per sequence;
    page_indices: i32[B, pages_per_seq].
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    q = q * scale  # kernel applies no softmax scale
    if supported(q.shape, k_pages.shape):
        from jax.experimental.pallas.ops.tpu.paged_attention import (
            paged_attention)
        # kernel requires pages_per_seq % pages_per_compute_block == 0
        ppseq = page_indices.shape[1]
        pages_per_block = next(b for b in (8, 4, 2, 1) if ppseq % b == 0)
        return paged_attention(
            q, k_pages, v_pages, lengths, page_indices,
            pages_per_compute_block=pages_per_block)
    return _dense_fallback(q, k_pages, v_pages, lengths, page_indices)


def _dense_fallback(q, k_pages, v_pages, lengths, page_indices):
    B, nh, d = q.shape
    kvh, _, page, _ = k_pages.shape
    ppseq = page_indices.shape[1]
    S = ppseq * page

    def gather(pages):  # -> [B, S, kvh, d]
        # pages[h, page_indices[b, p]] : [B, ppseq, kvh?, ...]
        x = pages[:, page_indices]                # [kvh, B, ppseq, page, d]
        x = jnp.moveaxis(x, 0, 3)                 # [B, ppseq, page, kvh, d]
        return x.reshape(B, S, kvh, d)

    k = gather(k_pages)
    v = gather(v_pages)
    rep = nh // kvh
    if rep > 1:
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bhd,bshd->bhs", q.astype(jnp.float32),
                   k.astype(jnp.float32))
    valid = jnp.arange(S)[None, :] < lengths[:, None]
    s = jnp.where(valid[:, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhs,bshd->bhd", p, v.astype(jnp.float32))
    return o.astype(q.dtype)


def decode_attention(q, cache_k, cache_v, cur_len, scale=None):
    """Convenience: q [B, 1, nh, d] + contiguous cache [B, S_max, kvh, d]
    -> [B, 1, nh, d]; routes through the paged kernel when eligible."""
    B = q.shape[0]
    q1 = q[:, 0]
    S = cache_k.shape[1]
    pad = (-S) % _PAGE
    if pad:
        cfg = [(0, 0), (0, pad), (0, 0), (0, 0)]
        cache_k = jnp.pad(cache_k, cfg)
        cache_v = jnp.pad(cache_v, cfg)
    kp, vp, pidx = paginate_cache(cache_k, cache_v)
    lengths = jnp.broadcast_to(jnp.asarray(cur_len, jnp.int32), (B,))
    out = paged_decode_attention(q1, kp, vp, lengths, pidx, scale=scale)
    return out[:, None]
