"""Pallas RMSNorm (ref: phi/kernels/fusion/gpu/fused_rms_norm; TPU-native
row-blocked kernel: one VMEM pass, f32 accumulation, bf16 in/out).

XLA usually fuses rms_norm chains already; this kernel exists for the long-
row case (hidden >= 8192) where explicit blocking beats the fusion, and as
the template for further norm kernels.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_TPU = True
except Exception:  # pragma: no cover
    _HAS_TPU = False


def _rms_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    o_ref[...] = (x * jax.lax.rsqrt(ms + eps) * w_ref[...].astype(jnp.float32)
                  ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("eps",))
def rms_norm(x, weight, eps=1e-6):
    """x: [..., H]; weight: [H]."""
    if not _HAS_TPU or jax.default_backend() != "tpu":
        x32 = x.astype(jnp.float32)
        ms = jnp.mean(x32 * x32, axis=-1, keepdims=True)
        return (x32 * jax.lax.rsqrt(ms + eps) * weight.astype(jnp.float32)
                ).astype(x.dtype)
    orig_shape = x.shape
    H = orig_shape[-1]
    xf = x.reshape(-1, H)
    rows = xf.shape[0]
    block_rows = max(1, min(256, rows))
    while rows % block_rows:
        block_rows -= 1
    grid = (rows // block_rows,)
    out = pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct(xf.shape, x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, H), lambda i: (i, 0)),
            pl.BlockSpec((H,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, H), lambda i: (i, 0)),
    )(xf, weight)
    return out.reshape(orig_shape)
