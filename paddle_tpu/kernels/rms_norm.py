"""Pallas RMSNorm (ref: phi/kernels/fusion/gpu/fused_rms_norm; TPU-native
row-blocked kernel: one VMEM pass, f32 accumulation, bf16 in/out).

XLA usually fuses rms_norm chains already; this kernel exists for the long-
row case (hidden >= 8192) where explicit blocking beats the fusion, and as
the template for further norm kernels. Reverse-mode AD is provided by an
analytic custom_vjp (Pallas calls carry no AD rule of their own):
  y  = x * r * w,  r = rsqrt(mean(x^2) + eps)
  dx = r*(g*w) - x * r^3/H * sum(g*w*x)
  dw = sum_rows(g * x * r)
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    _HAS_TPU = True
except Exception:  # pragma: no cover
    _HAS_TPU = False


def _rms_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    o_ref[...] = (x * jax.lax.rsqrt(ms + eps) * w_ref[...].astype(jnp.float32)
                  ).astype(o_ref.dtype)


def _fwd_impl(x, weight, eps):
    if not _HAS_TPU or jax.default_backend() != "tpu":
        x32 = x.astype(jnp.float32)
        ms = jnp.mean(x32 * x32, axis=-1, keepdims=True)
        return (x32 * jax.lax.rsqrt(ms + eps) * weight.astype(jnp.float32)
                ).astype(x.dtype)
    orig_shape = x.shape
    H = orig_shape[-1]
    xf = x.reshape(-1, H)
    rows = xf.shape[0]
    block_rows = max(1, min(256, rows))
    while rows % block_rows:
        block_rows -= 1
    grid = (rows // block_rows,)
    out = pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct(xf.shape, x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, H), lambda i: (i, 0)),
            pl.BlockSpec((H,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, H), lambda i: (i, 0)),
    )(xf, weight)
    return out.reshape(orig_shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm(x, weight, eps=1e-6):
    """x: [..., H]; weight: [H]."""
    return _fwd_impl(x, weight, eps)


def _rms_fwd(x, weight, eps):
    return _fwd_impl(x, weight, eps), (x, weight)


def _rms_bwd(eps, res, g):
    x, w = res
    H = x.shape[-1]
    x32 = x.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    w32 = w.astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    gw = g32 * w32
    dx = r * gw - x32 * (r ** 3) * jnp.sum(gw * x32, axis=-1,
                                           keepdims=True) / H
    dw = jnp.sum((g32 * x32 * r).reshape(-1, H), axis=0)
    return dx.astype(x.dtype), dw.astype(w.dtype)


rms_norm.defvjp(_rms_fwd, _rms_bwd)
