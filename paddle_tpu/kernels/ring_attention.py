"""Ring attention + Ulysses all-to-all attention for the `sep`
(sequence/context-parallel) mesh axis.

The reference has NO in-tree context-parallel attention kernel — its `sep`
axis only plumbs groups (SURVEY §5: fleet/base/topology.py:184 sep axis,
meta_parallel/segment_parallel.py broadcasts params; attention-level
all-to-all "left to model code"). These are designed from the papers
(RingAttention, DeepSpeed-Ulysses) TPU-first:

  ring_attention: each sep-rank holds a sequence chunk of q/k/v; k/v blocks
  rotate around the ring via lax.ppermute (ICI collective-permute) while an
  online-softmax accumulator (m, l, o) absorbs one block per round —
  blockwise-exact softmax, O(S/N) memory per chip, comm overlapped by XLA
  with the per-round matmuls.

  ulysses_attention: all-to-all converts the seq shard into a head shard,
  runs dense (flash) attention per head group, and converts back — cheaper
  comm volume than ring when heads >= sep degree.

Both are numerically exact (not approximations) and reverse-differentiable
(scan + ppermute transpose cleanly; per-round remat keeps memory flat).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["ring_attention", "ulysses_attention", "sep_attention"]


def _broadcast_kv_heads(q, k, v):
    """GQA/MQA support: repeat kv heads up to the q head count.

    [B, S, Hq, D] q with [B, S, Hkv, D] k/v is valid when Hq % Hkv == 0
    (each kv head serves Hq/Hkv query heads); anything else is rejected
    with a clear error instead of an opaque einsum shape failure.
    """
    hq, hkv = q.shape[2], k.shape[2]
    if k.shape[2] != v.shape[2]:
        raise ValueError(
            f"k and v head counts differ: {k.shape[2]} vs {v.shape[2]}")
    if hq == hkv:
        return k, v
    if hq % hkv != 0:
        raise ValueError(
            f"GQA needs q heads ({hq}) divisible by kv heads ({hkv})")
    rep = hq // hkv
    return jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2)


def _block_attn(q, k, v, mask, scale):
    """One blockwise attention round: (m, l, o) stats in f32.
    q: [B, Sq, H, D]; k/v: [B, Sk, H, D]; mask: [Sq, Sk] bool or None.

    Routed through the Pallas block kernel (kernels/block_attention.py)
    when shapes are tile-aligned on TPU — the f32 score matrix stays in
    VMEM; the jnp path covers unaligned/CPU. Fully-masked rows report
    (m=-1e30, l=0, o=0), which the ring merge treats as empty."""
    from .block_attention import block_attention_stats
    return block_attention_stats(q, k, v, mask, scale)


def _ring_body(q, k, v, axis_name, causal, scale):
    """Runs on one sep-rank inside shard_map. q/k/v: [B, S_loc, H, D]."""
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    S_loc = q.shape[1]

    q_pos = my * S_loc + jnp.arange(S_loc)        # global positions of my q

    def round_fn(carry, r):
        k_cur, v_cur, m_acc, l_acc, o_acc = carry
        src = (my - r) % n                        # whose kv block this is
        k_pos = src * S_loc + jnp.arange(S_loc)
        mask = (q_pos[:, None] >= k_pos[None, :]) if causal else None

        def compute(q, k_cur, v_cur):
            return _block_attn(q, k_cur, v_cur, mask, scale)

        m_b, l_b, o_b = jax.checkpoint(compute)(q, k_cur, v_cur)
        # online-softmax merge of (m,l,o) accumulators
        m_new = jnp.maximum(m_acc, m_b)
        c_old = jnp.exp(m_acc - m_new)
        c_new = jnp.exp(m_b - m_new)
        l_new = l_acc * c_old + l_b * c_new
        o_new = (o_acc * c_old[..., None].swapaxes(1, 2)
                 + o_b * c_new[..., None].swapaxes(1, 2))
        # rotate kv to the next rank (ring)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m_new, l_new, o_new), None

    B, _, H, D = q.shape
    m0 = jnp.full((B, H, S_loc), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, S_loc), jnp.float32)
    o0 = jnp.zeros((B, S_loc, H, D), jnp.float32)
    (k_f, v_f, m, l, o), _ = jax.lax.scan(
        round_fn, (k, v, m0, l0, o0), jnp.arange(n))
    l = jnp.where(l == 0.0, 1.0, l)               # fully-masked rows -> 0 out
    out = o / l[..., None].swapaxes(1, 2)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh=None, axis_name: str = "sep",
                   causal: bool = True, scale: Optional[float] = None):
    """q,k,v: logical [B, S, H, D] sharded over `axis_name` on dim 1.
    Call inside jit (TrainStep) — shard_map makes the ring explicit while
    the remaining mesh axes stay under GSPMD."""
    from ..distributed.topology import get_mesh
    mesh = mesh or get_mesh()
    k, v = _broadcast_kv_heads(q, k, v)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if mesh is None or axis_name not in mesh.axis_names \
            or mesh.shape[axis_name] == 1:
        # degenerate: plain blockwise attention on one device
        Sq = q.shape[1]
        mask = (jnp.arange(Sq)[:, None] >= jnp.arange(Sq)[None, :]) \
            if causal else None
        m, l, o = _block_attn(q, k, v, mask, scale)
        l = jnp.where(l == 0.0, 1.0, l)
        return (o / l[..., None].swapaxes(1, 2)).astype(q.dtype)
    spec = P(None, axis_name, None, None)
    body = jax.shard_map(
        functools.partial(_ring_body, axis_name=axis_name, causal=causal,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names=frozenset({axis_name}), check_vma=False)
    return body(q, k, v)


def _ulysses_body(q, k, v, axis_name, causal, scale):
    """Seq-shard -> head-shard via all_to_all, dense attention, back."""
    n = jax.lax.axis_size(axis_name)

    def seq_to_heads(x):  # [B, S/N, H, D] -> [B, S, H/N, D]
        B, Sl, H, D = x.shape
        x = x.reshape(B, Sl, n, H // n, D)
        x = jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                               tiled=False)
        return x.reshape(B, Sl * n, H // n, D)

    def heads_to_seq(x):  # [B, S, H/N, D] -> [B, S/N, H, D]
        B, S, Hl, D = x.shape
        x = x.reshape(B, n, S // n, Hl, D)
        x = jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=3,
                               tiled=False)                # [B, S/N, Hl, n, D]
        # chunk r carries heads [r*Hl, (r+1)*Hl) — merge rank-major to undo
        # the rank-major head split in seq_to_heads
        x = jnp.swapaxes(x, 2, 3)                          # [B, S/N, n, Hl, D]
        return x.reshape(B, S // n, Hl * n, D)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    Sq = qg.shape[1]
    mask = (jnp.arange(Sq)[:, None] >= jnp.arange(Sq)[None, :]) \
        if causal else None
    m, l, o = _block_attn(qg, kg, vg, mask, scale)
    l = jnp.where(l == 0.0, 1.0, l)
    out = (o / l[..., None].swapaxes(1, 2)).astype(q.dtype)
    return heads_to_seq(out)


def ulysses_attention(q, k, v, mesh=None, axis_name: str = "sep",
                      causal: bool = True, scale: Optional[float] = None):
    """DeepSpeed-Ulysses-style SP attention; requires H % sep_degree == 0."""
    from ..distributed.topology import get_mesh
    mesh = mesh or get_mesh()
    k, v = _broadcast_kv_heads(q, k, v)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if mesh is None or axis_name not in mesh.axis_names \
            or mesh.shape[axis_name] == 1:
        return ring_attention(q, k, v, mesh, axis_name, causal, scale)
    assert q.shape[2] % mesh.shape[axis_name] == 0, (
        f"ulysses needs heads {q.shape[2]} divisible by sep degree "
        f"{mesh.shape[axis_name]}")
    spec = P(None, axis_name, None, None)
    body = jax.shard_map(
        functools.partial(_ulysses_body, axis_name=axis_name, causal=causal,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names=frozenset({axis_name}), check_vma=False)
    return body(q, k, v)


def sep_attention(q, k, v, mesh=None, causal=True, mode="ring"):
    """Dispatcher used by model code on the sep axis."""
    fn = ring_attention if mode == "ring" else ulysses_attention
    return fn(q, k, v, mesh=mesh, causal=causal)
