"""paddle.linalg namespace (ref: python/paddle/linalg.py re-exports)."""
from .ops.linalg_ops import (  # noqa: F401
    cholesky, cholesky_inverse, cholesky_solve, cond, corrcoef, cov, det,
    eig, eigh, eigvals, eigvalsh, householder_product, inverse, lstsq, lu,
    lu_unpack, lu_solve, matrix_exp, matrix_power, matrix_rank, multi_dot,
    ormqr, pca_lowrank, pinv, qr, slogdet, solve, svd, svd_lowrank, svdvals,
    triangular_solve, vander, vecdot,
)
from .ops.reduction import norm  # noqa: F401
from .ops.linalg_ops import matmul, matrix_transpose  # noqa: F401
