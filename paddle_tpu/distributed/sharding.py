"""DistTensor / placements / reshard → JAX shardings
(ref: phi/core/distributed/auto_parallel/placement_types.h Shard/Replicate/
Partial; python/paddle/distributed/auto_parallel/api.py:124 shard_tensor,
:302 reshard; reshard functions phi/.../reshard/*).

TPU-native: a placement list maps 1:1 onto a PartitionSpec; `shard_tensor`
is `jax.device_put(NamedSharding)`; `reshard` is another device_put — XLA
emits exactly the r_to_s / s_to_r / p_to_r collective the reference
implements by hand per case. SPMD rules (phi/infermeta/spmd_rules/) are
GSPMD's propagation pass — nothing to reimplement.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..tensor import Parameter, Tensor


class Placement:
    pass


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"

    def __eq__(self, o):
        return isinstance(o, Replicate)

    def __hash__(self):
        return hash("replicate")


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = dim

    def get_dim(self):
        return self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))


class Partial(Placement):
    """Pending-reduction placement. GSPMD tracks partial sums internally;
    user-facing Partial materializes on reshard."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def __repr__(self):
        return f"Partial({self.reduce_type})"

    def __eq__(self, o):
        return isinstance(o, Partial) and o.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("partial", self.reduce_type))


class ProcessMesh:
    """ref: python/paddle/distributed/auto_parallel/process_mesh.py.
    Thin wrapper producing a jax Mesh over the same shape/dim_names."""

    def __init__(self, mesh=None, dim_names=None, shape=None, process_ids=None):
        if mesh is not None:
            arr = np.asarray(mesh)
            self.shape = list(arr.shape)
            self.process_ids = arr.ravel().tolist()
        else:
            self.shape = list(shape)
            self.process_ids = (list(process_ids) if process_ids is not None
                                else list(range(int(np.prod(self.shape)))))
        self.dim_names = (list(dim_names) if dim_names is not None
                          else [f"d{i}" for i in range(len(self.shape))])
        devs = np.asarray(jax.devices())
        n = int(np.prod(self.shape))
        assert n <= devs.size, (
            f"ProcessMesh wants {n} devices, only {devs.size} present")
        self._jax_mesh = Mesh(devs[:n].reshape(self.shape),
                              tuple(self.dim_names))

    @property
    def mesh(self):
        return np.asarray(self.process_ids).reshape(self.shape)

    @property
    def jax_mesh(self) -> Mesh:
        return self._jax_mesh

    @property
    def ndim(self):
        return len(self.shape)

    def get_dim_size(self, name):
        return self.shape[self.dim_names.index(name)]

    def __eq__(self, o):
        return (isinstance(o, ProcessMesh) and o.shape == self.shape
                and o.dim_names == self.dim_names)

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


def _as_jax_mesh(mesh):
    if isinstance(mesh, ProcessMesh):
        return mesh.jax_mesh
    return mesh


def to_placements(placements, mesh, ndim) -> P:
    """placement-per-mesh-dim list -> PartitionSpec over tensor dims."""
    jm = _as_jax_mesh(mesh)
    axis_names = list(jm.axis_names)
    spec: List[Any] = [None] * ndim
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            d = pl.dim
            if spec[d] is None:
                spec[d] = axis_names[mesh_dim]
            elif isinstance(spec[d], tuple):
                spec[d] = spec[d] + (axis_names[mesh_dim],)
            else:
                spec[d] = (spec[d], axis_names[mesh_dim])
    return P(*spec)


def placements_from_spec(spec: P, mesh, ndim):
    jm = _as_jax_mesh(mesh)
    axis_names = list(jm.axis_names)
    placements = [Replicate() for _ in axis_names]
    for d, entry in enumerate(tuple(spec) + (None,) * (ndim - len(tuple(spec)))):
        if entry is None:
            continue
        entries = entry if isinstance(entry, tuple) else (entry,)
        for a in entries:
            placements[axis_names.index(a)] = Shard(d)
    return placements


def shard_tensor(x, mesh, placements, dtype=None, stop_gradient=None):
    """ref: api.py:124 — place `x` with NamedSharding (GSPMD does layout)."""
    t = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    jm = _as_jax_mesh(mesh)
    spec = to_placements(placements, mesh, t.ndim)
    sharding = NamedSharding(jm, spec)
    data = jax.device_put(t.data, sharding)
    out = (Parameter(data, name=t.name) if isinstance(t, Parameter)
           else Tensor(data, stop_gradient=t.stop_gradient, name=t.name))
    if stop_gradient is not None:
        out.stop_gradient = stop_gradient
    out.pspec = spec
    if isinstance(x, Tensor):
        # in-place flavor used by shard-and-keep-module-reference patterns
        x.data = data
        x.pspec = spec
    return out


def reshard(x, mesh, placements):
    """ref: api.py:302 + phi reshard function table — one device_put."""
    jm = _as_jax_mesh(mesh)
    has_partial = any(isinstance(p, Partial) for p in placements)
    spec = to_placements(placements, mesh, x.ndim)
    data = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    if has_partial:
        raise NotImplementedError(
            "explicit Partial targets are internal to compiled programs; "
            "reshard to Shard/Replicate instead")
    out_data = jax.device_put(data, NamedSharding(jm, spec))
    out = Tensor(out_data, stop_gradient=getattr(x, "stop_gradient", True))
    out.pspec = spec
    return out


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    t = fn(*args, **kwargs)
    return shard_tensor(t, mesh, placements)


def data_axes_for(dim_size: int, mesh=None) -> tuple:
    """Mesh axes that carry the batch dim of activations (dp + the ZeRO
    sharding axis, which is data-parallel for activations), greedily
    restricted to axes whose running product divides `dim_size` —
    sharding constraints applied EAGERLY (outside jit) and jit
    in_shardings hard-require divisibility. Used to FULLY pin activation
    layouts at resharding boundaries: a partial constraint (batch dim
    None) lets GSPMD invent a different layout in the checkpointed
    backward and fall into 'involuntary full rematerialization' at the
    boundary collective."""
    from .topology import get_mesh
    mesh = mesh if mesh is not None else get_mesh()
    if mesh is None:
        return ()
    axes, prod = [], 1
    for a in ("dp", "sharding"):
        if a in mesh.axis_names and mesh.shape[a] > 1 \
                and dim_size % (prod * mesh.shape[a]) == 0:
            axes.append(a)
            prod *= mesh.shape[a]
    return tuple(axes)


def with_partial_annotation(x, spec: P):
    """with_sharding_constraint inside compiled programs.

    Routed through the tape (differentiable identity) — constructing a
    fresh Tensor here would sever the autograd graph and silently zero the
    gradients of everything upstream (r2 fix).
    """
    from jax.lax import with_sharding_constraint
    from .topology import get_mesh
    mesh = get_mesh()
    if mesh is None:
        return x
    if isinstance(x, Tensor):
        from ..autograd.tape import apply_op
        return apply_op(
            lambda a: with_sharding_constraint(a, NamedSharding(mesh, spec)),
            x, name="sharding_constraint")
    return with_sharding_constraint(x, NamedSharding(mesh, spec))


class ShardingPlan:
    """Placement policy consumed by jit.TrainStep: decides the NamedSharding
    of every model/optimizer array before compilation.

    This is the TPU-native form of fleet's sharding stages (SURVEY §2.5):
      stage 1/2 -> optimizer state (+grads) sharded on `sharding` axis
      stage 3   -> parameters sharded too (FSDP)
    plus tensor-parallel PartitionSpecs attached by mpu layers (p.pspec).
    """

    def __init__(self, mesh: Mesh, stage: int = 0, param_rules=None,
                 data_axes=("dp", "sharding"), shard_min_size: int = 2 ** 14,
                 grad_sync=None, grad_sync_block=None,
                 grad_sync_error_feedback: bool = False, zero: int = 0):
        self.mesh = mesh
        self.stage = stage
        self.param_rules = param_rules or {}
        self.pspecs: Dict[str, P] = {}  # model-annotated TP layouts (p.pspec)
        self._requested_data_axes = tuple(data_axes)  # pre-filter (remesh)
        self.data_axes = tuple(a for a in data_axes if a in mesh.axis_names
                               and mesh.shape[a] > 1) or tuple(
                                   a for a in data_axes if a in mesh.axis_names)
        self.shard_min_size = shard_min_size
        # quantized gradient sync (ISSUE 8, EQuARX): "int8"/"fp8" routes
        # the data-parallel grad mean through the blockwise-quantized
        # shard_map chain in collective.py instead of the implicit GSPMD
        # psum; None (default) keeps today's path. Armed only when
        # FLAGS_quant_collectives != 0 (evaluated at TrainStep build —
        # the kill switch restores the GSPMD path bitwise).
        self.grad_sync = grad_sync
        self.grad_sync_block = grad_sync_block
        self.grad_sync_error_feedback = bool(grad_sync_error_feedback)
        # explicit ZeRO sharded weight update (arxiv 2004.13336):
        # zero=1 shards optimizer state across the DP axis (grads still
        # all-reduced), zero=2 additionally reduce-scatters grads so the
        # full reduced gradient never materializes. Composes WITH
        # grad_sync (the quantized chain becomes the rs wire path);
        # armed only when FLAGS_zero != 0 (evaluated at TrainStep build
        # — the kill switch restores the replicated paths bitwise).
        if zero not in (0, 1, 2):
            raise ValueError(f"ShardingPlan(zero={zero!r}): ZeRO mode must "
                             f"be 0 (off), 1, or 2")
        self.zero = int(zero)
        if stage != 0 and (grad_sync is not None or self.zero):
            knobs = " and ".join(
                k for k, on in ((f"grad_sync={grad_sync!r}",
                                 grad_sync is not None),
                                (f"zero={zero}", bool(self.zero))) if on)
            raise ValueError(
                f"ShardingPlan(stage={stage}) GSPMD state/param sharding "
                f"does not compose with {knobs}: the explicit shard_map "
                f"paths (grad_sync= quantized sync, zero= ZeRO sharded "
                f"update) require fully replicated parameters/optimizer "
                f"state (stage=0) — pick ONE sharding story per plan")

    def remesh(self, mesh: Mesh) -> "ShardingPlan":
        """Re-derive this plan over a DIFFERENT (usually smaller) mesh —
        the degraded-world path of coordinated elastic recovery
        (ISSUE 6): when a rank is abandoned and survivors re-form at the
        smaller world size, the same stage/rules/annotations are
        re-applied over the shrunk mesh. Axis names absent from (or
        trivial on) the new mesh fall out of every spec through the
        existing `_valid_axes`/`data_axes` filtering; a re-`materialize`
        (or the next TrainStep compile, which keys its cache on shapes
        and tree structure) then places arrays in the new layout.
        Returns a NEW plan; the original keeps serving the old mesh."""
        plan = ShardingPlan(mesh, stage=self.stage,
                            param_rules=dict(self.param_rules),
                            data_axes=self._requested_data_axes,
                            shard_min_size=self.shard_min_size,
                            grad_sync=self.grad_sync,
                            grad_sync_block=self.grad_sync_block,
                            grad_sync_error_feedback=self
                            .grad_sync_error_feedback,
                            zero=self.zero)
        plan.pspecs = dict(self.pspecs)
        if hasattr(self, "_pid_to_name"):
            plan._pid_to_name = dict(self._pid_to_name)
        return plan

    def attach_model(self, model):
        """Collect per-parameter PartitionSpec annotations (TP layouts set by
        mpu/model layers via p.pspec) and the id->name map used to mirror
        parameter layouts onto their optimizer moments."""
        self._pid_to_name = {}
        for name, p in model.state_dict().items():
            self._pid_to_name[id(p)] = name
            if getattr(p, "pspec", None) is not None:
                self.pspecs[name] = p.pspec
        return self

    # -- spec decisions -----------------------------------------------------
    def _fsdp_axis(self):
        return "sharding" if "sharding" in self.mesh.axis_names else None

    def _valid_axes(self, spec_entry):
        """Drop axis names absent from this mesh (model annotated mp but the
        mesh has no mp axis, etc.)."""
        if spec_entry is None:
            return None
        entries = spec_entry if isinstance(spec_entry, tuple) else (spec_entry,)
        kept = tuple(a for a in entries if a in self.mesh.axis_names
                     and self.mesh.shape[a] > 1)
        if not kept:
            return None
        return kept if len(kept) > 1 else kept[0]

    def param_spec(self, name: str, arr) -> P:
        for pat, spec in self.param_rules.items():
            if pat in name:
                return spec
        annotated = self.pspecs.get(name)
        base = ([self._valid_axes(e) for e in
                 tuple(annotated) + (None,) * (arr.ndim - len(tuple(annotated)))]
                if annotated is not None else [None] * arr.ndim)
        ax = self._fsdp_axis()
        if self.stage >= 3 and ax and self.mesh.shape[ax] > 1 and arr.ndim >= 1:
            # FSDP-shard largest still-unsharded dim (ZeRO-3 partitioning),
            # composed with any TP annotation
            used = {a for e in base if e is not None
                    for a in (e if isinstance(e, tuple) else (e,))}
            if ax not in used:
                order = sorted(range(arr.ndim), key=lambda i: -arr.shape[i])
                for d in order:
                    if base[d] is not None:
                        continue
                    if arr.shape[d] % self.mesh.shape[ax] == 0 and \
                            arr.size >= self.shard_min_size:
                        base[d] = ax
                        break
        return P(*base)

    def opt_spec(self, key, arr, param_specs: Dict[str, P]) -> P:
        """Moments mirror their parameter's layout (id-keyed optimizer state,
        ref DygraphShardingOptimizer partitioning); extra FSDP-sharding of
        moments is what stage>=1 (ZeRO-1/2) means here."""
        if arr.ndim == 0:
            return P()
        pid = key[0] if isinstance(key, tuple) else None
        pname = getattr(self, "_pid_to_name", {}).get(pid)
        if pname is not None and pname in param_specs:
            pspec = param_specs[pname]
            if len(tuple(pspec)) == arr.ndim or self.stage >= 3:
                base = [self._valid_axes(e) for e in
                        tuple(pspec) + (None,) * (arr.ndim - len(tuple(pspec)))]
            else:
                base = [None] * arr.ndim
        else:
            base = [None] * arr.ndim
        ax = self._fsdp_axis()
        if self.stage >= 1 and ax and self.mesh.shape[ax] > 1:
            used = {a for e in base if e is not None
                    for a in (e if isinstance(e, tuple) else (e,))}
            if ax not in used:
                order = sorted(range(arr.ndim), key=lambda i: -arr.shape[i])
                for d in order:
                    if base[d] is not None:
                        continue
                    if arr.shape[d] % self.mesh.shape[ax] == 0 and \
                            arr.size >= self.shard_min_size:
                        base[d] = ax
                        break
        return P(*base)

    def batch_spec(self, arr) -> P:
        if arr.ndim == 0 or not self.data_axes:
            return P()
        return P(self.data_axes if len(self.data_axes) > 1
                 else self.data_axes[0])

    def reshard_batch(self, tree):
        """Reshard COMMITTED jax.Array leaves of a collated batch onto
        this plan's batch shardings — the belt both sharded step paths
        (jit.TrainStep.__call__, Engine._compiled_forward) wear before
        calling an executable compiled with explicit batch in_shardings.

        A DataLoader prefetcher may hand over batches committed to a
        sharding that is not this plan's (the active-plan registration
        is latest-wins: a later unsharded TrainStep clears it, or
        staging started before this plan existed); pjit refuses
        committed args whose sharding differs from in_shardings. A
        matching commit is a no-op; numpy/uncommitted leaves are left
        for jit to place (on a multi-process mesh device_put of local
        data would fail where jit's replicated placement succeeds),
        and a failed reshard falls through to jit for the real error."""
        def leaf(a):
            if isinstance(a, jax.Array):
                sh = NamedSharding(self.mesh, self.batch_spec(a))
                if a.sharding != sh:
                    try:
                        return jax.device_put(a, sh)
                    except Exception:
                        return a
            return a
        return jax.tree_util.tree_map(leaf, tree)

    # -- multi-host entry ----------------------------------------------------
    def materialize(self, model, optimizer=None):
        """Place every model array (and primed optimizer state) as a
        GLOBAL jax.Array in its planned sharding. Required before
        TrainStep on a multi-PROCESS mesh: eagerly created params are
        committed to one local device, and jit cannot implicitly
        reshard a single-device array onto devices other processes own.
        device_put from host numpy (same value on every process, as all
        ranks seed identically) is the documented multi-host path.
        Harmless on single-process meshes (it just places arrays).
        Ref: fleet sharding init broadcast (group_sharded stage init)."""
        from ..tensor import Parameter

        def _already_global(a):
            # a re-materialize (second prepare(), or after training) sees
            # global arrays spanning other processes' devices; np.asarray
            # on those raises — they are already placed, leave them be
            return isinstance(a, jax.Array) and not a.is_fully_addressable

        self.attach_model(model)
        p_specs = {}
        for name, t in model.state_dict().items():
            is_param = isinstance(t, Parameter) and not t.stop_gradient
            if _already_global(t.data):
                if is_param:
                    p_specs[name] = self.param_spec(
                        name, np.empty(t.data.shape))
                continue
            arr = np.asarray(t.data)
            spec = self.param_spec(name, arr) if is_param else P()
            t.data = jax.device_put(arr, NamedSharding(self.mesh, spec))
            if is_param:
                p_specs[name] = spec
        if optimizer is not None:
            if hasattr(optimizer, "prime"):
                optimizer.prime()
            for k, v in list(optimizer._state.items()):
                if _already_global(v):
                    continue
                arr = np.asarray(v)
                optimizer._state[k] = jax.device_put(
                    arr, NamedSharding(self.mesh,
                                       self.opt_spec(k, arr, p_specs)))
            for k, v in list(getattr(optimizer, "_master_weights",
                                     {}).items()):
                if _already_global(v):
                    continue
                arr = np.asarray(v)
                pname = getattr(self, "_pid_to_name", {}).get(k, "")
                spec = (p_specs.get(pname)
                        or self.param_spec(pname, arr))
                optimizer._master_weights[k] = jax.device_put(
                    arr, NamedSharding(self.mesh, spec))
        return self

    # -- TrainStep hook ------------------------------------------------------
    def compile_train_step(self, pure, donate):
        mesh = self.mesh

        def shardings_for(tree, spec_fn):
            return jax.tree_util.tree_map(
                lambda a: NamedSharding(mesh, spec_fn(a)), tree)

        def _master_spec(self, k, v, p_specs):
            pname = getattr(self, "_pid_to_name", {}).get(k, "")
            if pname in p_specs and len(tuple(p_specs[pname])) <= v.ndim:
                return p_specs[pname]
            return self.param_spec(pname, v)

        def compiled_factory(params, buffers, opt_state, master,
                             scaler_state, step_i, lr, key, batch):
            p_specs = {k: self.param_spec(k, v) for k, v in params.items()}
            in_shardings = (
                {k: NamedSharding(mesh, p_specs[k]) for k in params},
                {k: NamedSharding(mesh, P()) for k in buffers},
                {k: NamedSharding(mesh, self.opt_spec(k, v, p_specs))
                 for k, v in opt_state.items()},
                {k: NamedSharding(mesh, _master_spec(self, k, v, p_specs))
                 for k, v in master.items()},
                {k: NamedSharding(mesh, P()) for k in scaler_state},
                NamedSharding(mesh, P()),
                NamedSharding(mesh, P()),
                NamedSharding(mesh, P()),
                jax.tree_util.tree_map(
                    lambda a: NamedSharding(mesh, self.batch_spec(a)), batch),
            )
            # optimizer state / master weights are created lazily INSIDE the
            # first step; only then can the output tree be wider than the
            # input tree — shape-infer it abstractly to get out_shardings.
            # In steady state (both populated) skip the extra trace.
            # fast path only when BOTH lazily-created dicts are populated
            # (a restored opt_state with masters still pending would make
            # the output tree wider than the inputs)
            if opt_state and master:
                out_shardings = (NamedSharding(mesh, P()),) + \
                    in_shardings[:5]
            else:
                out_abs = jax.eval_shape(pure, params, buffers, opt_state,
                                         master, scaler_state, step_i, lr,
                                         key, batch)
                _, p_abs, b_abs, os_abs, mw_abs, sc_abs = out_abs
                out_shardings = (
                    NamedSharding(mesh, P()),
                    {k: NamedSharding(mesh, p_specs[k]) for k in p_abs},
                    {k: NamedSharding(mesh, P()) for k in b_abs},
                    {k: NamedSharding(mesh, self.opt_spec(k, v, p_specs))
                     for k, v in os_abs.items()},
                    {k: NamedSharding(mesh, _master_spec(self, k, v, p_specs))
                     for k, v in mw_abs.items()},
                    {k: NamedSharding(mesh, P()) for k in sc_abs},
                )
            return jax.jit(pure, in_shardings=in_shardings,
                           out_shardings=out_shardings,
                           donate_argnums=donate)

        cache = {}

        def run(params, buffers, opt_state, master, scaler_state, step_i,
                lr, key, batch):
            struct = jax.tree_util.tree_structure(
                (params, buffers, opt_state, master, scaler_state, batch))
            shapes = tuple(
                (a.shape, str(a.dtype)) for a in
                jax.tree_util.tree_leaves((params, opt_state, batch)))
            sig = (struct, shapes)
            if sig not in cache:
                cache[sig] = compiled_factory(params, buffers, opt_state,
                                              master, scaler_state, step_i,
                                              lr, key, batch)
            # place inputs (no-op if already placed)
            return cache[sig](params, buffers, opt_state, master,
                              scaler_state, step_i, lr, key, batch)

        return run

    # -- quantized grad-sync TrainStep hook (ISSUE 8) -----------------------
    def quant_sync_axis(self):
        """(axis_name, size) of the single data-parallel mesh axis the
        explicit shard_map paths (quantized grad sync, ZeRO update)
        reduce over; raises when the plan has no (or more than one)
        non-trivial data axis — the chain's all_to_all/all_gather
        decomposition is built per axis."""
        axes = [a for a in self.data_axes if self.mesh.shape[a] > 1]
        if len(axes) != 1:
            raise ValueError(
                f"the explicit data-parallel shard_map paths (grad_sync=/"
                f"zero=) need exactly one data-parallel "
                f"mesh axis of size > 1, plan has {axes or 'none'} "
                f"(mesh {dict(self.mesh.shape)})")
        return axes[0], int(self.mesh.shape[axes[0]])

    # -- ZeRO sharded-update TrainStep hooks (arxiv 2004.13336) -------------
    def zero_armed(self) -> bool:
        """True when this plan opted into ZeRO AND the FLAGS_zero kill
        switch is up — the single arming predicate shared by TrainStep's
        build and the checkpoint layout conversion."""
        from ..framework import core as _core
        return bool(self.zero) and _core.get_bool_flag("FLAGS_zero", True)

    def zero_wire_config(self):
        """The CommQuantConfig the ZeRO grad reduce-scatter puts on the
        wire, or None for the exact psum_scatter path. Quantization
        needs BOTH the plan's grad_sync opt-in and the quant kill
        switch up (same arming as the pure grad_sync path)."""
        from ..framework import core as _core
        if self.grad_sync is None or \
                not _core.get_bool_flag("FLAGS_quant_collectives", True):
            return None
        from ..quantization import comm as _qcomm
        return _qcomm.resolve_config(self.grad_sync, self.grad_sync_block,
                                     self.grad_sync_error_feedback)

    def zero_block(self) -> int:
        """Block size of the flat shard layout: the quant block when the
        wire is quantized (payloads, EF residuals, and param/state
        shards must agree on one partitioning), else 1 (minimal
        padding)."""
        cfg = self.zero_wire_config()
        return cfg.block if cfg is not None else 1

    def zero_layout(self, numel: int):
        """(per_rank_shard, padded_total) of a numel-element tensor in
        this plan's flat ZeRO layout — quantization/comm.py's
        shard_sizes contract, padding at the tail."""
        from ..quantization import comm as _qcomm
        _axis, nranks = self.quant_sync_axis()
        return _qcomm.shard_sizes(int(numel), nranks, self.zero_block())

    def compile_quantized_train_step(self, pure_local, donate):
        """Compile the quantized-grad-sync step: `pure_local` is the
        PER-SHARD body (jit.TrainStep builds it — step_fn + backward +
        collective.grad_sync_all_reduce on every grad + update), wrapped
        here in shard_map over the plan's data axis so each shard sees
        its local batch slice and the explicit quantized chain replaces
        the implicit GSPMD psum. Params/optimizer state stay replicated
        (enforced); the error-feedback residual tree rides sharded on
        the sync axis (one per-rank residual slice each)."""
        from jax.experimental.shard_map import shard_map

        mesh = self.mesh
        axis, _n = self.quant_sync_axis()
        repl = NamedSharding(mesh, P())

        def _check_replicated(params):
            for name in params:
                spec = self.param_spec(name, params[name])
                if any(e is not None for e in tuple(spec)):
                    raise ValueError(
                        f"quantized grad sync requires fully replicated "
                        f"parameters, but {name!r} has layout {spec} — "
                        f"drop the TP annotation/param_rules or disable "
                        f"grad_sync")

        def compiled_factory(params, buffers, opt_state, master,
                             scaler_state, step_i, lr, key, batch, ef):
            _check_replicated(params)
            batch_specs = jax.tree_util.tree_map(
                lambda a: P(axis) if getattr(a, "ndim", 0) else P(), batch)
            ef_specs = jax.tree_util.tree_map(lambda a: P(axis), ef)
            in_specs = (P(), P(), P(), P(), P(), P(), P(), P(),
                        batch_specs, ef_specs)
            out_specs = (P(), P(), P(), P(), P(), P(), ef_specs)
            fn = shard_map(pure_local, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_rep=False)
            batch_sh = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), batch_specs)
            ef_sh = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), ef_specs)
            in_shardings = (
                {k: repl for k in params}, {k: repl for k in buffers},
                {k: repl for k in opt_state}, {k: repl for k in master},
                {k: repl for k in scaler_state}, repl, repl, repl,
                batch_sh, ef_sh)
            # opt_state/master can widen inside the first step (lazily
            # created slots) — shape-infer the output tree abstractly,
            # same reasoning as compile_train_step
            out_abs = jax.eval_shape(fn, params, buffers, opt_state,
                                     master, scaler_state, step_i, lr,
                                     key, batch, ef)
            _, p_abs, b_abs, os_abs, mw_abs, sc_abs, _ef_abs = out_abs
            out_shardings = (
                repl, {k: repl for k in p_abs}, {k: repl for k in b_abs},
                {k: repl for k in os_abs}, {k: repl for k in mw_abs},
                {k: repl for k in sc_abs}, ef_sh)
            return jax.jit(fn, in_shardings=in_shardings,
                           out_shardings=out_shardings,
                           donate_argnums=donate)

        cache = {}

        def run(params, buffers, opt_state, master, scaler_state, step_i,
                lr, key, batch, ef):
            struct = jax.tree_util.tree_structure(
                (params, buffers, opt_state, master, scaler_state, batch,
                 ef))
            shapes = tuple(
                (a.shape, str(a.dtype)) for a in
                jax.tree_util.tree_leaves((params, opt_state, batch)))
            sig = (struct, shapes)
            if sig not in cache:
                cache[sig] = compiled_factory(params, buffers, opt_state,
                                              master, scaler_state, step_i,
                                              lr, key, batch, ef)
            return cache[sig](params, buffers, opt_state, master,
                              scaler_state, step_i, lr, key, batch, ef)

        return run

    def compile_zero_train_step(self, pure_local, donate):
        """Compile the ZeRO sharded-update step: `pure_local` is the
        PER-SHARD body (jit.TrainStep builds it — step_fn + backward +
        collective.zero_grad_reduce_scatter + per-shard optimizer
        update + collective.zero_param_all_gather), wrapped here in
        shard_map over the plan's data axis. Params stay replicated
        (enforced) but OPTIMIZER STATE rides sharded on the sync axis:
        each state slot is a flat (s*nranks,)-padded vector of which
        every rank materializes only its own (s,)-slice — the HBM win.
        The error-feedback residual tree (quantized wire only) rides
        sharded exactly as in the grad_sync path."""
        from jax.experimental.shard_map import shard_map

        mesh = self.mesh
        axis, _n = self.quant_sync_axis()
        repl = NamedSharding(mesh, P())
        shax = NamedSharding(mesh, P(axis))

        def _check_replicated(params):
            for name in params:
                spec = self.param_spec(name, params[name])
                if any(e is not None for e in tuple(spec)):
                    raise ValueError(
                        f"the ZeRO sharded update requires fully "
                        f"replicated parameters, but {name!r} has layout "
                        f"{spec} — drop the TP annotation/param_rules or "
                        f"set zero=0")

        def compiled_factory(params, buffers, opt_state, master,
                             scaler_state, step_i, lr, key, batch, ef):
            _check_replicated(params)
            batch_specs = jax.tree_util.tree_map(
                lambda a: P(axis) if getattr(a, "ndim", 0) else P(), batch)
            ef_specs = jax.tree_util.tree_map(lambda a: P(axis), ef)
            os_specs = {k: P(axis) for k in opt_state}
            in_specs = (P(), P(), os_specs, P(), P(), P(), P(), P(),
                        batch_specs, ef_specs)
            # opt_state widens inside the first step (slots created
            # lazily PER-SHARD — priming would allocate the full-size
            # state the mode exists to avoid), so the out tree is only
            # known abstractly; P(axis) as a spec PREFIX covers every
            # slot the body creates
            out_specs = (P(), P(), P(), P(axis), P(), P(), ef_specs)
            fn = shard_map(pure_local, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_rep=False)
            batch_sh = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), batch_specs)
            ef_sh = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), ef_specs)
            in_shardings = (
                {k: repl for k in params}, {k: repl for k in buffers},
                {k: shax for k in opt_state}, {k: repl for k in master},
                {k: repl for k in scaler_state}, repl, repl, repl,
                batch_sh, ef_sh)
            out_abs = jax.eval_shape(fn, params, buffers, opt_state,
                                     master, scaler_state, step_i, lr,
                                     key, batch, ef)
            _, p_abs, b_abs, os_abs, mw_abs, sc_abs, _ef_abs = out_abs
            out_shardings = (
                repl, {k: repl for k in p_abs}, {k: repl for k in b_abs},
                {k: shax for k in os_abs}, {k: repl for k in mw_abs},
                {k: repl for k in sc_abs}, ef_sh)
            return jax.jit(fn, in_shardings=in_shardings,
                           out_shardings=out_shardings,
                           donate_argnums=donate)

        cache = {}

        def run(params, buffers, opt_state, master, scaler_state, step_i,
                lr, key, batch, ef):
            struct = jax.tree_util.tree_structure(
                (params, buffers, opt_state, master, scaler_state, batch,
                 ef))
            shapes = tuple(
                (a.shape, str(a.dtype)) for a in
                jax.tree_util.tree_leaves((params, opt_state, batch)))
            sig = (struct, shapes)
            if sig not in cache:
                cache[sig] = compiled_factory(params, buffers, opt_state,
                                              master, scaler_state, step_i,
                                              lr, key, batch, ef)
            return cache[sig](params, buffers, opt_state, master,
                              scaler_state, step_i, lr, key, batch, ef)

        return run


def convert_zero_opt_state(saved, optimizer, plan=None):
    """Re-layout a checkpointed optimizer state dict across ZeRO worlds.

    ZeRO state checkpoints as flat (s*nranks,)-padded vectors (padding
    at the TAIL — quantization/comm.py's shard_sizes contract), each
    rank persisting only its own slice through dist_ckpt v2; dist_ckpt's
    tiling verification reassembles them on load. The flat length is
    world-size dependent, so restoring onto a different world (or back
    onto a replicated/FLAGS_zero=0 run) needs this conversion:

      * strip the tail padding of each slot (``ravel()[:numel]`` is
        layout-invariant — replicated param-shaped state passes through
        unchanged),
      * re-pad/re-place for the TARGET: `plan` with an armed zero mode
        re-pads to the new world's layout and shards it on the plan's
        data axis; plan=None (or zero off/disarmed) reshapes back to
        the param's own shape for the replicated update paths.

    `saved` maps optimizer state_dict() keys ("{param_name}.{slot}") to
    Tensors/arrays; returns a same-keyed dict ready for
    optimizer.set_state_dict(). Non-tensor entries ("@step",
    "LR_Scheduler") pass through untouched."""
    from ..tensor import Tensor as _T
    to_zero = plan is not None and plan.zero_armed()
    if to_zero:
        axis, nranks = plan.quant_sync_axis()
        target_sh = NamedSharding(plan.mesh, P(axis))
    prefix_map = {}
    for i, p in enumerate(optimizer._parameter_list):
        prefix_map.setdefault(f"{p.name or i}.", p)
    out = {}
    for k, v in saved.items():
        p = None
        if isinstance(k, str):
            pos = k.find(".")
            while pos != -1 and p is None:
                p = prefix_map.get(k[:pos + 1])
                pos = k.find(".", pos + 1)
        if p is None:
            out[k] = v
            continue
        arr = np.asarray(v.data if isinstance(v, _T) else v)
        numel = int(p.data.size)
        flat = arr.ravel()[:numel]
        if to_zero:
            s, padded = plan.zero_layout(numel)
            out[k] = jax.device_put(
                np.pad(flat, (0, padded - numel)), target_sh)
        else:
            out[k] = jnp.asarray(flat.reshape(p.data.shape))
    return out
