"""Collective desync watchdog (ref: phi/core/distributed/
comm_task_manager.cc CommTaskManager — a monitor thread that times every
in-flight NCCL task and warns/aborts when one exceeds
FLAGS_comm_timeout, catching rank desyncs and hangs).

TPU-native: there are no per-collective launches to time — a whole
compiled step is the scheduling unit, and a desynced/preempted peer
manifests as the step (or the jax.distributed barrier) never returning.
The watchdog therefore times *steps*: wrap the step callable (or use the
context manager), and a daemon monitor fires if completion doesn't land
within the timeout — logging the stage name, elapsed time, and rank, and
optionally aborting the process so the launch layer's elastic restart
(distributed/elastic.py) can take over, exactly the role the reference's
abort path plays."""
from __future__ import annotations

import contextlib
import os
import threading
import time
import warnings
from typing import Callable, Optional

from ..framework import core
from ..observability import metrics as _m

__all__ = ["CommWatchdog", "watch", "watched_step"]

def _default_timeout() -> float:
    """Resolved at watchdog CONSTRUCTION, not import: registered default
    in framework/core.py, overridable by paddle.set_flags at any point
    before the watchdog is built, and by the FLAGS_comm_timeout env var
    (get_flag prefers env)."""
    return float(core.get_flag("FLAGS_comm_timeout", 1800.0))

_WD_TIMEOUTS = _m.counter("watchdog.timeouts_total",
                          "watchdog sections that overran their timeout")


def _suspect_peers() -> str:
    """Under a supervising launcher (PADDLE_ELASTIC_SUPERVISED), ask the
    elastic master which expected ranks have NO fresh heartbeat — the
    likely culprits behind a hung step. Bounded (2s) and best-effort:
    the monitor thread must fire its warning/abort regardless. Returns
    '' when unsupervised or nothing is known."""
    if not os.environ.get("PADDLE_ELASTIC_SUPERVISED"):
        return ""
    try:
        from .collective import _membership_client
        status, info = _membership_client()._call(("hbar",), timeout_s=2.0)
        if status == "ok" and info.get("missing"):
            return (f"; elastic master reports rank(s) "
                    f"{info['missing']} with no fresh heartbeat "
                    f"(generation {info.get('gen')})")
        if status == "ok":
            return (f"; elastic master reports all expected ranks alive "
                    f"(generation {info.get('gen')}) — suspect a "
                    f"data/compile stall, not a dead peer")
    except Exception:
        pass
    return ""


class CommWatchdog:
    """Times named critical sections; fires on overrun.

    on_timeout: 'warn' (log and keep waiting) or 'abort' (os._exit(101) —
    the reference's faulted-worker exit code, which the elastic launch
    layer treats as relaunch-me)."""

    FAULT_EXIT_CODE = 101          # ref: fleet/elastic/manager.py:32

    def __init__(self, timeout: Optional[float] = None,
                 on_timeout: str = "warn",
                 logger: Optional[Callable[[str], None]] = None,
                 on_fire: Optional[Callable[[str, float], None]] = None):
        self.timeout = timeout if timeout is not None else \
            _default_timeout()
        self.on_timeout = on_timeout
        # observability hook (name, elapsed_s) — ElasticManager/chaos
        # tests count conversions of hangs into restarts through this
        self.on_fire = on_fire
        self._log = logger or (lambda msg: warnings.warn(
            msg, RuntimeWarning))
        self._lock = threading.Lock()
        self._active = {}          # (name, token) -> start time
        self._fired = set()
        self._token = 0
        self._stop = threading.Event()
        self._thread = None
        self.timeouts = 0          # observable for tests/telemetry

    # -- monitor ----------------------------------------------------------
    def _ensure_monitor(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="paddle-comm-watchdog")
            self._thread.start()

    def _loop(self):
        while not self._stop.wait(min(self.timeout / 10.0, 5.0)):
            now = time.monotonic()
            with self._lock:
                overdue = [(key, now - t0)
                           for key, t0 in self._active.items()
                           if now - t0 > self.timeout
                           and key not in self._fired]
                for key, _ in overdue:
                    self._fired.add(key)
            for (name, _tok), elapsed in overdue:
                self.timeouts += 1
                _WD_TIMEOUTS.inc(1, section=name)
                rank = os.environ.get("PADDLE_TRAINER_ID", "0")
                msg = (f"[CommWatchdog] step '{name}' has not completed "
                       f"after {elapsed:.0f}s (timeout {self.timeout:.0f}s) "
                       f"on rank {rank} — likely peer desync, preemption, "
                       "or a hung collective")
                # ISSUE 6: under a supervising launcher, consult the
                # elastic master's health view so the hang converts to a
                # DETECTED failure naming the dead peer(s) in the log
                # and flight dump (disarmed: one env lookup). One poll,
                # reused — a slow master must not double its bounded
                # stall in the monitor thread.
                suspects = _suspect_peers()
                msg += suspects
                self._log(msg)
                # post-mortem artifact BEFORE any abort: a hung trainer
                # leaves a flight-recorder dump naming the stuck section,
                # the open spans and the metric state at death
                try:
                    from ..observability.export import flight_dump
                    flight_dump(f"watchdog:{name} after {elapsed:.0f}s "
                                f"(timeout {self.timeout:.0f}s, "
                                f"rank {rank}){suspects}")
                except Exception:
                    pass    # telemetry must not kill the monitor
                if self.on_fire is not None:
                    try:
                        self.on_fire(name, elapsed)
                    except Exception:
                        pass    # a broken hook must not kill the monitor
                if self.on_timeout == "abort":
                    os._exit(self.FAULT_EXIT_CODE)

    def add_on_fire(self, cb: Callable[[str, float], None]) -> None:
        """Chain an ADDITIONAL fire hook after any existing one(s); each
        hook is isolated (one raising does not skip the rest). ISSUE 13
        wires `collective.abort` here so a survivor parked in a
        host-channel collective is interrupted in watchdog-bounded (not
        comm-timeout-bounded) time when the step overruns."""
        prev = self.on_fire
        if prev is None:
            self.on_fire = cb
            return

        def chained(name, elapsed, _prev=prev, _cb=cb):
            try:
                _prev(name, elapsed)
            except Exception:
                pass        # a broken hook must not starve the next one
            _cb(name, elapsed)

        self.on_fire = chained

    # -- section API -------------------------------------------------------
    @contextlib.contextmanager
    def section(self, name: str = "step"):
        self._ensure_monitor()
        with self._lock:
            self._token += 1
            key = (name, self._token)   # unique: concurrent/nested same-
            self._active[key] = time.monotonic()  # name sections tracked
        # armed telemetry: the watched section is a span, so a firing
        # watchdog's flight dump names it among the open spans
        from ..observability.spans import span as _span
        try:                                      # independently
            with _span("watchdog." + name):
                yield
        finally:
            with self._lock:
                self._active.pop(key, None)
                self._fired.discard(key)

    def wrap(self, fn: Callable, name: Optional[str] = None) -> Callable:
        """Wrap a step callable so every invocation is watched."""
        label = name or getattr(fn, "__name__", "step")

        def watched(*args, **kwargs):
            with self.section(label):
                out = fn(*args, **kwargs)
                # block so the watchdog sees true completion, not async
                # dispatch (a hung collective otherwise "returns" a future)
                try:
                    import jax
                except ImportError:
                    return out
                # runtime errors (failed collective, OOM) must propagate —
                # only a missing jax is ignorable. Unwrap Tensor wrappers
                # everywhere in the structure: block_until_ready silently
                # skips unknown leaf types, which would let a hung step
                # slip past the watchdog.
                jax.block_until_ready(jax.tree.map(
                    lambda t: t.data if hasattr(t, "data") else t, out))
                return out

        watched.__name__ = f"watched_{label}"
        return watched

    def shutdown(self):
        self._stop.set()


_global: Optional[CommWatchdog] = None


def watch(timeout: Optional[float] = None, on_timeout: Optional[str] = None):
    """Module-level singleton accessor (ref CommTaskManager::GetInstance).
    Explicitly passed settings update the live instance — later callers
    are not silently stuck with the first caller's configuration."""
    global _global
    if _global is None:
        _global = CommWatchdog(
            timeout=timeout, on_timeout=on_timeout or "warn")
    else:
        if timeout is not None:
            _global.timeout = timeout
        if on_timeout is not None:
            _global.on_timeout = on_timeout
    return _global


def _reset_global():  # test hook
    global _global
    if _global is not None:
        _global.shutdown()
    _global = None


def watched_step(fn: Callable, timeout: Optional[float] = None,
                 on_timeout: Optional[str] = None) -> Callable:
    """Convenience: wrap a TrainStep/step function with the global
    watchdog."""
    return watch(timeout, on_timeout).wrap(fn)
