"""Megatron-style sequence parallelism composed with tensor parallelism
(ref: python/paddle/distributed/fleet/utils/sequence_parallel_utils.py:229
ColumnSequenceParallelLinear, :339 RowSequenceParallelLinear, :191
register_sequence_parallel_allreduce_hooks; ScatterOp/GatherOp :33,:75).

TPU-native translation: Megatron-SP shards the ACTIVATIONS along the
sequence dim over the same device group as tensor parallelism (`mp` axis),
so the layernorm/dropout segments between TP blocks hold S/mp tokens per
device; entering a column-parallel matmul requires an all-gather of the
sequence, and leaving a row-parallel matmul emits a reduce-scatter instead
of the plain TP all-reduce (same total bytes, but the activation memory
between blocks is 1/mp).

Under GSPMD all four comm ops are DERIVED: these layers annotate the
sequence dim of their inputs/outputs with `mp` via sharding constraints and
XLA inserts the all-gather / reduce-scatter pairs during SPMD propagation.
The reference's hand-written autograd pairs (allgather fwd <-> reduce-
scatter bwd) fall out of the constraint's transpose. Layout convention is
[batch, seq, hidden] (this framework's convention; the reference uses
seq-major [s, b, h] — axis index differs, semantics identical).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ....nn import functional as F
from ....nn import initializer as I
from ....nn.layer.layers import Layer
from ....ops._helpers import to_tensor_like
from ...sharding import with_partial_annotation

__all__ = [
    "ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
    "scatter", "all_gather", "mark_as_sequence_parallel_parameter",
    "is_sequence_parallel_parameter",
    "register_sequence_parallel_allreduce_hooks",
    "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
    "create_fused_allreduce_gradient_hooks",
]

_SEQ_AXIS = 1  # [batch, seq, hidden]


def _act_spec(shape, seq_axis=None):
    """Full activation layout: batch over the data axes (those dividing
    the batch size — eager constraints require divisibility), seq over
    `mp` (when seq_axis given), rest replicated. Fully specified so the
    checkpointed backward reshards along the SAME layout instead of
    triggering GSPMD's replicate-everything fallback (driver dryrun
    '[SPMD] Involuntary full rematerialization' warning)."""
    from ...sharding import data_axes_for
    nd = len(shape)
    spec = [None] * nd
    if nd > 0:
        da = data_axes_for(int(shape[0]))
        if da:
            spec[0] = da
    if seq_axis is not None:
        spec[seq_axis] = "mp"
    return P(*spec)


def scatter(x, axis=_SEQ_AXIS):
    """Shard the sequence dim over `mp` (ref ScatterOp: split + keep own
    shard; here a resharding constraint)."""
    return with_partial_annotation(x, _act_spec(x.shape, seq_axis=axis))


def all_gather(x, axis=_SEQ_AXIS):
    """Re-replicate the sequence dim (ref GatherOp / AllGatherOp)."""
    return with_partial_annotation(x, _act_spec(x.shape))


# reference class-style aliases (autograd pairs are implicit here)
class ScatterOp:
    apply = staticmethod(scatter)


class GatherOp:
    apply = staticmethod(all_gather)


class AllGatherOp:
    apply = staticmethod(all_gather)


class ReduceScatterOp:
    apply = staticmethod(scatter)


def mark_as_sequence_parallel_parameter(parameter):
    """ref :178 — marks params whose grads the reference must all-reduce
    over the mp group (layernorm weights acting on seq-sharded acts).
    Under single-controller GSPMD gradients are global already; kept as a
    tag for introspection/parity."""
    parameter.sequence_parallel = True


def is_sequence_parallel_parameter(parameter):
    return getattr(parameter, "sequence_parallel", False)


def register_sequence_parallel_allreduce_hooks(layer, accumulation_steps=1,
                                               fuse=False):
    """ref :191 — no-op under GSPMD (grad allreduce is derived); kept for
    API parity."""
    return None


def create_fused_allreduce_gradient_hooks(parameters, accumulation_steps=1):
    return None


class ColumnSequenceParallelLinear(Layer):
    """ref :229. Input arrives sequence-sharded over `mp`; the weight is
    column-sharded. The all-gather of the sequence before the matmul (and
    its reduce-scatter transpose in backward) is derived by GSPMD from the
    input/output constraints."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.pspec = P(None, "mp")
        self.bias = (self.create_parameter((out_features,), is_bias=True)
                     if has_bias else None)
        if self.bias is not None:
            self.bias.pspec = P("mp")

    def forward(self, x):
        x = to_tensor_like(x)
        x = scatter(x)                       # assert/restore seq sharding
        out = F.linear(x, self.weight, self.bias)
        nd = out.ndim
        if self.gather_output:
            out = with_partial_annotation(out, _act_spec(out.shape))
        else:
            spec = list(_act_spec(out.shape))
            spec[-1] = "mp"
            out = with_partial_annotation(out, P(*spec))
        return out


class RowSequenceParallelLinear(Layer):
    """ref :339. Input is hidden-sharded (from a column-parallel block);
    output is REDUCE-SCATTERED along the sequence dim over `mp` instead of
    all-reduced — the constraint on the output derives exactly that."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.pspec = P("mp", None)
        self.bias = (self.create_parameter((out_features,), is_bias=True)
                     if has_bias else None)

    def forward(self, x):
        x = to_tensor_like(x)
        spec = list(_act_spec(x.shape))
        spec[-1] = "mp"
        x = with_partial_annotation(x, P(*spec))
        out = F.linear(x, self.weight, self.bias)
        return scatter(out)                  # seq-sharded output
