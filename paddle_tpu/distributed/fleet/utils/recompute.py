"""Activation recompute / checkpointing (ref: python/paddle/distributed/
fleet/utils/recompute.py — recompute(function, *args) re-runs the
function's forward during backward instead of storing activations;
recompute_sequential applies it per segment).

TPU-native: `jax.checkpoint` IS this feature at the XLA level. A Layer's
parameters are closure state the tape can't see, so the wrapper runs the
Layer functionally (use_state, the same pattern as jit.save): parameters
become explicit tape args, the whole segment body is one checkpointed op,
and grads flow to both inputs and parameters while the segment's
intermediate activations are rematerialized on backward.
preserve_rng_state is inherent — the tape threads RNG keys functionally,
so the recomputed forward sees identical randomness."""
from __future__ import annotations

import jax

from ....autograd.tape import apply_op
from ....framework import core
from ....tensor import Tensor

__all__ = ["recompute", "recompute_sequential"]


def recompute(function, *args, use_reentrant: bool = True,
              preserve_rng_state: bool = True, **kwargs):
    """ref: fleet/utils/recompute.py::recompute(function, *args).
    `function` is typically a Layer (its parameters get gradients); a
    plain callable works too when it only closes over constants."""
    tensor_pos = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    inputs = [args[i] for i in tensor_pos]
    for k, v in kwargs.items():
        if isinstance(v, Tensor) and not v.stop_gradient:
            raise ValueError(
                f"recompute: differentiable Tensor kwarg '{k}' would be "
                "closed over and receive no gradient — pass it "
                "positionally")

    is_layer = hasattr(function, "state_dict") and hasattr(function,
                                                           "use_state")
    if is_layer:
        sd = function.state_dict()
        keys = list(sd.keys())
        param_tensors = list(sd.values())
    else:
        keys, param_tensors = [], []
    n_params = len(param_tensors)

    def arr_fn(*arrays):
        p_arrays = arrays[:n_params]
        in_arrays = arrays[n_params:]
        it = iter(in_arrays)
        call_args = [Tensor(next(it)) if i in tensor_pos else a
                     for i, a in enumerate(args)]

        def run():
            out = function(*call_args, **kwargs)
            if isinstance(out, Tensor):
                return out.data
            if isinstance(out, (list, tuple)):
                return tuple(o.data if isinstance(o, Tensor) else o
                             for o in out)
            return out

        if is_layer:
            # functional state + no inner tape: the OUTER vjp over this
            # op differentiates params and inputs together
            with function.use_state(dict(zip(keys, p_arrays))), \
                    core.no_grad_guard():
                return run()
        with core.no_grad_guard():
            return run()

    ckpt = jax.checkpoint(arr_fn)
    datas = [t.data for t in param_tensors] + [t.data for t in inputs]
    out_aval = jax.eval_shape(arr_fn, *datas)
    n_out = len(out_aval) if isinstance(out_aval, tuple) else 1
    return apply_op(ckpt, *param_tensors, *inputs, n_outputs=n_out,
                    name="recompute")


def recompute_sequential(ctx: dict, functions, *args, **kwargs):
    """ref: recompute_sequential — run a Sequential's sublayers in
    `segments` chunks, each chunk one recomputed segment."""
    from ....nn import Sequential

    segments = int((ctx or {}).get("segments", 1))
    layers = list(functions)
    n_seg = max(min(segments, len(layers)), 1)
    per = -(-len(layers) // n_seg)        # ceil: at most `segments` chunks
    chunks = [layers[i:i + per] for i in range(0, len(layers), per)]

    out = args
    for chunk in chunks:
        seg = chunk[0] if len(chunk) == 1 else Sequential(*chunk)
        res = recompute(seg, *out, **kwargs)
        out = res if isinstance(res, tuple) else (res,)
    return out if len(out) > 1 else out[0]
