"""fleet.utils (ref: python/paddle/distributed/fleet/utils/)."""
from . import sequence_parallel_utils  # noqa: F401
from .recompute import recompute, recompute_sequential  # noqa: F401

__all__ = ["sequence_parallel_utils", "recompute", "recompute_sequential"]
