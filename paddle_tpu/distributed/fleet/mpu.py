"""Tensor-parallel (model-parallel) layers — the mpu layer set
(ref: python/paddle/distributed/fleet/layers/mpu/mp_layers.py:46
VocabParallelEmbedding, :335 ColumnParallelLinear, :542 RowParallelLinear,
:743 ParallelCrossEntropy; comm prims mp_ops.py:83,126,285).

TPU-native: the reference materializes per-rank weight shards and inserts
explicit c_identity/c_concat/mp_allreduce collectives. Under GSPMD the
layers hold the FULL logical weight annotated with a PartitionSpec over the
`mp` mesh axis; XLA partitions the weight and inserts the matching ICI
collectives (all-reduce after row-parallel, all-gather for gather_output)
during SPMD propagation. Rank-local arithmetic, weight slicing, and the
identity/allreduce autograd pairs all disappear.

The layers stay meaningful on a 1-device mesh (specs become no-ops), so
model code is portable across parallel configs — same property the
reference achieves via world_size==1 fallbacks (mp_layers.py:120 etc.).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...autograd.tape import apply_op
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer.layers import Layer
from ...ops._helpers import to_tensor_like
from ..sharding import with_partial_annotation
from ..topology import get_hybrid_communicate_group

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy",
           "get_rng_state_tracker", "RNGStatesTracker", "split"]


def _mp_degree():
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        return 1
    return hcg.get_model_parallel_world_size()


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over `mp`
    (ref mp_layers.py:46). GSPMD turns the gather into a masked local
    lookup + allreduce — the same algorithm the reference hand-codes."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        self.weight.pspec = P("mp", None)

    def forward(self, x):
        return apply_op(
            lambda ids, w: jnp.take(w, ids.astype(jnp.int32), axis=0),
            to_tensor_like(x), self.weight, name="vocab_parallel_embedding")


class ColumnParallelLinear(Layer):
    """Linear with out_features sharded over `mp` (ref mp_layers.py:335).
    gather_output=True re-replicates the activation (reference: c_concat)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.pspec = P(None, "mp")
        self.bias = (self.create_parameter((out_features,), is_bias=True)
                     if has_bias else None)
        if self.bias is not None:
            self.bias.pspec = P("mp")

    def forward(self, x):
        out = F.linear(to_tensor_like(x), self.weight, self.bias)
        if self.gather_output:
            out = with_partial_annotation(out, P(*([None] * out.ndim)))
        return out


class RowParallelLinear(Layer):
    """Linear with in_features sharded over `mp` (ref mp_layers.py:542).
    The partial-sum allreduce the reference emits by hand is inserted by
    GSPMD when the contraction crosses the sharded dim."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.pspec = P("mp", None)
        self.bias = (self.create_parameter((out_features,), is_bias=True)
                     if has_bias else None)

    def forward(self, x):
        return F.linear(to_tensor_like(x), self.weight, self.bias)


class ParallelCrossEntropy(Layer):
    """CE over mp-sharded logits (ref mp_layers.py:743). The reference
    computes a rank-local max/logsumexp then allreduces; GSPMD derives the
    identical schedule from the plain logsumexp formulation."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """ref: paddle.distributed.split (mp_ops.py:700) — builds the matching
    parallel layer. Kept for API parity."""
    if operation == "embedding":
        lyr = VocabParallelEmbedding(size[0], size[1], weight_attr)
    elif axis == 1:
        lyr = ColumnParallelLinear(size[0], size[1], weight_attr,
                                   has_bias=bias_attr is not False,
                                   gather_output=gather_out)
    else:
        lyr = RowParallelLinear(size[0], size[1], weight_attr,
                                has_bias=bias_attr is not False)
    return lyr(x)


class RNGStatesTracker:
    """ref: fleet/layers/mpu/random.py get_rng_state_tracker. On TPU the
    global PRNG key is threaded through compiled programs; mp ranks see the
    SAME key (replicated), so dropout masks agree across TP shards without
    per-rank seed juggling. The tracker survives as an API shim that forks
    named keys for local-parallel regions."""

    def __init__(self):
        self.states_ = {}

    def add(self, name, seed):
        import jax
        self.states_[name] = jax.random.PRNGKey(seed)

    def rng_state(self, name="model_parallel_rng"):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            from ...framework import core
            if name in self.states_:
                with core.rng_key_context(self.states_[name]):
                    yield
            else:
                yield
        return ctx()

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    import jax
    _RNG_STATE_TRACKER.states_ = {}
    _RNG_STATE_TRACKER.add("model_parallel_rng", seed or 0)
