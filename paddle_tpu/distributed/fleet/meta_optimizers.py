"""Meta-optimizers (ref: python/paddle/distributed/fleet/meta_optimizers/ —
GradientMergeOptimizer, LocalSGDOptimizer, DGCOptimizer; selected by
DistributedStrategy flags in fleet.distributed_optimizer).

TPU-native: each is an optimizer wrapper over the eager tape/TrainStep
path. Gradient merge accumulates host-side like the reference's
@GRAD@MERGED vars; LocalSGD averages parameters across the data-parallel
world every k steps (collective all_reduce — a no-op single-process,
where GSPMD already globalizes the batch); DGC does top-k gradient
sparsification with momentum correction + residual accumulation.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

__all__ = ["GradientMergeOptimizer", "LocalSGDOptimizer",
           "DGCMomentumOptimizer"]


class _Wrapper:
    """Delegate the Optimizer surface to the inner optimizer."""

    def __init__(self, inner):
        self._inner = inner
        self._parameter_list = inner._parameter_list

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def clear_grad(self, set_to_zero=True):
        self._inner.clear_grad(set_to_zero)

    clear_gradients = clear_grad


class GradientMergeOptimizer(_Wrapper):
    """ref: meta_optimizers/gradient_merge_optimizer.py — accumulate k
    micro-batches of gradients, apply once (avg=True divides by k)."""

    def __init__(self, inner, k_steps: int = 1, avg: bool = True):
        super().__init__(inner)
        self.k_steps = int(k_steps)
        self.avg = avg
        self._acc = {}
        self._count = 0

    def step(self):
        self._count += 1
        for p in self._parameter_list:
            if p.grad is None:
                continue
            g = p.grad.data if hasattr(p.grad, "data") else p.grad
            pid = id(p)
            self._acc[pid] = (g if pid not in self._acc
                              else self._acc[pid] + g)
        if self._count < self.k_steps:
            return  # merged step not yet due
        scale = 1.0 / self.k_steps if self.avg else 1.0
        from ...tensor import Tensor
        for p in self._parameter_list:
            pid = id(p)
            if pid in self._acc:
                p.grad = Tensor(self._acc[pid] * scale)
        self._inner.step()
        self._acc.clear()
        self._count = 0

    def clear_grad(self, set_to_zero=True):
        # per-micro-batch clear; merged accumulators persist
        self._inner.clear_grad(set_to_zero)


class LocalSGDOptimizer(_Wrapper):
    """ref: meta_optimizers/localsgd_optimizer.py — run k local steps,
    then average parameters across the dp world. Under a multi-process
    launch the averaging is a real cross-host collective; single-process
    it's the identity (GSPMD covers in-mesh dp)."""

    def __init__(self, inner, k_steps: int = 1):
        super().__init__(inner)
        self.k_steps = int(k_steps)
        self._count = 0

    def step(self):
        self._inner.step()
        self._count += 1
        if self._count % self.k_steps:
            return
        from ...framework import core
        from .. import env
        world = env.get_world_size()
        if world <= 1:
            return
        from ..collective import all_reduce
        for p in self._parameter_list:
            avg = all_reduce(p, op="avg")
            p.set_value(avg if not hasattr(avg, "data") else avg)


class DGCMomentumOptimizer(_Wrapper):
    """ref: meta_optimizers/dgc_optimizer.py + fluid DGCMomentumOptimizer —
    Deep Gradient Compression: momentum correction + residual accumulation
    with top-k sparsification. The dense update uses the inner optimizer's
    rule on the sparsified gradient."""

    def __init__(self, inner, momentum: float = 0.9,
                 rampup_begin_step: int = 0, sparsity: float = 0.999):
        super().__init__(inner)
        self.momentum = float(momentum)
        self.rampup_begin_step = int(rampup_begin_step)
        self.sparsity = float(sparsity)
        self._u = {}       # velocity (momentum correction)
        self._e = {}       # residual accumulator
        self._steps = 0

    def _sparsify(self, e):
        flat = jnp.abs(e).ravel()
        k = max(int(flat.size * (1.0 - self.sparsity)), 1)
        thresh = jnp.sort(flat)[-k]
        mask = jnp.abs(e) >= thresh
        return e * mask, mask

    def step(self):
        self._steps += 1
        if self._steps <= self.rampup_begin_step:
            self._inner.step()
            return
        from ...tensor import Tensor
        for p in self._parameter_list:
            if p.grad is None:
                continue
            g = p.grad.data if hasattr(p.grad, "data") else p.grad
            pid = id(p)
            u = self._u.get(pid)
            u = g if u is None else self.momentum * u + g
            e = self._e.get(pid)
            e = u if e is None else e + u
            sparse, mask = self._sparsify(e)
            self._u[pid] = u * (~mask)      # momentum factor masking
            self._e[pid] = e * (~mask)      # residual keeps the unsent part
            p.grad = Tensor(sparse)
        self._inner.step()
