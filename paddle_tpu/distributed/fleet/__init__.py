"""Fleet facade (ref: python/paddle/distributed/fleet/fleet.py:167 init,
model.py:32 distributed_model, hybrid_parallel_optimizer.py:254).
"""
from __future__ import annotations

from typing import Optional

from ..topology import (HybridCommunicateGroup, get_hybrid_communicate_group,
                        set_hybrid_communicate_group)
from . import layers  # noqa: F401
from . import meta_parallel  # noqa: F401
from . import mpu  # noqa: F401

__all__ = ["init", "DistributedStrategy", "distributed_model",
           "distributed_optimizer", "get_hybrid_communicate_group",
           "worker_index", "worker_num", "layers", "meta_parallel", "mpu",
           "UserDefinedRoleMaker", "Role", "is_server", "is_worker"]


class Role:
    """ref: fleet/base/role_maker.py Role enum."""
    WORKER = 1
    SERVER = 2


class UserDefinedRoleMaker:
    """ref: fleet/base/role_maker.py UserDefinedRoleMaker — explicit PS
    topology (server endpoints + this process's role)."""

    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None):
        self.current_id = current_id
        self.role = role
        self._worker_num = worker_num
        self.server_endpoints = server_endpoints or []

    def is_server(self):
        return self.role == Role.SERVER

    def is_worker(self):
        return self.role == Role.WORKER

    def worker_num(self):
        return self._worker_num


class DistributedStrategy:
    """ref: fleet/base/distributed_strategy.py (protobuf-backed there;
    a plain config object here — XLA removes most pass toggles)."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1, "ep_degree": 1,
        }
        self.sharding_configs = {"stage": 1}
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.pipeline_configs = {"accumulate_steps": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.localsgd = False
        self.localsgd_configs = {}
        self.dgc = False
        self.dgc_configs = {}
        self.find_unused_parameters = False


_fleet_initialized = False
_strategy: Optional[DistributedStrategy] = None


_role_maker: Optional[UserDefinedRoleMaker] = None


def is_server():
    return _role_maker is not None and _role_maker.is_server()


def is_worker():
    return _role_maker is None or _role_maker.is_worker()


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    global _fleet_initialized, _strategy, _role_maker
    # an explicit role maker implies PS mode (the reference's
    # fleet.init(role_maker) semantics, where is_collective defaults False)
    if role_maker is not None or not is_collective:
        # PS mode (ref fleet.init(role_maker) with a PS role maker):
        # no mesh/collective bootstrap — tables + pull/push live in
        # paddle_tpu.distributed.ps; the role maker names this process.
        _role_maker = role_maker or UserDefinedRoleMaker()
        _strategy = strategy or DistributedStrategy()
        _fleet_initialized = True
        return
    from ..env import init_parallel_env
    init_parallel_env()
    _strategy = strategy or DistributedStrategy()
    hc = _strategy.hybrid_configs
    hcg = HybridCommunicateGroup(
        dp_degree=hc.get("dp_degree", 1), mp_degree=hc.get("mp_degree", 1),
        pp_degree=hc.get("pp_degree", 1),
        sharding_degree=hc.get("sharding_degree", 1),
        sep_degree=hc.get("sep_degree", 1),
        ep_degree=hc.get("ep_degree", 1))
    set_hybrid_communicate_group(hcg)
    _fleet_initialized = True


def get_strategy():
    return _strategy


def worker_index():
    from ..env import get_rank
    return get_rank()


def worker_num():
    from ..env import get_world_size
    return get_world_size()


def distributed_model(model):
    """ref: fleet/model.py:32,141-160 — wraps per topology. PP gets the real
    scheduled runtime; TP/sharding/DP wrappers record intent (GSPMD
    partitions at compile inside TrainStep/ShardingPlan)."""
    from ..parallel import DataParallel
    from .meta_parallel import (PipelineLayer, PipelineParallel,
                                ShardingParallel, TensorParallel)
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        return model
    mode = hcg.get_parallel_mode()
    if mode == "pipeline":
        assert isinstance(model, PipelineLayer), (
            "pipeline parallel requires a PipelineLayer model "
            "(ref fleet/model.py:160 same constraint)")
        return PipelineParallel(model, hcg=hcg, strategy=_strategy)
    if mode == "tensor":
        return TensorParallel(model, hcg=hcg, strategy=_strategy)
    if mode == "sharding":
        return ShardingParallel(model, hcg=hcg, strategy=_strategy)
    return DataParallel(model)


def distributed_optimizer(optimizer, strategy=None):
    """ref: fleet/fleet.py distributed_optimizer → HybridParallelOptimizer
    (dygraph_optimizer/hybrid_parallel_optimizer.py:254). TP-aware grad
    clipping is already global under single-controller (grads are logical
    full tensors); the meta-optimizer strategy flags (ref
    meta_optimizers/) select the matching wrapper."""
    s = strategy or _strategy
    if s is None:
        return optimizer
    from .meta_optimizers import (DGCMomentumOptimizer,
                                  GradientMergeOptimizer, LocalSGDOptimizer)
    if getattr(s, "dgc", False):
        cfg = getattr(s, "dgc_configs", {}) or {}
        optimizer = DGCMomentumOptimizer(
            optimizer, momentum=cfg.get("momentum", 0.9),
            rampup_begin_step=cfg.get("rampup_begin_step", 0),
            sparsity=cfg.get("sparsity", 0.999))
    if getattr(s, "gradient_merge", False):
        cfg = getattr(s, "gradient_merge_configs", {}) or {}
        optimizer = GradientMergeOptimizer(
            optimizer, k_steps=cfg.get("k_steps", 1),
            avg=cfg.get("avg", True))
    if getattr(s, "localsgd", False):
        cfg = getattr(s, "localsgd_configs", {}) or {}
        optimizer = LocalSGDOptimizer(optimizer,
                                      k_steps=cfg.get("k_steps", 1))
    return optimizer
