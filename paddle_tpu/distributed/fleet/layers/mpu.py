"""paddle.distributed.fleet.layers.mpu — re-export (canonical impl lives in
fleet/mpu.py; ref path: python/paddle/distributed/fleet/layers/mpu/)."""
from ..mpu import *  # noqa: F401,F403
from ..mpu import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RNGStatesTracker,
    RowParallelLinear, VocabParallelEmbedding, get_rng_state_tracker,
    model_parallel_random_seed, split)
