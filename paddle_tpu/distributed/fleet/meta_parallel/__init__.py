"""fleet.meta_parallel (ref: python/paddle/distributed/fleet/meta_parallel/).

TensorParallel/ShardingParallel/SegmentParallel are annotation-recording
wrappers under GSPMD (partitioning happens at compile); PipelineParallel is
a real scheduled runtime (see pipeline_parallel.py).
"""
from .pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc  # noqa: F401
from .pipeline_parallel import PipelineParallel  # noqa: F401
from .hetero_pipeline import HeteroPipelineParallel  # noqa: F401

__all__ = ["LayerDesc", "PipelineLayer", "SharedLayerDesc",
           "PipelineParallel", "HeteroPipelineParallel", "TensorParallel",
           "ShardingParallel", "SegmentParallel"]


class _IdentityWrapper:
    """Base for wrappers that only record parallel intent (ref
    meta_parallel/{tensor,segment}_parallel.py do param broadcast + RNG
    sync — both automatic under single-controller GSPMD)."""

    def __init__(self, layers, hcg=None, strategy=None, **kw):
        self._layers = layers

    def __getattr__(self, item):
        return getattr(self.__dict__["_layers"], item)

    def __call__(self, *a, **kw):
        return self._layers(*a, **kw)


class TensorParallel(_IdentityWrapper):
    pass


class ShardingParallel(_IdentityWrapper):
    pass


class SegmentParallel(_IdentityWrapper):
    pass
