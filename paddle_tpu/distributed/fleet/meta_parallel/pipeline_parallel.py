"""Pipeline-parallel runtime
(ref: python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:150
PipelineParallel, :440 forward_backward_pipeline; p2p comm
pp_utils/p2p_communication.py; static schedules
distributed/passes/pipeline_scheduler_pass.py FThenB/1F1B).

TPU-native schedule: ONE compiled program per train step. The microbatch
loop is a lax.scan over T = M + S - 1 ticks inside shard_map over the `pp`
mesh axis; stage handoff is lax.ppermute (XLA collective-permute over ICI)
— replacing the reference's batched NCCL isend/irecv (p2p_communication).
Autodiff transposes the scan+ppermute into the reverse schedule, so
forward-then-backward (the reference's FThenB) falls out of jax.grad; per-
tick jax.checkpoint keeps live activations at one per in-flight microbatch,
matching 1F1B's peak-memory bound.

Stage bodies: the homogeneous middle blocks of a PipelineLayer, stacked
[S, L/S, ...] and sharded over `pp` (see pp_layers.py). Prefix/suffix
(embedding / norm+head) run at the edges, replicated over `pp` — GSPMD
shards them over the remaining mesh axes as annotated.
"""
from __future__ import annotations

import contextlib
import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ....framework import core
from ....tensor import Parameter, Tensor
from .pp_layers import PipelineLayer

__all__ = ["PipelineParallel"]


def _data_axes(mesh, mb_size):
    """Mesh data axes the microbatch dim can shard over (shared rule:
    sharding.data_axes_for — dp/sharding while the product divides)."""
    from ...sharding import data_axes_for
    return data_axes_for(mb_size, mesh=mesh)


def _globalize(arr, sharding):
    """Batch input -> global jax.Array in `sharding`. In multi-process
    runs jit refuses non-replicated shardings on numpy AND cannot
    reshard an array committed only to this process's devices (the
    result of paddle.to_tensor) onto devices other processes own — both
    cases rebuild the array shard-by-shard from the host value (every
    rank holds the full batch, as all ranks consume the same seeded
    data). Arrays already spanning other processes pass through."""
    if isinstance(arr, jax.Array):
        if jax.process_count() == 1 or not arr.is_fully_addressable:
            return arr
        arr = np.asarray(arr)      # locally-committed: rebuild globally
    a = np.asarray(arr)
    return jax.make_array_from_callback(a.shape, sharding,
                                        lambda idx: a[idx])


@functools.lru_cache(maxsize=64)
def _jit_reshape(shape):
    # cached per target shape: a fresh lambda per call would never hit
    # the jit cache and retrace every training step
    return jax.jit(lambda t: t.reshape(shape))


def _as_microbatches(x, M):
    """[B, ...] batch -> [M, B/M, ...]: host path for numpy / local
    arrays; jit-reshape for global arrays (eager ops on non-addressable
    arrays are disallowed)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        shape = (M, x.shape[0] // M) + tuple(x.shape[1:])
        return _jit_reshape(shape)(x)
    a = np.asarray(x)
    return a.reshape((M, a.shape[0] // M) + a.shape[1:])


@contextlib.contextmanager
def _swap(params, arrays):
    saved = [p.data for p in params]
    try:
        for p, a in zip(params, arrays):
            p.data = a
        yield
    finally:
        for p, s in zip(params, saved):
            p.data = s


def _run_layers_functional(layers, scope, edge_p, h):
    """Run prefix/suffix layers on raw array h with weights from edge_p."""
    for i, lyr in enumerate(layers):
        named = list(lyr.named_parameters())
        objs = [p for _, p in named]
        arrays = [edge_p[f"{scope}.{i}.{n}"] for n, _ in named]
        with _swap(objs, arrays), core.no_grad_guard():
            h = lyr(Tensor(h)).data
    return h


class PipelineParallel:
    """Wraps a PipelineLayer for compiled pipelined training.

    parameters() exposes the edge Parameters plus ONE stacked Parameter per
    block-weight (leading dim = num blocks, sharded over `pp`) — the
    optimizer updates the stacks directly; per-block layer Parameters are
    refreshed lazily via sync_to_layers() for eval/state_dict.
    """

    def __new__(cls, layers=None, *args, **kwargs):
        # non-uniform middles route to the heterogeneous-stage engine
        # (per-stage flat weight buffers + lax.switch bodies)
        if cls is PipelineParallel and layers is not None \
                and getattr(layers, "hetero_stages", None):
            from .hetero_pipeline import HeteroPipelineParallel
            return HeteroPipelineParallel(layers, *args, **kwargs)
        return super().__new__(cls)

    def __init__(self, layers: PipelineLayer, hcg=None, strategy=None,
                 num_microbatches: Optional[int] = None, vpp_degree: int = 1):
        from ...topology import get_hybrid_communicate_group, get_mesh
        self.pipe = layers
        self.hcg = hcg or get_hybrid_communicate_group()
        self.mesh = (self.hcg.mesh if self.hcg is not None else get_mesh())
        assert self.mesh is not None, "pipeline needs a device mesh"
        self.S = layers.num_stages
        if strategy is not None and vpp_degree == 1:
            vpp_degree = strategy.pipeline_configs.get("vpp_degree", 1)
        self.V = int(vpp_degree)
        self.num_microbatches = num_microbatches or (
            strategy.pipeline_configs.get("accumulate_steps", self.S)
            if strategy is not None else self.S)
        L = len(layers.blocks)
        assert self.V >= 1 and L % (self.S * self.V) == 0, (
            f"{L} blocks not divisible into {self.S} stages x "
            f"{self.V} virtual chunks")
        self.Lpc = L // (self.S * self.V)           # layers per chunk

        # VPP cyclic placement: global stage g = v*S + s lives on device s
        # as chunk v. Stacks are stored DEVICE-MAJOR, [s, v, l] order, so a
        # plain leading-axis shard over `pp` hands each device its chunks.
        S, V, Lpc = self.S, self.V, self.Lpc
        self._perm = np.array(
            [(v * S + s) * Lpc + l
             for s in range(S) for v in range(V) for l in range(Lpc)],
            np.int64)
        self._inv_perm = np.argsort(self._perm)

        self._edge = layers.edge_params()           # name -> Parameter
        self._stacks: Dict[str, Parameter] = {}
        stacked = layers.stacked_block_params()     # name -> [L, ...] array
        for n, arr in stacked.items():
            spec = P(*(("pp",) + (None,) * (arr.ndim - 1)))
            sharded = jax.device_put(np.asarray(arr)[self._perm],
                                     NamedSharding(self.mesh, spec))
            p = Parameter(sharded, name=f"pipe_stack::{n}")
            p.pspec = spec
            self._stacks[n] = p
        self._compiled = {}
        self.global_rank = 0

    # -- paddle-compatible surface ------------------------------------------
    def parameters(self):
        seen, out = set(), []
        for p in list(self._edge.values()) + list(self._stacks.values()):
            if id(p) not in seen:       # tied weights listed once
                seen.add(id(p))
                out.append(p)
        return out

    def named_parameters(self):
        seen, out = set(), []
        for k, p in list(self._edge.items()) + list(self._stacks.items()):
            if id(p) not in seen:
                seen.add(id(p))
                out.append((k, p))
        return out

    def _stack_sig(self):
        # jax arrays are immutable, so ANY update (train step, amp cast,
        # asp mask, user rebind) replaces the array object. Weakrefs give
        # identity WITHOUT pinning replaced arrays in memory, and a dead
        # ref (id-reuse hazard) always reads as changed.
        import weakref
        return tuple(weakref.ref(p.data) for p in self._stacks.values())

    def _sig_current(self, sig):
        if sig is None or len(sig) != len(self._stacks):
            return False
        return all(r() is p.data
                   for r, p in zip(sig, self._stacks.values()))

    def sync_to_layers(self):
        # lazy: re-gather per-layer views only when some stack array was
        # replaced since the last sync (VERDICT r1 weak 6)
        if self._sig_current(getattr(self, "_synced_sig", None)):
            return
        self.pipe.set_stacked_block_params(
            {n: p.data[self._inv_perm] for n, p in self._stacks.items()})
        self._synced_sig = self._stack_sig()

    def state_dict(self):
        self.sync_to_layers()
        return self.pipe.state_dict()

    def set_state_dict(self, sd):
        self.pipe.set_state_dict(sd)
        stacked = self.pipe.stacked_block_params()
        for n, arr in stacked.items():
            self._stacks[n].data = jax.device_put(
                np.asarray(arr)[self._perm],
                NamedSharding(self.mesh, self._stacks[n].pspec))
        self._synced_sig = self._stack_sig()  # views rebuilt from sd

    def eval(self):
        self.sync_to_layers()
        self.pipe.eval()
        return self

    def train(self):
        self.pipe.train()
        return self

    def __call__(self, x):
        self.sync_to_layers()
        return self.pipe(x)

    # -- the compiled pipelined loss ----------------------------------------
    def _build_loss_fn(self, mb_size):
        """Schedule-driven pipelined loss (FThenB when V==1, interleaved
        VPP when V>1 — ref pipeline_parallel.py:440, :906).

        One lax.scan over the precomputed tick schedule inside shard_map
        over `pp`; each tick = one chunk-work per device + one cyclic
        ppermute. Backward is the AD transpose of the scan — the reverse
        schedule — so FThenB/interleave semantics carry over to grads.
        """
        from .pipeline_schedule import build_interleave_schedule
        pipe = self.pipe
        S, V, Lpc = self.S, self.V, self.Lpc
        M = self.num_microbatches
        mesh = self.mesh
        sched = build_interleave_schedule(S, V, M)
        T = sched.T
        template = pipe.blocks[0] if pipe.blocks else None
        t_named = list(template.named_parameters()) if template else []
        t_objs = [p for _, p in t_named]
        t_names = [n for n, _ in t_named]

        def block_fwd(h, bp):
            with _swap(t_objs, [bp[n] for n in t_names]), core.no_grad_guard():
                return template(Tensor(h)).data

        def chunk_fwd(h, bp_chunk):
            # bp_chunk leaves: [Lpc, ...] — scan the chunk's sub-stack
            def step(carry, pl):
                return block_fwd(carry, pl), None
            h, _ = jax.lax.scan(step, h, bp_chunk)
            return h

        def loss_of(out, y):
            with core.no_grad_guard():
                val = pipe.loss_fn(Tensor(out), Tensor(y))
            return val.data if isinstance(val, Tensor) else val

        # [T, S] int32 schedule constants, indexed [t][axis_index("pp")]
        sc = {k: jnp.asarray(getattr(sched, k), jnp.int32)
              for k in ("ex_act", "ex_v", "ex_m", "store_act", "store_v",
                        "loss_act")}

        # Pin the stage-handoff carrier's GSPMD sharding: microbatch dim
        # over the data axes, rest replicated. Without this, XLA derives
        # DIFFERENT shardings for the ppermute input (from the block's
        # mp-sharded dot) and the scan carry, and falls back to
        # "involuntary full rematerialization" — replicating the
        # activation on every tick (driver dryrun warning, VERDICT r2
        # weak #3; ref pipeline_parallel.py:906 p2p overlap).
        data_axes = _data_axes(mesh, mb_size)

        def pin(a, lead_dims=0):
            # shard the microbatch dim (position `lead_dims`) over the
            # data axes; auto axes elsewhere stay GSPMD-free (replicated).
            # A bare PartitionSpec resolves against the context (manual-
            # over-pp) abstract mesh — a concrete NamedSharding would not.
            spec = P(*((None,) * lead_dims
                       + ((data_axes,) if data_axes else (None,))))
            return jax.lax.with_sharding_constraint(a, spec)

        def device_body(edge_p, bp_local, x, y):
            # bp_local leaves: [V*Lpc, ...] (device-major shard of stacks)
            s = jax.lax.axis_index("pp")
            flat = x.reshape((-1,) + x.shape[2:])
            h0 = _run_layers_functional(pipe.prefix, "prefix", edge_p, flat)
            h0 = pin(h0.reshape((M, x.shape[1]) + h0.shape[1:]),
                     lead_dims=1)
            bp_chunks = jax.tree_util.tree_map(
                lambda a: a.reshape((V, Lpc) + a.shape[1:]), bp_local)

            def tick(carry, sched_row):
                inb, loss_sum = carry            # inb: [V, mb...]
                ea = sched_row["ex_act"][s]
                ev = sched_row["ex_v"][s]
                em = sched_row["ex_m"][s]
                sa = sched_row["store_act"][s]
                sv = sched_row["store_v"][s]
                la = sched_row["loss_act"][s]

                first_in = jax.lax.dynamic_index_in_dim(
                    h0, em, axis=0, keepdims=False)
                slot_in = jax.lax.dynamic_index_in_dim(
                    inb, ev, axis=0, keepdims=False)
                is_g0 = jnp.logical_and(s == 0, ev == 0)
                h_in = jnp.where(is_g0, first_in, slot_in)
                bp_chunk = jax.tree_util.tree_map(
                    lambda a: jax.lax.dynamic_index_in_dim(
                        a, ev, axis=0, keepdims=False), bp_chunks)

                def compute(h_in, bp_chunk):
                    out = chunk_fwd(h_in, bp_chunk)
                    tail = _run_layers_functional(pipe.suffix, "suffix",
                                                  edge_p, out)
                    yt = jax.lax.dynamic_index_in_dim(y, em, axis=0,
                                                      keepdims=False)
                    return out, loss_of(tail, yt)

                out, mb_loss = jax.checkpoint(compute)(h_in, bp_chunk)
                loss_sum = loss_sum + jnp.where(
                    jnp.logical_and(ea == 1, la == 1),
                    mb_loss.astype(jnp.float32), 0.0)
                # cyclic handoff: chunk v of device S-1 feeds chunk v+1 of
                # device 0 (the VPP wrap); receivers store per schedule.
                # Both sides of the permute carry the pinned spec so the
                # collective never needs an implicit reshard.
                recv = jax.lax.ppermute(
                    pin(out), "pp", [(i, (i + 1) % S) for i in range(S)])
                stored = jax.lax.dynamic_update_index_in_dim(
                    inb, pin(recv), sv, axis=0)
                inb = jnp.where(sa == 1, stored, inb)
                return (inb, loss_sum), None

            init = (pin(jnp.zeros((V,) + h0.shape[1:], h0.dtype),
                        lead_dims=1),
                    jnp.float32(0.0))
            (_, loss_sum), _ = jax.lax.scan(tick, init, sc)
            # loss lives on the last device; psum replicates it over pp
            return jax.lax.psum(loss_sum / M, "pp")

        stack_spec = jax.tree_util.tree_map(
            lambda p: P(*(("pp",) + (None,) * (p.data.ndim - 1))),
            dict(self._stacks), is_leaf=lambda v: isinstance(v, Parameter))

        def pipelined(edge_p, stack_p, x, y):
            # manual only over `pp`; remaining mesh axes stay under GSPMD
            body = jax.shard_map(
                device_body, mesh=mesh,
                in_specs=(P(), stack_spec, P(), P()),
                out_specs=P(), axis_names=frozenset({"pp"}),
                check_vma=False)
            return body(edge_p, stack_p, x, y)

        return pipelined

    def _get_compiled(self, xshape, yshape):
        key = (xshape, yshape)
        if key not in self._compiled:
            pipelined = self._build_loss_fn(xshape[1])
            vg = jax.value_and_grad(pipelined, argnums=(0, 1))
            mesh = self.mesh
            edge_shard = {k: NamedSharding(mesh, P())
                          for k in self._edge}
            stack_shard = {k: NamedSharding(mesh, p.pspec)
                           for k, p in self._stacks.items()}
            # microbatch data sharded over the data axes (dim 1 = mb),
            # matching the pinned carrier spec inside the body
            data_axes = _data_axes(mesh, xshape[1])
            data_spec = P(*((None, data_axes) if data_axes else ()))
            jitted = jax.jit(
                vg,
                in_shardings=(edge_shard, stack_shard,
                              NamedSharding(mesh, data_spec),
                              NamedSharding(mesh, data_spec)),
            )
            self._compiled[key] = (jitted, NamedSharding(mesh, data_spec))
        return self._compiled[key]

    def _globalize(self, arr, sharding):
        return _globalize(arr, sharding)

    # -- training entry (ref pipeline_parallel.py train_batch) ---------------
    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        x, y = data
        # keep jax arrays (possibly global) as-is; anything else (lists,
        # numpy) normalizes through numpy so .shape/.dtype reads work
        xa = x.data if isinstance(x, Tensor) else (
            x if isinstance(x, jax.Array) else np.asarray(x))
        ya = y.data if isinstance(y, Tensor) else (
            y if isinstance(y, jax.Array) else np.asarray(y))
        M = self.num_microbatches
        assert xa.shape[0] % M == 0, (
            f"batch {xa.shape[0]} not divisible into {M} microbatches")
        xm = _as_microbatches(xa, M)
        ym = _as_microbatches(ya, M)

        fn, data_sharding = self._get_compiled(tuple(xm.shape),
                                               tuple(ym.shape))
        edge_arr = {k: p.data for k, p in self._edge.items()}
        stack_arr = {k: p.data for k, p in self._stacks.items()}
        loss, (g_edge, g_stack) = fn(edge_arr, stack_arr,
                                     self._globalize(xm, data_sharding),
                                     self._globalize(ym, data_sharding))

        # tied weights appear under several edge keys (SharedLayerDesc):
        # accumulate partial grads per Parameter object, don't overwrite
        for k, g in g_edge.items():
            p = self._edge[k]
            if not p.stop_gradient:
                gt = g.astype(p.data.dtype)
                p.grad = (Tensor(gt) if p.grad is None
                          else Tensor(p.grad.data + gt))
        for k, g in g_stack.items():
            p = self._stacks[k]
            if not p.stop_gradient:
                p.grad = Tensor(g.astype(p.data.dtype))
        optimizer.step()
        optimizer.clear_grad(set_to_zero=False)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(loss)

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        self.sync_to_layers()
        with core.no_grad_guard():
            out = self.pipe(x if isinstance(x, Tensor) else Tensor(x))
            if compute_loss:
                return self.pipe.loss_fn(out, y if isinstance(y, Tensor)
                                         else Tensor(y))
        return out
