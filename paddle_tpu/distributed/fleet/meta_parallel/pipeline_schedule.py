"""Static interleaved-pipeline (VPP) schedule generation.

ref: python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:906
(PipelineParallelWithInterleave). The reference builds its interleave
schedule imperatively per rank at runtime; here the whole pipeline is ONE
compiled XLA program (scan over ticks inside shard_map), so the schedule is
precomputed host-side into dense [T, S] arrays the traced tick body indexes
with (t, axis_index("pp")).

Model: G = S*V global stages; global stage g lives on device g % S as its
chunk g // S (cyclic VPP placement, same as Megatron/the reference). One
tick = every device executes at most ONE chunk-work (1/V of its layers) and
one collective-permute hands every produced activation to the next device.
Inter-stage handoff buffers are 1-deep per (device, chunk) — the scheduler
only lets a producer fire when the consumer's slot is free, which is the
flow-control the reference gets from blocking p2p sends.

The generator is a greedy list scheduler: per tick each device picks its
highest-priority ready item (input arrived + downstream slot free), with
the Megatron-style depth-first priority (finish a group of S microbatches
on chunk v before advancing to chunk v+1). Senders whose target slot is
occupied (and not consumed this tick) are cancelled and retry next tick.

Why interleave helps here: a compiled masked schedule pays for EVERY tick
on every device (bubbles are computed-and-discarded, not skipped), so total
step time ~ T * (work per chunk-tick). FThenB costs (M+S-1)*V chunk-units;
the interleaved schedule's T approaches M*V + O(S*V) with a smaller fill
coefficient — the classic (S-1)/(M*V) bubble shrink, realized as a shorter
scan.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

__all__ = ["InterleaveSchedule", "build_interleave_schedule"]


@dataclass
class InterleaveSchedule:
    S: int              # devices (pipeline stages per chunk ring)
    V: int              # vpp degree (chunks per device)
    M: int              # microbatches
    T: int              # total ticks
    # all arrays [T, S]
    ex_act: np.ndarray      # 1 if device executes a chunk-work this tick
    ex_v: np.ndarray        # chunk index executed
    ex_m: np.ndarray        # microbatch index executed
    store_act: np.ndarray   # 1 if device stores the permuted value this tick
    store_v: np.ndarray     # chunk slot the received value goes to
    loss_act: np.ndarray    # 1 if executed item is the final global stage

    @property
    def n_units(self):
        return int(self.ex_act.sum())

    def bubble_fraction(self):
        return 1.0 - (self.S * self.V * self.M) / (self.T * self.S)


def build_interleave_schedule(S: int, V: int, M: int) -> InterleaveSchedule:
    """Greedy 1-deep-buffer list schedule for the cyclic-placement VPP
    pipeline. Deterministic; O(T*S*V)."""
    G = S * V
    next_m = [0] * G                 # FIFO per global stage
    # slot[s][v]: microbatch id waiting at device s for chunk v, or None
    slot: List[List] = [[None] * V for _ in range(S)]
    done_last = 0

    ex_act, ex_v, ex_m = [], [], []
    store_act, store_v = [], []
    loss_act = []

    def ready_items(s):
        """Candidate (priority_key, v, m) items device s could run now."""
        out = []
        for v in range(V):
            g = v * S + s
            m = next_m[g]
            if m >= M:
                continue
            if g == 0:
                avail = True          # fed from the local prefix output
            else:
                avail = slot[s][v] == m
            if not avail:
                continue
            # Megatron depth-first: groups of S microbatches per chunk,
            # lower chunk first within a group wave
            key = (m // S * V + v, m)
            out.append((key, v, m))
        return sorted(out)

    max_ticks = 4 * (M * V + G) + 16  # generous safety bound
    for t in range(max_ticks):
        if done_last >= M:
            break
        # phase 1+2: per-device ranked candidates; fixed-point dropping any
        # pick whose send target is occupied and not consumed this tick
        # (on conflict a device falls back to its next-ranked candidate)
        cands = {s: [it[1:] for it in ready_items(s)] for s in range(S)}
        choice = {s: 0 for s in range(S)}

        def pick_of(s):
            i = choice[s]
            return cands[s][i] if i < len(cands[s]) else None

        changed = True
        while changed:
            changed = False
            consumed = {(s, pick_of(s)[0]) for s in range(S)
                        if pick_of(s) is not None}
            for s in range(S):
                p = pick_of(s)
                if p is None:
                    continue
                v, m = p
                g = v * S + s
                if g + 1 >= G:
                    continue                      # final stage: no send
                ds, dv = (s + 1) % S, (g + 1) // S
                if slot[ds][dv] is not None and (ds, dv) not in consumed:
                    choice[s] += 1                # try next candidate
                    changed = True
        picks = {s: pick_of(s) for s in range(S) if pick_of(s) is not None}
        # phase 3: commit
        ea = np.zeros(S, np.int32)
        ev = np.zeros(S, np.int32)
        em = np.zeros(S, np.int32)
        sa = np.zeros(S, np.int32)
        sv = np.zeros(S, np.int32)
        la = np.zeros(S, np.int32)
        # consume first, then store arrivals — a same-tick (consume, send)
        # pair on one slot must net to the arriving value
        for s, (v, m) in picks.items():
            if v * S + s > 0:
                slot[s][v] = None                 # consumed
        for s, (v, m) in picks.items():
            g = v * S + s
            ea[s], ev[s], em[s] = 1, v, m
            next_m[g] += 1
            if g == G - 1:
                la[s] = 1
                done_last += 1
            else:
                ds, dv = (s + 1) % S, (g + 1) // S
                slot[ds][dv] = m                  # arrives end of tick
                sa[ds], sv[ds] = 1, dv
        ex_act.append(ea); ex_v.append(ev); ex_m.append(em)
        store_act.append(sa); store_v.append(sv); loss_act.append(la)
    else:
        raise RuntimeError(
            f"interleave scheduler failed to converge for S={S} V={V} M={M}")

    return InterleaveSchedule(
        S=S, V=V, M=M, T=len(ex_act),
        ex_act=np.stack(ex_act), ex_v=np.stack(ex_v), ex_m=np.stack(ex_m),
        store_act=np.stack(store_act), store_v=np.stack(store_v),
        loss_act=np.stack(loss_act))
