"""Heterogeneous-stage pipeline engine.

ref: the reference's PipelineLayer supports arbitrary per-stage layer
structure (pp_layers.py:237, seg_method "uniform"/"param") because each
rank materializes only its own stage's layers and NCCL p2p carries
activations. Round 1's TPU engine required one global block template
(VERDICT weak #6); this engine removes that restriction TPU-natively:

* Per-device weights: each stage's parameters are raveled into per-dtype
  flat buffers, zero-padded to the max stage length, stacked [S, maxlen]
  and sharded over `pp` on the leading axis — so device s holds (only) its
  own stage's bytes, like the reference, even though stage param TREES
  differ in structure.
* Per-device compute: the tick body runs `lax.switch(axis_index("pp"),
  branches)` where branch s statically unravels its stage's params from
  the flat row and runs that stage's layers. XLA compiles S branches into
  the one SPMD program; each device executes its own.
* Inter-stage handoff: activation shapes differ per boundary, so the
  ppermute carrier is a flat f32 buffer sized to the widest boundary;
  each branch unflattens its statically-known input shape/dtype and
  re-flattens its output (bf16<->f32 round-trip is exact).

Schedule: FThenB via the same precomputed tick schedule as the uniform
engine (pipeline_schedule.py, V=1); backward is the AD transpose.
"""
from __future__ import annotations

import contextlib
import math
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ....framework import core
from ....tensor import Parameter, Tensor

__all__ = ["HeteroPipelineParallel"]


from .pipeline_parallel import _swap


class _StageMeta:
    """Static packing layout of one stage's parameters."""

    def __init__(self, layers, stage_idx):
        self.layers = layers
        self.entries = []          # (param_obj, name, dtype_str, off, shape)
        offsets: Dict[str, int] = {}
        for i, lyr in enumerate(layers):
            for n, p in lyr.named_parameters():
                d = str(p.data.dtype)
                off = offsets.get(d, 0)
                size = int(np.prod(p.shape)) if p.shape else 1
                self.entries.append((p, f"{stage_idx}.{i}.{n}", d, off,
                                     tuple(p.shape)))
                offsets[d] = off + size
        self.sizes = offsets        # dtype -> used length

    def pack(self, maxlens):
        bufs = {d: np.zeros((L,), _np_dtype(d)) for d, L in maxlens.items()}
        for p, _, d, off, shape in self.entries:
            size = int(np.prod(shape)) if shape else 1
            bufs[d][off:off + size] = np.asarray(p.data).reshape(-1)
        return bufs

    def unpack_into_layers(self, bufs):
        for p, _, d, off, shape in self.entries:
            size = int(np.prod(shape)) if shape else 1
            p.data = jnp.asarray(bufs[d][off:off + size]).reshape(shape)

    def slices(self, bufs):
        """Traced: ravel views of each param from flat buffers."""
        out = []
        for p, _, d, off, shape in self.entries:
            size = int(np.prod(shape)) if shape else 1
            out.append(jax.lax.dynamic_slice_in_dim(
                bufs[d], off, size).reshape(shape))
        return out


def _np_dtype(d):
    import jax.numpy as jnp
    return jnp.dtype(d)


class HeteroPipelineParallel:
    """Pipelined training over per-stage-heterogeneous layers (vpp=1)."""

    def __init__(self, layers, hcg=None, strategy=None,
                 num_microbatches=None, vpp_degree=1):
        from ...topology import get_hybrid_communicate_group, get_mesh
        if strategy is not None and vpp_degree == 1:
            vpp_degree = strategy.pipeline_configs.get("vpp_degree", 1)
        if vpp_degree != 1:
            raise ValueError(
                "heterogeneous pipeline stages do not compose with "
                f"vpp_degree={vpp_degree}; interleaved VPP needs the uniform "
                "engine (structurally identical middle blocks)")
        assert layers.hetero_stages, "PipelineLayer is uniform; use PipelineParallel"
        self.pipe = layers
        self.hcg = hcg or get_hybrid_communicate_group()
        self.mesh = (self.hcg.mesh if self.hcg is not None else get_mesh())
        assert self.mesh is not None, "pipeline needs a device mesh"
        self.S = layers.num_stages
        self.V = 1
        self.num_microbatches = num_microbatches or (
            strategy.pipeline_configs.get("accumulate_steps", self.S)
            if strategy is not None else self.S)

        self.metas = [_StageMeta(st, i)
                      for i, st in enumerate(layers.hetero_stages)]
        dtypes = sorted({d for m in self.metas for d in m.sizes})
        self.maxlens = {d: max(m.sizes.get(d, 0) for m in self.metas)
                        for d in dtypes}
        self.maxlens = {d: max(L, 1) for d, L in self.maxlens.items()}
        # tied-weight registry: the same Parameter object packed into
        # several regions (SharedLayerDesc across stages). Gradients are
        # symmetrized across the group each step, and regions start equal,
        # so elementwise optimizers keep every copy identical — tying by
        # invariant rather than by aliasing.
        by_param: Dict[int, List] = {}
        for s, m in enumerate(self.metas):
            for p, _, d, off, shape in m.entries:
                size = int(np.prod(shape)) if shape else 1
                by_param.setdefault(id(p), []).append((p, d, s, off, size))
        self._tied_groups = [v for v in by_param.values() if len(v) > 1]
        self._frozen = [(d, s, off, size)
                        for v in by_param.values()
                        for (p, d, s, off, size) in v if p.stop_gradient]
        self._bufs: Dict[str, Parameter] = {}
        packed = [m.pack(self.maxlens) for m in self.metas]
        for d in dtypes:
            stack = np.stack([row[d] for row in packed])  # [S, maxlen]
            sharded = jax.device_put(
                stack, NamedSharding(self.mesh, P("pp", None)))
            p = Parameter(sharded, name=f"pipe_hetero::{d}")
            p.pspec = P("pp", None)
            self._bufs[d] = p
        self._compiled = {}
        self._layers_stale = False   # buffers were just packed FROM layers
        self.global_rank = 0

    # -- paddle-compatible surface ------------------------------------------
    def parameters(self):
        return list(self._bufs.values())

    def named_parameters(self):
        return list(self._bufs.items())

    def sync_to_layers(self):
        if not getattr(self, "_layers_stale", True):
            return
        for s, m in enumerate(self.metas):
            m.unpack_into_layers(
                {d: np.asarray(p.data[s]) for d, p in self._bufs.items()})
        self._layers_stale = False

    def state_dict(self):
        self.sync_to_layers()
        return self.pipe.state_dict()

    def set_state_dict(self, sd):
        self.pipe.set_state_dict(sd)
        packed = [m.pack(self.maxlens) for m in self.metas]
        for d in self._bufs:
            self._bufs[d].data = jax.device_put(
                np.stack([row[d] for row in packed]),
                NamedSharding(self.mesh, P("pp", None)))
        self._layers_stale = False

    def eval(self):
        self.sync_to_layers()
        self.pipe.eval()
        return self

    def train(self):
        self.pipe.train()
        return self

    def __call__(self, x):
        self.sync_to_layers()
        return self.pipe(x)

    # -- compiled pipelined loss --------------------------------------------
    def _boundary_shapes(self, x_mb_shape, x_dtype):
        """eval_shape each stage chain to get inter-stage act shapes."""
        shapes = []   # input shape/dtype of each stage (stage 0 = x)
        cur = jax.ShapeDtypeStruct(x_mb_shape, x_dtype)

        for m in self.metas:
            shapes.append((cur.shape, cur.dtype))

            def run(h, meta=m):
                arrs = [jnp.zeros(sh, _np_dtype(d))
                        for _, _, d, _, sh in meta.entries]
                with _swap([e[0] for e in meta.entries], arrs), \
                        core.no_grad_guard():
                    t = Tensor(h)
                    for lyr in meta.layers:
                        t = lyr(t)
                return t.data

            cur = jax.eval_shape(run, cur)
        shapes.append((cur.shape, cur.dtype))            # final output
        return shapes

    def _build_loss_fn(self, x_mb_shape, y_mb_shape, x_dtype):
        from .pipeline_schedule import build_interleave_schedule
        pipe = self.pipe
        S = self.S
        M = self.num_microbatches
        mesh = self.mesh
        metas = self.metas
        sched = build_interleave_schedule(S, 1, M)
        bshapes = self._boundary_shapes(x_mb_shape, x_dtype)
        carrier_len = max(int(np.prod(sh)) for sh, _ in bshapes[:S])
        carrier_len = max(carrier_len, 1)

        def branch(s):
            in_shape, in_dtype = bshapes[s]
            out_shape, out_dtype = bshapes[s + 1]

            def run(h_flat, bufs, yt):
                h = jax.lax.dynamic_slice_in_dim(
                    h_flat, 0, int(np.prod(in_shape))).astype(in_dtype)
                h = h.reshape(in_shape)
                arrs = metas[s].slices(bufs)
                with _swap([e[0] for e in metas[s].entries], arrs), \
                        core.no_grad_guard():
                    t = Tensor(h)
                    for lyr in metas[s].layers:
                        t = lyr(t)
                out = t.data
                if s == S - 1:
                    with core.no_grad_guard():
                        val = pipe.loss_fn(Tensor(out), Tensor(yt))
                    mb_loss = (val.data if isinstance(val, Tensor)
                               else val).astype(jnp.float32)
                    flat = jnp.zeros((carrier_len,), jnp.float32)
                else:
                    mb_loss = jnp.float32(0.0)
                    of = out.reshape(-1).astype(jnp.float32)
                    flat = jnp.zeros((carrier_len,), jnp.float32)
                    flat = jax.lax.dynamic_update_slice_in_dim(
                        flat, of, 0, axis=0)
                return flat, mb_loss

            return run

        branches = [branch(s) for s in range(S)]
        sc = {k: jnp.asarray(getattr(sched, k), jnp.int32)
              for k in ("ex_act", "ex_m", "loss_act", "store_act")}

        def device_body(bufs_local, x, y):
            s = jax.lax.axis_index("pp")
            # shard_map hands each device its [1, maxlen] row; drop the dim
            bufs_local = {d: a.reshape(a.shape[-1])
                          for d, a in bufs_local.items()}
            x_flat = x.reshape((M, -1)).astype(jnp.float32)
            if x_flat.shape[1] < carrier_len:
                x_flat = jnp.pad(
                    x_flat, ((0, 0), (0, carrier_len - x_flat.shape[1])))

            def tick(carry, row):
                inb, loss_sum = carry
                em = row["ex_m"][s]
                ea = row["ex_act"][s]
                la = row["loss_act"][s]
                sa = row["store_act"][s]
                first_in = jax.lax.dynamic_index_in_dim(
                    x_flat, em, axis=0, keepdims=False)
                h_in = jnp.where(s == 0, first_in, inb)
                yt = jax.lax.dynamic_index_in_dim(y, em, axis=0,
                                                  keepdims=False)

                def compute(h_in, bufs_local, yt):
                    return jax.lax.switch(s, branches, h_in, bufs_local, yt)

                out, mb_loss = jax.checkpoint(compute)(h_in, bufs_local, yt)
                loss_sum = loss_sum + jnp.where(
                    jnp.logical_and(ea == 1, la == 1), mb_loss, 0.0)
                recv = jax.lax.ppermute(
                    out, "pp", [(i, (i + 1) % S) for i in range(S)])
                inb = jnp.where(sa == 1, recv, inb)
                return (inb, loss_sum), None

            init = (jnp.zeros((carrier_len,), jnp.float32), jnp.float32(0.0))
            (_, loss_sum), _ = jax.lax.scan(tick, init, sc)
            return jax.lax.psum(loss_sum / M, "pp")

        buf_spec = {d: P("pp", None) for d in self._bufs}

        def pipelined(bufs, x, y):
            body = jax.shard_map(
                device_body, mesh=mesh,
                in_specs=(buf_spec, P(), P()),
                out_specs=P(), axis_names=frozenset({"pp"}),
                check_vma=False)
            return body(bufs, x, y)

        return pipelined

    def _get_compiled(self, xshape, yshape, x_dtype):
        key = (xshape, yshape, str(x_dtype))
        if key not in self._compiled:
            x_mb_shape = (xshape[1],) + xshape[2:]
            y_mb_shape = (yshape[1],) + yshape[2:]
            pipelined = self._build_loss_fn(x_mb_shape, y_mb_shape, x_dtype)
            vg = jax.value_and_grad(pipelined, argnums=0)
            mesh = self.mesh
            buf_shard = {d: NamedSharding(mesh, P("pp", None))
                         for d in self._bufs}
            self._compiled[key] = jax.jit(
                vg, in_shardings=(buf_shard, NamedSharding(mesh, P()),
                                  NamedSharding(mesh, P())))
        return self._compiled[key]

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        x, y = data
        xa = x.data if isinstance(x, Tensor) else jnp.asarray(x)
        ya = y.data if isinstance(y, Tensor) else jnp.asarray(y)
        M = self.num_microbatches
        assert xa.shape[0] % M == 0
        mb = xa.shape[0] // M
        xm = xa.reshape((M, mb) + xa.shape[1:])
        ym = ya.reshape((M, mb) + ya.shape[1:])
        fn = self._get_compiled(xm.shape, ym.shape, xa.dtype)
        bufs = {d: p.data for d, p in self._bufs.items()}
        loss, g = fn(bufs, xm, ym)
        # tied weights: symmetrize grads across every region of the group
        for group in self._tied_groups:
            total = None
            for _, d, s, off, size in group:
                piece = jax.lax.dynamic_slice(g[d], (s, off), (1, size))
                total = piece if total is None else total + piece
            for _, d, s, off, size in group:
                g[d] = jax.lax.dynamic_update_slice(g[d], total, (s, off))
        # frozen params: no gradient
        for d, s, off, size in self._frozen:
            g[d] = jax.lax.dynamic_update_slice(
                g[d], jnp.zeros((1, size), g[d].dtype), (s, off))
        frozen_save = [(d, s, off, size,
                        jax.lax.dynamic_slice(self._bufs[d].data, (s, off),
                                              (1, size)))
                       for d, s, off, size in self._frozen]
        for d, gd in g.items():
            p = self._bufs[d]
            p.grad = Tensor(gd.astype(p.data.dtype))
        optimizer.step()
        # weight decay must not move frozen params either
        for d, s, off, size, saved in frozen_save:
            self._bufs[d].data = jax.lax.dynamic_update_slice(
                self._bufs[d].data, saved, (s, off))
        optimizer.clear_grad()
        self._layers_stale = True
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(loss)

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        self.sync_to_layers()
        with core.no_grad_guard():
            out = self.pipe(x if isinstance(x, Tensor) else Tensor(x))
            if compute_loss:
                return self.pipe.loss_fn(out, y if isinstance(y, Tensor)
                                         else Tensor(y))
        return out
