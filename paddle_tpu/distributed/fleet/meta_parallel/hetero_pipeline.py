"""Heterogeneous-stage pipeline engine.

ref: the reference's PipelineLayer supports arbitrary per-stage layer
structure (pp_layers.py:237, seg_method "uniform"/"param") because each
rank materializes only its own stage's layers and NCCL p2p carries
activations. Round 1's TPU engine required one global block template
(VERDICT weak #6); this engine removes that restriction TPU-natively:

* Per-device weights: each global stage's parameters are raveled into
  per-dtype flat buffers, zero-padded to the max stage length, stacked
  [G, maxlen] in DEVICE-MAJOR order and sharded over `pp` on the leading
  axis — so device s holds (only) its own stages' bytes, like the
  reference, even though stage param TREES differ in structure.
* Per-device compute: the tick body runs `lax.switch(g, branches)` where
  g = chunk*S + axis_index("pp") and branch g statically unravels its
  stage's params from the flat row and runs that stage's layers. XLA
  compiles G branches into the one SPMD program; each device executes
  its own.
* Inter-stage handoff: activation shapes differ per boundary, so each
  boundary gets its OWN ppermute with the exact shape/dtype and a
  single source->target pair. Per-tick link traffic is the sum of ALL
  boundary sizes (each permute ships its payload every tick, zeros
  included — XLA cannot elide runtime data), which still upper-bounds
  at and usually beats the previous scheme's num_stages x widest
  boundary in f32: transfers are exact-dtype (bf16 stays bf16) and
  exact-shape (VERDICT r2 weak #5).
* Interleaved VPP (vpp_degree=V > 1): the layer chain is re-segmented
  into G = S*V chunks placed cyclically (global stage g = v*S + s on
  device s as chunk v), driven by the same interleave schedule as the
  uniform engine. The previous engine rejected hetero+VPP outright.

Schedule: FThenB (V=1) / interleaved (V>1) via the precomputed tick
schedule (pipeline_schedule.py); backward is the AD transpose.
"""
from __future__ import annotations

import contextlib
import math
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ....framework import core
from ....tensor import Parameter, Tensor

__all__ = ["HeteroPipelineParallel"]


from .pipeline_parallel import _swap


class _StageMeta:
    """Static packing layout of one global stage's parameters."""

    def __init__(self, layers, stage_idx):
        self.layers = layers
        self.entries = []          # (param_obj, name, dtype_str, off, shape)
        offsets: Dict[str, int] = {}
        for i, lyr in enumerate(layers):
            for n, p in lyr.named_parameters():
                d = str(p.data.dtype)
                off = offsets.get(d, 0)
                size = int(np.prod(p.shape)) if p.shape else 1
                self.entries.append((p, f"{stage_idx}.{i}.{n}", d, off,
                                     tuple(p.shape)))
                offsets[d] = off + size
        self.sizes = offsets        # dtype -> used length

    def pack(self, maxlens):
        bufs = {d: np.zeros((L,), _np_dtype(d)) for d, L in maxlens.items()}
        for p, _, d, off, shape in self.entries:
            size = int(np.prod(shape)) if shape else 1
            bufs[d][off:off + size] = np.asarray(p.data).reshape(-1)
        return bufs

    def unpack_into_layers(self, bufs):
        for p, _, d, off, shape in self.entries:
            size = int(np.prod(shape)) if shape else 1
            p.data = jnp.asarray(bufs[d][off:off + size]).reshape(shape)

    def slices(self, bufs):
        """Traced: ravel views of each param from flat buffers."""
        out = []
        for p, _, d, off, shape in self.entries:
            size = int(np.prod(shape)) if shape else 1
            out.append(jax.lax.dynamic_slice_in_dim(
                bufs[d], off, size).reshape(shape))
        return out


def _np_dtype(d):
    import jax.numpy as jnp
    return jnp.dtype(d)


class HeteroPipelineParallel:
    """Pipelined training over per-stage-heterogeneous layers."""

    def __init__(self, layers, hcg=None, strategy=None,
                 num_microbatches=None, vpp_degree=1):
        from ...topology import get_hybrid_communicate_group, get_mesh
        if strategy is not None and vpp_degree == 1:
            vpp_degree = strategy.pipeline_configs.get("vpp_degree", 1)
        assert layers.hetero_stages, \
            "PipelineLayer is uniform; use PipelineParallel"
        self.pipe = layers
        self.hcg = hcg or get_hybrid_communicate_group()
        self.mesh = (self.hcg.mesh if self.hcg is not None else get_mesh())
        assert self.mesh is not None, "pipeline needs a device mesh"
        self.S = layers.num_stages
        self.V = int(vpp_degree)
        assert self.V >= 1
        self.G = self.S * self.V               # global stages
        self.num_microbatches = num_microbatches or (
            strategy.pipeline_configs.get("accumulate_steps", self.S)
            if strategy is not None else self.S)

        # V>1: re-segment the chain into G chunks (cyclic placement);
        # V==1: the PipelineLayer's own S-way hetero segmentation
        stage_layers = (layers.hetero_stages if self.V == 1
                        else layers._segment_hetero(self.G))
        self.metas = [_StageMeta(st, g) for g, st in enumerate(stage_layers)]
        # device-major row order: row r = s*V + v holds global stage
        # g = v*S + s, so a leading-axis shard over `pp` hands device s
        # rows [s*V, (s+1)*V) = exactly its V chunks
        S, V = self.S, self.V
        self._row_of = [0] * self.G            # g -> buffer row
        for g in range(self.G):
            s, v = g % S, g // S
            self._row_of[g] = s * V + v
        dtypes = sorted({d for m in self.metas for d in m.sizes})
        self.maxlens = {d: max(m.sizes.get(d, 0) for m in self.metas)
                        for d in dtypes}
        self.maxlens = {d: max(L, 1) for d, L in self.maxlens.items()}
        # tied-weight registry: the same Parameter object packed into
        # several regions (SharedLayerDesc across stages). Gradients are
        # symmetrized across the group each step, and regions start equal,
        # so elementwise optimizers keep every copy identical — tying by
        # invariant rather than by aliasing. Rows recorded DEVICE-MAJOR.
        by_param: Dict[int, List] = {}
        for g, m in enumerate(self.metas):
            for p, _, d, off, shape in m.entries:
                size = int(np.prod(shape)) if shape else 1
                by_param.setdefault(id(p), []).append(
                    (p, d, self._row_of[g], off, size))
        self._tied_groups = [v for v in by_param.values() if len(v) > 1]
        self._frozen = [(d, r, off, size)
                        for v in by_param.values()
                        for (p, d, r, off, size) in v if p.stop_gradient]
        self._bufs: Dict[str, Parameter] = {}
        packed = [m.pack(self.maxlens) for m in self.metas]
        for d in dtypes:
            rows = [None] * self.G
            for g in range(self.G):
                rows[self._row_of[g]] = packed[g][d]
            stack = np.stack(rows)              # [G, maxlen], device-major
            sharded = jax.device_put(
                stack, NamedSharding(self.mesh, P("pp", None)))
            p = Parameter(sharded, name=f"pipe_hetero::{d}")
            p.pspec = P("pp", None)
            self._bufs[d] = p
        self._compiled = {}
        self._layers_stale = False   # buffers were just packed FROM layers
        self.global_rank = 0

    # -- paddle-compatible surface ------------------------------------------
    def parameters(self):
        return list(self._bufs.values())

    def named_parameters(self):
        return list(self._bufs.items())

    def sync_to_layers(self):
        if not getattr(self, "_layers_stale", True):
            return
        for g, m in enumerate(self.metas):
            r = self._row_of[g]
            m.unpack_into_layers(
                {d: np.asarray(p.data[r]) for d, p in self._bufs.items()})
        self._layers_stale = False

    def state_dict(self):
        self.sync_to_layers()
        return self.pipe.state_dict()

    def set_state_dict(self, sd):
        self.pipe.set_state_dict(sd)
        packed = [m.pack(self.maxlens) for m in self.metas]
        for d in self._bufs:
            rows = [None] * self.G
            for g in range(self.G):
                rows[self._row_of[g]] = packed[g][d]
            self._bufs[d].data = jax.device_put(
                np.stack(rows), NamedSharding(self.mesh, P("pp", None)))
        self._layers_stale = False

    def eval(self):
        self.sync_to_layers()
        self.pipe.eval()
        return self

    def train(self):
        self.pipe.train()
        return self

    def __call__(self, x):
        self.sync_to_layers()
        return self.pipe(x)

    # -- compiled pipelined loss --------------------------------------------
    def _boundary_shapes(self, x_mb_shape, x_dtype):
        """eval_shape each global stage chain: entry g = input shape/dtype
        of stage g (entry 0 = x); entry G = final output."""
        shapes = []
        cur = jax.ShapeDtypeStruct(x_mb_shape, x_dtype)

        for m in self.metas:
            shapes.append((cur.shape, cur.dtype))

            def run(h, meta=m):
                arrs = [jnp.zeros(sh, _np_dtype(d))
                        for _, _, d, _, sh in meta.entries]
                with _swap([e[0] for e in meta.entries], arrs), \
                        core.no_grad_guard():
                    t = Tensor(h)
                    for lyr in meta.layers:
                        t = lyr(t)
                return t.data

            cur = jax.eval_shape(run, cur)
        shapes.append((cur.shape, cur.dtype))            # final output
        return shapes

    def _build_loss_fn(self, x_mb_shape, x_dtype):
        from .pipeline_schedule import build_interleave_schedule
        pipe = self.pipe
        S, V, G = self.S, self.V, self.G
        M = self.num_microbatches
        mesh = self.mesh
        metas = self.metas
        sched = build_interleave_schedule(S, V, M)
        bshapes = self._boundary_shapes(x_mb_shape, x_dtype)
        # carrier slot b carries stage b's output (= stage b+1's input):
        # exact shape AND dtype per boundary — no widest-boundary f32
        # padding, and bf16 boundaries move half the bytes
        n_bnd = G - 1
        bnd = [bshapes[b + 1] for b in range(n_bnd)]

        def zero_carriers():
            return tuple(jnp.zeros(sh, dt) for sh, dt in bnd)

        def branch(g):
            in_shape, in_dtype = bshapes[g]
            v = g // S

            def run(h_all, bufs, yt):
                # h_all: (x_first, carriers...); stage g reads its input
                # statically — boundary g-1, or the microbatch input
                h = (h_all[0] if g == 0
                     else h_all[1 + (g - 1)]).astype(in_dtype)
                h = h.reshape(in_shape)
                row_bufs = {d: jax.lax.dynamic_index_in_dim(
                    a, v, axis=0, keepdims=False)
                    for d, a in bufs.items()}
                arrs = metas[g].slices(row_bufs)
                with _swap([e[0] for e in metas[g].entries], arrs), \
                        core.no_grad_guard():
                    t = Tensor(h)
                    for lyr in metas[g].layers:
                        t = lyr(t)
                out = t.data
                carriers = list(zero_carriers())
                if g == G - 1:
                    with core.no_grad_guard():
                        val = pipe.loss_fn(Tensor(out), Tensor(yt))
                    mb_loss = (val.data if isinstance(val, Tensor)
                               else val).astype(jnp.float32)
                else:
                    mb_loss = jnp.float32(0.0)
                    carriers[g] = out.astype(bnd[g][1]).reshape(bnd[g][0])
                return tuple(carriers), mb_loss

            return run

        branches = [branch(g) for g in range(G)]
        sc = {k: jnp.asarray(getattr(sched, k), jnp.int32)
              for k in ("ex_act", "ex_v", "ex_m", "store_act", "store_v",
                        "loss_act")}

        def device_body(bufs_local, x, y):
            s = jax.lax.axis_index("pp")
            # shard_map hands each device its [V, maxlen] rows
            x_mb = x.astype(x_dtype)

            def tick(carry, row):
                inb, loss_sum = carry          # inb: per-boundary tuple
                em = row["ex_m"][s]
                ev = row["ex_v"][s]
                ea = row["ex_act"][s]
                la = row["loss_act"][s]
                sa = row["store_act"][s]
                sv = row["store_v"][s]
                first_in = jax.lax.dynamic_index_in_dim(
                    x_mb, em, axis=0, keepdims=False)
                yt = jax.lax.dynamic_index_in_dim(y, em, axis=0,
                                                  keepdims=False)

                def compute(first_in, inb, bufs_local, yt):
                    g = ev * S + s             # global stage this tick
                    return jax.lax.switch(g, branches,
                                          (first_in,) + inb, bufs_local, yt)

                out_c, mb_loss = jax.checkpoint(compute)(
                    first_in, inb, bufs_local, yt)
                loss_sum = loss_sum + jnp.where(
                    jnp.logical_and(ea == 1, la == 1), mb_loss, 0.0)
                # one exact-shape ppermute per boundary, single pair
                # (b%S -> (b%S+1)%S): collective-permute moves bytes only
                # for listed pairs, so inactive boundaries cost nothing
                new_inb = []
                for b in range(n_bnd):
                    src = b % S
                    dst = (src + 1) % S
                    recv = jax.lax.ppermute(out_c[b], "pp", [(src, dst)])
                    # store when the schedule says chunk sv's input (that
                    # is boundary sv*S + s - 1) arrives at this device
                    want = jnp.logical_and(
                        sa == 1, jnp.equal(sv * S + s - 1, b))
                    new_inb.append(jnp.where(want, recv, inb[b]))
                return (tuple(new_inb), loss_sum), None

            init = (zero_carriers(), jnp.float32(0.0))
            (_, loss_sum), _ = jax.lax.scan(tick, init, sc)
            return jax.lax.psum(loss_sum / M, "pp")

        buf_spec = {d: P("pp", None) for d in self._bufs}

        def pipelined(bufs, x, y):
            body = jax.shard_map(
                device_body, mesh=mesh,
                in_specs=(buf_spec, P(), P()),
                out_specs=P(), axis_names=frozenset({"pp"}),
                check_vma=False)
            return body(bufs, x, y)

        return pipelined

    def _get_compiled(self, xshape, yshape, x_dtype):
        key = (xshape, yshape, str(x_dtype))
        if key not in self._compiled:
            x_mb_shape = (xshape[1],) + xshape[2:]
            pipelined = self._build_loss_fn(x_mb_shape, x_dtype)
            vg = jax.value_and_grad(pipelined, argnums=0)
            mesh = self.mesh
            buf_shard = {d: NamedSharding(mesh, P("pp", None))
                         for d in self._bufs}
            self._compiled[key] = jax.jit(
                vg, in_shardings=(buf_shard, NamedSharding(mesh, P()),
                                  NamedSharding(mesh, P())))
        return self._compiled[key]

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        x, y = data
        # host numpy unless already a (possibly global) jax array: on a
        # multi-process mesh jit places numpy per in_shardings, but a
        # committed single-local-device array cannot be resharded onto
        # devices other processes own
        from .pipeline_parallel import _as_microbatches
        # keep jax arrays (possibly global) as-is; anything else (lists,
        # numpy) normalizes through numpy so .shape/.dtype reads work
        xa = x.data if isinstance(x, Tensor) else (
            x if isinstance(x, jax.Array) else np.asarray(x))
        ya = y.data if isinstance(y, Tensor) else (
            y if isinstance(y, jax.Array) else np.asarray(y))
        M = self.num_microbatches
        assert xa.shape[0] % M == 0
        xm = _as_microbatches(xa, M)
        ym = _as_microbatches(ya, M)
        fn = self._get_compiled(tuple(xm.shape), tuple(ym.shape), xa.dtype)
        bufs = {d: p.data for d, p in self._bufs.items()}
        from .pipeline_parallel import _globalize
        rep = NamedSharding(self.mesh, P())
        loss, g = fn(bufs, _globalize(xm, rep), _globalize(ym, rep))
        # tied weights: symmetrize grads across every region of the group
        for group in self._tied_groups:
            total = None
            for _, d, r, off, size in group:
                piece = jax.lax.dynamic_slice(g[d], (r, off), (1, size))
                total = piece if total is None else total + piece
            for _, d, r, off, size in group:
                g[d] = jax.lax.dynamic_update_slice(g[d], total, (r, off))
        # frozen params: no gradient
        for d, r, off, size in self._frozen:
            g[d] = jax.lax.dynamic_update_slice(
                g[d], jnp.zeros((1, size), g[d].dtype), (r, off))
        frozen_save = [(d, r, off, size,
                        jax.lax.dynamic_slice(self._bufs[d].data, (r, off),
                                              (1, size)))
                       for d, r, off, size in self._frozen]
        for d, gd in g.items():
            p = self._bufs[d]
            p.grad = Tensor(gd.astype(p.data.dtype))
        optimizer.step()
        # weight decay must not move frozen params either
        for d, r, off, size, saved in frozen_save:
            self._bufs[d].data = jax.lax.dynamic_update_slice(
                self._bufs[d].data, saved, (r, off))
        optimizer.clear_grad(set_to_zero=False)
        self._layers_stale = True
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(loss)

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        self.sync_to_layers()
        with core.no_grad_guard():
            out = self.pipe(x if isinstance(x, Tensor) else Tensor(x))
            if compute_loss:
                return self.pipe.loss_fn(out, y if isinstance(y, Tensor)
                                         else Tensor(y))
        return out
