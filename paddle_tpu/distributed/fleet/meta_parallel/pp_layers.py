"""PipelineLayer — layer list + stage segmentation
(ref: python/paddle/distributed/fleet/meta_parallel/parallel_layers/
pp_layers.py:237 PipelineLayer, :56 LayerDesc, :76 SharedLayerDesc).

TPU-native reinterpretation: the reference materializes only this rank's
stage and wires NCCL p2p between ranks. Under single-controller JAX the
PipelineLayer holds the WHOLE model; stage segmentation decides which
layers run inside the shard_map pipeline loop (the homogeneous "middle"
blocks, stacked [num_stages, layers_per_stage, ...] and sharded over the
`pp` mesh axis) versus the prefix/suffix (embedding, final norm, head)
that run replicated-over-pp at the pipeline's edges.

Like the reference's "uniform" segmentation (pp_layers.py seg_method), the
middle must split evenly across stages; unlike it, middle blocks must be
structurally identical (same class/config) — true for every transformer
the reference pipelines, and the property that lets one compiled body
serve every stage.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ....nn.layer.layers import Layer
from ....tensor import Tensor

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer"]


class LayerDesc:
    """Deferred layer constructor (ref pp_layers.py:56)."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer appearing in several stages (ref pp_layers.py:76,
    used for tied embeddings). Single-controller: the SAME built Layer
    object is reused, so tying is aliasing — no broadcast/allreduce of
    tied grads needed (the tape accumulates both uses)."""

    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr
                 ="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """ref pp_layers.py:237. Builds every described layer; segments into
    num_stages stages. Callable as a plain sequential model (the 1-stage /
    debug path the reference also supports)."""

    def __init__(self, layers: Sequence, num_stages: Optional[int] = None,
                 loss_fn: Optional[Callable] = None, topology=None,
                 seg_method: str = "uniform", recompute_interval: int = 0,
                 **kwargs):
        super().__init__()
        self.descs = list(layers)
        self._loss_fn = loss_fn
        self.recompute_interval = recompute_interval
        if num_stages is None:
            from ...topology import get_hybrid_communicate_group
            hcg = get_hybrid_communicate_group()
            num_stages = (hcg.get_pipe_parallel_world_size()
                          if hcg is not None else 1)
        self.num_stages = num_stages

        shared = {}
        built: List[Layer] = []
        self._shared_keys = []
        for d in self.descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name not in shared:
                    shared[d.layer_name] = d.build_layer()
                base = shared[d.layer_name]
                # later occurrences run forward_func(layer, x) (ref
                # pp_layers.py SharedLayerDesc — tied-embedding head)
                built.append(base if d.layer_name not in self._shared_keys
                             or d.forward_func is None
                             else _SharedFnLayer(base, d.forward_func))
                self._shared_keys.append(d.layer_name)
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            elif callable(d):
                built.append(_FnLayer(d))
            else:
                raise TypeError(f"bad pipeline entry {d!r}")
        self.run_function = built
        for i, lyr in enumerate(built):
            self.add_sublayer(str(i), lyr)
        self._segment()

    # -- segmentation -------------------------------------------------------
    def _segment(self):
        """Find the longest run of structurally-identical layers (the
        pipelined middle); everything before/after is prefix/suffix."""
        sig = [self._sig(l) for l in self.run_function]
        best_start, best_len = 0, 0
        i = 0
        n = len(sig)
        while i < n:
            j = i
            while j < n and sig[j] == sig[i]:
                j += 1
            if j - i > best_len:
                best_start, best_len = i, j - i
            i = j
        S = self.num_stages
        self.hetero_stages = None
        if S > 1 and (best_len < S or best_len % S):
            if best_len >= S:
                import warnings
                warnings.warn(
                    f"pipeline middle has {best_len} identical blocks, not "
                    f"divisible into {S} stages — falling back to the "
                    "heterogeneous engine (slower: per-stage switch "
                    "branches). Prefer a block count divisible by "
                    "num_stages.", stacklevel=3)
            # non-uniform middle: fall back to heterogeneous per-stage
            # segmentation (ref pp_layers.py seg_method "param": balance
            # stages by parameter cost; layers inside a stage may differ)
            self.prefix = []
            self.blocks = []
            self.suffix = []
            self.hetero_stages = self._segment_hetero(S)
            return
        self.prefix = self.run_function[:best_start]
        self.blocks = self.run_function[best_start:best_start + best_len]
        self.suffix = self.run_function[best_start + best_len:]

    def _segment_hetero(self, S):
        """Split run_function into S contiguous groups balancing parameter
        count (the reference's "param" cost segmentation, pp_layers.py:237).
        Every stage must be non-empty."""
        layers = self.run_function
        n = len(layers)
        if n < S:
            raise ValueError(f"{n} layers cannot fill {S} stages")
        costs = [max(1, sum(int(np.prod(p.shape))
                            for _, p in lyr.named_parameters()))
                 for lyr in layers]
        total = sum(costs)
        # greedy boundaries at cumulative-cost quantiles, each stage >= 1
        stages, start, acc = [], 0, 0
        for s in range(S):
            target = total * (s + 1) / S
            end = start + 1
            acc += costs[start]
            while end < n - (S - s - 1) and acc + costs[end] / 2 < target:
                acc += costs[end]
                end += 1
            stages.append(layers[start:end])
            start = end
        assert start == n and all(stages)
        return stages

    @staticmethod
    def _sig(layer):
        return (type(layer).__name__,
                tuple(sorted((n, tuple(p.shape), str(p.dtype))
                             for n, p in layer.named_parameters())))

    @property
    def layers_per_stage(self):
        return len(self.blocks) // max(1, self.num_stages)

    def loss_fn(self, *args, **kwargs):
        if self._loss_fn is None:
            raise ValueError("PipelineLayer built without loss_fn")
        return self._loss_fn(*args, **kwargs)

    # -- plain sequential execution (1-stage/debug path) --------------------
    def forward(self, x):
        for lyr in self.run_function:
            x = lyr(x)
        return x

    # -- functional views used by PipelineParallel --------------------------
    def edge_params(self):
        ps = {}
        for scope, layers in (("prefix", self.prefix), ("suffix", self.suffix)):
            for i, lyr in enumerate(layers):
                for n, p in lyr.named_parameters():
                    ps[f"{scope}.{i}.{n}"] = p
        return ps

    def block_param_names(self):
        if not self.blocks:
            return []
        return [n for n, _ in self.blocks[0].named_parameters()]

    def stacked_block_params(self):
        """{name: [L, ...] Tensor-data stack} over the middle blocks."""
        names = self.block_param_names()
        out = {}
        for n in names:
            arrs = []
            for b in self.blocks:
                arrs.append(dict(b.named_parameters())[n].data)
            out[n] = jnp.stack(arrs)
        return out

    def scatter_block_grads(self, grads):
        """Write [L, ...] grad stacks back onto per-block Parameters."""
        for n, g in grads.items():
            for i, b in enumerate(self.blocks):
                p = dict(b.named_parameters())[n]
                piece = Tensor(g[i])
                p.grad = piece if p.grad is None else Tensor(
                    p.grad.data + piece.data)

    def set_stacked_block_params(self, values):
        for n, v in values.items():
            for i, b in enumerate(self.blocks):
                dict(b.named_parameters())[n].data = v[i]


class _FnLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        self.fn = fn

    def forward(self, x):
        return self.fn(x)


class _SharedFnLayer(Layer):
    """A repeated SharedLayerDesc occurrence: same underlying layer (weight
    tying by aliasing), alternate forward."""

    def __init__(self, base, forward_func):
        super().__init__()
        self.base = base            # registered: named_parameters dedupes
        self.forward_func = forward_func

    def forward(self, x):
        return self.forward_func(self.base, x)
