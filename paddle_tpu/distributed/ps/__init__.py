"""Parameter-server training mode (ref: paddle/fluid/distributed/ps/ —
table/ (MemoryDenseTable, MemorySparseTable), accessors with per-table
optimizer rules; python/paddle/distributed/fleet PS mode: workers
pull params / push grads, servers apply updates; the_one_ps.py wires
tables to a brpc service).

TPU-native position: PS is a HOST-side subsystem — sparse embedding tables
too big for HBM live in host RAM on server processes, while the dense math
stays on the TPU mesh. Tables are numpy (host memory by definition);
transport is an authenticated-pickle channel in the style of
paddle_tpu.distributed.rpc (kept separate: PS connections are stateful
and long-lived, rpc's are per-call); update rules (SGD/Adagrad/Adam) mirror the
reference's accessor rules. Workers can also embed a server in-process
(single-host async training) — no socket needed.
"""
from __future__ import annotations

import struct
import threading
import time
from multiprocessing import AuthenticationError
from multiprocessing.connection import Client, Listener
from typing import Dict, Optional

import numpy as np

__all__ = ["SGDRule", "AdagradRule", "AdamRule", "DenseTable", "SparseTable",
           "NativeSparseTable", "ParameterServer", "PSClient", "run_server"]

def _auth(bind_host=None) -> bytes:
    """Per-job secret (distributed/_auth.py): PADDLE_PS_AUTHKEY, else
    the launcher's PADDLE_JOB_AUTHKEY, else derived from the job's
    published endpoints, else a same-user 0600 key file — never a
    source-code constant (pickle channel = RCE to anyone holding the
    key). Listeners pass bind_host: non-loopback binds refuse the
    derivable fallbacks (advisor r3, medium)."""
    from paddle_tpu.distributed._auth import derive_authkey
    return derive_authkey("PADDLE_PS_AUTHKEY", "ps", bind_host=bind_host)


# explicit service surface: the wire protocol may only invoke these —
# getattr on arbitrary client-supplied names would expose every method
# (and attribute!) of the server object to the network
_SERVICE_OPS = frozenset({
    "pull_dense", "push_dense", "pull_sparse", "push_sparse", "barrier",
})


# ---------------- update rules (ref: ps/table/sparse_sgd_rule.cc) ---------

class SGDRule:
    def __init__(self, learning_rate=0.01):
        self.lr = learning_rate

    def init_state(self, shape):
        return {}

    def apply(self, param, grad, state):
        param -= self.lr * grad
        return param


class AdagradRule:
    def __init__(self, learning_rate=0.01, epsilon=1e-6):
        self.lr = learning_rate
        self.eps = epsilon

    def init_state(self, shape):
        return {"g2": np.zeros(shape, np.float32)}

    def apply(self, param, grad, state):
        state["g2"] += grad * grad
        param -= self.lr * grad / (np.sqrt(state["g2"]) + self.eps)
        return param


class AdamRule:
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8):
        self.lr, self.b1, self.b2, self.eps = (learning_rate, beta1, beta2,
                                               epsilon)

    def init_state(self, shape):
        return {"m": np.zeros(shape, np.float32),
                "v": np.zeros(shape, np.float32), "t": 0}

    def apply(self, param, grad, state):
        state["t"] += 1
        t = state["t"]
        state["m"] = self.b1 * state["m"] + (1 - self.b1) * grad
        state["v"] = self.b2 * state["v"] + (1 - self.b2) * grad * grad
        mhat = state["m"] / (1 - self.b1 ** t)
        vhat = state["v"] / (1 - self.b2 ** t)
        param -= self.lr * mhat / (np.sqrt(vhat) + self.eps)
        return param


_RULES = {"sgd": SGDRule, "adagrad": AdagradRule, "adam": AdamRule}


def _make_rule(rule):
    if isinstance(rule, str):
        return _RULES[rule]()
    return rule


# ---------------- tables (ref: ps/table/memory_dense_table.cc, ----------
#                  memory_sparse_table.cc)

class DenseTable:
    """Replicated dense parameter block living on the server."""

    def __init__(self, shape, rule="sgd", initializer=None):
        self.param = (np.zeros(shape, np.float32) if initializer is None
                      else np.asarray(initializer(shape), np.float32))
        self.rule = _make_rule(rule)
        self.state = self.rule.init_state(self.param.shape)
        self.lock = threading.Lock()

    def pull(self):
        with self.lock:
            return self.param.copy()

    def push(self, grad):
        grad = np.asarray(grad, np.float32)
        with self.lock:
            self.param = self.rule.apply(self.param, grad, self.state)

    def set(self, value):
        with self.lock:
            self.param = np.asarray(value, np.float32)


class SparseTable:
    """id -> embedding-row store with lazy row creation (ref
    MemorySparseTable: rows materialize on first touch, per-row optimizer
    state)."""

    def __init__(self, emb_dim, rule="sgd", initializer=None, seed=0):
        self.dim = int(emb_dim)
        self.rule = _make_rule(rule)
        self.rows: Dict[int, np.ndarray] = {}
        self.states: Dict[int, dict] = {}
        self.lock = threading.Lock()
        self._rng = np.random.default_rng(seed)
        self._init = initializer or (
            lambda shape: (self._rng.standard_normal(shape) * 0.01))

    def _row(self, i: int) -> np.ndarray:
        r = self.rows.get(i)
        if r is None:
            r = np.asarray(self._init((self.dim,)), np.float32)
            self.rows[i] = r
            self.states[i] = self.rule.init_state((self.dim,))
        return r

    def pull(self, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64).ravel()
        with self.lock:
            return np.stack([self._row(int(i)) for i in ids])

    def push(self, ids, grads):
        """Duplicate ids accumulate (ref: push_sparse merges by key)."""
        ids = np.asarray(ids, np.int64).ravel()
        grads = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        uniq, inv = np.unique(ids, return_inverse=True)
        merged = np.zeros((len(uniq), self.dim), np.float32)
        np.add.at(merged, inv, grads)
        with self.lock:
            for j, i in enumerate(uniq):
                i = int(i)
                self._row(i)
                self.rows[i] = self.rule.apply(self.rows[i], merged[j],
                                               self.states[i])

    def __len__(self):
        return len(self.rows)


class NativeSparseTable:
    """C++ contiguous-arena sparse table (ref: the reference's
    MemorySparseTable is C++, ps/table/memory_sparse_table.cc) — same
    pull/push contract as SparseTable, backed by
    ps/_native/table.cpp via ctypes: id->row hash over one float arena,
    duplicate-id merge, fused SGD/Adagrad/Adam rules, binary snapshots.

    Raises RuntimeError at construction when no C++ toolchain is
    available (callers choose the Python table instead)."""

    _RULE_IDS = {"sgd": 0, "adagrad": 1, "adam": 2}

    def __init__(self, emb_dim, rule="sgd", seed=0):
        from . import _native
        self._lib = _native.load()
        if self._lib is None:
            raise RuntimeError("native PS table unavailable "
                               "(no C++ toolchain)")
        self.dim = int(emb_dim)
        self.rule = _make_rule(rule)
        # EXACT types only: a subclass (GeoSGDRule blends deltas with
        # param += lr*delta) has different semantics than the fused C++
        # update — silently degrading it to SGD would invert updates.
        # Raising keeps such rules on the Python table via the fallback.
        if type(self.rule) is AdamRule:
            self._rule_id = 2
            self._params = (self.rule.lr, self.rule.b1, self.rule.b2,
                            self.rule.eps)
        elif type(self.rule) is AdagradRule:
            self._rule_id = 1
            self._params = (self.rule.lr, self.rule.eps, 0.0, 0.0)
        elif type(self.rule) is SGDRule:
            self._rule_id = 0
            self._params = (self.rule.lr, 0.0, 0.0, 0.0)
        else:
            raise RuntimeError(
                f"native PS table has no fused rule for "
                f"{type(self.rule).__name__}; use the Python table")
        self._h = self._lib.pst_create(self.dim, self._rule_id, int(seed))
        if not self._h:
            raise RuntimeError("pst_create failed")

    def _ids(self, ids):
        import ctypes
        arr = np.ascontiguousarray(np.asarray(ids, np.int64).ravel())
        return arr, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))

    def pull(self, ids) -> np.ndarray:
        import ctypes
        arr, ptr = self._ids(ids)
        out = np.empty((len(arr), self.dim), np.float32)
        self._lib.pst_pull(self._h, ptr, len(arr),
                           out.ctypes.data_as(
                               ctypes.POINTER(ctypes.c_float)))
        return out

    def push(self, ids, grads):
        import ctypes
        arr, ptr = self._ids(ids)
        g = np.ascontiguousarray(
            np.asarray(grads, np.float32).reshape(len(arr), self.dim))
        self._lib.pst_push(
            self._h, ptr, len(arr),
            g.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            *[float(p) for p in self._params])

    def save(self, path: str):
        if self._lib.pst_save(self._h, path.encode()) != 0:
            raise OSError(f"native table save failed: {path}")

    def load(self, path: str):
        if self._lib.pst_load(self._h, path.encode()) != 0:
            raise OSError(f"native table load failed: {path}")

    def __len__(self):
        return int(self._lib.pst_len(self._h))

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.pst_destroy(self._h)
                self._h = None
        except Exception:
            pass


# ---------------- server ------------------------------------------------

class ParameterServer:
    """Holds named tables and services pull/push ops (ref the_one_ps.py
    TheOnePSRuntime + brpc PsService). Usable in-process (call methods
    directly) or over a socket via serve()/PSClient."""

    def __init__(self):
        self.tables: Dict[str, object] = {}
        self._barrier_lock = threading.Lock()
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_cv = threading.Condition(self._barrier_lock)
        self._stop = threading.Event()
        self._listener = None
        self._thread = None

    # -- table management
    def create_dense_table(self, name, shape, rule="sgd", initializer=None):
        self.tables[name] = DenseTable(shape, rule, initializer)
        return self.tables[name]

    def create_sparse_table(self, name, emb_dim, rule="sgd",
                            initializer=None, backend="python"):
        """backend='native' uses the C++ arena table (no custom
        initializer support — rows init deterministically from the
        seed); falls back to Python when the toolchain is missing."""
        if backend == "native" and initializer is None:
            try:
                self.tables[name] = NativeSparseTable(emb_dim, rule)
                return self.tables[name]
            except RuntimeError:
                pass
        self.tables[name] = SparseTable(emb_dim, rule, initializer)
        return self.tables[name]

    # -- ops (the wire protocol dispatches here)
    def pull_dense(self, table):
        return self.tables[table].pull()

    def push_dense(self, table, grad):
        self.tables[table].push(grad)

    def pull_sparse(self, table, ids):
        return self.tables[table].pull(ids)

    def push_sparse(self, table, ids, grads):
        self.tables[table].push(ids, grads)

    def barrier(self, n_workers):
        """Block until n_workers callers arrive (ref barrier_with_table)."""
        with self._barrier_cv:
            gen = self._barrier_gen
            self._barrier_count += 1
            if self._barrier_count >= n_workers:
                self._barrier_count = 0
                self._barrier_gen += 1
                self._barrier_cv.notify_all()
                return
            while self._barrier_gen == gen:
                if self._stop.is_set():
                    raise RuntimeError(
                        "parameter server shut down while waiting at "
                        "barrier — synchronization not reached")
                self._barrier_cv.wait(timeout=0.1)

    # -- socket service
    def serve(self, endpoint: str, n_threads: int = None):
        """n_threads is accepted for API compat but connections are
        long-lived (one per worker), so each gets a dedicated daemon
        thread — a bounded pool would deadlock at barrier() once workers
        outnumber threads."""
        host, port = endpoint.rsplit(":", 1)
        self._listener = Listener((host, int(port)),
                                  authkey=_auth(bind_host=host))

        def loop():
            from paddle_tpu.distributed.collective import _listener_closed
            while not self._stop.is_set():
                try:
                    conn = self._listener.accept()
                    from paddle_tpu.distributed._net import \
                        enable_nodelay
                    enable_nodelay(conn)
                except Exception:
                    # a failed handshake (AuthenticationError / EOFError /
                    # ConnectionResetError from a port scan or wrong key)
                    # must not stop service; only a closed listener does.
                    # Exception type alone can't tell them apart — check
                    # the listener socket.
                    if _listener_closed(self._listener):
                        break
                    time.sleep(0.02)  # no busy-spin on persistent errors
                    continue
                # per-connection handler: exits on the client's EOF /
                # server stop; no join path by design
                # graft-lint: disable=thread-hygiene
                threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True,
                                 name="paddle-ps-conn").start()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="paddle-ps-accept")
        self._thread.start()
        return self

    def _handle(self, conn):
        try:
            while not self._stop.is_set():
                op, args = conn.recv()
                if op == "stop":
                    conn.send(("ok", None))
                    self.shutdown()
                    break
                try:
                    if op not in _SERVICE_OPS:
                        raise ValueError(f"unknown PS op {op!r}")
                    out = getattr(self, op)(*args)
                    conn.send(("ok", out))
                except Exception as e:  # worker sees the server error
                    conn.send(("err", repr(e)))
        except (EOFError, OSError):
            pass
        finally:
            conn.close()

    def shutdown(self):
        self._stop.set()
        with self._barrier_cv:
            self._barrier_cv.notify_all()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass


def run_server(endpoint, build_fn):
    """Convenience for a server process: build tables, serve until stopped.
    build_fn(server) registers tables."""
    ps = ParameterServer()
    build_fn(ps)
    ps.serve(endpoint)
    while not ps._stop.is_set():
        time.sleep(0.05)
    return ps


# ---------------- worker client -----------------------------------------

class PSClient:
    """Worker-side handle (ref: fleet PS worker push/pull API). Either
    wraps an in-process ParameterServer or a socket endpoint."""

    def __init__(self, server: Optional[ParameterServer] = None,
                 endpoint: Optional[str] = None, retries: int = 50):
        assert (server is None) != (endpoint is None), \
            "exactly one of server/endpoint"
        self._local = server
        self._conn = None
        self._lock = threading.Lock()
        if endpoint is not None:
            host, port = endpoint.rsplit(":", 1)
            last = None
            for _ in range(retries):
                try:
                    self._conn = Client((host, int(port)),
                                        authkey=_auth())
                    from paddle_tpu.distributed._net import \
                        enable_nodelay
                    enable_nodelay(self._conn)
                    break
                except (ConnectionError, OSError, AuthenticationError) as e:
                    # AuthenticationError can be transient: a peer midway
                    # through creating the shared key file
                    last = e
                    time.sleep(0.1)
            if self._conn is None:
                hint = ""
                if isinstance(last, AuthenticationError):
                    from paddle_tpu.distributed._auth import authkey_source
                    hint = (" (ps authkey: "
                            f"{authkey_source('PADDLE_PS_AUTHKEY')})")
                raise ConnectionError(
                    f"PS at {endpoint} unreachable: {last}{hint}")

    def _call(self, op, *args):
        if self._local is not None:
            return getattr(self._local, op)(*args)
        with self._lock:
            # the lock IS this client's socket serializer: request and
            # reply must stay paired on one connection, and no other
            # lock is ever taken around it (bounded by the server's
            # 30s abandoned-connection drop)
            self._conn.send((op, args))
            # graft-lint: disable=lock-discipline
            status, out = self._conn.recv()
        if status == "err":
            raise RuntimeError(f"server error in {op}: {out}")
        return out

    def pull_dense(self, table):
        return self._call("pull_dense", table)

    def push_dense(self, table, grad):
        return self._call("push_dense", table, np.asarray(grad))

    def pull_sparse(self, table, ids):
        return self._call("pull_sparse", table, np.asarray(ids))

    def push_sparse(self, table, ids, grads):
        return self._call("push_sparse", table, np.asarray(ids),
                          np.asarray(grads))

    def barrier(self, n_workers):
        return self._call("barrier", n_workers)

    def stop_server(self):
        if self._local is not None:
            self._local.shutdown()
            return
        try:
            self._call("stop")
        except (EOFError, OSError, RuntimeError):
            pass

    def close(self):
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass


class SSDSparseTable(SparseTable):
    """Disk-extended sparse table (ref: ps/table/ssd_sparse_table.cc —
    hot rows in memory, cold rows spilled to an on-disk KV store so the
    embedding table can exceed host RAM; the reference uses RocksDB).

    TPU-native/host-side: an LRU of `cache_rows` hot rows in memory;
    colder rows (values + optimizer state) live in LOG-STRUCTURED
    per-shard append files — a spill APPENDS one record, a fault SEEKS
    and reads one record, and a shard compacts when over half its bytes
    are stale (the same LSM-ish behavior the reference gets from
    RocksDB). Replaces the r4 .npz read-modify-write design whose whole
    -shard rewrites measured ~45 rows/s (benchmarks/PS_BENCH.json).
    """

    # record header: row id, payload length
    _HDR = struct.Struct("<qI")

    def __init__(self, emb_dim, rule="sgd", initializer=None, seed=0,
                 path=None, cache_rows=100_000, shards=64):
        import os
        import tempfile
        super().__init__(emb_dim, rule, initializer, seed)
        self.path = path or tempfile.mkdtemp(prefix="paddle_tpu_ssd_")
        os.makedirs(self.path, exist_ok=True)
        self.cache_rows = int(cache_rows)
        self.n_shards = int(shards)
        self._lru: Dict[int, None] = {}     # ordered dict as LRU
        self._on_disk: set = set()
        self._disk_index: Dict[int, tuple] = {}  # id -> (shard, off, ln)
        self._garbage: Dict[int, int] = {}       # shard -> stale bytes
        self._handles: Dict[int, object] = {}
        self._rebuild_index()

    # -- log-structured shard helpers ---------------------------------------
    def _shard_of(self, i: int) -> int:
        return int(i) % self.n_shards

    def _log_path(self, s: int) -> str:
        import os
        return os.path.join(self.path, f"shard_{s}.log")

    def _handle(self, s: int):
        h = self._handles.get(s)
        if h is None or h.closed:
            h = open(self._log_path(s), "a+b")
            self._handles[s] = h
        return h

    def _encode_row(self, value, state) -> bytes:
        import io
        buf = io.BytesIO()
        arrs = {"r": np.asarray(value, np.float32)}
        for k, v in (state or {}).items():
            arrs[f"s:{k}"] = np.asarray(v)
        # plain numeric arrays only — allow_pickle would turn a
        # tampered shard file into code execution
        np.savez(buf, **arrs)
        return buf.getvalue()

    def _decode_row(self, payload: bytes):
        import io
        with np.load(io.BytesIO(payload), allow_pickle=False) as z:
            val = np.asarray(z["r"], np.float32)
            st = {}
            for k in z.files:
                if k.startswith("s:"):
                    v = z[k]
                    st[k[2:]] = v.item() if v.ndim == 0 else v
        return val, st

    def _mark_garbage(self, entry):
        s, _, ln = entry
        self._garbage[s] = self._garbage.get(s, 0) + ln + self._HDR.size

    def _append_record(self, i: int, payload: bytes):
        s = self._shard_of(i)
        h = self._handle(s)
        h.seek(0, 2)
        off = h.tell()
        h.write(self._HDR.pack(int(i), len(payload)))
        h.write(payload)
        h.flush()
        old = self._disk_index.get(i)
        if old is not None:
            self._mark_garbage(old)
        self._disk_index[i] = (s, off, len(payload))
        self._on_disk.add(i)
        self._maybe_compact(s, off + self._HDR.size + len(payload))

    def _spill_many(self, victims):
        for i in victims:
            val = self.rows.pop(i)
            st = self.states.pop(i, None)
            self._lru.pop(i, None)
            self._append_record(i, self._encode_row(val, st))

    def _spill(self, i: int):
        self._spill_many([i])

    def _fault_in(self, i: int):
        s, off, ln = self._disk_index[i]
        h = self._handle(s)
        h.seek(off + self._HDR.size)
        val, st = self._decode_row(h.read(ln))
        self.rows[i] = val
        self.states[i] = st or self.rule.init_state((self.dim,))
        self._on_disk.discard(i)
        # the disk copy is stale the moment the row is hot again
        self._mark_garbage(self._disk_index.pop(i))

    def _maybe_compact(self, s: int, size: int):
        g = self._garbage.get(s, 0)
        if g > (1 << 20) and g * 2 > size:
            self._compact(s)

    def _compact(self, s: int):
        """Rewrite a shard keeping only live records (the LSM
        compaction step; stale bytes accumulate from re-spills)."""
        import os
        h = self._handle(s)
        live = []
        for i, (s_, off, ln) in self._disk_index.items():
            if s_ == s:
                h.seek(off + self._HDR.size)
                live.append((i, h.read(ln)))
        h.close()
        self._handles.pop(s, None)
        tmp = self._log_path(s) + ".tmp"
        with open(tmp, "wb") as f:
            off = 0
            for i, payload in live:
                f.write(self._HDR.pack(int(i), len(payload)))
                f.write(payload)
                self._disk_index[i] = (s, off, len(payload))
                off += self._HDR.size + len(payload)
        os.replace(tmp, self._log_path(s))
        self._garbage[s] = 0

    def _rebuild_index(self):
        """Recover the id->record index by scanning existing shard logs
        (path reuse across processes); the LAST record per id wins. A
        torn tail record (process killed mid-append: full header,
        truncated payload) is dropped and the log truncated there —
        indexing it would make every later read of that id fail."""
        import os
        for s in range(self.n_shards):
            p = self._log_path(s)
            if not os.path.exists(p):
                continue
            size = os.path.getsize(p)
            with open(p, "rb") as f:
                off = 0
                while True:
                    hdr = f.read(self._HDR.size)
                    if len(hdr) < self._HDR.size:
                        torn = off + len(hdr) < size
                        break
                    i, ln = self._HDR.unpack(hdr)
                    if off + self._HDR.size + ln > size:
                        torn = True
                        break
                    prev = self._disk_index.get(i)
                    if prev is not None:
                        self._mark_garbage(prev)
                    self._disk_index[i] = (s, off, ln)
                    self._on_disk.add(i)
                    f.seek(ln, 1)
                    off += self._HDR.size + ln
            if torn and off < size:
                with open(p, "r+b") as f:
                    f.truncate(off)

    def close(self):
        for h in list(self._handles.values()):
            try:
                h.close()
            except OSError:
                pass
        self._handles.clear()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _touch(self, i: int):
        self._lru.pop(i, None)
        self._lru[i] = None
        if len(self._lru) > self.cache_rows:
            # evict in one batch down to 7/8 capacity so sequential cold
            # scans amortize shard rewrites instead of evicting per row
            n_evict = len(self._lru) - (self.cache_rows * 7 // 8)
            it = iter(self._lru)
            self._spill_many([next(it) for _ in range(n_evict)])

    def _fault_many(self, ids):
        """Batch fault-in: each record reads with ONE seek — no shard
        rewrite or whole-shard load anywhere on the read path."""
        for i in ids:
            if int(i) in self._on_disk:
                self._fault_in(int(i))

    def pull(self, ids) -> np.ndarray:
        with self.lock:
            self._fault_many(np.unique(np.asarray(ids, np.int64)))
        return super().pull(ids)     # re-takes the lock; per-row _row
                                     # fault-in covers eviction races

    def push(self, ids, grads):
        with self.lock:
            self._fault_many(np.unique(np.asarray(ids, np.int64)))
        return super().push(ids, grads)

    def _row(self, i: int) -> np.ndarray:
        if i in self._on_disk:
            self._fault_in(i)
        r = super()._row(i)
        self._touch(i)
        return r

    def __len__(self):
        return len(self.rows) + len(self._on_disk)


class GeoSGDRule(SGDRule):
    """Geometric-SGD async rule (ref: ps/table/sparse_geo_table.cc +
    fleet GeoSGD mode): workers train LOCALLY for k steps and
    periodically push the parameter DELTA; the server blends deltas
    (delta / trainer_count) into the global table instead of applying
    raw gradients — tolerating stale, bursty updates."""

    def __init__(self, learning_rate=1.0, trainer_count=1):
        super().__init__(learning_rate)
        self.trainer_count = max(1, int(trainer_count))

    def apply(self, param, delta, state):
        # `delta` is (local_param - global_param), NOT a gradient
        param += self.lr * np.asarray(delta, np.float32) \
            / self.trainer_count
        return param


_RULES["geo_sgd"] = GeoSGDRule
__all__ += ["SSDSparseTable", "GeoSGDRule"]
