"""ctypes bindings for the native sparse-table engine (table.cpp)
(ref: ps/table/memory_sparse_table.cc — the reference PS tables are
C++). Uses the shared build-on-first-use loader
(utils/_native_build.py); returns None when no toolchain is available —
the PS then stays on the pure-Python row-dict tables."""
from __future__ import annotations

import ctypes
import os

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "table.cpp")
_SO = os.path.join(_HERE, "libpstable.so")


def _configure(lib):
    c = ctypes
    lib.pst_create.restype = c.c_void_p
    lib.pst_create.argtypes = [c.c_int, c.c_int, c.c_uint64]
    lib.pst_destroy.argtypes = [c.c_void_p]
    lib.pst_len.restype = c.c_int64
    lib.pst_len.argtypes = [c.c_void_p]
    lib.pst_pull.argtypes = [c.c_void_p, c.POINTER(c.c_int64),
                             c.c_int64, c.POINTER(c.c_float)]
    lib.pst_push.argtypes = [c.c_void_p, c.POINTER(c.c_int64),
                             c.c_int64, c.POINTER(c.c_float),
                             c.c_float, c.c_float, c.c_float, c.c_float]
    lib.pst_save.restype = c.c_int
    lib.pst_save.argtypes = [c.c_void_p, c.c_char_p]
    lib.pst_load.restype = c.c_int
    lib.pst_load.argtypes = [c.c_void_p, c.c_char_p]


def load():
    """Returns the ctypes lib or None."""
    from ....utils._native_build import build_and_load
    return build_and_load(_SRC, _SO, configure=_configure)
