"""ctypes bindings for the native sparse-table engine (table.cpp)
(ref: ps/table/memory_sparse_table.cc — the reference PS tables are
C++; this loader mirrors io/_native's build-on-first-use pattern).

Builds libpstable.so with g++ on first use (cached next to the source);
returns None when no toolchain is available — the PS then stays on the
pure-Python row-dict tables."""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "table.cpp")
_SO = os.path.join(_HERE, "libpstable.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _build():
    cmd = ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", _SO]
    subprocess.run(cmd, check=True, capture_output=True)


def load():
    """Returns the ctypes lib or None."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_SO) or (
                    os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_SO)
        except Exception:
            return None
        c = ctypes
        lib.pst_create.restype = c.c_void_p
        lib.pst_create.argtypes = [c.c_int, c.c_int, c.c_uint64]
        lib.pst_destroy.argtypes = [c.c_void_p]
        lib.pst_len.restype = c.c_int64
        lib.pst_len.argtypes = [c.c_void_p]
        lib.pst_pull.argtypes = [c.c_void_p, c.POINTER(c.c_int64),
                                 c.c_int64, c.POINTER(c.c_float)]
        lib.pst_push.argtypes = [c.c_void_p, c.POINTER(c.c_int64),
                                 c.c_int64, c.POINTER(c.c_float),
                                 c.c_float, c.c_float, c.c_float,
                                 c.c_float]
        lib.pst_save.restype = c.c_int
        lib.pst_save.argtypes = [c.c_void_p, c.c_char_p]
        lib.pst_load.restype = c.c_int
        lib.pst_load.argtypes = [c.c_void_p, c.c_char_p]
        _lib = lib
        return _lib
