// Native sparse-embedding table for the parameter server
// (ref: paddle/fluid/distributed/ps/table/memory_sparse_table.cc — the
// reference's PS tables are C++ with contiguous row storage and fused
// per-row optimizer rules; this is the TPU-framework's host-side
// equivalent behind a ctypes ABI, replacing the pure-Python row dict
// for throughput-sensitive deployments).
//
// Design: id -> row index hash map over a contiguous float arena
// (rows + optimizer slots), duplicate-id gradient merging before the
// rule applies (matching the Python SparseTable semantics), fused
// SGD/Adagrad/Adam updates, deterministic per-(seed,id,col) row init,
// and a flat binary snapshot for save/load. One mutex per table:
// callers batch, so the lock is per-batch, not per-row.

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

constexpr int RULE_SGD = 0;
constexpr int RULE_ADAGRAD = 1;
constexpr int RULE_ADAM = 2;

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

// deterministic N(0, 0.01) init per (seed, id, col) via Box-Muller
inline float init_val(uint64_t seed, int64_t id, int col) {
  uint64_t h = splitmix64(seed ^ splitmix64((uint64_t)id * 2654435761ULL
                                            + (uint64_t)col));
  uint64_t h2 = splitmix64(h);
  double u1 = ((h >> 11) + 1.0) * (1.0 / 9007199254740993.0);   // (0,1)
  double u2 = (h2 >> 11) * (1.0 / 9007199254740992.0);          // [0,1)
  double z = std::sqrt(-2.0 * std::log(u1)) *
             std::cos(2.0 * M_PI * u2);
  return (float)(z * 0.01);
}

struct Table {
  int dim;
  int rule;
  uint64_t seed;
  std::unordered_map<int64_t, int64_t> index;  // id -> row number
  std::vector<int64_t> ids;                    // row number -> id
  std::vector<float> rows;                     // [n, dim]
  std::vector<float> s1;                       // adagrad g2 / adam m
  std::vector<float> s2;                       // adam v
  std::vector<int64_t> steps;                  // adam t (per row)
  std::mutex mu;

  int n_slots() const {
    return rule == RULE_ADAGRAD ? 1 : (rule == RULE_ADAM ? 2 : 0);
  }

  int64_t row_of(int64_t id) {
    auto it = index.find(id);
    if (it != index.end()) return it->second;
    int64_t r = (int64_t)ids.size();
    index.emplace(id, r);
    ids.push_back(id);
    rows.resize(rows.size() + dim);
    float* p = rows.data() + r * dim;
    for (int c = 0; c < dim; ++c) p[c] = init_val(seed, id, c);
    if (n_slots() >= 1) s1.resize(s1.size() + dim, 0.0f);
    if (n_slots() >= 2) s2.resize(s2.size() + dim, 0.0f);
    if (rule == RULE_ADAM) steps.push_back(0);
    return r;
  }
};

}  // namespace

extern "C" {

void* pst_create(int dim, int rule, uint64_t seed) try {
  if (dim <= 0 || rule < 0 || rule > 2) return nullptr;
  Table* t = new Table();
  t->dim = dim;
  t->rule = rule;
  t->seed = seed;
  return t;
} catch (...) {
  return nullptr;
}

void pst_destroy(void* h) { delete (Table*)h; }

int64_t pst_len(void* h) {
  Table* t = (Table*)h;
  std::lock_guard<std::mutex> g(t->mu);
  return (int64_t)t->ids.size();
}

void pst_pull(void* h, const int64_t* ids, int64_t n, float* out) try {
  Table* t = (Table*)h;
  std::lock_guard<std::mutex> g(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    int64_t r = t->row_of(ids[i]);
    std::memcpy(out + i * t->dim, t->rows.data() + r * t->dim,
                sizeof(float) * t->dim);
  }
} catch (...) {
}

// grads [n, dim]; duplicate ids MERGE before one rule application
// (matching the Python SparseTable / reference push_sparse semantics).
// p1..p4: sgd(lr) | adagrad(lr, eps) | adam(lr, b1, b2, eps)
void pst_push(void* h, const int64_t* ids, int64_t n, const float* grads,
              float p1, float p2, float p3, float p4) try {
  Table* t = (Table*)h;
  std::lock_guard<std::mutex> g(t->mu);
  const int dim = t->dim;
  // merge duplicates: id -> accumulated grad (order-preserving rows)
  std::unordered_map<int64_t, int64_t> uniq;
  std::vector<int64_t> order;
  std::vector<float> acc;
  uniq.reserve((size_t)n * 2);
  for (int64_t i = 0; i < n; ++i) {
    auto it = uniq.find(ids[i]);
    int64_t slot;
    if (it == uniq.end()) {
      slot = (int64_t)order.size();
      uniq.emplace(ids[i], slot);
      order.push_back(ids[i]);
      acc.resize(acc.size() + dim, 0.0f);
    } else {
      slot = it->second;
    }
    float* a = acc.data() + slot * dim;
    const float* gsrc = grads + i * dim;
    for (int c = 0; c < dim; ++c) a[c] += gsrc[c];
  }
  for (size_t u = 0; u < order.size(); ++u) {
    int64_t r = t->row_of(order[u]);
    float* w = t->rows.data() + r * dim;
    const float* gv = acc.data() + (int64_t)u * dim;
    if (t->rule == RULE_SGD) {
      const float lr = p1;
      for (int c = 0; c < dim; ++c) w[c] -= lr * gv[c];
    } else if (t->rule == RULE_ADAGRAD) {
      const float lr = p1, eps = p2;
      float* g2 = t->s1.data() + r * dim;
      for (int c = 0; c < dim; ++c) {
        g2[c] += gv[c] * gv[c];
        w[c] -= lr * gv[c] / (std::sqrt(g2[c]) + eps);
      }
    } else {  // adam
      const float lr = p1, b1 = p2, b2 = p3, eps = p4;
      float* m = t->s1.data() + r * dim;
      float* v = t->s2.data() + r * dim;
      int64_t step = ++t->steps[r];
      const float c1 = 1.0f - std::pow(b1, (float)step);
      const float c2 = 1.0f - std::pow(b2, (float)step);
      for (int c = 0; c < dim; ++c) {
        m[c] = b1 * m[c] + (1.0f - b1) * gv[c];
        v[c] = b2 * v[c] + (1.0f - b2) * gv[c] * gv[c];
        w[c] -= lr * (m[c] / c1) / (std::sqrt(v[c] / c2) + eps);
      }
    }
  }
} catch (...) {
}

// flat binary snapshot: magic, dim, rule, n, then ids / rows / slots
int pst_save(void* h, const char* path) try {
  Table* t = (Table*)h;
  std::lock_guard<std::mutex> g(t->mu);
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  const uint64_t magic = 0x70737462UL;  // "pstb"
  uint64_t dim = (uint64_t)t->dim, rule = (uint64_t)t->rule;
  uint64_t n = (uint64_t)t->ids.size();
  int ok = 1;
  ok &= std::fwrite(&magic, 8, 1, f) == 1;
  ok &= std::fwrite(&dim, 8, 1, f) == 1;
  ok &= std::fwrite(&rule, 8, 1, f) == 1;
  ok &= std::fwrite(&t->seed, 8, 1, f) == 1;
  ok &= std::fwrite(&n, 8, 1, f) == 1;
  if (n) {
    ok &= std::fwrite(t->ids.data(), 8, n, f) == n;
    ok &= std::fwrite(t->rows.data(), 4, n * dim, f) == n * dim;
    if (t->n_slots() >= 1)
      ok &= std::fwrite(t->s1.data(), 4, n * dim, f) == n * dim;
    if (t->n_slots() >= 2)
      ok &= std::fwrite(t->s2.data(), 4, n * dim, f) == n * dim;
    if (t->rule == RULE_ADAM)
      ok &= std::fwrite(t->steps.data(), 8, n, f) == n;
  }
  std::fclose(f);
  return ok ? 0 : -1;
} catch (...) {
  return -1;
}

// STAGED load: everything reads into temporaries and commits only on
// full success — a truncated/corrupt snapshot must never leave the
// table with an index pointing past a shrunken arena (heap OOB on the
// next pull). The on-disk row count is validated against the actual
// file size before any allocation, and the whole body is exception-
// guarded: C++ exceptions must not cross the C ABI into ctypes.
int pst_load(void* h, const char* path) try {
  Table* t = (Table*)h;
  std::lock_guard<std::mutex> g(t->mu);
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  uint64_t magic = 0, dim = 0, rule = 0, seed = 0, n = 0;
  int ok = 1;
  ok &= std::fread(&magic, 8, 1, f) == 1 && magic == 0x70737462UL;
  ok &= std::fread(&dim, 8, 1, f) == 1;
  ok &= std::fread(&rule, 8, 1, f) == 1;
  ok &= std::fread(&seed, 8, 1, f) == 1;
  ok &= std::fread(&n, 8, 1, f) == 1;
  if (!ok || (int)dim != t->dim || (int)rule != t->rule) {
    std::fclose(f);
    return -1;
  }
  // size sanity: header-claimed n must match what the file can hold
  long data_start = std::ftell(f);
  std::fseek(f, 0, SEEK_END);
  long fsize = std::ftell(f);
  std::fseek(f, data_start, SEEK_SET);
  uint64_t per_row = 8 + 4 * dim * (1 + (uint64_t)t->n_slots())
                     + (t->rule == RULE_ADAM ? 8 : 0);
  if (n > 0 && (fsize < data_start
                || (uint64_t)(fsize - data_start) < n * per_row)) {
    std::fclose(f);
    return -1;
  }
  std::vector<int64_t> ids(n, 0);
  std::vector<float> rows(n * dim, 0.0f);
  std::vector<float> s1(t->n_slots() >= 1 ? n * dim : 0, 0.0f);
  std::vector<float> s2(t->n_slots() >= 2 ? n * dim : 0, 0.0f);
  std::vector<int64_t> steps(t->rule == RULE_ADAM ? n : 0, 0);
  if (n) {
    ok &= std::fread(ids.data(), 8, n, f) == n;
    ok &= std::fread(rows.data(), 4, n * dim, f) == n * dim;
    if (t->n_slots() >= 1)
      ok &= std::fread(s1.data(), 4, n * dim, f) == n * dim;
    if (t->n_slots() >= 2)
      ok &= std::fread(s2.data(), 4, n * dim, f) == n * dim;
    if (t->rule == RULE_ADAM)
      ok &= std::fread(steps.data(), 8, n, f) == n;
  }
  std::fclose(f);
  if (!ok) return -1;
  std::unordered_map<int64_t, int64_t> index;
  index.reserve(n * 2);
  for (uint64_t r = 0; r < n; ++r) index.emplace(ids[r], (int64_t)r);
  // commit
  t->seed = seed;
  t->ids.swap(ids);
  t->rows.swap(rows);
  t->s1.swap(s1);
  t->s2.swap(s2);
  t->steps.swap(steps);
  t->index.swap(index);
  return 0;
} catch (...) {
  return -1;
}

}  // extern "C"
