"""Distributed checkpoint with resharding-on-load
(ref: python/paddle/distributed/checkpoint/save_state_dict.py:104
save_state_dict, load_state_dict.py — per-rank shard files + a global
`metadata` mapping tensor -> (file, offset) with resharding across
different mesh/degree on load).

TPU-native layout: one `.metadata.json` (tensor name -> dtype, global
shape, shard files with index slices) plus per-process `.shard_{i}.npz`
holding the locally-addressable shards. Under single-controller JAX one
process usually addresses every device, so saves are one shard file; the
format still records per-shard slices so a future multi-host run (or a
differently-sharded reload) reads only what it needs — the same metadata
idea as the reference. Loading `device_put`s each assembled tensor to the
requested sharding: GSPMD-level "reshard on load".

Async: `save_state_dict(..., async_save=True)` snapshots to host then
writes in a daemon thread (the reference gets this from its dedicated
checkpoint threads; Orbax-style)."""
from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict", "wait_save"]

_pending: list = []


def _to_host_shards(arr):
    """[(index_tuple, np.ndarray)] for every addressable shard."""
    if isinstance(arr, jax.Array) and len(arr.sharding.device_set) > 1:
        out = []
        seen = set()
        for s in arr.addressable_shards:
            key = tuple((sl.start or 0, sl.stop) for sl in s.index)
            if key in seen:     # replicated copies: keep one
                continue
            seen.add(key)
            out.append((s.index, np.asarray(s.data)))
        return out
    return [((slice(None),) * np.ndim(arr), np.asarray(arr))]


def _index_to_json(index, shape):
    spec = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        spec.append([start, stop])
    return spec


def save_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0, async_save: bool = False):
    """state_dict: name -> Tensor/array (possibly sharded over a mesh)."""
    os.makedirs(path, exist_ok=True)
    rank = jax.process_index()

    meta = {"tensors": {}, "world_size": jax.process_count(),
            "format": "paddle_tpu.dist_ckpt.v1"}
    rank_shards: Dict[str, list] = {}   # this rank's shard entries
    blobs = {}
    for name, t in state_dict.items():
        arr = t.data if isinstance(t, Tensor) else t
        if not isinstance(arr, (jax.Array, np.ndarray, jnp.ndarray)):
            arr = np.asarray(arr)
        shards = _to_host_shards(arr)
        shape = tuple(int(s) for s in np.shape(arr))
        dtype_name = str(np.asarray(shards[0][1]).dtype)
        entries = []
        for i, (index, data) in enumerate(shards):
            key = f"{name}::shard{i}"
            # npz has no portable bf16: store as f32 bytes, dtype in meta
            blobs[key] = (data.astype(np.float32)
                          if dtype_name == "bfloat16" else data)
            entries.append({
                "key": key, "file": f"shard_{rank}.npz",
                "slices": _index_to_json(index, shape)})
        rank_shards[name] = entries
        meta["tensors"][name] = {
            "dtype": dtype_name, "shape": list(shape)}

    def _write():
        np.savez(os.path.join(path, f"shard_{rank}.npz"), **blobs)
        # every rank records which shards IT holds (a multi-host save
        # on a shared filesystem merges all fragments at load time —
        # the coordinator cannot see other ranks' addressable shards)
        with open(os.path.join(path, f"shards_rank{rank}.json"), "w") as f:
            json.dump(rank_shards, f)
        if rank == coordinator_rank:
            with open(os.path.join(path, "metadata.json"), "w") as f:
                json.dump(meta, f)

    if async_save:
        th = threading.Thread(target=_write, daemon=True)
        th.start()
        _pending.append(th)
    else:
        _write()


def wait_save():
    while _pending:
        _pending.pop().join()


def load_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0,
                    mesh=None, shardings: Optional[Dict] = None) -> Dict:
    """Fills `state_dict` (name -> Tensor with target shapes/shardings)
    in place, resharding saved shards as needed; also returns it.
    If `state_dict` is empty, reconstructs every tensor replicated (or per
    `shardings`: name -> NamedSharding)."""
    import glob as _glob
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    shard_map: Dict[str, list] = {}
    for frag in sorted(_glob.glob(os.path.join(path, "shards_rank*.json"))):
        with open(frag) as f:
            for name, entries in json.load(f).items():
                shard_map.setdefault(name, []).extend(entries)
    files = {}

    def blob(fname, key):
        if fname not in files:
            files[fname] = np.load(os.path.join(path, fname))
        return files[fname][key]

    names = list(state_dict.keys()) or list(meta["tensors"].keys())
    out = state_dict if state_dict else {}
    for name in names:
        info = meta["tensors"].get(name)
        if info is None:
            raise KeyError(f"{name} not in checkpoint {path}")
        full = np.zeros(tuple(info["shape"]),
                        dtype=np.dtype(info["dtype"]
                                       if info["dtype"] != "bfloat16"
                                       else np.float32))
        for sh in shard_map.get(name, []):
            idx = tuple(slice(a, b) for a, b in sh["slices"])
            piece = blob(sh["file"], sh["key"])
            full[idx] = piece.astype(full.dtype)
        if info["dtype"] == "bfloat16":
            arr = jnp.asarray(full, dtype=jnp.bfloat16)
        else:
            arr = jnp.asarray(full)
        target = out.get(name) if isinstance(out, dict) else None
        sharding = (shardings or {}).get(name)
        if sharding is None and isinstance(target, Tensor) and \
                isinstance(target.data, jax.Array):
            try:
                sharding = target.data.sharding
            except Exception:
                sharding = None
        if sharding is not None:
            arr = jax.device_put(arr, sharding)     # reshard on load
        if isinstance(target, Tensor):
            target.data = arr.astype(target.dtype)
        else:
            out[name] = Tensor(arr)
    return out
