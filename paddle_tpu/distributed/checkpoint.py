"""Distributed checkpoint with resharding-on-load
(ref: python/paddle/distributed/checkpoint/save_state_dict.py:104
save_state_dict, load_state_dict.py — per-rank shard files + a global
`metadata` mapping tensor -> (file, offset) with resharding across
different mesh/degree on load).

TPU-native layout: one `metadata.json` (tensor name -> dtype, global
shape, per-blob CRC32 checksums and the coordinator's slice-coverage
map) plus per-process `shard_{i}.npz` holding the locally-addressable
shards and `shards_rank{i}.json` naming which slices that rank wrote.
Under single-controller JAX one process usually addresses every device,
so saves are one shard file; the format still records per-shard slices
so a future multi-host run (or a differently-sharded reload) reads only
what it needs — the same metadata idea as the reference. Loading
`device_put`s each assembled tensor to the requested sharding:
GSPMD-level "reshard on load".

Durability (format v2): every file is committed via tmp + fsync +
`os.replace` (framework.io.atomic_write), so a crash at any instant
leaves no torn visible file; `metadata.json` is written LAST and is the
commit point. On load the shard slices must exactly tile each tensor's
global shape and every blob's CRC32 must match — missing / overlapping /
corrupt shards raise `CheckpointError` instead of silently zero-filling,
which is what makes ElasticManager's fall-back-to-previous-checkpoint
recovery sound.

Async: `save_state_dict(..., async_save=True)` snapshots to host then
writes in a background thread drawn from a bounded in-flight window
(the reference gets this from its dedicated checkpoint threads;
Orbax-style). A second async save to the SAME path waits for the
in-flight one instead of racing it, and write errors are captured and
re-raised by `wait_save()` or the next `save_state_dict` call — they do
not die silently in a daemon thread."""
from __future__ import annotations

import json
import os
import threading
import time
import zlib
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.io import atomic_write
from ..observability import goodput as _goodput
from ..observability import metrics as _m
from ..observability.spans import span as _span
from ..tensor import Tensor

# checkpoint telemetry (ISSUE 3): durations, bytes and verify failures.
# The ckpt.save / ckpt.load spans also put checkpoint phases into the
# span ring + XProf, and — through the flight recorder's write-through
# sink — let the chaos suite see which phase a killed worker died in.
_CKPT_SAVES = _m.counter("ckpt.saves_total", "completed checkpoint saves")
_CKPT_LOADS = _m.counter("ckpt.loads_total", "completed checkpoint loads")
_CKPT_BYTES_WRITTEN = _m.counter("ckpt.bytes_written_total",
                                 "tensor bytes written by checkpoint saves")
_CKPT_VERIFY_FAILURES = _m.counter(
    "ckpt.verify_failures_total",
    "CheckpointError raised by load/verify (torn, missing, corrupt)")
_CKPT_SAVE_SECONDS = _m.histogram("ckpt.save_seconds",
                                  "checkpoint save wall time")
_CKPT_LOAD_SECONDS = _m.histogram("ckpt.load_seconds",
                                  "checkpoint load wall time")

__all__ = ["save_state_dict", "load_state_dict", "wait_save",
           "verify_checkpoint", "CheckpointError"]

_FORMAT_V1 = "paddle_tpu.dist_ckpt.v1"
_FORMAT_V2 = "paddle_tpu.dist_ckpt.v2"


class CheckpointError(RuntimeError):
    """A checkpoint is incomplete, torn, or corrupt — the caller must NOT
    trust its tensors (ElasticManager falls back to an older one)."""


def _incarnation() -> int:
    """elastic.incarnation, imported lazily (elastic imports this module
    at top level) — ONE parser for PADDLE_INCARNATION, malformed-env
    tolerant, so a typo'd value can't fail every checkpoint save."""
    from .elastic import incarnation
    return incarnation()


def _crc(data: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(data).tobytes()) & 0xFFFFFFFF


def _to_host_shards(arr):
    """[(index_tuple, np.ndarray)] for every addressable shard."""
    if isinstance(arr, jax.Array) and len(arr.sharding.device_set) > 1:
        out = []
        seen = set()
        for s in arr.addressable_shards:
            key = tuple((sl.start or 0, sl.stop) for sl in s.index)
            if key in seen:     # replicated copies: keep one
                continue
            seen.add(key)
            out.append((s.index, np.asarray(s.data)))
        return out
    return [((slice(None),) * np.ndim(arr), np.asarray(arr))]


def _index_to_json(index, shape):
    spec = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        spec.append([start, stop])
    return spec


# -- bounded async-save machinery -------------------------------------------

class _PendingSave:
    def __init__(self, path: str):
        self.path = path            # realpath of the checkpoint dir
        self.thread: Optional[threading.Thread] = None
        self.error: Optional[BaseException] = None


_MAX_PENDING = max(1, int(os.environ.get("PADDLE_CKPT_MAX_PENDING", "2")))
_pending: List[_PendingSave] = []   # in-flight saves, start order
_async_errors: List[BaseException] = []


def _join(rec: _PendingSave):
    # the caller (trainer) blocks here on an in-flight async write —
    # checkpoint stall in the goodput ledger (a finished thread joins
    # instantly and attributes ~0)
    with _goodput.time_section("checkpoint_stall"):
        rec.thread.join()
    if rec in _pending:
        _pending.remove(rec)
    if rec.error is not None:
        _async_errors.append(rec.error)


def _raise_async_errors():
    for rec in [r for r in _pending if not r.thread.is_alive()]:
        _join(rec)                  # reap finished threads
    if _async_errors:
        first = _async_errors[0]
        extra = len(_async_errors) - 1
        _async_errors.clear()
        raise CheckpointError(
            "async checkpoint save failed: %r%s" % (
                first, " (+%d more)" % extra if extra else "")) from first


def wait_save():
    """Block until every in-flight async save lands; re-raise the first
    captured write error (further errors are noted in the message)."""
    while _pending:
        _join(_pending[0])
    _raise_async_errors()


# -- save --------------------------------------------------------------------

def save_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0, async_save: bool = False):
    """state_dict: name -> Tensor/array (possibly sharded over a mesh).

    Raises CheckpointError here if a PREVIOUS async save failed — the
    error surfaces at the next checkpoint attempt instead of vanishing
    in a daemon thread."""
    _raise_async_errors()
    os.makedirs(path, exist_ok=True)
    rank = jax.process_index()
    world = jax.process_count()

    meta = {"tensors": {}, "world_size": world, "format": _FORMAT_V2,
            # true when the coordinator's shard entries in this metadata
            # are the WHOLE coverage map (single-controller common case);
            # multi-host saves merge the per-rank index fragments instead
            "coverage_complete": world == 1,
            # forensics for coordinated elastic recovery (ISSUE 6):
            # which relaunch of which rank committed this checkpoint —
            # post-mortems of a chaos run can line checkpoints up
            # against the supervisor's death/relaunch records
            "writer": {"rank": rank, "incarnation": _incarnation()}}
    rank_shards: Dict[str, list] = {}   # this rank's shard entries
    blobs = {}
    for name, t in state_dict.items():
        arr = t.data if isinstance(t, Tensor) else t
        if not isinstance(arr, (jax.Array, np.ndarray, jnp.ndarray)):
            arr = np.asarray(arr)
        shards = _to_host_shards(arr)
        shape = tuple(int(s) for s in np.shape(arr))
        dtype_name = str(np.asarray(shards[0][1]).dtype)
        entries = []
        for i, (index, data) in enumerate(shards):
            key = f"{name}::shard{i}"
            # npz has no portable bf16: store as f32 bytes, dtype in meta
            stored = (data.astype(np.float32)
                      if dtype_name == "bfloat16" else data)
            blobs[key] = stored
            entries.append({
                "key": key, "file": f"shard_{rank}.npz",
                "slices": _index_to_json(index, shape),
                "crc32": _crc(stored)})
        rank_shards[name] = entries
        meta["tensors"][name] = {
            "dtype": dtype_name, "shape": list(shape),
            # per-blob checksums + slice-coverage map (coordinator view)
            "shards": entries}

    def _write():
        t0 = time.perf_counter()
        with _span("ckpt.save", path=path, rank=rank):
            atomic_write(os.path.join(path, f"shard_{rank}.npz"),
                         lambda f: np.savez(f, **blobs),
                         fault_name="ckpt.write_shard")
            # every rank records which shards IT holds (a multi-host save
            # on a shared filesystem merges all fragments at load time —
            # the coordinator cannot see other ranks' addressable shards)
            frag = json.dumps(rank_shards).encode()
            atomic_write(os.path.join(path, f"shards_rank{rank}.json"),
                         lambda f: f.write(frag),
                         fault_name="ckpt.write_index")
            if rank == coordinator_rank:
                # metadata last: its presence is the commit point
                mb = json.dumps(meta).encode()
                atomic_write(os.path.join(path, "metadata.json"),
                             lambda f: f.write(mb),
                             fault_name="ckpt.write_meta")
        if _m.enabled():
            _CKPT_SAVES.inc()
            _CKPT_BYTES_WRITTEN.inc(
                sum(int(b.nbytes) for b in blobs.values()))
            _CKPT_SAVE_SECONDS.observe(time.perf_counter() - t0)

    apath = os.path.realpath(path)
    # any save to a path with an in-flight async save WAITS for it —
    # concurrent writers to one directory share the pid-suffixed tmp
    # names and would interleave torn state (sync saves included)
    for rec in [r for r in _pending if r.path == apath]:
        _join(rec)
    if not async_save:
        _raise_async_errors()
        # synchronous commit blocks the trainer for the whole write
        with _goodput.time_section("checkpoint_stall"):
            _write()
        return

    while len(_pending) >= _MAX_PENDING:    # bounded in-flight window
        _join(_pending[0])
    _raise_async_errors()

    rec = _PendingSave(apath)

    def _run():
        try:
            _write()
        except BaseException as e:      # captured; re-raised on the
            rec.error = e               # caller's thread, never lost

    rec.thread = threading.Thread(target=_run, daemon=True,
                                  name="paddle-ckpt-save")
    _pending.append(rec)
    rec.thread.start()


# -- load / verify -----------------------------------------------------------

def _read_json(fp: str, desc: str):
    try:
        with open(fp) as f:
            return json.load(f)
    except FileNotFoundError:
        raise CheckpointError(f"checkpoint {desc} missing: {fp}") from None
    except (json.JSONDecodeError, UnicodeDecodeError, OSError) as e:
        raise CheckpointError(
            f"checkpoint {desc} torn/unreadable: {fp}: {e}") from e


def _read_index(path: str):
    """metadata + merged per-rank shard map; raises CheckpointError on
    missing/torn metadata or index fragments."""
    meta = _read_json(os.path.join(path, "metadata.json"), "metadata")
    if not isinstance(meta, dict) or "tensors" not in meta:
        raise CheckpointError(
            f"checkpoint metadata malformed: {path}/metadata.json")
    fmt = meta.get("format", _FORMAT_V1)
    if fmt not in (_FORMAT_V1, _FORMAT_V2):
        raise CheckpointError(
            f"unknown checkpoint format {fmt!r} in {path}")
    world = int(meta.get("world_size", 1))
    shard_map: Dict[str, list] = {}
    for r in range(world):      # every rank's fragment must be present
        frag = _read_json(os.path.join(path, f"shards_rank{r}.json"),
                          f"shard index (rank {r})")
        for name, entries in frag.items():
            shard_map.setdefault(name, []).extend(entries)
    return meta, shard_map


def _dedup_replicas(entries):
    """Replicated tensors are saved once per rank with identical slices;
    keep one entry per distinct slice spec."""
    seen = set()
    out = []
    for e in entries:
        key = tuple(tuple(s) for s in e["slices"])
        if key in seen:
            continue
        seen.add(key)
        out.append(e)
    return out


def _verify_tiling(name: str, shape: tuple, entries: list, path: str):
    """Shard slices must EXACTLY tile the global shape — a gap means a
    lost shard (the old code zero-filled it), an overlap means two ranks
    claim the same elements. Interval arithmetic only (in-bounds +
    pairwise-disjoint + volumes summing to the tensor's): no dense
    coverage array, so verifying a multi-GB tensor costs O(shards^2)
    ints, not O(elements) host memory mid-crash-recovery."""
    for e in entries:
        sl = e["slices"]
        if len(sl) != len(shape) or any(
                not (0 <= a <= b <= dim)
                for (a, b), dim in zip(sl, shape)):
            raise CheckpointError(
                f"shard slices {sl} for '{name}' out of bounds for "
                f"shape {list(shape)} in {path}")

    def _vol(slices):
        v = 1
        for a, b in slices:
            v *= b - a
        return v

    boxes = [e["slices"] for e in entries if _vol(e["slices"])]
    for i in range(len(boxes)):
        for j in range(i + 1, len(boxes)):
            # boxes intersect iff their intervals overlap in EVERY dim
            # (vacuously true for 0-d scalars: duplicate claims)
            if all(max(a1, a2) < min(b1, b2)
                   for (a1, b1), (a2, b2) in zip(boxes[i], boxes[j])):
                raise CheckpointError(
                    f"shards for '{name}' do not tile shape "
                    f"{list(shape)} in {path}: slices {boxes[i]} and "
                    f"{boxes[j]} are multiply covered — refusing to "
                    f"load")
    total = 1
    for dim in shape:
        total *= dim
    covered = sum(_vol(b) for b in boxes)
    if covered != total:       # disjoint + in-bounds => covered <= total
        raise CheckpointError(
            f"shards for '{name}' do not tile shape {list(shape)} in "
            f"{path}: {total - covered} element(s) uncovered — refusing "
            f"to load (zero-filling gaps silently corrupts weights)")


class _BlobReader:
    """npz access with per-blob CRC32 verification; torn zip containers
    and checksum mismatches surface as CheckpointError."""

    def __init__(self, path: str):
        self.path = path
        self._files: Dict[str, object] = {}

    def get(self, fname: str, key: str, crc: Optional[int]):
        if fname not in self._files:
            fp = os.path.join(self.path, fname)
            try:
                self._files[fname] = np.load(fp)
            except FileNotFoundError:
                raise CheckpointError(
                    f"checkpoint shard file missing: {fp}") from None
            except Exception as e:
                raise CheckpointError(
                    f"checkpoint shard file torn/unreadable: {fp}: "
                    f"{e}") from e
        try:
            arr = self._files[fname][key]
        except KeyError:
            raise CheckpointError(
                f"blob {key!r} missing from {fname} in "
                f"{self.path}") from None
        except CheckpointError:
            raise
        except Exception as e:      # zip member CRC failure on lazy read
            raise CheckpointError(
                f"blob {key!r} in {fname} torn/unreadable: {e}") from e
        if crc is not None and _crc(arr) != crc:
            raise CheckpointError(
                f"checksum mismatch for blob {key!r} in {fname} "
                f"(stored crc32 {crc}, recomputed {_crc(arr)}) — "
                f"corrupt shard in {self.path}")
        return arr

    def close(self):
        for z in self._files.values():
            try:
                z.close()
            except Exception:
                pass
        self._files.clear()


def verify_checkpoint(path: str, names=None) -> dict:
    """Full integrity check WITHOUT assembling tensors: metadata + every
    index fragment readable, shard slices exactly tile every tensor, and
    every blob's CRC32 matches. Returns the metadata dict; raises
    CheckpointError otherwise. ElasticManager.restore() runs this before
    trusting a checkpoint."""
    try:
        return _verify_checkpoint_impl(path, names)
    except CheckpointError:
        _CKPT_VERIFY_FAILURES.inc()
        raise


def _verify_checkpoint_impl(path: str, names=None) -> dict:
    meta, shard_map = _read_index(path)
    reader = _BlobReader(path)
    try:
        for name in (names if names is not None else meta["tensors"]):
            info = meta["tensors"].get(name)
            if info is None:
                raise CheckpointError(
                    f"tensor '{name}' not in checkpoint {path}")
            entries = _dedup_replicas(shard_map.get(name, []))
            _verify_tiling(name, tuple(info["shape"]), entries, path)
            for sh in entries:
                reader.get(sh["file"], sh["key"], sh.get("crc32"))
    finally:
        reader.close()
    return meta


def load_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0,
                    mesh=None, shardings: Optional[Dict] = None) -> Dict:
    """Fills `state_dict` (name -> Tensor with target shapes/shardings)
    in place, resharding saved shards as needed; also returns it.
    If `state_dict` is empty, reconstructs every tensor replicated (or per
    `shardings`: name -> NamedSharding). Integrity failures (missing or
    overlapping shards, checksum mismatch, torn files) raise
    CheckpointError before any target tensor is mutated."""
    t0 = time.perf_counter()
    try:
        with _span("ckpt.load", path=path):
            out = _load_state_dict_impl(state_dict, path,
                                        shardings=shardings)
    except CheckpointError:
        _CKPT_VERIFY_FAILURES.inc()
        raise
    if _m.enabled():
        _CKPT_LOADS.inc()
        _CKPT_LOAD_SECONDS.observe(time.perf_counter() - t0)
    return out


def _load_state_dict_impl(state_dict: Dict, path: str,
                          shardings: Optional[Dict] = None) -> Dict:
    meta, shard_map = _read_index(path)
    names = list(state_dict.keys()) or list(meta["tensors"].keys())
    out = state_dict if state_dict else {}
    reader = _BlobReader(path)
    assembled = {}
    try:
        # phase 1: assemble + verify on host — a corrupt blob found here
        # leaves the caller's tensors untouched (no partial restore)
        for name in names:
            info = meta["tensors"].get(name)
            if info is None:
                raise CheckpointError(
                    f"tensor '{name}' not in checkpoint {path}")
            shape = tuple(info["shape"])
            entries = _dedup_replicas(shard_map.get(name, []))
            _verify_tiling(name, shape, entries, path)
            full = np.empty(shape,
                            dtype=np.dtype(info["dtype"]
                                           if info["dtype"] != "bfloat16"
                                           else np.float32))
            for sh in entries:
                piece = reader.get(sh["file"], sh["key"], sh.get("crc32"))
                want = tuple(b - a for a, b in sh["slices"])
                if tuple(piece.shape) != want:
                    raise CheckpointError(
                        f"blob {sh['key']!r} shape {tuple(piece.shape)} "
                        f"!= declared slice shape {want} in {path}")
                full[tuple(slice(a, b) for a, b in sh["slices"])] = \
                    piece.astype(full.dtype)
            assembled[name] = (info, full)
    finally:
        reader.close()

    # phase 2: device placement / reshard
    for name, (info, full) in assembled.items():
        if info["dtype"] == "bfloat16":
            arr = jnp.asarray(full, dtype=jnp.bfloat16)
        else:
            arr = jnp.asarray(full)
        target = out.get(name) if isinstance(out, dict) else None
        sharding = (shardings or {}).get(name)
        if sharding is None and isinstance(target, Tensor) and \
                isinstance(target.data, jax.Array):
            try:
                sharding = target.data.sharding
            except Exception:
                sharding = None
        if sharding is not None:
            arr = jax.device_put(arr, sharding)     # reshard on load
        if isinstance(target, Tensor):
            target.data = arr.astype(target.dtype)
        else:
            out[name] = Tensor(arr)
    return out
