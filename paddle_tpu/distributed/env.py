"""Process/bootstrap layer (ref: paddle/fluid/distributed/collective TCPStore
rendezvous + ProcessGroup init, python/paddle/distributed/parallel.py:943).

TPU-native: `jax.distributed.initialize` is the rendezvous (coordination
service replaces TCPStore); collectives are XLA-compiled, so there is no
ProcessGroup object to create per ring — only mesh bookkeeping.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False
_jax_coordinated = False    # init_parallel_env actually ran jax.distributed


def init_parallel_env(strategy=None):
    """ref: paddle.distributed.init_parallel_env."""
    global _initialized, _jax_coordinated
    if _initialized:
        return
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    nproc = int(os.environ.get("PADDLE_TRAINERS_NUM",
                               os.environ.get("JAX_NUM_PROCESSES", "1")))
    pid = int(os.environ.get("PADDLE_TRAINER_ID",
                             os.environ.get("JAX_PROCESS_ID", "0")))
    # preflight health barrier (ISSUE 6): under a supervising launcher,
    # refuse to walk into the rendezvous (which would hang indefinitely)
    # until every expected rank has a fresh heartbeat — a dead peer
    # surfaces as a TimeoutError naming its rank instead. No-op (one env
    # lookup) when unsupervised.
    from . import collective
    collective.health_barrier("init")
    if coord and nproc > 1:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nproc, process_id=pid)
        _jax_coordinated = True
    _initialized = True


def reinit_coordinator(world: int, rank: int) -> bool:
    """Re-initialize the jax.distributed coordination service across an
    ELASTIC world change (ISSUE 13): a degraded/grown world has a
    different process count and (contiguous-remapped) process ids, and
    the old coordinator membership would reject or wedge the next
    cross-process rendezvous. Tears the client down and re-runs the
    rendezvous at the new (world, rank). No-op — returns False — when
    this process never ran a multi-process `jax.distributed.initialize`
    (single-controller jobs, the host-channel CPU test world), so the
    unsupervised paths stay bitwise untouched."""
    if not _jax_coordinated:
        return False
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if not coord:
        return False
    try:
        jax.distributed.shutdown()
    except Exception:
        # a dead coordinator makes shutdown raise; the re-init below is
        # the actual recovery, so a noisy teardown must not stop it
        pass
    # _jax_coordinated stays ARMED across a failed initialize: a
    # transiently unreachable coordinator must not latch re-init off
    # for the rest of the process — the next world change retries (the
    # caller warns about this failure)
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=int(world),
                               process_id=int(rank))
    return True


def is_initialized() -> bool:
    return _initialized


def get_rank(group=None) -> int:
    if group is not None:
        return group.rank
    return jax.process_index()


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    # logical world size = number of addressable devices across processes
    return jax.device_count()


def get_device_count() -> int:
    return jax.local_device_count()


class ParallelEnv:
    """ref: python/paddle/distributed/parallel.py::ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS",
                              "127.0.0.1:6170").split(",")
