"""paddle.distributed.communication (ref: python/paddle/distributed/
communication/ — the op-level API re-exported at paddle.distributed top
level, plus the `stream` variants)."""
from ..collective import (  # noqa: F401
    ReduceOp, all_gather, all_reduce, alltoall, barrier, broadcast,
    reduce, reduce_scatter, scatter)
from . import stream  # noqa: F401

__all__ = ["stream", "ReduceOp", "all_reduce", "all_gather", "broadcast",
           "reduce", "reduce_scatter", "alltoall", "scatter", "barrier"]
