"""paddle.distributed.communication.stream (ref: python/paddle/
distributed/communication/stream/*.py — collective variants taking
sync_op / use_calc_stream).

TPU-native: XLA exposes no user-visible streams; dispatch is async and
ordering is the compiler's job (SURVEY §2.4 TPU mapping), so the stream
variants are the same collectives with the scheduling knobs accepted for
API compatibility. sync_op=False returns a completed no-op task whose
wait() is immediate — matching semantics, since the result array is
already a future under JAX's async dispatch."""
from __future__ import annotations

from ...observability import metrics as _m
from .. import collective as C

__all__ = ["all_reduce", "all_gather", "broadcast", "reduce",
           "reduce_scatter", "alltoall", "scatter"]

# the underlying collectives carry the per-op count/bytes/wall-time
# telemetry (collective.py); this counter just tracks how often the
# stream API's async form is exercised
_STREAM_ASYNC = _m.counter("collective.stream_async_total",
                           "stream-API collective calls with sync_op=False")


class _DoneTask:
    """ref: the returned task of async stream ops (task.wait())."""

    def __init__(self, result=None):
        self.result = result

    def wait(self):
        return self.result

    def is_completed(self):
        return True


def _wrap(fn):
    def op(*args, sync_op=True, use_calc_stream=False, **kw):
        if not sync_op:
            _STREAM_ASYNC.inc(1, op=fn.__name__)
        out = fn(*args, **kw)
        return out if sync_op else _DoneTask(out)
    op.__name__ = fn.__name__
    op.__doc__ = fn.__doc__
    return op


all_reduce = _wrap(C.all_reduce)
all_gather = _wrap(C.all_gather)
broadcast = _wrap(C.broadcast)
reduce = _wrap(C.reduce)
reduce_scatter = _wrap(C.reduce_scatter)
alltoall = _wrap(C.alltoall)
scatter = _wrap(C.scatter)
