"""paddle.distributed.rpc (ref: python/paddle/distributed/rpc/ — brpc-based
user RPC: init_rpc, rpc_sync/rpc_async, get_worker_info, shutdown).

TPU-native: the reference's brpc service is replaced by Python's
multiprocessing.connection (authenticated pickle channel) — RPC here is a
host-side control-plane utility (parameter servers, custom coordination),
not a tensor fast path, so the collective/ICI stack is unaffected.
Endpoints rendezvous through the rank-0 registry, mirroring the
reference's master-based worker discovery."""
from __future__ import annotations

import os
import pickle
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass
from multiprocessing.connection import Client, Listener
from typing import Any, Dict, Optional

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo"]

from paddle_tpu.observability import metrics as _m

# rpc call telemetry (connect retries are counted by _net.py, which
# every rpc connect funnels through)
_RPC_CALLS = _m.counter("rpc.calls_total",
                        "outbound rpc calls by target worker")
_RPC_ERRORS = _m.counter("rpc.errors_total",
                         "outbound rpc calls that raised")

def _AUTH(bind_host=None) -> bytes:
    """Per-job secret (distributed/_auth.py) — never a source constant
    (authenticated-pickle channel = RCE to anyone holding the key).
    Listeners pass bind_host: non-loopback binds refuse the derivable
    fallbacks (advisor r3, medium)."""
    from paddle_tpu.distributed._auth import derive_authkey
    return derive_authkey("PADDLE_RPC_AUTHKEY", "rpc", bind_host=bind_host)


@dataclass
class WorkerInfo:
    name: str
    rank: int
    endpoint: str           # host:port


class _State:
    def __init__(self):
        self.me: Optional[WorkerInfo] = None
        self.workers: Dict[str, WorkerInfo] = {}
        self.listener = None
        self.serve_thread = None
        self.registry_thread = None
        self.pool = None
        self.stop = threading.Event()


_state = _State()


def _addr(endpoint):
    host, port = endpoint.rsplit(":", 1)
    return (host, int(port))


def _serve_loop(listener):
    from ..collective import _listener_closed
    while not _state.stop.is_set():
        try:
            conn = listener.accept()
            from paddle_tpu.distributed._net import enable_nodelay
            enable_nodelay(conn)
        except Exception:
            # a peer dropping mid-handshake (port scan, stale key)
            # raises AuthenticationError/EOFError/ConnectionResetError —
            # none of which may kill the service; only listener closure
            # ends the loop (same hardening as collective/ps channels)
            if _listener_closed(listener):
                break
            time.sleep(0.02)
            continue
        _state.pool.submit(_handle, conn)


def _handle(conn):
    try:
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                return
            kind = msg[0]
            if kind == "call":
                _, fn, args, kwargs = msg
                try:
                    res = ("ok", fn(*args, **kwargs))
                except Exception as e:  # errors travel back to the caller
                    res = ("err", e)
                try:
                    conn.send(res)
                except Exception:
                    # unpicklable result/exception: send a picklable repr
                    conn.send(("err", RuntimeError(
                        f"rpc: remote value not picklable: {res[1]!r}")))
            elif kind == "register":           # registry (rank 0 only)
                _, info = msg
                _state.workers[info.name] = info
                conn.send(("ok", None))
            elif kind == "workers":
                # reply IMMEDIATELY with the current table (holding a pool
                # thread until world_size register would deadlock for
                # world_size > pool size); callers poll until complete
                conn.send(("ok", dict(_state.workers)))
            elif kind == "bye":
                conn.send(("ok", None))
                return
    finally:
        conn.close()


def init_rpc(name: str, rank: int = None, world_size: int = None,
             master_endpoint: str = None):
    """ref: rpc/internal.py init_rpc. master_endpoint: host:port of rank 0's
    registry (env PADDLE_MASTER_ENDPOINT fallback)."""
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER_ENDPOINT", "127.0.0.1:18813")
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None \
        else rank
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) \
        if world_size is None else world_size
    _state.stop.clear()
    _state.pool = ThreadPoolExecutor(max_workers=8)

    # my serving endpoint: the master endpoint for rank 0, an ephemeral
    # port otherwise
    mhost = _addr(master_endpoint)[0]
    local_job = mhost.strip().lower() in ("127.0.0.1", "localhost", "::1")
    if rank == 0:
        listener = Listener(_addr(master_endpoint),
                            authkey=_AUTH(bind_host=mhost))
        my_ep = master_endpoint
    else:
        # a loopback master means a single-host job: bind loopback too
        # (no wildcard exposure). Cross-host jobs bind all interfaces and
        # advertise a reachable address (PADDLE_LOCAL_IP overrides;
        # hostname lookup fallback) — the authkey guard then requires an
        # explicit per-job secret.
        import socket as _socket
        bind = "127.0.0.1" if local_job else "0.0.0.0"
        listener = Listener((bind, 0), authkey=_AUTH(bind_host=bind))
        host = os.environ.get("PADDLE_LOCAL_IP")
        if not host:
            if local_job:
                host = "127.0.0.1"
            else:
                try:
                    host = _socket.gethostbyname(_socket.gethostname())
                except OSError:
                    host = "127.0.0.1"
        my_ep = "%s:%d" % (host, listener.address[1])
    _state.listener = listener
    _state.me = WorkerInfo(name, rank, my_ep)
    _state.serve_thread = threading.Thread(
        target=_serve_loop, args=(listener,), daemon=True,
        name="paddle-rpc-serve")
    _state.serve_thread.start()

    # register with rank 0 and fetch the full worker table (shared
    # retry helper — same hardening as worker-to-worker calls)
    deadline = time.time() + 60
    c = _connect_with_retry(_addr(master_endpoint), 60.0)
    c.send(("register", _state.me))
    c.recv()
    while True:
        c.send(("workers", world_size))
        status, table = c.recv()
        if len(table) >= world_size:
            break
        if time.time() > deadline:
            raise TimeoutError(
                f"rpc: only {len(table)}/{world_size} workers registered")
        time.sleep(0.05)
    c.send(("bye", None))
    c.recv()
    c.close()
    _state.workers = table


def get_worker_info(name: str = None) -> WorkerInfo:
    if name is None:
        return _state.me
    return _state.workers[name]


def get_all_worker_infos():
    return list(_state.workers.values())


def _auth_hint() -> str:
    from paddle_tpu.distributed._auth import authkey_source
    return f" (rpc authkey: {authkey_source('PADDLE_RPC_AUTHKEY')})"


def _connect_with_retry(addr, timeout_s: float):
    """Cross-host transport hardening shared by the registry connect and
    worker calls — delegates to the channel-generic
    _net.connect_with_retry (elastic membership polls share it)."""
    from paddle_tpu.distributed._net import connect_with_retry
    return connect_with_retry(addr, _AUTH, timeout_s,
                              describe="rpc: endpoint",
                              auth_hint=_auth_hint,
                              fault_name="rpc.connect")


def _call(to: str, fn, args, kwargs):
    info = _state.workers[to] if to in _state.workers else None
    if info is None:
        raise KeyError(f"rpc: unknown worker '{to}'")
    _RPC_CALLS.inc(1, to=to)
    # short default: these retries run on the SHARED thread pool that
    # also serves inbound calls — a dead peer must not starve it for
    # long (raise PADDLE_RPC_CONNECT_TIMEOUT for flaky networks)
    c = _connect_with_retry(
        _addr(info.endpoint),
        float(os.environ.get("PADDLE_RPC_CONNECT_TIMEOUT", "5")))
    try:
        c.send(("call", fn, tuple(args or ()), dict(kwargs or {})))
        status, payload = c.recv()
        c.send(("bye", None))
        c.recv()
    finally:
        c.close()
    if status == "err":
        _RPC_ERRORS.inc(1, to=to)
        raise payload
    return payload


def rpc_sync(to: str, fn, args=None, kwargs=None, timeout=None):
    """ref: rpc/rpc.py rpc_sync — run fn(*args, **kwargs) on worker `to`.
    timeout (seconds): the call is abandoned (TimeoutError) if the worker
    does not reply in time; the connection is left to the daemon pool."""
    if timeout is None:
        return _call(to, fn, args, kwargs)
    fut = _state.pool.submit(_call, to, fn, args, kwargs)
    return fut.result(timeout=timeout)


def rpc_async(to: str, fn, args=None, kwargs=None, timeout=None) -> Future:
    """ref: rpc_async — returns a Future (fut.wait() paddle-style)."""
    fut = _state.pool.submit(_call, to, fn, args, kwargs)
    fut.wait = fut.result      # paddle API: fut.wait()
    return fut


def shutdown():
    _state.stop.set()
    if _state.listener is not None:
        try:
            _state.listener.close()
        except OSError:
            pass
    if _state.pool is not None:
        _state.pool.shutdown(wait=False)
    _state.workers.clear()
    _state.me = None
