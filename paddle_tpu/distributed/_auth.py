"""Shared per-job authkey derivation for host-side pickle channels.

multiprocessing.connection deserializes pickles after HMAC auth, so a
constant key in public source would hand RCE to anything that can reach
the port (ref hazard: paddle/fluid/distributed uses brpc with its own
auth; our host channels must supply an equivalent). Every channel
(collective p2p, parameter server, rpc, elastic) derives its key here:

1. an explicit env var set by the launcher (strongest, per-job),
2. else a digest of ONE job-identity env var + a namespace tag (not
   guessable from source alone). Exactly one var is used — the FIRST
   set among PADDLE_MASTER, PADDLE_TRAINER_ENDPOINTS,
   PADDLE_PSERVERS_IP_PORT_LIST — never a concatenation, because
   different processes of one job may legitimately see different
   SUBSETS of these (a PS server launched with only the pserver list
   must still derive the same key as a trainer that has all three).
   Launchers must publish the highest-priority var to every process.
3. else — bare local runs — a same-user 0600 secret file (one file per
   namespace, so channels stay key-isolated even in this mode),
   created atomically so concurrent ranks converge on ONE key.
"""
from __future__ import annotations

import os

__all__ = ["derive_authkey"]

# priority order of the job-identity vars; see module docstring
_JOB_VARS = ("PADDLE_MASTER", "PADDLE_TRAINER_ENDPOINTS",
             "PADDLE_PSERVERS_IP_PORT_LIST")


def derive_authkey(env_var: str, namespace: str) -> bytes:
    secret = os.environ.get(env_var)
    if secret:
        return secret.encode()
    for var in _JOB_VARS:
        job = os.environ.get(var, "")
        if job:
            import hashlib
            return hashlib.sha256(
                (f"paddle_tpu_{namespace}:{var}={job}").encode()).digest()
    # Bare local runs: a same-user secret file (0600) — other local users
    # cannot read it, unlike anything derivable from uid/source. Creation
    # is atomic (temp + hard link) and creation races settle by
    # re-reading, so concurrent ranks always converge on ONE key and a
    # live listener's key is never clobbered.
    import secrets
    import tempfile
    path = os.path.join(os.path.expanduser("~"),
                        f".paddle_tpu_{namespace}_key")
    for _ in range(10):
        try:
            with open(path, "rb") as f:
                key = f.read()
            if len(key) >= 16:
                return key
            # short/corrupt file (killed writer, disk-full): self-heal by
            # removing it so the link below can install a fresh key
            try:
                os.unlink(path)
            except OSError:
                pass
        except OSError:
            pass
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   prefix=".p2p_key_")
        try:
            os.fchmod(fd, 0o600)
            with os.fdopen(fd, "wb") as f:
                f.write(secrets.token_bytes(32))
            # O_EXCL-style: only create if absent; losers re-read winner's
            try:
                os.link(tmp, path)
            except FileExistsError:
                pass
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
    raise RuntimeError(f"could not establish authkey file at {path}")
