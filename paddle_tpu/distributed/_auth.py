"""Shared per-job authkey derivation for host-side pickle channels.

multiprocessing.connection deserializes pickles after HMAC auth, so a
constant key in public source would hand RCE to anything that can reach
the port (ref hazard: paddle/fluid/distributed uses brpc with its own
auth; our host channels must supply an equivalent). Every channel
(collective p2p, parameter server, rpc, elastic) derives its key here:

1. an explicit per-channel env var set by the operator (strongest),
2. else PADDLE_JOB_AUTHKEY — a RANDOM per-job secret the launcher
   generates for single-node jobs and distributes to every role
   (launch/main.py); namespaced per channel by digest,
3. else a digest of ONE job-identity env var + a namespace tag. Exactly
   one var is used — the FIRST set among PADDLE_MASTER,
   PADDLE_TRAINER_ENDPOINTS, PADDLE_PSERVERS_IP_PORT_LIST — never a
   concatenation, because different processes of one job may
   legitimately see different SUBSETS of these (a PS server launched
   with only the pserver list must still derive the same key as a
   trainer that has all three). Launchers must publish the
   highest-priority var to every process.
4. else — bare local runs — a same-user 0600 secret file (one file per
   namespace, so channels stay key-isolated even in this mode),
   created atomically so concurrent ranks converge on ONE key.

SECURITY (advisor r3, medium): tiers 3 and 4 are computable by anyone
who can observe the endpoint lists (process args, logs, conn metadata),
so a listener BINDING A NON-LOOPBACK INTERFACE refuses to fall back to
them — callers pass `bind_host` and get a RuntimeError directing them
to set the explicit secret (or PADDLE_ALLOW_DERIVED_AUTHKEY=1 to
accept the risk with a loud warning). Loopback-only channels keep the
convenient fallbacks.
"""
from __future__ import annotations

import os

__all__ = ["derive_authkey", "authkey_source"]

# priority order of the job-identity vars; see module docstring
_JOB_VARS = ("PADDLE_MASTER", "PADDLE_TRAINER_ENDPOINTS",
             "PADDLE_PSERVERS_IP_PORT_LIST")

# NOTE: "" is NOT loopback — binding "" means INADDR_ANY (all
# interfaces), the same exposure as "0.0.0.0"
_LOOPBACK = ("127.0.0.1", "localhost", "::1", "0:0:0:0:0:0:0:1")

_warned = set()


def _digest(namespace: str, tag: str, value: str) -> bytes:
    import hashlib
    return hashlib.sha256(
        (f"paddle_tpu_{namespace}:{tag}={value}").encode()).digest()


def authkey_source(env_var: str) -> str:
    """Human-readable description of where this channel's key comes
    from — appended to AuthenticationError handling so a key MISMATCH
    (two roles seeing different job-var subsets) is diagnosable instead
    of a bare auth failure (advisor r3, low)."""
    if os.environ.get(env_var):
        return f"explicit {env_var}"
    if os.environ.get("PADDLE_JOB_AUTHKEY"):
        return "launcher-distributed PADDLE_JOB_AUTHKEY"
    for var in _JOB_VARS:
        if os.environ.get(var):
            return (f"derived from {var} (roles seeing a different "
                    f"subset of {'/'.join(_JOB_VARS)} derive DIFFERENT "
                    f"keys — export {env_var} or PADDLE_JOB_AUTHKEY to "
                    "every role)")
    return "same-user key file (~/.paddle_tpu_*_key)"


def _guard_exposed(env_var: str, namespace: str, bind_host: str,
                   fallback: str):
    """Non-loopback listener + guessable fallback: refuse (or warn once
    when explicitly overridden)."""
    if os.environ.get("PADDLE_ALLOW_DERIVED_AUTHKEY"):
        key = (namespace, bind_host)
        if key not in _warned:
            _warned.add(key)
            import warnings
            warnings.warn(
                f"paddle_tpu.{namespace}: listener on {bind_host!r} is "
                f"using a {fallback} authkey that a network-adjacent "
                "observer who knows the job endpoints can compute; "
                f"set {env_var} (or PADDLE_JOB_AUTHKEY) to a random "
                "per-job secret for network-exposed channels",
                RuntimeWarning, stacklevel=3)
        return
    raise RuntimeError(
        f"paddle_tpu.{namespace}: refusing to bind {bind_host!r} with a "
        f"{fallback} authkey — it is computable from non-secret job "
        f"metadata. Set {env_var} (or PADDLE_JOB_AUTHKEY) to a random "
        "per-job secret (the launcher exports one automatically for "
        "single-node jobs), or set PADDLE_ALLOW_DERIVED_AUTHKEY=1 to "
        "accept the risk.")


def derive_authkey(env_var: str, namespace: str,
                   bind_host: str | None = None) -> bytes:
    """bind_host: pass the listener's bind address when deriving a key
    for a LISTENER; non-loopback binds require an explicit secret (tier
    1/2). Client-side derivations (connect) omit it."""
    secret = os.environ.get(env_var)
    if secret:
        return secret.encode()
    job = os.environ.get("PADDLE_JOB_AUTHKEY")
    if job:
        return _digest(namespace, "job", job)
    exposed = (bind_host is not None
               and bind_host.strip().lower() not in _LOOPBACK)
    for var in _JOB_VARS:
        val = os.environ.get(var, "")
        if val:
            if exposed:
                _guard_exposed(env_var, namespace, bind_host,
                               f"{var}-derived")
            return _digest(namespace, var, val)
    if exposed:
        _guard_exposed(env_var, namespace, bind_host, "key-file")
    # Bare local runs: a same-user secret file (0600) — other local users
    # cannot read it, unlike anything derivable from uid/source. Creation
    # is atomic (temp + hard link) and creation races settle by
    # re-reading, so concurrent ranks always converge on ONE key and a
    # live listener's key is never clobbered.
    import secrets
    import tempfile
    path = os.path.join(os.path.expanduser("~"),
                        f".paddle_tpu_{namespace}_key")
    for _ in range(10):
        try:
            with open(path, "rb") as f:
                key = f.read()
            if len(key) >= 16:
                return key
            # short/corrupt file (killed writer, disk-full): self-heal by
            # removing it so the link below can install a fresh key
            try:
                os.unlink(path)
            except OSError:
                pass
        except OSError:
            pass
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   prefix=".p2p_key_")
        try:
            os.fchmod(fd, 0o600)
            with os.fdopen(fd, "wb") as f:
                f.write(secrets.token_bytes(32))
            # O_EXCL-style: only create if absent; losers re-read winner's
            try:
                os.link(tmp, path)
            except FileExistsError:
                pass
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
    raise RuntimeError(f"could not establish authkey file at {path}")
